"""Fleet flight recorder: cross-replica correlation, merged timelines,
the ownership Gantt, the steal-latency SLI, the replica metrics label,
and the live steady-state sentinel.

The PR 13 tentpole contract (designs/fleet-flight-recorder.md): one
CorrelationId per pod/claim lifecycle, minted identically on every
replica with zero coordination; every hop stamped with the replica that
performed it (and the fencing token that sanctioned it); FleetRecorder
merges the shared world's hops into one deterministic decision timeline
per object; and the sentinel re-detects attribution cliffs live while
staying silent on quiet runs.
"""

from __future__ import annotations

import json

import pytest

from karpenter_provider_aws_tpu.metrics import REGISTRY
from karpenter_provider_aws_tpu.models import Disruption, NodePool
from karpenter_provider_aws_tpu.models import labels as lbl
from karpenter_provider_aws_tpu.models.pod import make_pods
from karpenter_provider_aws_tpu.obs.fleet import FleetRecorder
from karpenter_provider_aws_tpu.obs.sentinel import (
    SteadyStateSentinel,
    detect_cliffs,
    span_family,
)
from karpenter_provider_aws_tpu.operator.sharding import GLOBAL_KEY
from karpenter_provider_aws_tpu.state.cluster import Node
from karpenter_provider_aws_tpu.testenv import new_environment, new_replicaset
from karpenter_provider_aws_tpu.trace.correlate import (
    CorrelationLedger,
    chain_complete,
    correlation_id,
)
from karpenter_provider_aws_tpu.utils.clock import FakeClock


def _pool():
    return NodePool(name="default",
                    disruption=Disruption(consolidate_after_s=None))


# ---------------------------------------------------------------------------
# the correlation ledger
# ---------------------------------------------------------------------------

class TestCorrelationLedger:
    def test_correlation_id_is_pure(self):
        assert correlation_id("Pod", "pod-1") == correlation_id("Pod", "pod-1")
        assert correlation_id("Pod", "pod-1") != correlation_id("Pod", "pod-2")
        assert correlation_id("Pod", "x") != correlation_id("NodeClaim", "x")

    def test_record_once_dedupes(self):
        led = CorrelationLedger(clock=FakeClock())
        cid = led.mint("Pod", "pod-1", name="web-0")
        assert led.record_once(cid, "route") is not None
        assert led.record_once(cid, "route") is None
        assert led.record_once(cid, "route", key="other") is not None
        assert len(led.hops(cid)) == 2

    def test_alias_resolution_by_name_and_uid(self):
        led = CorrelationLedger(clock=FakeClock())
        cid = led.mint("Pod", "pod-7", name="web-3")
        assert led.resolve("Pod", "web-3") == cid
        assert led.resolve("Pod", "pod-7") == cid
        assert led.resolve("Pod", "missing") is None

    def test_ring_bound_prunes_index(self):
        led = CorrelationLedger(capacity=8, clock=FakeClock())
        for i in range(20):
            led.record(led.mint("Pod", f"pod-{i}"), "pending")
        assert len(led) == 8
        # the first 12 pods' hops were evicted WITH their index entries
        assert led.hops(correlation_id("Pod", "pod-0")) == []
        assert len(led.hops(correlation_id("Pod", "pod-19"))) == 1

    def test_snapshot_roundtrip(self):
        clock = FakeClock()
        led = CorrelationLedger(clock=clock)
        cid = led.mint("Pod", "pod-1", name="web-0")
        led.record(cid, "pending", subject_kind="Pod", subject="web-0")
        clock.advance(5)
        led.record(cid, "bind", subject_kind="Pod", subject="web-0",
                   fence=("karpenter-shard/__global__/", 2),
                   detail={"node": "n1"})
        data = json.loads(json.dumps(led.snapshot()))
        led2 = CorrelationLedger.from_snapshot(data)
        assert led2.resolve("Pod", "web-0") == cid
        hops = led2.hops(cid)
        assert [h.kind for h in hops] == ["pending", "bind"]
        assert hops[1].fence == ("karpenter-shard/__global__/", 2)

    def test_merge_order_time_then_seq(self):
        clock = FakeClock()
        led = CorrelationLedger(clock=clock)
        cid = led.mint("Pod", "pod-1")
        led.record(cid, "route", fence=None)
        led.record(cid, "claim", fence=("l", 5))  # same instant, later seq
        clock.advance(1)
        led.record(cid, "bind")
        assert [h.kind for h in led.hops(cid)] == ["route", "claim", "bind"]

    def test_chain_complete_rule(self):
        assert chain_complete({"pending", "bind"})
        assert chain_complete({"evict", "bind"})  # drained ballast re-bind
        assert not chain_complete({"pending"})
        assert not chain_complete({"bind"})


# ---------------------------------------------------------------------------
# single-replica chain through the real controller stack
# ---------------------------------------------------------------------------

class TestSingleReplicaChain:
    def test_full_lifecycle_chain_and_coverage(self):
        env = new_environment(use_tpu_solver=False)
        try:
            env.apply_defaults()
            for p in make_pods(3, "web", {"cpu": "500m", "memory": "1Gi"}):
                env.cluster.apply(p)
            for _ in range(6):
                env.step(1)
                env.clock.advance(5)
            assert not env.cluster.pending_pods()
            fr = FleetRecorder(env)
            cov = fr.coverage()
            assert cov["bound"] == 3 and cov["coverage"] == 1.0
            view = fr.explain("Pod", "web-0")
            kinds = [h["kind"] for h in view["hops"]
                     if h["subject"] == "web-0"]
            assert kinds[0] == "pending"
            assert "solve" in kinds and "launch" in kinds
            assert kinds[-1] == "bind"
            # the launch hop links the claim; its hops merged in
            claim_kinds = {h["kind"] for h in view["hops"]
                           if h["subject_kind"] == "NodeClaim"}
            assert {"launched", "register", "ready"} <= claim_kinds
            text = fr.render_explain(view)
            assert "Pod/web-0" in text and "bind" in text
        finally:
            env.close()

    def test_debug_flight_page_serves_ledger(self):
        env = new_environment(use_tpu_solver=False)
        try:
            env.apply_defaults()
            (p,) = make_pods(1, "flight", {"cpu": "250m", "memory": "512Mi"})
            env.cluster.apply(p)
            env.step(2)
            page = REGISTRY.debug_page("/debug/flight")
            assert page is not None
            assert any(
                h["kind"] == "pending" for h in page["ledger"]["hops"]
            )
            assert page["coverage"]["bound"] >= 0
            # the snapshot round-trips into an offline recorder
            fr = FleetRecorder.from_snapshot(json.loads(json.dumps(page)))
            assert fr.ledger.resolve("Pod", "flight-0")
        finally:
            env.close()


# ---------------------------------------------------------------------------
# cross-replica explain (satellite: seeded replica-loss reconstruction)
# ---------------------------------------------------------------------------

def _replica_loss_run():
    """Route a global pod, kill its claimant mid-lifecycle, let a
    survivor steal/adopt and finish the bind. Returns (env, recorder)."""
    rs = new_replicaset(4)
    rs.apply_defaults(_pool())
    rs.step(2)
    holder = next(r for r in rs.replicas
                  if GLOBAL_KEY in r.elector.ownership().keys)
    for p in make_pods(2, "loss", {"cpu": "1", "memory": "2Gi"}):
        rs.cluster.apply(p)
    rs.step(1)          # holder claims + launches (fenced)
    rs.crash(rs.replicas.index(holder))
    rs.clock.advance(16)  # the dead holder's leases lapse
    for _ in range(12):
        rs.step(1)
        rs.clock.advance(3)
    return rs, FleetRecorder(rs)


def _normalized_chain(view: dict) -> list[tuple]:
    """The hop chain with process-global ids normalized away (claim
    names / node names / uids carry process counters)."""
    from karpenter_provider_aws_tpu.sim.report import normalize_ids

    out = []
    for h in view["hops"]:
        out.append((
            round(h["at"], 3), h["replica"], h["kind"],
            normalize_ids(h["subject"]),
            normalize_ids(json.dumps(h.get("detail", {}), sort_keys=True)),
            normalize_ids(json.dumps(h.get("fence", []))),
        ))
    return out


class TestCrossReplicaExplain:
    def test_replica_loss_chain_reconstructs(self):
        rs, fr = _replica_loss_run()
        try:
            assert not rs.cluster.pending_pods()
            view = fr.explain("Pod", "loss-0")
            kinds = [h["kind"] for h in view["hops"]]
            for want in ("pending", "route", "claim", "solve", "launch",
                         "adopt", "register", "bind"):
                assert want in kinds, f"missing hop {want}: {kinds}"
            # causal order of the pod's own lifecycle hops
            pod_kinds = [h["kind"] for h in view["hops"]
                         if h["subject_kind"] == "Pod"]
            assert pod_kinds.index("route") < pod_kinds.index("claim")
            assert pod_kinds.index("claim") < pod_kinds.index("launch")
            assert pod_kinds.index("launch") < pod_kinds.index("bind")
            # the lifecycle genuinely crossed replicas
            doers = {h["replica"] for h in view["hops"]
                     if h["replica"].startswith("replica-")}
            assert len(doers) >= 2, doers
            # the launch carried the claimant's fencing token
            launch = next(h for h in view["hops"] if h["kind"] == "launch")
            assert launch["fence"] and launch["fence"][1] >= 1
            # the adopt hop carries the SUCCESSOR's (newer) tenancy
            adopt = next(h for h in view["hops"] if h["kind"] == "adopt")
            assert adopt["fence"][1] > launch["fence"][1]
            assert fr.coverage()["coverage"] == 1.0
        finally:
            rs.close()

    def test_replica_loss_chain_byte_identical_per_seed(self):
        rs1, fr1 = _replica_loss_run()
        chain1 = _normalized_chain(fr1.explain("Pod", "loss-0"))
        rs1.close()
        rs2, fr2 = _replica_loss_run()
        chain2 = _normalized_chain(fr2.explain("Pod", "loss-0"))
        rs2.close()
        assert chain1 == chain2
        assert len(chain1) >= 8

    def test_ownership_gantt_records_handoff(self):
        rs, fr = _replica_loss_run()
        try:
            gantt = fr.ownership_gantt()
            key = "/".join(str(k) for k in GLOBAL_KEY)
            segs = gantt["segments"].get(key, [])
            holders = [s["holder"] for s in segs if s["holder"]]
            assert len(holders) >= 2, segs  # the GLOBAL lease changed hands
            tokens = [s["token"] for s in segs if s["holder"]]
            assert tokens == sorted(tokens)  # tenancies only move forward
            assert any(a["claims"] for a in gantt["adoptions"])
            text = fr.render_gantt(gantt)
            assert "__global__" in text
        finally:
            rs.close()

    def test_fleet_cli_explains_from_snapshot(self, tmp_path, capsys):
        from karpenter_provider_aws_tpu.obs.__main__ import main as obs_main

        rs, fr = _replica_loss_run()
        path = str(tmp_path / "flight.json")
        fr.save(path)
        rs.close()
        assert obs_main(["fleet", "explain", "pod/loss-0",
                         "--flight-file", path]) == 0
        out = capsys.readouterr().out
        assert "Pod/loss-0" in out and "bind" in out and "claim" in out
        assert obs_main(["fleet", "timeline", "--flight-file", path]) == 0
        assert "__global__" in capsys.readouterr().out
        assert obs_main(["fleet", "coverage", "--flight-file", path]) == 0
        assert "coverage: 1.0" in capsys.readouterr().out
        # unknown object exits non-zero (absence must be loud)
        assert obs_main(["fleet", "explain", "pod/ghost",
                         "--flight-file", path]) == 3


# ---------------------------------------------------------------------------
# satellite: per-replica reconcile metrics must not silently sum
# ---------------------------------------------------------------------------

class TestReplicaMetricsLabel:
    def test_two_replicas_distinguishable_on_metrics(self):
        rs = new_replicaset(2)
        try:
            rs.apply_defaults(_pool())
            rs.step(2)
            body = REGISTRY.expose()
            for identity in ("replica-0", "replica-1"):
                needle = (
                    'karpenter_controller_reconcile_duration_seconds_count'
                    f'{{controller="provisioning",replica="{identity}"}}'
                )
                assert needle in body, f"missing per-replica series: {needle}"
        finally:
            rs.close()

    def test_single_replica_series_unlabeled(self):
        env = new_environment(use_tpu_solver=False)
        try:
            env.apply_defaults()
            env.step(1)
            body = REGISTRY.expose()
            assert ('karpenter_controller_reconcile_duration_seconds_count'
                    '{controller="provisioning"}') in body
        finally:
            env.close()


# ---------------------------------------------------------------------------
# satellite: rendezvous imbalance is measured, not anecdotal
# ---------------------------------------------------------------------------

class TestRendezvousImbalance:
    def test_gauges_exported_from_lease_table(self):
        from karpenter_provider_aws_tpu.metrics import (
            LEASE_OWNERSHIP,
            RENDEZVOUS_IMBALANCE,
        )

        rs = new_replicaset(2)
        try:
            rs.apply_defaults(_pool())
            for z in ("zone-a", "zone-b", "zone-c"):
                rs.cluster.apply(Node(
                    name=f"seed-{z}", nodepool_name="default",
                    labels={lbl.TOPOLOGY_ZONE: z}, ready=True,
                ))
            rs.step(3)
            held = {
                r.identity: LEASE_OWNERSHIP.value(replica=r.identity)
                for r in rs.replicas
            }
            assert sum(held.values()) >= 4  # 3 partitions + GLOBAL
            imb = RENDEZVOUS_IMBALANCE.value()
            mean = sum(held.values()) / len(held)
            assert imb == pytest.approx(max(held.values()) / mean, abs=1e-3)
            assert 'karpenter_lease_ownership{replica="replica-0"}' in (
                REGISTRY.expose()
            )
        finally:
            rs.close()

    def test_dead_holder_ownership_drops_to_zero(self):
        from karpenter_provider_aws_tpu.metrics import LEASE_OWNERSHIP

        rs = new_replicaset(2)
        try:
            rs.apply_defaults(_pool())
            rs.step(3)
            dead = next(
                r for r in rs.replicas
                if LEASE_OWNERSHIP.value(replica=r.identity) > 0
            )
            rs.crash(rs.replicas.index(dead))
            rs.clock.advance(16)  # its leases lapse
            rs.step(2)
            # the survivor's export must zero the vanished holder, not
            # leave its series frozen at the pre-crash value
            assert LEASE_OWNERSHIP.value(replica=dead.identity) == 0.0
        finally:
            rs.close()


# ---------------------------------------------------------------------------
# satellite: steal-latency SLI
# ---------------------------------------------------------------------------

class TestStealWaitSLI:
    def test_healthy_claims_have_zero_queue_wait(self):
        rs = new_replicaset(2)
        try:
            rs.apply_defaults(_pool())
            rs.step(2)
            for p in make_pods(4, "q", {"cpu": "500m", "memory": "1Gi"}):
                rs.cluster.apply(p)
            rs.step(2)
            waits = rs.obs.sli.queue_wait_durations()
            assert len(waits) == 4
            assert max(waits) == 0.0  # routed and claimed in the same pass
            assert rs.obs.sli.steal_wait_durations() == []
        finally:
            rs.close()

    def test_steal_wait_measures_the_loss_window(self):
        """The bench scenario's teeth: a killed GLOBAL holder's pods are
        stolen only after the lease TTL, and the SLI measures exactly
        that wait (benchmarks/sli_bench.py emits the row)."""
        from benchmarks.sli_bench import _steal_wait_row

        row = _steal_wait_row(5.0)
        assert row["benchmark"] == "pod_steal_wait_sli"
        assert row["stolen"] == 10
        assert row["unbound"] == 0
        # enqueue -> steal spans the 15s lease TTL the survivor waits out
        assert 15.0 <= row["steal_wait_p99_s"] <= 20.0
        assert row["queue_wait_p50_s"] == 0.0  # healthy phase unaffected
        row2 = _steal_wait_row(5.0)
        assert {k: v for k, v in row.items() if k != "wall_s"} == \
               {k: v for k, v in row2.items() if k != "wall_s"}


# ---------------------------------------------------------------------------
# the live steady-state sentinel
# ---------------------------------------------------------------------------

def _profiles_to_source(profiles):
    """A profile_source yielding each cumulative profile in turn, then
    holding the last one."""
    it = iter(profiles)
    state = {"cur": None}

    def source():
        try:
            state["cur"] = next(it)
        except StopIteration:
            pass
        return state["cur"]

    return source


def _cumulate(tick_deltas):
    """Turn per-tick {span: ms} deltas into cumulative profiles."""
    out = []
    totals: dict[str, float] = {}
    for delta in tick_deltas:
        for name, ms in delta.items():
            totals[name] = totals.get(name, 0.0) + ms
        out.append({
            "spans": {n: {"count": 1, "total_ms": t}
                      for n, t in totals.items()},
        })
    return out


QUIET_TICK = {
    "controller.disruption": 900.0,
    "controller.provisioning": 400.0,
    "solve.device": 300.0,
    "consolidate.screen": 400.0,
}


class TestSteadyStateSentinel:
    def test_quiet_steady_state_is_silent(self):
        clock = FakeClock()
        s = SteadyStateSentinel(
            clock=clock,
            profile_source=_profiles_to_source(_cumulate([QUIET_TICK] * 20)),
        )
        findings = []
        for _ in range(20):
            clock.advance(10)
            findings += s.tick()
        assert findings == []
        assert s.summary()["warmed_up"]

    def test_redetects_the_50k_disruption_cliff(self):
        """The PR 10 finding, replayed live: controller.disruption's
        share jumps 44.8% -> 67.4% of a multi-second tick when the
        dirty-sweep fix is off — the sentinel must raise an
        edge-triggered finding NAMING the controller."""
        quiet = {
            "controller.disruption": 900.0,   # ~45% of a 2s tick
            "controller.provisioning": 500.0,
            "solve.device": 300.0,
            "consolidate.screen": 300.0,
        }
        cliff = {
            "controller.disruption": 4200.0,  # ~67% of a 6.2s tick
            "controller.provisioning": 800.0,
            "solve.device": 500.0,
            "consolidate.screen": 700.0,
        }
        clock = FakeClock()
        s = SteadyStateSentinel(
            clock=clock,
            profile_source=_profiles_to_source(
                _cumulate([quiet] * 8 + [cliff] * 3)
            ),
        )
        all_findings = []
        for _ in range(11):
            clock.advance(10)
            all_findings += s.tick()
        shifts = [f for f in all_findings
                  if f["kind"] == "attribution-shift"]
        assert shifts, all_findings
        assert shifts[0]["family"] == "controller.disruption"
        # edge-triggered: the persisting cliff raised exactly ONE
        # attribution-shift episode for the named controller
        assert len([f for f in shifts
                    if f["family"] == "controller.disruption"]) == 1

    def test_tick_superlinear_names_top_family(self):
        quiet = {"controller.liveness": 200.0}
        blowup = {"controller.liveness": 200.0, "solve.device": 9000.0}
        clock = FakeClock()
        s = SteadyStateSentinel(
            clock=clock,
            profile_source=_profiles_to_source(
                _cumulate([quiet] * 8 + [blowup])
            ),
        )
        findings = []
        for _ in range(9):
            clock.advance(10)
            findings += s.tick()
        supers = [f for f in findings if f["kind"] == "tick-superlinear"]
        assert supers and supers[0]["family"] == "solve"

    def test_events_published_only_when_enabled(self):
        from karpenter_provider_aws_tpu.events import EventRecorder

        clock = FakeClock()
        recorder = EventRecorder(clock=clock)
        profiles = _cumulate(
            [QUIET_TICK] * 8
            + [{**QUIET_TICK, "controller.disruption": 9000.0}]
        )
        s = SteadyStateSentinel(
            clock=clock, recorder=recorder,
            profile_source=_profiles_to_source(profiles),
        )
        s.publish_events = False
        for _ in range(9):
            clock.advance(10)
            s.tick()
        assert recorder.query(reason="SteadyStateRegression") == []
        assert s.findings  # ...but the finding itself was recorded

    def test_events_fire_when_publishing(self):
        from karpenter_provider_aws_tpu.events import EventRecorder

        clock = FakeClock()
        recorder = EventRecorder(clock=clock)
        profiles = _cumulate(
            [QUIET_TICK] * 8
            + [{**QUIET_TICK, "controller.disruption": 9000.0}]
        )
        s = SteadyStateSentinel(
            clock=clock, recorder=recorder,
            profile_source=_profiles_to_source(profiles),
        )
        for _ in range(9):
            clock.advance(10)
            s.tick()
        events = recorder.query(reason="SteadyStateRegression")
        assert events and events[0].name == "controller.disruption"

    def test_sim_container_spans_excluded(self):
        assert span_family("controller.disruption") == "controller.disruption"
        assert span_family("solve.device") == "solve"
        assert span_family("consolidate.screen") == "consolidate"
        clock = FakeClock()
        # sim.* container spans contain every controller span; folding
        # them in would double-count the tick
        ticks = [dict(QUIET_TICK, **{"sim.controllers": 10000.0})] * 8
        s = SteadyStateSentinel(
            clock=clock, profile_source=_profiles_to_source(_cumulate(ticks)),
        )
        for _ in range(8):
            clock.advance(10)
            s.tick()
        assert "sim" not in s.summary()["baseline_shares"]

    def test_detect_cliffs_reexported_for_sim(self):
        # the simulator's import path must keep working after the lift
        from karpenter_provider_aws_tpu.sim.cliffs import (
            detect_cliffs as sim_detect,
        )

        assert sim_detect is detect_cliffs

    def test_share_gauge_zeroed_for_absent_families(self):
        from karpenter_provider_aws_tpu.metrics import SENTINEL_SHARE

        clock = FakeClock()
        ticks = _cumulate([
            {"controller.liveness": 100.0, "solve.device": 100.0},
            {"controller.liveness": 100.0},  # solve does nothing this tick
        ])
        s = SteadyStateSentinel(
            clock=clock, profile_source=_profiles_to_source(ticks),
        )
        clock.advance(10)
        s.tick()
        assert SENTINEL_SHARE.value(family="solve") == 0.5
        clock.advance(10)
        s.tick()
        # absent from this tick -> 0, not frozen at the stale 0.5
        assert SENTINEL_SHARE.value(family="solve") == 0.0
        assert SENTINEL_SHARE.value(family="controller.liveness") == 1.0

    def test_debug_sentinel_page(self):
        env = new_environment(use_tpu_solver=False)
        try:
            env.apply_defaults()
            env.step(1)
            env.obs.tick()
            page = REGISTRY.debug_page("/debug/sentinel")
            assert page is not None and "ticks" in page
        finally:
            env.close()
