"""Incremental (delta-aware) encoding: the PR-3 tentpole contract.

The property at the center: for ANY sequence of sanctioned cluster
mutations (node add/remove, pod bind/unbind, nodeclaim updates, occupancy
changes — plus direct attribute flips, which the defensive version scan
covers), the incrementally patched ``ClusterTensors`` must be EXACTLY equal
(canonical form, no tolerance) to a from-scratch ``_encode_cluster``.

Also here: the change journal's semantics, every full-re-encode fallback
trigger (journal overflow, catalog seqnum change, heavy churn, refresh
period, store epoch change), the revision-cached ``ZoneOccupancy``, and the
``/metrics`` encode-cache counters guarding against silent cache
regressions (two identical reconcile passes against the fake cloud must
increment the hit counter).
"""

import urllib.request

import numpy as np
import pytest

from karpenter_provider_aws_tpu.catalog import CatalogProvider
from karpenter_provider_aws_tpu.models import Disruption, NodePool
from karpenter_provider_aws_tpu.models import labels as lbl
from karpenter_provider_aws_tpu.models.nodeclaim import NodeClaim
from karpenter_provider_aws_tpu.models.pod import (
    PodAffinityTerm,
    TopologySpreadConstraint,
    make_pods,
)
from karpenter_provider_aws_tpu.ops.consolidate import _encode_cluster, encode_cluster
from karpenter_provider_aws_tpu.ops.encode import ZoneOccupancy
from karpenter_provider_aws_tpu.ops.encode_delta import (
    canonical_equal,
    canonical_form,
    invalidate_cluster_encoders,
)
from karpenter_provider_aws_tpu.state.cluster import JOURNAL_CAP, Cluster, Node


def _add_node(cluster, catalog, i, zone="zone-a", pool="default"):
    candidates = [t for t in catalog.list() if t.category in ("c", "m")]
    it = candidates[i % len(candidates)]
    claim = NodeClaim.fresh(
        nodepool_name=pool,
        nodeclass_name="default",
        instance_type_options=[it.name],
        zone_options=[zone],
        capacity_type_options=["spot"],
    )
    claim.status.provider_id = f"cloud:///{zone}/i-enc{i}"
    claim.status.capacity = it.capacity()
    claim.status.allocatable = catalog.allocatable(it)
    claim.labels.update(it.labels())
    claim.labels[lbl.TOPOLOGY_ZONE] = zone
    claim.labels[lbl.CAPACITY_TYPE] = "spot"
    claim.labels[lbl.NODEPOOL] = pool
    for c in ("Launched", "Registered", "Initialized"):
        claim.status.set_condition(c, True)
    cluster.apply(claim)
    node = Node(
        name=f"node-enc{i}",
        provider_id=claim.status.provider_id,
        nodepool_name=pool,
        nodeclaim_name=claim.name,
        labels=dict(claim.labels),
        capacity=claim.status.capacity,
        allocatable=claim.status.allocatable,
        ready=True,
    )
    node.labels[lbl.HOSTNAME] = node.name
    claim.status.node_name = node.name
    cluster.apply(node)
    return node, claim


def _small_cluster(catalog, n=12):
    cluster = Cluster()
    cluster.apply(NodePool(name="default",
                           disruption=Disruption(consolidate_after_s=60)))
    nodes = []
    for i in range(n):
        zone = ("zone-a", "zone-b", "zone-c")[i % 3]
        node, _ = _add_node(cluster, catalog, i, zone=zone)
        nodes.append(node)
        for p in make_pods(1 + i % 3, f"seed{i}",
                           {"cpu": "250m", "memory": "512Mi"}):
            cluster.apply(p)
            cluster.bind_pod(p.uid, node.name)
    return cluster, nodes


def _assert_equal(cluster, catalog, tag=""):
    inc = encode_cluster(cluster, catalog)
    fresh = _encode_cluster(cluster, catalog, 32)
    diffs = canonical_equal(canonical_form(inc), canonical_form(fresh))
    assert not diffs, f"{tag}: patched tensors diverge from fresh encode: {diffs}"
    return inc


class TestChangeJournal:
    def test_rev_monotonic_and_changes(self, session_catalog):
        cluster = Cluster()
        r0 = cluster.rev
        node, claim = _add_node(cluster, session_catalog, 0)
        assert cluster.rev > r0
        ch = cluster.changes_since(r0)
        assert "node" in ch and node.name in ch["node"]
        assert "claim" in ch and claim.name in ch["claim"]
        assert cluster.changes_since(cluster.rev) == {}

    def test_pod_entries_carry_node_names(self, session_catalog):
        cluster = Cluster()
        node, _ = _add_node(cluster, session_catalog, 0)
        p = make_pods(1, "w", {"cpu": "100m"})[0]
        cluster.apply(p)
        r = cluster.rev
        cluster.bind_pod(p.uid, node.name)
        assert node.name in cluster.changes_since(r)["pod"]
        r = cluster.rev
        cluster.unbind_pod(p.uid)
        assert node.name in cluster.changes_since(r)["pod"]

    def test_overflow_returns_none(self):
        cluster = Cluster()
        r0 = cluster.rev
        for i in range(JOURNAL_CAP + 5):
            cluster._record("pdb", f"x{i}")
        assert cluster.changes_since(r0) is None
        # a recent revision is still covered
        r1 = cluster.rev
        cluster._record("pdb", "y")
        assert cluster.changes_since(r1) == {"pdb": ["y"]}

    def test_unbind_pod_through_store(self, session_catalog):
        cluster = Cluster()
        node, _ = _add_node(cluster, session_catalog, 0)
        p = make_pods(1, "w", {"cpu": "100m"})[0]
        cluster.apply(p)
        cluster.bind_pod(p.uid, node.name)
        assert cluster.pods_on_nodes([node.name])[node.name] == [p]
        cluster.unbind_pod(p.uid)
        assert p.is_pending()
        assert cluster.pods_on_nodes([node.name]) == {}


class TestIncrementalClusterEncode:
    def test_unchanged_cluster_returns_same_object(self, session_catalog):
        cluster, _ = _small_cluster(session_catalog)
        ct1 = encode_cluster(cluster, session_catalog)
        ct2 = encode_cluster(cluster, session_catalog)
        assert ct1 is ct2

    def test_full_matches_fresh(self, session_catalog):
        cluster, _ = _small_cluster(session_catalog)
        _assert_equal(cluster, session_catalog, "cold")

    def test_catalog_seq_change_forces_full_and_matches(self, session_catalog):
        # a private catalog: ICE marks must not leak into other tests
        catalog = CatalogProvider()
        cluster, _ = _small_cluster(catalog)
        ct1 = encode_cluster(cluster, catalog)
        catalog.unavailable.mark_unavailable("c7g.4xlarge", "zone-a", "on-demand")
        ct2 = encode_cluster(cluster, catalog)
        assert ct2 is not ct1
        _assert_equal(cluster, catalog, "post-catalog-change")

    def test_journal_overflow_falls_back_to_full(self, session_catalog):
        cluster, nodes = _small_cluster(session_catalog)
        encode_cluster(cluster, session_catalog)
        for i in range(JOURNAL_CAP + 5):
            cluster._record("pdb", f"noise{i}")
        _assert_equal(cluster, session_catalog, "post-overflow")

    def test_epoch_reset_is_not_served_stale(self, session_catalog):
        cluster, _ = _small_cluster(session_catalog)
        ct1 = encode_cluster(cluster, session_catalog)
        assert ct1 is not None
        cluster.__init__()  # Environment.reset() re-runs __init__ in place
        assert encode_cluster(cluster, session_catalog) is None

    def test_kill_switch(self, session_catalog, monkeypatch):
        cluster, _ = _small_cluster(session_catalog)
        monkeypatch.setenv("KARPENTER_TPU_INCREMENTAL_ENCODE", "0")
        ct1 = encode_cluster(cluster, session_catalog)
        ct2 = encode_cluster(cluster, session_catalog)
        assert ct1 is not ct2  # full encode every call

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_property_random_mutation_sequences(self, session_catalog, seed):
        """THE acceptance property: after every randomized mutation batch,
        patched tensors == fresh encode, exactly."""
        rng = np.random.RandomState(seed)
        cluster, nodes = _small_cluster(session_catalog)
        names = [n.name for n in nodes]
        encode_cluster(cluster, session_catalog)
        next_node = len(nodes)
        for step in range(25):
            for _ in range(rng.randint(1, 5)):
                op = rng.randint(7)
                if op == 0:  # bind a fresh pod (sometimes topology-bearing)
                    kwargs = {}
                    r = rng.rand()
                    if r < 0.2:
                        kwargs = dict(
                            labels={"app": f"s{rng.randint(3)}"},
                            topology_spread=[TopologySpreadConstraint(
                                topology_key=lbl.TOPOLOGY_ZONE, max_skew=1,
                                label_selector={"app": f"s{rng.randint(3)}"},
                            )],
                        )
                    elif r < 0.35:
                        kwargs = dict(
                            labels={"app": f"a{rng.randint(3)}"},
                            anti_affinity=[PodAffinityTerm(
                                topology_key=lbl.HOSTNAME,
                                label_selector={"app": f"a{rng.randint(3)}"},
                            )],
                        )
                    elif r < 0.45:
                        kwargs = dict(node_selector={lbl.ARCH: "arm64"})
                    p = make_pods(1, f"m{seed}_{step}", {
                        "cpu": f"{int(rng.choice([100, 250, 500]))}m",
                        "memory": "256Mi",
                    }, **kwargs)[0]
                    cluster.apply(p)
                    cluster.bind_pod(p.uid, names[rng.randint(len(names))])
                elif op == 1:  # unbind
                    bound = [p for p in cluster.pods.values() if p.node_name]
                    if bound:
                        cluster.unbind_pod(bound[rng.randint(len(bound))].uid)
                elif op == 2:  # delete a bound pod
                    bound = [p for p in cluster.pods.values() if p.node_name]
                    if bound:
                        cluster.delete(bound[rng.randint(len(bound))])
                elif op == 3:  # direct eligibility flip (defensive scan)
                    n = cluster.nodes.get(names[rng.randint(len(names))])
                    if n is not None:
                        n.cordoned = not n.cordoned
                elif op == 4:  # nodeclaim update: mark a claim deleted
                    live = [c for c in cluster.nodeclaims.values()
                            if not c.deleted]
                    if len(live) > 3:
                        c = live[rng.randint(len(live))]
                        c.finalizers = ["karpenter"]
                        cluster.delete(c)
                elif op == 5:  # add a whole node
                    zone = ("zone-a", "zone-b", "zone-c", "zone-d")[
                        rng.randint(4)]
                    node, _ = _add_node(cluster, session_catalog, next_node,
                                        zone=zone)
                    names.append(node.name)
                    next_node += 1
                else:  # remove a node object entirely
                    n = cluster.nodes.get(names[rng.randint(len(names))])
                    if n is not None:
                        cluster.delete(n)
            _assert_equal(cluster, session_catalog, f"seed{seed} step{step}")

    def test_heavy_churn_falls_back_to_full(self, session_catalog):
        """Touching most of the cluster patches nothing — the encoder must
        rebuild (and still match)."""
        from karpenter_provider_aws_tpu.metrics import ENCODE_CACHE

        cluster, nodes = _small_cluster(session_catalog, n=10)
        encode_cluster(cluster, session_catalog)
        full0 = ENCODE_CACHE.sum(path="cluster", outcome="full")
        for node in nodes[:8]:  # 80% of rows dirty > PATCH_FRAC
            p = make_pods(1, f"hc{node.name}", {"cpu": "100m"})[0]
            cluster.apply(p)
            cluster.bind_pod(p.uid, node.name)
        _assert_equal(cluster, session_catalog, "heavy churn")
        assert ENCODE_CACHE.sum(path="cluster", outcome="full") > full0


class TestOccupancyRevisionCache:
    def test_same_revision_reuses_snapshot(self, session_catalog):
        cluster, nodes = _small_cluster(session_catalog, n=4)
        occ1 = ZoneOccupancy.from_cluster(cluster)
        occ2 = ZoneOccupancy.from_cluster(cluster)
        assert occ1 is occ2

    def test_pod_change_invalidates(self, session_catalog):
        cluster, nodes = _small_cluster(session_catalog, n=4)
        occ1 = ZoneOccupancy.from_cluster(cluster)
        p = make_pods(1, "w", {"cpu": "100m"}, labels={"app": "db"})[0]
        cluster.apply(p)
        cluster.bind_pod(p.uid, nodes[0].name)
        occ2 = ZoneOccupancy.from_cluster(cluster)
        assert occ2 is not occ1
        zone = nodes[0].zone()
        assert occ2.counts({"app": "db"}).get(zone) == 1

    def test_unrelated_change_keeps_snapshot(self, session_catalog):
        cluster, nodes = _small_cluster(session_catalog, n=4)
        occ1 = ZoneOccupancy.from_cluster(cluster)
        cluster.apply(NodePool(name="other"))  # pool churn: zones unaffected
        assert ZoneOccupancy.from_cluster(cluster) is occ1

    def test_reset_store_rebuilds(self, session_catalog):
        cluster, nodes = _small_cluster(session_catalog, n=4)
        occ1 = ZoneOccupancy.from_cluster(cluster)
        cluster.__init__()
        occ2 = ZoneOccupancy.from_cluster(cluster)
        assert occ2 is not occ1
        assert occ2.counts({}) == {}

    def test_direct_node_label_mutation_invalidates(self, session_catalog):
        """A node label reassignment outside Cluster methods (no journal
        entry) must still invalidate via NODE_WRITE_SEQ — the zone is an
        occupancy input (review finding)."""
        cluster, nodes = _small_cluster(session_catalog, n=4)
        occ1 = ZoneOccupancy.from_cluster(cluster)
        nodes[0].labels = {**nodes[0].labels, lbl.TOPOLOGY_ZONE: "zone-moved"}
        occ2 = ZoneOccupancy.from_cluster(cluster)
        assert occ2 is not occ1
        assert "zone-moved" in occ2.counts({})


class TestEncodeCacheMetrics:
    def test_two_identical_reconciles_increment_hit_counter(self):
        """S5 guard: two identical disruption passes against the fake cloud
        must hit the persistent encoder, visible at /metrics — a silent
        cache regression (every pass a full re-encode) fails here."""
        from karpenter_provider_aws_tpu.metrics import REGISTRY
        from karpenter_provider_aws_tpu.testenv import new_environment

        env = new_environment(use_tpu_solver=False)
        pool, _ = env.apply_defaults()
        pool.disruption.consolidate_after_s = 60
        pool.disruption.consolidation_policy = "WhenUnderutilized"
        pool.disruption.budgets = ["0%"]  # decide-only: pass 2 must see an
        # IDENTICAL cluster, not one minus pass 1's disruptions
        for i in range(4):
            node, _ = _add_node(env.cluster, env.catalog, i)
            for p in make_pods(2, f"w{i}", {"cpu": "250m", "memory": "512Mi"}):
                env.cluster.apply(p)
                env.cluster.bind_pod(p.uid, node.name)
        env.clock.advance(120)

        def metric_value(text: str, line_prefix: str) -> float:
            for line in text.splitlines():
                if line.startswith(line_prefix):
                    return float(line.rsplit(" ", 1)[1])
            return 0.0

        prefix = ('karpenter_encode_cache_total{outcome="hit",path="cluster"}')
        port = REGISTRY.serve(0)
        try:
            env.disruption.reconcile()  # pass 1: full build
            before = metric_value(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics").read().decode(),
                prefix,
            )
            env.disruption.reconcile()  # pass 2: identical cluster -> hit
            after = metric_value(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics").read().decode(),
                prefix,
            )
        finally:
            REGISTRY.stop()
            env.close()
        assert after >= before + 1, (
            f"encode-cache hit counter did not increment ({before} -> {after})"
        )


class TestPendingPodIndex:
    """The incrementally-maintained pending-pod index must be a drop-in
    for the legacy full scan: same MEMBERSHIP and same STORE ORDER — a
    pod that goes pending late (an eviction) surfaces at its apply
    position, not appended at the index's tail. Provisioning's packing is
    order-sensitive; the 2-replica chaos envelope regressed ~570s of
    bind p99 when the index leaked accretion order."""

    def _store(self):
        cl = Cluster()
        cl.apply(Node(name="n0", capacity={}, allocatable={}))
        pods = make_pods(5, "ord", {"cpu": "100m"})
        for p in pods:
            cl.apply(p)
        return cl, pods

    def test_membership_and_store_order(self):
        cl, pods = self._store()
        assert [p.uid for p in cl.pending_pods()] == [p.uid for p in pods]
        # bind the SECOND pod, then evict it: it re-enters pendingness
        # after every other pod, but must still surface at position 1
        cl.bind_pod(pods[1].uid, "n0")
        assert [p.uid for p in cl.pending_pods()] == [
            p.uid for p in pods if p is not pods[1]
        ]
        cl.unbind_pod(pods[1].uid)
        assert [p.uid for p in cl.pending_pods()] == [p.uid for p in pods]

    def test_foreign_write_rescan_keeps_order(self):
        cl, pods = self._store()
        assert len(cl.pending_pods()) == 5
        pods[3].phase = "Succeeded"  # direct write outside the surface
        got = cl.pending_pods()      # POD_BIND_SEQ forces a full rescan
        assert [p.uid for p in got] == [
            p.uid for p in pods if p is not pods[3]
        ]

    def test_delete_and_reapply_moves_to_store_tail(self):
        cl, pods = self._store()
        cl.delete(pods[0])
        cl.apply(pods[0])  # re-applied: store position moves to the end
        assert [p.uid for p in cl.pending_pods()] == [
            p.uid for p in pods[1:] + [pods[0]]
        ]
