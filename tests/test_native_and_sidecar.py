"""Native C++ solver + gRPC sidecar: parity with the in-process solvers."""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.catalog import CatalogProvider
from karpenter_provider_aws_tpu.models import NodePool
from karpenter_provider_aws_tpu.models import labels as lbl
from karpenter_provider_aws_tpu.models.pod import make_pods, PodAffinityTerm
from karpenter_provider_aws_tpu.scheduling import HostSolver, TPUSolver
from karpenter_provider_aws_tpu.scheduling.native import NativeSolver, native_available

needs_native = pytest.mark.skipif(not native_available(), reason="no C++ toolchain")


@pytest.fixture(scope="module")
def catalog():
    return CatalogProvider()


@pytest.fixture(scope="module")
def pool():
    return NodePool(name="default")


def workload():
    pods = make_pods(80, "a", {"cpu": "500m", "memory": "1Gi"})
    pods += make_pods(25, "b", {"cpu": "2", "memory": "8Gi"})
    pods += make_pods(6, "gpu", {"cpu": "4", "nvidia.com/gpu": 1})
    pods += make_pods(5, "aa", {"cpu": "1"}, labels={"app": "web"},
                      anti_affinity=[PodAffinityTerm(topology_key=lbl.HOSTNAME,
                                                    label_selector={"app": "web"})])
    pods += make_pods(8, "zonal", {"cpu": "1"},
                      node_selector={lbl.TOPOLOGY_ZONE: "zone-b"})
    return pods


@needs_native
class TestNativeSolver:
    def test_exact_parity_with_host(self, catalog, pool):
        pods = workload()
        rn = NativeSolver().solve(pods, [pool], catalog)
        rh = HostSolver().solve(pods, [pool], catalog)
        assert rn.pods_placed() == rh.pods_placed()
        assert len(rn.node_specs) == len(rh.node_specs)
        assert sorted(s.instance_type_options[0] for s in rn.node_specs) == sorted(
            s.instance_type_options[0] for s in rh.node_specs
        )
        assert rn.total_cost == pytest.approx(rh.total_cost, rel=1e-5)

    def test_parity_with_tpu(self, catalog, pool, monkeypatch):
        # FFD-only: parity is a property of the greedy scan; the optimizer
        # lane legitimately beats it (tests/test_optimizer_lane.py)
        monkeypatch.setenv("KARPENTER_TPU_OPTIMIZER", "0")
        pods = workload()
        rn = NativeSolver().solve(pods, [pool], catalog)
        # refine=False: the native path is the plain greedy scan
        rt = TPUSolver(refine=False).solve(pods, [pool], catalog)
        assert len(rn.node_specs) == len(rt.node_specs)
        assert rn.total_cost == pytest.approx(rt.total_cost, rel=1e-4)

    def test_respects_anti_affinity(self, catalog, pool):
        pods = make_pods(4, "w", {"cpu": "1"}, labels={"app": "web"},
                         anti_affinity=[PodAffinityTerm(topology_key=lbl.HOSTNAME,
                                                        label_selector={"app": "web"})])
        res = NativeSolver().solve(pods, [pool], catalog)
        assert res.pods_placed() == 4
        assert all(len(s.pods) == 1 for s in res.node_specs)


class TestSidecar:
    @pytest.fixture(scope="class")
    def server(self):
        from karpenter_provider_aws_tpu.runtime import SolverServer

        srv = SolverServer("127.0.0.1:0")
        srv.start()
        yield srv
        srv.stop()

    @pytest.fixture(scope="class")
    def client(self, server):
        from karpenter_provider_aws_tpu.runtime import SolverClient

        c = SolverClient(f"127.0.0.1:{server.port}")
        yield c
        c.close()

    def test_health(self, client):
        assert client.health() >= 1

    def test_remote_solve_matches_local(self, catalog, pool, client, monkeypatch):
        from karpenter_provider_aws_tpu.runtime.sidecar import RemoteSolver

        # FFD-only on the local side: the sidecar wire carries the plain
        # greedy plan, which the optimizer lane legitimately undercuts
        monkeypatch.setenv("KARPENTER_TPU_OPTIMIZER", "0")
        pods = workload()
        remote = RemoteSolver(client).solve(pods, [pool], catalog)
        # refine=False: the sidecar wire carries the plain greedy plan
        local = TPUSolver(refine=False).solve(pods, [pool], catalog)
        assert remote.pods_placed() == local.pods_placed()
        assert len(remote.node_specs) == len(local.node_specs)
        assert remote.total_cost == pytest.approx(local.total_cost, rel=1e-5)

    def test_remote_consolidation_screening(self, client):
        G, N, GMAX, R = 4, 16, 4, 8
        rng = np.random.RandomState(1)
        requests = np.zeros((G, R), dtype=np.float32)
        requests[:, 0] = [500, 1000, 2000, 250]
        requests[:, 2] = 1
        free = np.zeros((N, R), dtype=np.float32)
        free[:, 0] = 4000
        free[:, 2] = 50
        gids = rng.randint(0, G, (N, GMAX)).astype(np.int32)
        gcounts = (rng.rand(N, GMAX) < 0.5).astype(np.int32)
        out = client.simulate_consolidation(
            free=free, requests=requests, group_ids=gids,
            group_counts=gcounts, compat=np.ones((G, N), dtype=bool),
            candidates=np.arange(N, dtype=np.int32),
        )
        assert out["ok"].shape == (N,)

    def test_bad_payload_is_an_rpc_error(self, client):
        import grpc

        with pytest.raises(grpc.RpcError):
            client._call("Solve", b"not an npz archive")

    def test_rpc_latency_and_errors_observable(self, catalog, pool, client):
        """SURVEY section 5 'optional gRPC tracing': server-side RPC latency
        histograms + error counters per method."""
        import grpc

        from karpenter_provider_aws_tpu.metrics import REGISTRY
        from karpenter_provider_aws_tpu.runtime.sidecar import RemoteSolver

        RemoteSolver(client).solve(
            make_pods(5, "m", {"cpu": "500m"}), [pool], catalog
        )
        with pytest.raises(grpc.RpcError):
            client._call("Solve", b"garbage")
        exposed = REGISTRY.expose()
        assert 'karpenter_sidecar_rpc_duration_seconds_count{method="Solve"}' in exposed
        err_lines = [
            l for l in exposed.splitlines()
            if l.startswith("karpenter_sidecar_rpc_errors_total{")
            and 'method="Solve"' in l
        ]
        # error-type label, same convention as the cloudprovider decorator
        assert err_lines and any('error="ValueError"' in l for l in err_lines)


class TestZeroRequestAlignment:
    """An all-zero request row (only possible via raw tensors — Pod always
    carries a pods=1 slot) must behave identically in all three solvers:
    unbounded fit clamped to 1<<30, capped by max_per_node/count."""

    def _problem(self, catalog, pool):
        from karpenter_provider_aws_tpu.models.pod import make_pods
        from karpenter_provider_aws_tpu.ops.encode import encode_problem

        pods = make_pods(3, "z", {"cpu": "0"})
        problem = encode_problem(pods, catalog, nodepool=pool)
        problem.requests[:] = 0.0  # strip even the implicit pods slot
        return problem

    def test_oracle_places_zero_request(self, catalog, pool):
        from karpenter_provider_aws_tpu.scheduling.oracle import ffd_oracle

        nodes, unplaced = ffd_oracle(self._problem(catalog, pool))
        assert not unplaced
        assert len(nodes) == 1  # all replicas fit one node

    @pytest.mark.skipif(not native_available(), reason="native build unavailable")
    def test_native_matches_oracle(self, catalog, pool):
        specs, _, unplaced = NativeSolver().solve_encoded(self._problem(catalog, pool))
        assert not unplaced
        assert len(specs) == 1
        assert len(specs[0].pods) == 3

    def test_tpu_matches_oracle(self, catalog, pool):
        specs, _, unplaced = TPUSolver().solve_encoded(self._problem(catalog, pool))
        assert not unplaced
        assert len(specs) == 1
        assert len(specs[0].pods) == 3


class TestCrossLanguageSidecarClient:
    """Round-3 VERDICT missing #4: prove the sidecar's wire contract from
    OUTSIDE Python. tools/sidecar_client.cpp speaks real gRPC (HTTP/2
    prior-knowledge + 5-byte framing + grpc-status trailers) and the npz
    tensor-bundle payload format with zero Python in the path; this test
    compiles it, round-trips Solve + SimulateConsolidation + Health against
    a live sidecar, and cross-checks the results against the in-process
    solver on the SAME tensors."""

    @pytest.fixture(scope="class")
    def client_bin(self, tmp_path_factory):
        import shutil
        import subprocess
        import sys

        if shutil.which("g++") is None:
            pytest.skip("no C++ toolchain")
        out = tmp_path_factory.mktemp("bin") / "sidecar_client"
        build = subprocess.run(
            ["g++", "-O2", "-o", str(out), "tools/sidecar_client.cpp", "-ldl", "-lz"],
            capture_output=True, text=True,
        )
        assert build.returncode == 0, build.stderr[-2000:]
        return str(out)

    @pytest.fixture(scope="class")
    def server(self):
        from karpenter_provider_aws_tpu.runtime import SolverServer

        srv = SolverServer("127.0.0.1:0")
        port = srv.start()
        yield port
        srv.stop()

    def _run(self, client_bin, mode, port):
        import json
        import subprocess

        out = subprocess.run(
            [client_bin, mode, str(port)], capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr[-1000:]
        return json.loads(out.stdout.strip())

    def test_health(self, client_bin, server):
        row = self._run(client_bin, "health", server)
        assert row["device_count"] >= 1

    def test_solve_round_trip_matches_in_process(self, client_bin, server):
        import jax.numpy as jnp

        from karpenter_provider_aws_tpu.ops.ffd import ffd_solve

        row = self._run(client_bin, "solve", server)
        # the same tensors the C++ client hard-codes, solved in-process
        res = ffd_solve(
            jnp.asarray(np.array([[1, 2], [2, 4]], np.float32)),
            jnp.asarray(np.array([5, 3], np.int32)),
            jnp.asarray(np.ones((2, 3), bool)),
            jnp.asarray(np.array([[4, 8], [8, 16], [2, 4]], np.float32)),
            jnp.asarray(np.array([[1.0, 1.8, 0.6]] * 2, np.float32)),
            jnp.asarray(np.ones((2, 1, 1), bool)),
            jnp.asarray(np.ones((3, 1, 1), bool)),
            max_per_node=jnp.asarray(np.full(2, 1 << 30, np.int32)),
            max_nodes=16,
        )
        assert row["n_open"] == int(res.n_open)
        assert row["placed"] == int(np.asarray(res.placed).sum())
        assert row["unplaced"] == int(np.asarray(res.unplaced).sum())
        assert row["node_types"] == list(
            np.asarray(res.node_type)[: int(res.n_open)]
        )

    def test_simulate_round_trip_matches_in_process(self, client_bin, server):
        import jax.numpy as jnp

        from karpenter_provider_aws_tpu.ops.consolidate import repack_check

        row = self._run(client_bin, "simulate", server)
        ok = repack_check(
            jnp.asarray(np.array([[2], [3], [3], [0]], np.float32)),
            jnp.asarray(np.array([[1], [4]], np.float32)),
            jnp.asarray(np.array([[0, 0], [0, 0], [0, 0], [1, 0]], np.int32)),
            jnp.asarray(np.array([[3, 0], [1, 0], [1, 0], [1, 0]], np.int32)),
            jnp.asarray(np.ones((2, 4), bool)),
            jnp.asarray(np.array([0, 3], np.int32)),
        )
        assert row["ok"] == [bool(x) for x in np.asarray(ok)]
