"""The trace/ flight recorder: span nesting + exception safety, disabled-
mode overhead, Chrome trace-event export, the metrics bridge, and the
per-solve provenance records (ISSUE satellite: every backend path must
stamp its results)."""

import json
import threading

import numpy as np
import pytest

from karpenter_provider_aws_tpu.catalog import CatalogProvider
from karpenter_provider_aws_tpu.models import Disruption, NodePool, Operator, Requirement
from karpenter_provider_aws_tpu.models import labels as lbl
from karpenter_provider_aws_tpu.models.pod import make_pods
from karpenter_provider_aws_tpu.scheduling import HostSolver, TPUSolver
from karpenter_provider_aws_tpu.trace import (
    TRACER,
    Tracer,
    to_chrome_trace,
    validate_chrome_trace,
    write_chrome_trace,
)
from karpenter_provider_aws_tpu.trace.provenance import (
    ProvenanceRecord,
    git_sha,
    last_record,
    stamp_row,
)


@pytest.fixture(scope="module")
def catalog():
    return CatalogProvider()


@pytest.fixture
def pool():
    return NodePool(
        name="default",
        requirements=[Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m", "r"))],
        disruption=Disruption(consolidate_after_s=None),
    )


class TestSpans:
    def test_nesting_parent_child_edges(self):
        t = Tracer(capacity=16)
        with t.span("outer") as o:
            with t.span("inner") as i:
                assert i.span.parent_id == o.span.span_id
        spans = t.snapshot()
        assert [s.name for s in spans] == ["inner", "outer"]  # finish order
        inner, outer = spans
        assert inner.parent_id == outer.span_id
        assert outer.parent_id == 0
        assert inner.dur_ns >= 0 and outer.dur_ns >= inner.dur_ns

    def test_exception_safety_pops_stack_and_marks_error(self):
        t = Tracer()
        with pytest.raises(ValueError):
            with t.span("boom"):
                raise ValueError("x")
        assert t.current() is None  # stack fully unwound
        (s,) = t.snapshot()
        assert s.attrs["error"] == "ValueError"
        # the NEXT span on this thread must be a root, not a child of the
        # raised one
        with t.span("after"):
            pass
        assert t.snapshot()[-1].parent_id == 0

    def test_annotate_hits_innermost_live_span(self):
        t = Tracer()
        with t.span("a"):
            with t.span("b"):
                t.annotate(retries=3)
        spans = {s.name: s for s in t.snapshot()}
        assert spans["b"].attrs["retries"] == 3
        assert "retries" not in spans["a"].attrs

    def test_ring_buffer_bounded(self):
        t = Tracer(capacity=4)
        for i in range(10):
            with t.span(f"s{i}"):
                pass
        names = [s.name for s in t.snapshot()]
        assert names == ["s6", "s7", "s8", "s9"]  # oldest evicted

    def test_disabled_mode_no_allocation_growth(self):
        import tracemalloc

        t = Tracer(enabled=False)
        # one shared no-op object — nothing allocated per call site
        assert t.span("x", a=1) is t.span("y", b=2)
        tracemalloc.start()
        before = tracemalloc.take_snapshot()
        for _ in range(2000):
            with t.span("hot", attr="val"):
                pass
        after = tracemalloc.take_snapshot()
        tracemalloc.stop()
        growth = sum(
            s.size_diff
            for s in after.compare_to(before, "lineno")
            if s.size_diff > 0
        )
        assert growth < 16_384, f"disabled tracer grew {growth} bytes"
        assert t.snapshot() == []

    def test_threads_get_independent_stacks(self):
        t = Tracer(capacity=64)
        errs = []

        def worker(n):
            try:
                with t.span(f"root-{n}"):
                    with t.span(f"child-{n}") as c:
                        pass
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errs
        by_name = {s.name: s for s in t.snapshot()}
        for i in range(4):
            child, root = by_name[f"child-{i}"], by_name[f"root-{i}"]
            assert child.parent_id == root.span_id

    def test_traced_decorator(self):
        t = Tracer()

        @t.traced("solve.custom")
        def fn(x):
            return x + 1

        assert fn(1) == 2
        assert t.snapshot()[-1].name == "solve.custom"

    def test_finish_callback_failures_swallowed(self):
        t = Tracer()
        t.on_finish(lambda s: 1 / 0)
        with t.span("safe"):
            pass  # must not raise
        assert t.snapshot()[-1].name == "safe"


class TestChromeExport:
    def test_round_trip_validates(self, tmp_path):
        t = Tracer()
        with t.span("solve.encode", pool="default"):
            with t.span("solve.device", rows=128):
                pass
        path = str(tmp_path / "trace.json")
        write_chrome_trace(path, tracer=t)
        with open(path) as f:
            doc = json.load(f)
        assert validate_chrome_trace(doc) == []
        events = doc["traceEvents"]
        assert {e["name"] for e in events} == {"solve.encode", "solve.device"}
        enc = next(e for e in events if e["name"] == "solve.encode")
        assert enc["ph"] == "X" and enc["dur"] >= 0
        assert enc["args"]["pool"] == "default"
        # parent linkage survives export
        dev = next(e for e in events if e["name"] == "solve.device")
        assert dev["args"]["parent_id"] == enc["args"]["span_id"]

    def test_validator_rejects_malformed(self):
        assert validate_chrome_trace("not json {{") != []
        assert validate_chrome_trace({"events": []}) != []
        bad = {"traceEvents": [{"name": "x", "ph": "X", "ts": -1, "pid": 1, "tid": 1, "dur": -5}]}
        assert validate_chrome_trace(bad) != []

    def test_2k_pod_solve_exports_valid_trace(self, catalog, pool, tmp_path):
        """Acceptance criterion: a Chrome trace export of a 2k-pod solve
        validates as trace-event JSON and carries the phase taxonomy."""
        TRACER.drain()
        pods = make_pods(2000, "web", {"cpu": "500m", "memory": "1Gi"})
        res = TPUSolver().solve(pods, [pool], catalog)
        assert res.pods_placed() == 2000
        spans = TRACER.drain()
        names = {s.name for s in spans}
        assert {"solve", "solve.encode", "solve.dispatch",
                "solve.device", "solve.decode"} <= names
        doc = to_chrome_trace(spans)
        assert validate_chrome_trace(json.dumps(doc)) == []
        assert len(doc["traceEvents"]) == len(spans)


class TestMetricsBridge:
    def test_solve_phases_reach_metrics_registry(self, catalog, pool):
        from karpenter_provider_aws_tpu.metrics import REGISTRY, SOLVE_PHASE_SECONDS

        def count(phase):
            key = tuple(sorted({"phase": phase}.items()))
            counts = SOLVE_PHASE_SECONDS._counts.get(key)
            return counts[-1] if counts else 0

        before = {p: count(p) for p in ("encode", "device", "decode")}
        pods = make_pods(32, "w", {"cpu": "1", "memory": "1Gi"})
        TPUSolver().solve(pods, [pool], catalog)
        for phase in ("encode", "device", "decode"):
            assert count(phase) > before[phase], f"phase {phase} not bridged"
        text = REGISTRY.expose()
        assert 'karpenter_solver_phase_duration_seconds_bucket{le="+Inf",phase="encode"}' in text

    def test_controller_spans_feed_reconcile_histogram(self):
        from karpenter_provider_aws_tpu.controllers.base import Manager
        from karpenter_provider_aws_tpu.metrics import RECONCILE_SECONDS

        class Dummy:
            name = "dummy-traced"
            interval_s = 1.0

            def reconcile(self):
                pass

        key = tuple(sorted({"controller": "dummy-traced"}.items()))
        before = (RECONCILE_SECONDS._counts.get(key) or [0])[-1]
        Manager([Dummy()]).reconcile_all_once()
        after = (RECONCILE_SECONDS._counts.get(key) or [0])[-1]
        assert after == before + 1

    def test_aws_spans_feed_service_histogram_and_retries(self):
        from karpenter_provider_aws_tpu.metrics import (
            AWS_REQUEST_RETRIES,
            AWS_REQUEST_SECONDS,
        )

        key = tuple(sorted({"service": "ec2"}.items()))
        before = (AWS_REQUEST_SECONDS._counts.get(key) or [0])[-1]
        retries_before = AWS_REQUEST_RETRIES.value(service="ec2")
        with TRACER.span("aws.ec2", action="DescribeImages") as sp:
            sp.set(retries=2, status=200)
        after = (AWS_REQUEST_SECONDS._counts.get(key) or [0])[-1]
        assert after == before + 1
        assert AWS_REQUEST_RETRIES.value(service="ec2") == retries_before + 2


class TestProvenance:
    def test_host_solver_stamps(self, catalog, pool):
        pods = make_pods(8, "w", {"cpu": "1", "memory": "1Gi"})
        res = HostSolver().solve(pods, [pool], catalog)
        prov = res.provenance
        assert prov is not None
        assert prov.kind == "solve"
        assert prov.backend == "host"
        assert prov.scale["pods"] == 8
        assert prov.wall_ms > 0
        assert prov.git_sha and prov.git_sha != ""
        d = prov.as_dict()
        json.dumps(d)  # JSON-ready
        assert d["schema"] == 1

    def test_tpu_solver_xla_path_stamps(self, catalog, pool):
        pods = make_pods(8, "w", {"cpu": "1", "memory": "1Gi"})
        res = TPUSolver().solve(pods, [pool], catalog)
        prov = res.provenance
        assert prov.backend == "xla-scan"  # auto resolves off-TPU
        assert prov.device in ("cpu", "tpu", "gpu")
        assert prov.device_count >= 1
        assert "encode" in prov.phases_ms and "device" in prov.phases_ms
        assert prov.fallback == ""

    def test_tpu_solver_pallas_interpret_path_stamps(self, catalog, pool, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_FFD", "pallas-interpret")
        pods = make_pods(4, "w", {"cpu": "1", "memory": "1Gi"})
        res = TPUSolver().solve(pods, [pool], catalog)
        assert res.provenance.backend == "pallas-interpret"
        assert res.pods_placed() == 4

    def test_fallback_is_named_in_backend_label(self):
        solver = TPUSolver()
        solver.timings["pallas_fallback"] = "RuntimeError: mosaic gap"
        assert solver.backend_label() == "xla-scan(pallas-fallback)"
        record = ProvenanceRecord(kind="solve", backend=solver.backend_label(),
                                  fallback=solver.timings["pallas_fallback"])
        assert "(fallback)" in record.label()

    def test_consolidation_screen_stamps_vmap_backend(self):
        from karpenter_provider_aws_tpu.ops.consolidate import (
            consolidatable,
            encode_cluster,
        )
        from karpenter_provider_aws_tpu.testenv import new_environment

        env = new_environment(use_tpu_solver=False)
        env.apply_defaults()
        for p in make_pods(3, "w", {"cpu": "1", "memory": "2Gi"}):
            env.cluster.apply(p)
        env.step(3)
        ct = encode_cluster(env.cluster, env.catalog)
        assert ct is not None
        consolidatable(ct)
        rec = last_record("consolidate.screen")
        assert rec is not None
        assert rec.kind == "consolidate.screen"
        assert rec.backend in ("vmap", "vmap-fallback", "pallas", "mesh", "native")
        assert rec.scale["nodes"] == len(ct.node_names)
        assert rec.wall_ms >= 0

    def test_stamp_row_ambient_and_explicit(self):
        row = {"benchmark": "x", "p99_ms": 1.0}
        stamp_row(row)
        assert row["provenance"]["git_sha"] == git_sha()
        assert row["provenance"]["schema"] == 1
        rec = ProvenanceRecord(kind="solve", backend="xla-scan", device="tpu")
        row2 = stamp_row({"benchmark": "y"}, provenance=rec)
        assert row2["provenance"]["backend"] == "xla-scan"
        assert row2["provenance"]["device"] == "tpu"


class TestBenchStampEnforcement:
    def _bench(self):
        import importlib.util
        import pathlib

        repo = pathlib.Path(__file__).resolve().parent.parent
        spec = importlib.util.spec_from_file_location("bench_mod", repo / "bench.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_emit_refuses_unstamped_rows(self, capsys):
        bench = self._bench()
        with pytest.raises(ValueError, match="provenance"):
            bench.emit({"metric": "p99", "value": 1.0})
        # a stamp that resolves to backend=unknown is refused too — the
        # [cpu/unknown@...] rows this retired must name a real backend
        with pytest.raises(ValueError, match="unknown backend"):
            bench.emit(bench.stamp({"metric": "p99", "value": 1.0}))
        row = bench.stamp({"metric": "p99", "value": 1.0, "backend": "host"})
        bench.emit(row)
        out = capsys.readouterr().out.strip()
        parsed = json.loads(out)
        assert parsed["provenance"]["git_sha"] == git_sha()
        assert parsed["provenance"]["backend"] == "host"
