"""Chaos subsystem: seeded fault injection, scenario timelines, invariant
checking — plus the satellite regressions that ride with it (interruption
poison-message isolation, batcher close semantics, Retry-After honoring,
ICE-cache locking/gauge).

The four canned scenarios each run end to end (fast: stepped FakeClock,
host solver); the determinism contract — same seed, byte-identical fault
sequence — is asserted directly, which is the acceptance gate
``python -m karpenter_provider_aws_tpu.chaos --scenario spot-storm
--seed 7`` enforces from the CLI.
"""

import json
import pathlib
import random
import threading
import time

import pytest

from karpenter_provider_aws_tpu.chaos import (
    ChaosTransport,
    ConnectionDrop,
    DeviceLost,
    EventualConsistencyLag,
    Ice,
    InjectedLatency,
    Scenario,
    ServerError,
    SpotInterrupt,
    StubAwsTransport,
    Throttle,
    canned,
    fault_from_dict,
    inject_spot_interruptions,
    install_consistency_lag,
    list_canned,
    run_deterministic,
    run_scenario,
    spot_interruption_message,
    uninstall_consistency_lag,
)
from karpenter_provider_aws_tpu.chaos.faults import synthesize_error_body
from karpenter_provider_aws_tpu.providers.aws import (
    AwsApiError,
    Credentials,
    Ec2Client,
    ReplayTransport,
    Session,
)
from karpenter_provider_aws_tpu.providers.aws.session import _parse_error
from karpenter_provider_aws_tpu.providers.aws.transport import (
    AwsRequest,
    AwsResponse,
)
from karpenter_provider_aws_tpu.utils.clock import FakeClock

GOLDEN = pathlib.Path(__file__).resolve().parent / "golden" / "aws"


def chaos_session(transport, **kw):
    return Session(
        region="us-east-1",
        credentials=Credentials("AKIDEXAMPLE", "secret"),
        transport=transport,
        sleep=kw.pop("sleep", lambda s: None),
        now_amz=lambda: "20260804T000000Z",
        rand=kw.pop("rand", lambda: 0.0),
        **kw,
    )


# ---------------------------------------------------------------------------
# fault primitives
# ---------------------------------------------------------------------------

class TestFaultPrimitives:
    def test_match_predicates_service_action_glob(self):
        f = Throttle(service="ec2", action="Describe*")
        assert f.matches("ec2", "DescribeInstances")
        assert not f.matches("ec2", "CreateFleet")
        assert not f.matches("sqs", "DescribeInstances")

    def test_match_window(self):
        f = Throttle(start_s=10.0, end_s=20.0)
        assert not f.matches("ec2", "X", now=9.9)
        assert f.matches("ec2", "X", now=10.0)
        assert not f.matches("ec2", "X", now=20.0)

    def test_count_limits_fires(self):
        f = Throttle(count=2)
        rng = random.Random(0)
        assert f.should_fire(rng)
        f.fires = 2
        assert not f.should_fire(rng)

    def test_probability_draws_are_seeded(self):
        draws = [
            [Throttle(probability=0.5).should_fire(random.Random(7))
             for _ in range(20)]
            for _ in range(2)
        ]
        assert draws[0] == draws[1]

    def test_dict_round_trip(self):
        for f in (
            Throttle(service="ec2", probability=0.4, retry_after_s=1.5),
            ServerError(code="ServiceUnavailable", status=503),
            ConnectionDrop(action="CreateFleet"),
            InjectedLatency(delay_s=0.5),
            Ice(capacity_types=("spot",)),
            SpotInterrupt(fraction=0.5, terminate=False),
            EventualConsistencyLag(lag_s=30.0),
            DeviceLost(backends=("xla-scan", "pallas")),
        ):
            clone = fault_from_dict(json.loads(json.dumps(f.to_dict())))
            assert clone == f, f.kind

    def test_unknown_kind_and_field_rejected(self):
        with pytest.raises(ValueError):
            fault_from_dict({"kind": "Nope"})
        with pytest.raises(ValueError):
            fault_from_dict({"kind": "Throttle", "bogus": 1})

    def test_error_bodies_parse_like_real_aws(self):
        """Synthesized bodies must round-trip through _parse_error into
        the exact codes the retryer classifies on — all three protocol
        shapes."""
        ec2_req = AwsRequest("POST", "https://ec2.us-east-1.amazonaws.com/",
                             service="ec2")
        body = synthesize_error_body(ec2_req, "RequestLimitExceeded", "slow")
        err = _parse_error("ec2", AwsResponse(400, body))
        assert err.code == "RequestLimitExceeded"

        sqs_req = AwsRequest("POST", "https://sqs.us-east-1.amazonaws.com/",
                             service="sqs")
        body = synthesize_error_body(sqs_req, "ServiceUnavailable", "down")
        assert _parse_error("sqs", AwsResponse(503, body)).code == "ServiceUnavailable"

        json_req = AwsRequest(
            "POST", "https://api.pricing.us-east-1.amazonaws.com/",
            headers={"x-amz-target": "AWSPriceListService.GetProducts",
                     "content-type": "application/x-amz-json-1.1"},
            service="pricing",
        )
        body = synthesize_error_body(json_req, "ThrottlingException", "slow")
        assert _parse_error("pricing", AwsResponse(400, body)).code == "ThrottlingException"


# ---------------------------------------------------------------------------
# the chaos transport at the wire seam
# ---------------------------------------------------------------------------

class TestChaosTransport:
    def test_throttle_drives_session_retrying_end_to_end(self):
        clock = FakeClock()
        ct = ChaosTransport(StubAwsTransport(), clock=clock)
        ct.add_fault(Throttle(count=2))
        session = chaos_session(ct)
        Ec2Client(session).describe_availability_zones()  # no raise: retried
        assert len(ct.log) == 2
        assert [r.kind for r in ct.log.records] == ["Throttle", "Throttle"]
        assert ct.log.records[0].action == "DescribeAvailabilityZones"

    def test_connection_drop_is_retryable(self):
        ct = ChaosTransport(StubAwsTransport(), clock=FakeClock())
        ct.add_fault(ConnectionDrop(count=1))
        Ec2Client(chaos_session(ct)).describe_availability_zones()
        assert ct.log.records[0].kind == "ConnectionDrop"

    def test_latency_advances_fake_clock_and_passes_through(self):
        clock = FakeClock()
        ct = ChaosTransport(StubAwsTransport(), clock=clock)
        ct.add_fault(InjectedLatency(delay_s=2.5, count=1))
        Ec2Client(chaos_session(ct)).describe_availability_zones()
        assert clock.now() == 2.5  # virtual cost only

    def test_exhausted_retries_surface_the_real_error(self):
        ct = ChaosTransport(StubAwsTransport(), clock=FakeClock())
        ct.add_fault(ServerError(code="ServiceUnavailable", status=503))
        with pytest.raises(AwsApiError) as e:
            Ec2Client(chaos_session(ct)).describe_availability_zones()
        assert e.value.code == "ServiceUnavailable"

    def test_injection_metric_counts_by_kind(self):
        from karpenter_provider_aws_tpu.metrics import CHAOS_FAULTS_INJECTED

        before = CHAOS_FAULTS_INJECTED.value(kind="Throttle")
        ct = ChaosTransport(StubAwsTransport(), clock=FakeClock())
        ct.add_fault(Throttle(count=1))
        Ec2Client(chaos_session(ct)).describe_availability_zones()
        assert CHAOS_FAULTS_INJECTED.value(kind="Throttle") == before + 1

    def test_chaos_fault_annotated_on_request_span(self):
        from karpenter_provider_aws_tpu.trace import TRACER

        ct = ChaosTransport(StubAwsTransport(), clock=FakeClock())
        ct.add_fault(Throttle(count=1))
        Ec2Client(chaos_session(ct)).describe_availability_zones()
        aws_spans = [s for s in TRACER.snapshot() if s.name == "aws.ec2"]
        assert aws_spans and aws_spans[-1].attrs.get("chaos_fault") == "Throttle"
        assert aws_spans[-1].attrs.get("retries", 0) >= 1

    def test_composes_with_replay_transport(self):
        """ChaosTransport over ReplayTransport: the fault answers first,
        the golden contract replay still verifies the retried request."""
        replay = ReplayTransport.from_file(GOLDEN / "throttle_retry_success.json")
        # the fixture itself contains the throttle exchanges; wrap it and
        # add a latency fault to prove pass-through composition
        clock = FakeClock()
        ct = ChaosTransport(replay, clock=clock)
        ct.add_fault(InjectedLatency(delay_s=1.0))
        zones = Ec2Client(chaos_session(ct)).describe_availability_zones()
        assert zones and zones[0]["zoneName"] == "us-east-1a"
        replay.assert_drained()
        assert clock.now() == 3.0  # one virtual second per wire attempt


# ---------------------------------------------------------------------------
# session retry satellites: Retry-After + per-class reasons
# ---------------------------------------------------------------------------

class TestRetryAfterAndReasons:
    def test_golden_throttle_retry_success_honors_retry_after(self):
        """The shipped golden fixture: throttle (Retry-After: 1.2) ->
        503 -> success. The first backoff is the server's number, the
        second is full-jitter (rand=0 -> 0)."""
        sleeps = []
        transport = ReplayTransport.from_file(GOLDEN / "throttle_retry_success.json")
        session = chaos_session(transport, sleep=sleeps.append)
        zones = Ec2Client(session).describe_availability_zones()
        assert [z["zoneName"] for z in zones] == ["us-east-1a"]
        assert sleeps == [1.2, 0.0]
        transport.assert_drained()

    def test_retry_after_clamped_to_cap(self):
        calls = []
        sleeps = []

        def transport(req):
            calls.append(1)
            if len(calls) == 1:
                return AwsResponse(
                    400,
                    b"<Response><Errors><Error><Code>RequestLimitExceeded"
                    b"</Code><Message>x</Message></Error></Errors></Response>",
                    headers={"Retry-After": "120"},
                )
            return AwsResponse(200, b"<DescribeAvailabilityZonesResponse/>")

        Ec2Client(chaos_session(transport, sleep=sleeps.append)).describe_availability_zones()
        assert sleeps == [5.0]  # hostile header clamped to the 5s cap

    def test_retry_reason_classes_tagged_and_counted(self):
        from karpenter_provider_aws_tpu.metrics import AWS_REQUEST_RETRY_REASONS
        from karpenter_provider_aws_tpu.trace import TRACER

        before = {
            r: AWS_REQUEST_RETRY_REASONS.value(service="ec2", reason=r)
            for r in ("throttle", "server", "connection")
        }
        replies = [
            AwsResponse(400, b"<Response><Errors><Error><Code>RequestLimitExceeded"
                             b"</Code><Message>x</Message></Error></Errors></Response>"),
            AwsResponse(503, b"<Response><Errors><Error><Code>InternalError"
                             b"</Code><Message>x</Message></Error></Errors></Response>"),
            None,  # sentinel: raise a connection error
            AwsResponse(200, b"<DescribeAvailabilityZonesResponse/>"),
        ]

        def transport(req):
            reply = replies.pop(0)
            if reply is None:
                raise AwsApiError(599, "ConnectionError", "reset by chaos")
            return reply

        Ec2Client(chaos_session(transport)).describe_availability_zones()
        for r in ("throttle", "server", "connection"):
            assert AWS_REQUEST_RETRY_REASONS.value(service="ec2", reason=r) == before[r] + 1
        span = [s for s in TRACER.snapshot() if s.name == "aws.ec2"][-1]
        assert span.attrs["retries"] == 3
        assert span.attrs["retry_reason"] == "connection"  # last class wins


# ---------------------------------------------------------------------------
# cloud/queue hooks
# ---------------------------------------------------------------------------

class TestCloudHooks:
    def test_spot_message_parses_as_interruption(self):
        from karpenter_provider_aws_tpu.controllers.interruption import parse_message

        ev = parse_message(spot_interruption_message("i-0abc"))
        assert ev.kind == "SpotInterruption"
        assert ev.instance_ids == ("i-0abc",)
        assert ev.action_drain

    def test_inject_spot_interruptions_deterministic_sample(self):
        from karpenter_provider_aws_tpu.fake import FakeCloud, FakeQueue
        from karpenter_provider_aws_tpu.fake.cloud import Instance

        cloud = FakeCloud()
        for i in range(6):
            inst = Instance(id=f"i-{i:04d}", instance_type="m5.large",
                            zone="zone-a", capacity_type="spot" if i < 4 else "on-demand",
                            image_id="img-std-2")
            cloud.instances[inst.id] = inst
        picks = [
            inject_spot_interruptions(FakeQueue(), cloud, fraction=0.5,
                                      rng=random.Random(3))
            for _ in range(2)
        ]
        assert picks[0] == picks[1]
        assert len(picks[0]) == 2
        assert all(cloud.instances[i].capacity_type == "spot" for i in picks[0])

    def test_consistency_lag_hides_then_reveals(self):
        from karpenter_provider_aws_tpu.fake import FakeCloud
        from karpenter_provider_aws_tpu.fake.cloud import Instance

        clock = FakeClock(start=100.0)
        cloud = FakeCloud(clock=clock)
        inst = Instance(id="i-new", instance_type="m5.large", zone="zone-a",
                        capacity_type="spot", image_id="img-std-2",
                        launch_time=clock.now())
        cloud.instances[inst.id] = inst
        install_consistency_lag(cloud, lag_s=45.0)
        assert cloud.list_instances() == []
        assert cloud.describe_instances(["i-new"]) == []
        clock.advance(46.0)
        assert [i.id for i in cloud.list_instances()] == ["i-new"]
        uninstall_consistency_lag(cloud)
        clock.advance(-46.0)  # rewound: the unwrapped reads see it anyway
        assert [i.id for i in cloud.list_instances()] == ["i-new"]


# ---------------------------------------------------------------------------
# scenario plans
# ---------------------------------------------------------------------------

class TestScenarioPlans:
    def test_canned_scenarios_ship(self):
        assert list_canned() == [
            "api-brownout", "eventual-consistency", "optimizer-lane-lost",
            "provisioning-replica-loss", "replica-loss", "solver-brownout",
            "spot-price-spike", "spot-storm", "sts-outage",
        ]

    def test_scenario_json_round_trip(self):
        for name in list_canned():
            sc = canned(name)
            clone = Scenario.from_json(sc.to_json())
            assert clone == sc, name

    def test_timeline_is_sorted_on_load(self):
        sc = Scenario.from_dict({
            "name": "x",
            "timeline": [
                {"at_s": 30, "fault": {"kind": "Throttle"}},
                {"at_s": 10, "fault": {"kind": "ServerError"}},
            ],
        })
        assert [t.at_s for t in sc.timeline] == [10, 30]


# ---------------------------------------------------------------------------
# the four canned scenarios, end to end (fast: stepped clock, host solver)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def reports():
    return {name: run_scenario(name, seed=7) for name in list_canned()}


class TestCannedScenarios:
    def test_all_invariants_pass(self, reports):
        for name, report in reports.items():
            assert report.passed, f"{name}:\n{report.summary()}"

    def test_spot_storm_drained_and_relaunched(self, reports):
        r = reports["spot-storm"]
        assert r.faults_by_kind.get("SpotInterrupt", 0) >= 2
        # warnings were received AND deleted (queue-drained invariant
        # already asserts depth 0; this pins that traffic existed)
        assert any("warned i#" in line for line in r.signature.splitlines())

    def test_api_brownout_drives_session_retrying(self, reports):
        """Acceptance: retry-count spans > 0, no controller crash, no
        leaked instance."""
        r = reports["api-brownout"]
        assert r.retry_attempts > 0
        assert r.faults_by_kind.get("Throttle", 0) > 0
        by_name = {i.name: i for i in r.invariants}
        assert by_name["controllers-healthy"].passed
        assert by_name["no-leaked-instances"].passed

    def test_sts_outage_fails_closed_then_recovers(self, reports):
        r = reports["sts-outage"]
        assert r.probe_failures > 0               # the outage bit
        assert r.probe_failures < r.probe_calls   # ...and recovery happened
        assert r.faults_by_kind.get("CredentialExpiry", 0) >= 1

    def test_eventual_consistency_no_false_reaps(self, reports):
        r = reports["eventual-consistency"]
        assert r.nodes_launched >= 1
        by_name = {i.name: i for i in r.invariants}
        assert by_name["no-leaked-instances"].passed
        assert by_name["pods-bound-once"].passed

    def test_same_seed_byte_identical_fault_sequence(self):
        """The acceptance gate: two same-seed runs, identical sequences
        (run_deterministic raises on divergence)."""
        a, b = run_deterministic("spot-storm", seed=7, runs=2)
        assert a.signature == b.signature
        assert len(a.signature) > 0

    def test_different_seed_diverges_brownout(self, reports):
        """Sanity that the seed MEANS something: a different seed shifts
        the probabilistic brownout sequence."""
        other = run_scenario("api-brownout", seed=8)
        assert other.signature != reports["api-brownout"].signature

    def test_report_dict_is_json_ready(self, reports):
        doc = json.loads(json.dumps(reports["spot-storm"].as_dict()))
        assert doc["scenario"] == "spot-storm"
        assert doc["passed"] is True
        assert {i["name"] for i in doc["invariants"]} >= {
            "pods-bound-once", "converged", "no-leaked-instances",
            "ice-mask-expired", "queue-drained", "controllers-healthy",
        }

    def test_solver_brownout_binds_via_host_while_breakers_open(self, reports):
        """Acceptance (ISSUE 5 capstone): DeviceLost kills every device
        dispatch; the first failures are served host-side in-pass, the
        breaker opens, later waves ride the degraded path, and ALL pods
        still bind (converged + pods-bound-once already assert binding;
        this pins the degradation behavior)."""
        r = reports["solver-brownout"]
        assert r.passed, r.summary()
        # fewer DeviceLost fires than solve-bearing waves under the fault:
        # once the breaker opens the device path is not even attempted
        assert r.faults_by_kind.get("DeviceLost", 0) >= 3
        by_name = {i.name: i for i in r.invariants}
        assert by_name["breakers-recovered"].passed
        assert by_name["controllers-healthy"].passed

    def test_solver_brownout_breaker_full_cycle_and_audit(self):
        """The breaker walks closed -> open -> half-open -> open (probe
        under fire) -> half-open -> closed (recovery wave), provisioning
        writes degraded audit records + Warning events, and solve
        provenance carries the breaker fallback."""
        from karpenter_provider_aws_tpu.chaos import ChaosHarness
        from karpenter_provider_aws_tpu.resilience import breakers

        h = ChaosHarness("solver-brownout", seed=7)
        report = h.run()
        assert report.passed, report.summary()
        br = breakers.get("solver.xla-scan")
        assert br.state == "closed"
        transitions = [to for _, to in br.history]
        assert transitions == [
            "open", "half-open", "open", "half-open", "closed",
        ]
        recs = h.env.obs.audit.query(kind="resilience")
        assert recs, "expected degraded-mode audit records"
        assert {r.decision for r in recs} == {"degraded:host-ffd"}
        fallbacks = {r.detail["fallback"] for r in recs}
        assert "breaker:solver.xla-scan" in fallbacks  # open-breaker passes
        assert any("DeviceLostError" in f for f in fallbacks)  # failing passes
        events = h.env.events.query(kind="Solver", name="provisioning")
        assert any(e.reason == "DegradedProvisioning" for e in events)

    def test_solver_brownout_same_seed_byte_identical(self):
        a, b = run_deterministic("solver-brownout", seed=3, runs=2)
        assert a.signature == b.signature
        assert "DeviceLost" in a.signature

    def test_solve_provenance_stamped_with_chaos_context(self):
        """Solves that happen under active faults carry the scenario in
        their provenance context forever."""
        from karpenter_provider_aws_tpu.trace.provenance import last_record

        run_scenario("api-brownout", seed=11)
        rec = last_record("solve")
        assert rec is not None
        assert rec.context.get("chaos_scenario") == "api-brownout"
        assert rec.context.get("chaos_seed") == 11
        assert "context" in rec.as_dict()


# ---------------------------------------------------------------------------
# satellite regressions
# ---------------------------------------------------------------------------

class TestInterruptionPoisonMessage:
    def test_poison_message_counted_deleted_and_batch_continues(self):
        """A handler raising mid-message (recorder.publish here) must not
        abort the batch or leave the message for eternal redelivery."""
        from karpenter_provider_aws_tpu.metrics import (
            INTERRUPTION_MESSAGE_ERRORS,
        )
        from karpenter_provider_aws_tpu.models import NodePool
        from karpenter_provider_aws_tpu.models.pod import make_pods
        from karpenter_provider_aws_tpu.testenv import new_environment

        env = new_environment(use_tpu_solver=False)
        try:
            env.apply_defaults(NodePool(name="default"))
            for p in make_pods(2, "w", {"cpu": "1", "memory": "2Gi"}):
                env.cluster.apply(p)
            env.step(3)
            iids = sorted(env.cloud.instances)
            assert len(iids) >= 1

            class PoisonRecorder:
                def publish(self, *a, **kw):
                    raise RuntimeError("poisoned recorder")

            env.interruption.recorder = PoisonRecorder()
            before = INTERRUPTION_MESSAGE_ERRORS.value(kind="SpotInterruption")
            env.queue.send(json.dumps(spot_interruption_message(iids[0])))
            env.queue.send(json.dumps({"source": "aws.ec2",
                                       "detail-type": "EC2 Instance Rebalance Recommendation",
                                       "detail": {"instance-id": iids[-1]}}))
            env.interruption.reconcile()
            # both messages deleted despite the poisoned handler
            assert len(env.queue) == 0
            assert env.queue.deleted_count == 2
            assert INTERRUPTION_MESSAGE_ERRORS.value(kind="SpotInterruption") == before + 1
            # both messages were parsed and recorded before the poison hit
            assert {e.kind for e in env.interruption.handled} >= {"SpotInterruption"}
        finally:
            env.close()


class TestBatcherClose:
    def test_close_flushes_pending_bucket_and_cancels_timers(self):
        from karpenter_provider_aws_tpu.utils.batcher import (
            Batcher,
            BatcherOptions,
        )

        b = Batcher(
            executor=lambda reqs: [r * 2 for r in reqs],
            options=BatcherOptions(idle_timeout_s=60.0, max_timeout_s=120.0),
        )
        results = {}
        t = threading.Thread(target=lambda: results.update(v=b.add(21)))
        t.start()
        for _ in range(200):  # wait for the add() to arm its timer
            with b._lock:
                if b._buckets:
                    break
            time.sleep(0.005)
        t0 = time.monotonic()
        b.close()
        t.join(timeout=10.0)
        assert not t.is_alive(), "pending add() hung through close()"
        assert results["v"] == 42
        assert time.monotonic() - t0 < 30.0  # not the 4xmax+30s watchdog
        assert b._timers == {}
        with pytest.raises(RuntimeError):
            b.add(1)


class TestUnavailableEntriesAndGauge:
    def test_entries_under_lock_and_gauge_tracks_live_set(self, clock):
        from karpenter_provider_aws_tpu.metrics import ICE_CACHE_SIZE
        from karpenter_provider_aws_tpu.utils import UnavailableOfferings

        u = UnavailableOfferings(clock=clock)
        u.mark_unavailable("m5.large", "zone-a", "spot")
        u.mark_unavailable("c5.large", "zone-b", "on-demand")
        assert ICE_CACHE_SIZE.value() == 2.0
        assert sorted(u.entries()) == [
            ("on-demand", "c5.large", "zone-b"),
            ("spot", "m5.large", "zone-a"),
        ]
        clock.advance(181.0)  # TTL lapses silently inside TTLCache
        assert u.entries() == []
        assert ICE_CACHE_SIZE.value() == 0.0

    def test_concurrent_entries_and_marks_do_not_tear(self, clock):
        from karpenter_provider_aws_tpu.utils import UnavailableOfferings

        u = UnavailableOfferings(clock=clock)
        stop = threading.Event()
        errors = []

        def reader():
            while not stop.is_set():
                try:
                    for e in u.entries():
                        assert len(e) == 3
                except Exception as exc:  # pragma: no cover
                    errors.append(exc)

        threads = [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        for i in range(300):
            u.mark_unavailable(f"t{i % 7}.large", f"zone-{i % 3}", "spot")
            if i % 5 == 0:
                u.flush()
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        assert not errors


# ---------------------------------------------------------------------------
# sharded control plane: the replica-loss scenario (PR 9 tentpole proof)
# ---------------------------------------------------------------------------

class TestReplicaLossScenario:
    def test_invariants_and_fencing(self, reports):
        r = reports["replica-loss"]
        assert r.passed, r.summary()
        by_name = {i.name: i for i in r.invariants}
        # the three sharded-lease invariants ran FOR REAL (not the
        # single-replica n/a skip) and passed
        for name in ("no-double-launch", "no-orphaned-claims",
                     "leases-partition-the-fleet"):
            assert by_name[name].passed, by_name[name]
            assert "n/a" not in by_name[name].detail
        assert r.faults_by_kind.get("ReplicaCrash", 0) >= 1
        assert r.faults_by_kind.get("ReplicaPause", 0) >= 1
        assert r.faults_by_kind.get("ReplicaNetsplit", 0) >= 1

    def test_single_replica_scenarios_skip_lease_invariants(self, reports):
        by_name = {i.name: i for i in reports["spot-storm"].invariants}
        assert by_name["no-double-launch"].passed
        assert "n/a" in by_name["no-double-launch"].detail

    def test_replica_faults_require_multi_replica_scenario(self):
        """A Replica* fault dropped into a single-replica scenario must
        fail LOUDLY at activation, not silently no-op."""
        from karpenter_provider_aws_tpu.chaos.faults import ReplicaCrash

        class FakeHarness:
            env = object()  # a plain Environment: no crash/restart seams

        with pytest.raises(ValueError, match="replicas"):
            ReplicaCrash(replica=0).on_activate(FakeHarness())

    def test_replica_loss_same_seed_byte_identical(self):
        """Seeded chaos e2e for lease adoption (PR 9 satellite): the
        crash -> adoption -> re-registration sequence is byte-identical
        per seed (run_deterministic raises on divergence)."""
        a, b = run_deterministic("replica-loss", seed=3, runs=2)
        assert a.signature == b.signature
        assert a.passed, a.summary()
