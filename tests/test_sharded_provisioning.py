"""Sharded provisioning: partition-owned pending pods, the work-stealing
GLOBAL queue, and no-double-launch under replica loss.

The PR 12 tentpole contract (designs/sharded-provisioning.md): pods whose
required constraints pin them to an owned (nodepool, zone) partition are
solved locally by that partition's lease holder; truly global pods flow
through a fenced, exactly-once work-stealing queue on the lease host; and
the union of per-replica outcomes equals the single-replica outcome —
no pod solved twice, no capacity launched twice, packing/cost inside the
single-replica envelope.
"""

from __future__ import annotations

import pytest

from karpenter_provider_aws_tpu.fake import FakeCloud
from karpenter_provider_aws_tpu.models import (
    Disruption,
    NodePool,
    Operator,
    Requirement,
)
from karpenter_provider_aws_tpu.models import labels as lbl
from karpenter_provider_aws_tpu.models.pod import make_pods
from karpenter_provider_aws_tpu.operator import sharding
from karpenter_provider_aws_tpu.operator.sharding import (
    GLOBAL_KEY,
    WORK_QUEUE,
    Ownership,
    lease_name,
    pod_partition,
    split_pending,
    steal_fence,
)
from karpenter_provider_aws_tpu.state.cluster import Node
from karpenter_provider_aws_tpu.testenv import new_environment, new_replicaset
from karpenter_provider_aws_tpu.utils.clock import FakeClock
from karpenter_provider_aws_tpu.utils.errors import StaleFencingTokenError


def _pool(name="default"):
    return NodePool(name=name, disruption=Disruption(consolidate_after_s=None))


def _seed_node(cluster, zone, pool="default"):
    cluster.apply(Node(
        name=f"seed-{pool}-{zone}", nodepool_name=pool,
        labels={lbl.TOPOLOGY_ZONE: zone}, ready=True,
    ))


# ---------------------------------------------------------------------------
# pod -> partition routing
# ---------------------------------------------------------------------------

class TestPodPartition:
    def test_unpinned_pod_is_global(self):
        (p,) = make_pods(1, "w", {"cpu": "1", "memory": "1Gi"})
        assert pod_partition(p, [_pool()]) is None

    def test_zone_selector_with_single_pool_pins(self):
        (p,) = make_pods(1, "w", {"cpu": "1", "memory": "1Gi"},
                         node_selector={lbl.TOPOLOGY_ZONE: "zone-b"})
        assert pod_partition(p, [_pool()]) == ("default", "zone-b")

    def test_zone_selector_with_many_pools_needs_pool_pin(self):
        (p,) = make_pods(1, "w", {"cpu": "1", "memory": "1Gi"},
                         node_selector={lbl.TOPOLOGY_ZONE: "zone-b"})
        pools = [_pool("a"), _pool("b")]
        assert pod_partition(p, pools) is None
        (q,) = make_pods(1, "w2", {"cpu": "1", "memory": "1Gi"},
                         node_selector={lbl.TOPOLOGY_ZONE: "zone-b",
                                        lbl.NODEPOOL: "b"})
        assert pod_partition(q, pools) == ("b", "zone-b")

    def test_required_affinity_single_zone_pins(self):
        (p,) = make_pods(
            1, "w", {"cpu": "1", "memory": "1Gi"},
            node_affinity=[Requirement(lbl.TOPOLOGY_ZONE, Operator.IN,
                                       ("zone-c",))],
        )
        assert pod_partition(p, [_pool()]) == ("default", "zone-c")

    def test_multi_zone_affinity_is_global(self):
        (p,) = make_pods(
            1, "w", {"cpu": "1", "memory": "1Gi"},
            node_affinity=[Requirement(lbl.TOPOLOGY_ZONE, Operator.IN,
                                       ("zone-a", "zone-b"))],
        )
        assert pod_partition(p, [_pool()]) is None

    def test_routing_matches_owns_key(self):
        """The split agrees pod-by-pod with the owns_key predicate the
        rest of the control plane filters through."""
        own = Ownership(replica="r0", keys={("default", "zone-a"): 3})
        object.__setattr__(own, "_known", frozenset(
            [GLOBAL_KEY, ("default", "zone-a"), ("default", "zone-b")]
        ))
        pools = [_pool()]
        pinned_a = make_pods(2, "a", {"cpu": "1", "memory": "1Gi"},
                             node_selector={lbl.TOPOLOGY_ZONE: "zone-a"})
        pinned_b = make_pods(2, "b", {"cpu": "1", "memory": "1Gi"},
                             node_selector={lbl.TOPOLOGY_ZONE: "zone-b"})
        pinned_new = make_pods(1, "n", {"cpu": "1", "memory": "1Gi"},
                               node_selector={lbl.TOPOLOGY_ZONE: "zone-new"})
        free = make_pods(2, "g", {"cpu": "1", "memory": "1Gi"})
        local, global_pods, foreign = split_pending(
            pinned_a + pinned_b + pinned_new + free, pools, own
        )
        assert {p.name for p in local[("default", "zone-a")]} == {"a-0", "a-1"}
        assert {p.name for p in foreign} == {"b-0", "b-1"}
        # unpinned AND pinned-to-unleased-partition pods are GLOBAL work —
        # exactly the owns_key fall-through
        assert {p.name for p in global_pods} == {"g-0", "g-1", "n-0"}
        with sharding.scope(own):
            for p in pinned_a:
                assert sharding.owns_key(pod_partition(p, pools))
            for p in pinned_b:
                assert not sharding.owns_key(pod_partition(p, pools))

    def test_steal_fence_prefers_global_then_stable_partition(self):
        own = Ownership(replica="r0", keys={
            GLOBAL_KEY: 7, ("default", "zone-a"): 3,
        })
        key, fence = steal_fence(own)
        assert key == GLOBAL_KEY and fence == (lease_name(GLOBAL_KEY), 7)
        own2 = Ownership(replica="r0", keys={
            ("default", "zone-b"): 5, ("default", "zone-a"): 3,
        })
        key2, fence2 = steal_fence(own2)
        assert key2 == ("default", "zone-a")  # lease-name order: stable
        assert fence2 == (lease_name(("default", "zone-a")), 3)
        assert steal_fence(Ownership(replica="r0", keys={})) is None


# ---------------------------------------------------------------------------
# the fenced work-claim table (the queue on the lease host)
# ---------------------------------------------------------------------------

class TestWorkQueue:
    def _cloud(self):
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        name = lease_name(GLOBAL_KEY)
        _, token, _ = cloud.try_acquire_lease_fenced(name, "a", 15.0, nonce="n")
        return clock, cloud, (name, token)

    def test_steal_once_under_concurrent_holders(self):
        clock, cloud, fence = self._cloud()
        name2 = lease_name(("default", "zone-a"))
        _, t2, _ = cloud.try_acquire_lease_fenced(name2, "b", 15.0, nonce="m")
        fence_b = (name2, t2)
        got_a = cloud.try_claim_work(WORK_QUEUE, ["p1", "p2"], "a", 15.0, fence)
        got_b = cloud.try_claim_work(WORK_QUEUE, ["p1", "p2", "p3"], "b", 15.0,
                                     fence_b)
        assert got_a == ["p1", "p2"]
        assert got_b == ["p3"]  # live claims are never silently stolen
        # the owner renews its own claims
        clock.advance(10)
        cloud.try_acquire_lease_fenced(lease_name(GLOBAL_KEY), "a", 15.0,
                                       nonce="n")
        assert cloud.try_claim_work(
            WORK_QUEUE, ["p1"], "a", 15.0, fence) == ["p1"]

    def test_expired_claim_is_re_stealable(self):
        clock, cloud, fence = self._cloud()
        cloud.try_claim_work(WORK_QUEUE, ["p1"], "a", 15.0, fence)
        name2 = lease_name(("default", "zone-a"))
        _, t2, _ = cloud.try_acquire_lease_fenced(name2, "b", 60.0, nonce="m")
        clock.advance(16)  # a's claim (and lease) expire: a died
        got = cloud.try_claim_work(WORK_QUEUE, ["p1"], "b", 15.0, (name2, t2))
        assert got == ["p1"]
        assert cloud.list_work_claims(WORK_QUEUE)["p1"][0] == "b"

    def test_stale_fence_cannot_claim(self):
        clock, cloud, fence = self._cloud()
        name, token = fence
        clock.advance(16)
        cloud.try_acquire_lease_fenced(name, "b", 15.0, nonce="m")  # deposes a
        with pytest.raises(StaleFencingTokenError):
            cloud.try_claim_work(WORK_QUEUE, ["p1"], "a", 15.0, (name, token))
        assert cloud.list_work_claims(WORK_QUEUE) == {}
        assert cloud.fenced_rejections

    def test_release_only_drops_own_claims(self):
        clock, cloud, fence = self._cloud()
        cloud.try_claim_work(WORK_QUEUE, ["p1"], "a", 15.0, fence)
        cloud.release_work(WORK_QUEUE, ["p1"], "not-a")
        assert cloud.list_work_claims(WORK_QUEUE)["p1"][0] == "a"
        cloud.release_work(WORK_QUEUE, ["p1"], "a")
        assert cloud.list_work_claims(WORK_QUEUE) == {}


# ---------------------------------------------------------------------------
# the sharded provisioner over a ReplicaSet
# ---------------------------------------------------------------------------

class TestShardedProvisioning:
    def test_pinned_pods_launch_under_their_partition_lease(self):
        rs = new_replicaset(3)
        try:
            rs.apply_defaults(_pool())
            for z in ("zone-a", "zone-b"):
                _seed_node(rs.cluster, z)
            rs.step(2)
            for z in ("zone-a", "zone-b"):
                for p in make_pods(2, f"pin-{z}", {"cpu": "1", "memory": "2Gi"},
                                   node_selector={lbl.TOPOLOGY_ZONE: z}):
                    rs.cluster.apply(p)
            for _ in range(8):
                rs.step(1)
                rs.clock.advance(1)
            assert not rs.cluster.pending_pods()
            with rs.cloud._lock:
                instances = list(rs.cloud.instances.values())
            assert instances
            by_lease = {i.launch_fence[0] for i in instances}
            # every launch sanctioned by the PARTITION lease of the zone
            # it serves, not the GLOBAL lease
            assert by_lease <= {
                lease_name(("default", "zone-a")),
                lease_name(("default", "zone-b")),
            }
            assert rs.lease_overlaps == []
        finally:
            rs.close()

    def test_global_pods_claimed_then_launched_under_global_lease(self):
        rs = new_replicaset(2)
        try:
            rs.apply_defaults(_pool())
            _seed_node(rs.cluster, "zone-a")
            rs.step(2)
            for p in make_pods(3, "glob", {"cpu": "1", "memory": "2Gi"}):
                rs.cluster.apply(p)
            rs.step(1)
            claims = rs.work_claims()
            assert len(claims) == 3
            holders = {owner for owner, _exp in claims.values()}
            assert len(holders) == 1  # one claimant: the GLOBAL holder
            for _ in range(6):
                rs.step(1)
                rs.clock.advance(1)
            with rs.cloud._lock:
                fences = {
                    i.launch_fence[0] for i in rs.cloud.instances.values()
                }
            assert lease_name(GLOBAL_KEY) in fences
        finally:
            rs.close()

    def test_partition_holder_steals_when_global_holder_dead(self):
        """The work-stealing edge, pinned deterministically: the GLOBAL
        lease is expired (holder crashed) and a surviving partition
        holder's provisioner must claim the queue with ITS OWN lease
        token and launch — before any elector rendezvous hands GLOBAL
        over."""
        from karpenter_provider_aws_tpu.metrics import PROVISIONING_STEALS

        rs = new_replicaset(2)
        try:
            rs.apply_defaults(_pool())
            _seed_node(rs.cluster, "zone-a")
            rs.step(2)
            holder = next(r for r in rs.replicas
                          if GLOBAL_KEY in r.elector.ownership().keys)
            survivor = next(r for r in rs.replicas if r is not holder)
            rs.crash(rs.replicas.index(holder))
            rs.clock.advance(16)  # every lease (incl. GLOBAL) expires
            for p in make_pods(2, "steal", {"cpu": "1", "memory": "2Gi"}):
                rs.cluster.apply(p)
            # the survivor re-acquires ONLY its partition lease (its
            # elector's rendezvous pass hasn't run yet — exactly the
            # pre-rendezvous window work stealing exists for)
            key = ("default", "zone-a")
            _, tok, _ = rs.cloud.try_acquire_lease_fenced(
                lease_name(key), survivor.identity, 15.0,
                nonce=survivor.elector._nonce,
            )
            own = Ownership(replica=survivor.identity, keys={key: tok})
            object.__setattr__(own, "_known", frozenset([GLOBAL_KEY, key]))
            assert GLOBAL_KEY not in own.keys and own.keys
            before = PROVISIONING_STEALS.value(outcome="stolen")
            with sharding.scope(own):
                survivor.provisioning.reconcile()
            assert PROVISIONING_STEALS.value(outcome="stolen") - before >= 2
            claims = rs.work_claims()
            assert {o for o, _ in claims.values()} == {survivor.identity}
            with rs.cloud._lock:
                fences = {
                    i.launch_fence[0] for i in rs.cloud.instances.values()
                }
            # the steal's launches carry the SURVIVOR'S partition lease
            key = sorted(own.keys, key=lease_name)[0]
            assert fences == {lease_name(key)}
        finally:
            rs.close()

    def test_netsplit_replica_claims_nothing(self):
        rs = new_replicaset(2)
        try:
            rs.apply_defaults(_pool())
            rs.step(2)
            holder = next(r for r in rs.replicas
                          if GLOBAL_KEY in r.elector.ownership().keys)
            rs.netsplit(rs.replicas.index(holder))
            for p in make_pods(2, "cut", {"cpu": "1", "memory": "2Gi"}):
                rs.cluster.apply(p)
            own = holder.elector.ownership()  # snapshot still live pre-deadline
            with sharding.scope(own):
                holder.provisioning.reconcile()
            # cut off from the lease host: no work claimed, no launches
            assert rs.work_claims() == {}
            with rs.cloud._lock:
                assert not rs.cloud.instances
        finally:
            rs.close()

    def test_deposed_replica_claim_is_fenced_out(self):
        from karpenter_provider_aws_tpu.metrics import PROVISIONING_STEALS

        rs = new_replicaset(2)
        try:
            rs.apply_defaults(_pool())
            rs.step(2)
            holder = next(r for r in rs.replicas
                          if GLOBAL_KEY in r.elector.ownership().keys)
            stale_own = holder.elector.ownership()
            # depose: a contender takes the GLOBAL tenancy (token bumps)
            rs.clock.advance(16)
            rs.cloud.try_acquire_lease_fenced(
                lease_name(GLOBAL_KEY), "intruder", 60.0, nonce="x")
            for p in make_pods(2, "late", {"cpu": "1", "memory": "2Gi"}):
                rs.cluster.apply(p)
            before = PROVISIONING_STEALS.value(outcome="fenced")
            with sharding.scope(stale_own):
                holder.provisioning.reconcile()
            assert PROVISIONING_STEALS.value(outcome="fenced") > before
            assert rs.work_claims() == {}  # the stale claim bounced
            with rs.cloud._lock:
                assert not rs.cloud.instances
        finally:
            rs.close()

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_bind_set_union_equals_single_replica(self, seed):
        """Property (3 seeds): the union of per-replica handled sets —
        pods bound or nominated, by name — equals the single-replica
        run's, order-insensitive, with no pod handled by two replicas."""
        import random

        rng = random.Random(seed)
        zones = ("zone-a", "zone-b", "zone-c")

        def workload():
            pods = []
            for z in zones:
                pods += make_pods(
                    rng.randint(1, 3), f"s{seed}-pin-{z}",
                    {"cpu": "1", "memory": "2Gi"},
                    node_selector={lbl.TOPOLOGY_ZONE: z},
                )
            pods += make_pods(rng.randint(2, 4), f"s{seed}-glob",
                              {"cpu": "1", "memory": "2Gi"})
            return pods

        def drive(env, is_rs):
            env.apply_defaults(_pool())
            for z in zones:
                _seed_node(env.cluster, z)
            env.step(2)
            for p in workload():
                env.cluster.apply(p)
            for _ in range(10):
                env.step(1)
                env.clock.advance(1)
            bound = sorted(
                p.name for p in env.cluster.pods.values()
                if p.name.startswith(f"s{seed}-") and p.node_name
            )
            if is_rs:
                # no pod nominated by two replicas (exactly-once claim)
                seen: dict = {}
                for r in env.replicas:
                    for uid in r.provisioning.nominations:
                        assert uid not in seen, uid
                        seen[uid] = r.identity
            return bound

        # seeded RNG is consumed identically for both runs
        rng = random.Random(seed)
        rs = new_replicaset(3)
        try:
            multi = drive(rs, True)
            assert rs.lease_overlaps == []
        finally:
            rs.close()
        rng = random.Random(seed)
        env = new_environment(use_tpu_solver=False)
        try:
            single = drive(env, False)
        finally:
            env.close()
        assert multi == single
        assert len(multi) == len(set(multi))  # no duplicates


# ---------------------------------------------------------------------------
# the packing-envelope-parity invariant
# ---------------------------------------------------------------------------

class TestPackingEnvelopeInvariant:
    def _harness(self, envelope):
        class _H:
            pass

        h = _H()
        h.env = new_replicaset(2)
        h.envelope = envelope
        return h

    def test_within_envelope_passes(self):
        from karpenter_provider_aws_tpu.chaos.invariants import (
            check_packing_envelope_parity,
        )

        h = self._harness({"packing_ratio": 0.95, "cost_ratio": 1.05})
        try:
            assert check_packing_envelope_parity(h).passed
        finally:
            h.env.close()

    def test_packing_below_envelope_fails(self):
        from karpenter_provider_aws_tpu.chaos.invariants import (
            check_packing_envelope_parity,
        )

        h = self._harness({"packing_ratio": 0.85, "cost_ratio": 1.0})
        try:
            r = check_packing_envelope_parity(h)
            assert not r.passed and "packing" in r.detail
        finally:
            h.env.close()

    def test_cost_above_envelope_fails(self):
        from karpenter_provider_aws_tpu.chaos.invariants import (
            check_packing_envelope_parity,
        )

        h = self._harness({"packing_ratio": 1.0, "cost_ratio": 1.2})
        try:
            r = check_packing_envelope_parity(h)
            assert not r.passed and "cost" in r.detail
        finally:
            h.env.close()

    def test_missing_reference_self_skips(self):
        from karpenter_provider_aws_tpu.chaos.invariants import (
            check_packing_envelope_parity,
        )

        h = self._harness(None)
        try:
            r = check_packing_envelope_parity(h)
            assert r.passed and "n/a" in r.detail
        finally:
            h.env.close()
