"""Launch-template + bootstrap + version-provider behavior.

Parity targets: launchtemplate.go (hash naming, dedupe cache, hydration,
LT-not-found retry, termination cleanup), amifamily/bootstrap (per-family
userdata, kubelet args, MIME merge), version.go (cached version + support
window), and the metrics decorator (main.go:44).
"""

import pytest

from karpenter_provider_aws_tpu.models import NodePool
from karpenter_provider_aws_tpu.models.nodeclass import (
    KubeletConfiguration,
    NodeClass,
)
from karpenter_provider_aws_tpu.models.pod import make_pods
from karpenter_provider_aws_tpu.providers.bootstrap import (
    ClusterInfo,
    bootstrapper_for,
    mime_merge,
)
from karpenter_provider_aws_tpu.testenv import new_environment


@pytest.fixture
def env():
    e = new_environment(use_tpu_solver=False)
    e.apply_defaults()
    return e


class TestBootstrap:
    info = ClusterInfo(name="prod", endpoint="https://api.prod", ca_bundle="Q0E=", dns_ip="10.0.0.10")

    def test_shell_family_kubelet_args(self):
        kc = KubeletConfiguration(
            max_pods=58,
            cluster_dns=("10.0.0.10",),
            system_reserved=(("cpu", "100m"),),
            eviction_hard=(("memory.available", "100Mi"),),
        )
        script = bootstrapper_for(
            "standard", self.info, kubelet=kc, labels={"team": "ml"}
        ).script()
        assert script.startswith("#!/bin/bash")
        assert "--max-pods=58" in script
        assert "--cluster-dns=10.0.0.10" in script
        assert "--system-reserved=cpu=100m" in script
        assert "--eviction-hard=memory.available=100Mi" in script
        assert "--node-labels=team=ml" in script
        assert "'prod'" in script and "https://api.prod" in script

    def test_custom_userdata_mime_merged_first(self):
        script = bootstrapper_for(
            "standard", self.info, custom="#!/bin/bash\necho pre-bootstrap"
        ).script()
        assert "multipart/mixed" in script
        # the user part must come before the generated bootstrap call
        assert script.index("pre-bootstrap") < script.index("/etc/node/bootstrap.sh")

    def test_toml_family(self):
        tomllib = pytest.importorskip(
            "tomllib", reason="needs Python >= 3.11 (stdlib TOML parser)"
        )

        from karpenter_provider_aws_tpu.models.nodepool import Taint

        script = bootstrapper_for(
            "bottlerocket", self.info,
            kubelet=KubeletConfiguration(max_pods=29),
            labels={"a": "b"},
            taints=[Taint(key="gpu", value="true", effect="NoSchedule")],
        ).script()
        parsed = tomllib.loads(script)  # must be valid TOML
        k8s = parsed["settings"]["kubernetes"]
        assert k8s["cluster-name"] == "prod"
        assert k8s["max-pods"] == 29
        assert k8s["node-taints"]["gpu"] == "true:NoSchedule"
        assert k8s["node-labels"]["a"] == "b"

    def test_toml_custom_merged_generated_wins(self):
        tomllib = pytest.importorskip(
            "tomllib", reason="needs Python >= 3.11 (stdlib TOML parser)"
        )

        custom = '[settings.kubernetes]\nmax-pods = 20\nextra = "kept"\n[settings.host]\nhostname = "h"\n'
        script = bootstrapper_for(
            "bottlerocket", self.info,
            kubelet=KubeletConfiguration(max_pods=29),
            custom=custom,
        ).script()
        parsed = tomllib.loads(script)  # duplicate tables would raise here
        k8s = parsed["settings"]["kubernetes"]
        assert k8s["max-pods"] == 29          # generated wins
        assert k8s["extra"] == "kept"         # custom keys survive
        assert parsed["settings"]["host"]["hostname"] == "h"

    def test_toml_invalid_custom_raises(self):
        # the producer parses custom userdata with the stdlib TOML parser
        pytest.importorskip(
            "tomllib", reason="needs Python >= 3.11 (stdlib TOML parser)"
        )
        with pytest.raises(ValueError, match="not valid TOML"):
            bootstrapper_for("bottlerocket", self.info, custom="not = [toml").script()

    def test_nodeadm_family_yaml(self):
        script = bootstrapper_for("nodeadm", self.info,
                                  kubelet=KubeletConfiguration(max_pods=10)).script()
        assert 'kind: "NodeConfig"' in script
        assert "apiServerEndpoint" in script
        assert "--max-pods=10" in script

    def test_nodeadm_carries_service_cidr(self):
        info = ClusterInfo(name="prod", endpoint="https://api", ca_bundle="Q0E=",
                           service_cidr="10.100.0.0/16")
        script = bootstrapper_for("nodeadm", info).script()
        assert 'cidr: "10.100.0.0/16"' in script

    def test_custom_family_verbatim(self):
        script = bootstrapper_for("custom", self.info, custom="my-exact-script").script()
        assert script == "my-exact-script"

    def test_mime_merge_shape(self):
        doc = mime_merge(["#!/bin/sh\na", "plain"])
        assert doc.count("--//KARPENTER-TPU-BOUNDARY//") == 3  # 2 parts + terminator
        assert "text/x-shellscript" in doc and "text/plain" in doc


class TestLaunchTemplates:
    def _provision(self, env, n=3):
        for p in make_pods(n, "w", {"cpu": "1", "memory": "2Gi"}):
            env.cluster.apply(p)
        env.step(3)

    def test_launch_creates_template_and_instances_reference_it(self, env):
        self._provision(env)
        lts = env.cloud.describe_launch_templates()
        assert len(lts) >= 1
        assert lts[0].name.startswith("karpenter.tpu/cluster-1/")
        assert lts[0].user_data  # bootstrap script rendered
        # launched requests carried the template
        reqs = [r for batch in env.cloud.calls["create_fleet"] for r in batch]
        assert all(r.launch_template_name for r in reqs)

    def test_detailed_monitoring_reaches_template(self, env):
        """parity: launchtemplate.go:255-257 Monitoring.Enabled follows
        nodeclass.spec.detailedMonitoring (default off)."""
        nc = env.cluster.nodeclasses["default"]
        nc.detailed_monitoring = True
        env.cloudprovider.launch_templates._cache.flush()
        self._provision(env)
        lts = env.cloud.describe_launch_templates()
        assert lts and all(lt.detailed_monitoring for lt in lts)

    def test_public_ip_disabled_only_when_all_subnets_private(self, env):
        """parity: subnet.go:119-130 AssociatePublicIPAddressValue — the
        template pins associatePublicIP=False iff every resolved subnet is
        known private; any public subnet leaves the cloud default (None)."""
        for s in env.cloud.subnets:
            s.public = False
        self._provision(env)
        lts = env.cloud.describe_launch_templates()
        assert all(lt.associate_public_ip is False for lt in lts)
        # a public subnet flips the inference back to "leave default" and
        # the changed parameter mints a NEW template hash
        env.cloud.subnets[0].public = True
        env.cloudprovider.subnets.reset()
        env.cloudprovider.launch_templates._cache.flush()
        self._provision(env, n=2)
        lts2 = env.cloud.describe_launch_templates()
        assert any(lt.associate_public_ip is None for lt in lts2)

    def test_gc_requeue_backs_off_after_20_clean_passes(self, env):
        """parity: garbagecollection/controller.go:84 — 10s requeue for the
        first 20 successful passes, 2m steady-state after."""
        assert env.garbagecollection.interval_s == 10.0
        for _ in range(20):
            env.garbagecollection.reconcile()
        assert env.garbagecollection.interval_s == 10.0
        env.garbagecollection.reconcile()
        assert env.garbagecollection.interval_s == 120.0

    def test_template_deduped_across_launches(self, env):
        self._provision(env, n=2)
        created_1 = len(env.cloud.calls.get("create_launch_template", []))
        self._provision(env, n=2)
        created_2 = len(env.cloud.calls.get("create_launch_template", []))
        assert created_1 == created_2 == 1  # same resolved params -> one LT

    def test_lt_not_found_single_retry(self, env):
        """Deleting the LT behind the provider's back triggers exactly one
        re-ensure + retry (parity: instance.go:106-110)."""
        self._provision(env, n=1)
        name = env.cloud.describe_launch_templates()[0].name
        env.cloud.delete_launch_template(name)
        self._provision(env, n=1)
        assert len(env.cloud.describe_launch_templates()) == 1
        # every pod got a node eventually
        assert not env.cluster.pending_pods()

    def test_nodeclass_termination_deletes_templates(self, env):
        self._provision(env)
        assert env.cloud.describe_launch_templates()
        # drain claims then delete the nodeclass
        for claim in list(env.cluster.nodeclaims.values()):
            env.cluster.finalize(claim)
            env.cluster.delete(claim)
        nc = next(iter(env.cluster.nodeclasses.values()))
        nc.deleted = True
        env.step(2)
        assert env.cloud.describe_launch_templates() == []

    def test_hydration_warms_cache_from_cloud(self, env):
        """A pre-existing managed template is adopted, not re-created."""
        from karpenter_provider_aws_tpu.providers.launchtemplates import (
            MANAGED_BY_TAG,
            LaunchTemplateProvider,
        )

        self._provision(env, n=1)
        existing = env.cloud.describe_launch_templates()[0]
        assert existing.tags.get(MANAGED_BY_TAG) == "cluster-1"
        fresh = LaunchTemplateProvider(env.cloud, ClusterInfo(name="cluster-1"))
        fresh._hydrate_once()
        assert fresh._cache.get(("lt", existing.name)) is not None


class TestVersionProvider:
    def test_cached_version_and_support_window(self, env):
        from karpenter_provider_aws_tpu.providers.version import VersionProvider

        env.cluster.server_version = "1.29"
        vp = VersionProvider(env.cluster)
        assert vp.get() == "1.29"
        assert vp.minor() == 29
        assert vp.supported()
        env.cluster.server_version = "1.99"
        assert vp.get() == "1.29"  # cached
        vp.reset()
        assert vp.get() == "1.99"
        assert not vp.supported()


class TestMetricsDecorator:
    def test_methods_observed_and_errors_counted(self, env):
        from karpenter_provider_aws_tpu.cloudprovider.decorator import (
            METHOD_DURATION,
            METHOD_ERRORS,
            decorate,
        )

        cp = decorate(env.cloudprovider)
        before = METHOD_DURATION._counts.get((("method", "get_instance_types"),))
        before_n = before[-1] if before else 0
        cp.get_instance_types(None)
        after = METHOD_DURATION._counts[(("method", "get_instance_types"),)]
        assert after[-1] == before_n + 1
        # errors are labeled by method + exception type
        err_before = METHOD_ERRORS.value(method="get", error="NotFoundError")
        with pytest.raises(Exception):
            cp.get("bogus-id")
        assert METHOD_ERRORS.value(method="get", error="NotFoundError") == err_before + 1

    def test_non_decorated_attrs_proxy_through(self, env):
        from karpenter_provider_aws_tpu.cloudprovider.decorator import decorate

        cp = decorate(env.cloudprovider)
        assert cp.catalog is env.cloudprovider.catalog
        assert cp.launch_templates is env.cloudprovider.launch_templates


class TestKubeletThreading:
    def test_nodepool_kubelet_reaches_userdata(self, env):
        pool = next(iter(env.cluster.nodepools.values()))
        pool.kubelet = KubeletConfiguration(max_pods=42)
        for p in make_pods(1, "w", {"cpu": "1", "memory": "2Gi"}):
            env.cluster.apply(p)
        env.step(3)
        lts = env.cloud.describe_launch_templates()
        assert any("--max-pods=42" in t.user_data for t in lts)


class TestCustomFamilyLaunch:
    def test_custom_family_userdata_verbatim_in_template(self, env):
        """nodeclass.image_family='custom' must ship user_data verbatim even
        though the resolved image has its own family."""
        nc = next(iter(env.cluster.nodeclasses.values()))
        nc.image_family = "custom"
        nc.user_data = "my-exact-bootstrap"
        # custom family without selector terms resolves no images by family
        # name; select the standard images explicitly
        from karpenter_provider_aws_tpu.models.nodeclass import SelectorTerm
        nc.image_selector = [SelectorTerm.of(name="standard-v2"),
                             SelectorTerm.of(name="standard-arm-v2")]
        env.cloudprovider.reset_caches()
        env.step(1)  # re-resolve nodeclass status
        for p in make_pods(1, "w", {"cpu": "1", "memory": "2Gi"}):
            env.cluster.apply(p)
        env.step(3)
        lts = env.cloud.describe_launch_templates()
        assert lts and all(t.user_data == "my-exact-bootstrap" for t in lts)


class TestPublicIPOverrideAndContext:
    """associatePublicIPAddress as a SPEC field (ec2nodeclass.go:45-47 —
    the user's setting wins over subnet inference) and the reserved EC2
    launch context pass-through (instance.go:220)."""

    def _provision(self, env, n=2):
        for p in make_pods(n, "w", {"cpu": "1", "memory": "2Gi"}):
            env.cluster.apply(p)
        env.step(3)

    def test_explicit_public_ip_wins_over_private_subnets(self, env):
        for s in env.cloud.subnets:
            s.public = False          # inference alone would pin False
        nc = env.cluster.nodeclasses["default"]
        nc.associate_public_ip = True
        env.cloudprovider.launch_templates._cache.flush()
        self._provision(env)
        lts = env.cloud.describe_launch_templates()
        assert lts and all(lt.associate_public_ip is True for lt in lts)

    def test_context_reaches_fleet_request(self, env):
        nc = env.cluster.nodeclasses["default"]
        nc.context = "ctx-outpost-1"
        self._provision(env)
        reqs = [r for batch in env.cloud.calls["create_fleet"] for r in batch]
        assert reqs and all(r.context == "ctx-outpost-1" for r in reqs)
