"""Disruption + interruption behavior (reference: designs/consolidation.md,
pkg/controllers/interruption suite, scale deprovisioning suites)."""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.models import Disruption, NodePool, Operator, Requirement
from karpenter_provider_aws_tpu.models import labels as lbl
from karpenter_provider_aws_tpu.models.pod import make_pods
from karpenter_provider_aws_tpu.testenv import new_environment


@pytest.fixture(scope="module")
def env():
    return new_environment()


@pytest.fixture(autouse=True)
def _reset(env):
    env.reset()
    yield


def pool_with(max_cpu=None, **disruption_kwargs):
    disruption_kwargs.setdefault("budgets", ["100%"])
    disruption_kwargs.setdefault("consolidate_after_s", None)
    reqs = [Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m", "r"))]
    if max_cpu is not None:
        # the real catalog carries 192-448 vCPU giants; tests asserting
        # multi-node plans pin the node size so pods cannot all land on one
        reqs.append(Requirement(lbl.INSTANCE_CPU, Operator.LT, (str(max_cpu),)))
    return NodePool(
        name="default",
        requirements=reqs,
        disruption=Disruption(**disruption_kwargs),
    )


def provision(env, pods):
    for p in pods:
        env.cluster.apply(p)
    env.step(3)
    assert not env.cluster.pending_pods()


class TestTermination:
    def test_claim_delete_drains_and_terminates(self, env):
        env.apply_defaults(pool_with())
        pods = make_pods(5, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        claim = next(
            c for c in env.cluster.nodeclaims.values()
            if env.cluster.pods_on_node(c.status.node_name)
        )
        provider_id = claim.status.provider_id
        drained = env.cluster.pods_on_node(claim.status.node_name)
        assert drained
        env.cluster.delete(claim)
        env.termination.reconcile()
        # pods evicted back to pending, instance gone, claim finalized
        assert claim.name not in env.cluster.nodeclaims
        with pytest.raises(Exception):
            env.cloudprovider.get(provider_id)
        assert all(p.is_pending() for p in drained)

    def test_drained_pods_reprovisioned(self, env):
        env.apply_defaults(pool_with())
        pods = make_pods(5, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        claim = next(iter(env.cluster.nodeclaims.values()))
        env.cluster.delete(claim)
        env.step(4)
        assert not env.cluster.pending_pods()
        assert len(env.cluster.nodes) >= 1


class TestScheduler:
    def test_pending_pod_lands_on_existing_free_node(self, env):
        env.apply_defaults(pool_with())
        # a 6cpu pod lands on an 8-vcpu-class node, leaving headroom
        provision(env, make_pods(1, "big", {"cpu": "6", "memory": "6Gi"}))
        n_nodes = len(env.cluster.nodes)
        extra = make_pods(2, "extra", {"cpu": "500m", "memory": "1Gi"})
        for p in extra:
            env.cluster.apply(p)
        env.scheduling.reconcile()
        assert all(not p.is_pending() for p in extra)
        assert len(env.cluster.nodes) == n_nodes  # no new nodes

    def test_scheduler_respects_taints_and_labels(self, env):
        from karpenter_provider_aws_tpu.models import Taint

        env.apply_defaults(pool_with())
        provision(env, make_pods(2, "w", {"cpu": "1", "memory": "2Gi"}))
        for node in env.cluster.nodes.values():
            node.taints = [Taint(key="quarantine", effect="NoSchedule")]
        p = make_pods(1, "x", {"cpu": "100m"})[0]
        env.cluster.apply(p)
        env.scheduling.reconcile()
        assert p.is_pending()  # not tolerated -> not bound


class TestEmptiness:
    def test_empty_node_deleted_after_consolidate_after(self, env):
        env.apply_defaults(pool_with(consolidation_policy="WhenEmpty", consolidate_after_s=30))
        pods = make_pods(3, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        for p in pods:  # all pods finish
            env.cluster.delete(p)
        env.disruption.reconcile()
        assert not any(c.deleted for c in env.cluster.nodeclaims.values())  # too soon
        env.clock.advance(31)
        env.disruption.reconcile()
        assert all(c.deleted for c in env.cluster.nodeclaims.values())


class TestExpiration:
    def test_expired_claims_disrupted(self, env):
        env.apply_defaults(pool_with(expire_after_s=3600, consolidate_after_s=None))
        provision(env, make_pods(3, "w", {"cpu": "1", "memory": "2Gi"}))
        env.disruption.reconcile()
        assert not any(c.deleted for c in env.cluster.nodeclaims.values())
        env.clock.advance(3601)
        env.disruption.reconcile()
        assert all(c.deleted for c in env.cluster.nodeclaims.values())


class TestDriftDisruption:
    def test_static_drift_triggers_disruption(self, env):
        env.apply_defaults(pool_with(consolidate_after_s=None))
        provision(env, make_pods(2, "w", {"cpu": "1", "memory": "2Gi"}))
        env.cluster.nodeclasses["default"].user_data = "changed"
        env.disruption.reconcile()
        assert any("drifted" in r for _, r in env.disruption.disrupted)

    def test_nodepool_template_drift_triggers_disruption(self, env):
        """Editing the pool TEMPLATE (labels/taints/requirements) drifts
        claims stamped from the old template (core NodePool static drift);
        non-template knobs (weight, budgets) must not."""
        pool, _ = env.apply_defaults(pool_with(consolidate_after_s=None))
        provision(env, make_pods(2, "w", {"cpu": "1", "memory": "2Gi"}))
        pool.weight = 7  # decision-steering field: NOT drift
        env.disruption.reconcile()
        assert not any("NodePool" in r for _, r in env.disruption.disrupted)
        pool.labels = {"team": "b"}  # template field: drift
        env.disruption.reconcile()
        assert any("NodePoolHashDrifted" in r for _, r in env.disruption.disrupted)


class TestValidationWindow:
    @pytest.fixture(autouse=True)
    def _window(self, env):
        env.disruption.validation_period_s = 15.0
        yield
        env.disruption.validation_period_s = 0.0

    def _thin_out(self, env, pods):
        """Delete most pods but keep one per stretch, so every node retains
        a pod — emptiness (which has no validation window) must not fire."""
        for i, p in enumerate(pods):
            if i % 8 != 0:
                env.cluster.delete(p)

    def test_candidate_must_persist_before_commit(self, env):
        """Core consolidation validation: a node must stay consolidatable
        across the validation window before any delete commits — a
        transient dip never kills a node on first sight."""
        env.apply_defaults(pool_with(max_cpu=17, consolidate_after_s=10))
        pods = make_pods(30, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        self._thin_out(env, pods)
        env.clock.advance(61)
        env.disruption.reconcile()  # first sight: starts the window
        assert not any(
            r.startswith("consolidatable") for _, r in env.disruption.disrupted
        )
        env.clock.advance(16)
        env.disruption.reconcile()  # window passed: commits
        assert any(
            r.startswith("consolidatable") for _, r in env.disruption.disrupted
        )

    def test_flapping_candidate_restarts_window(self, env):
        env.apply_defaults(pool_with(max_cpu=17, consolidate_after_s=10))
        pods = make_pods(30, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        self._thin_out(env, pods)
        env.clock.advance(61)
        env.disruption.reconcile()  # window starts
        # load returns: candidates vanish, first-seen entries prune
        refill = make_pods(26, "w2", {"cpu": "1", "memory": "2Gi"})
        provision(env, refill)
        env.clock.advance(16)
        env.disruption.reconcile()
        assert not any(
            r.startswith("consolidatable") for _, r in env.disruption.disrupted
        )


class TestBudgets:
    def test_budget_caps_disruptions_per_pass(self, env):
        pool = pool_with(max_cpu=100, expire_after_s=60, consolidate_after_s=None)
        pool.disruption.budgets = ["1"]
        env.apply_defaults(pool)
        # several nodes: one pod each, big enough that each pod needs its own node
        provision(env, make_pods(4, "w", {"cpu": "60", "memory": "120Gi"}))
        assert len(env.cluster.nodeclaims) >= 3
        env.clock.advance(61)
        env.disruption.reconcile()
        assert sum(1 for c in env.cluster.nodeclaims.values() if c.deleted) == 1


class TestConsolidation:
    def test_underutilized_nodes_consolidated(self, env):
        # consolidate only after a quiet window, so provisioning settles first
        env.apply_defaults(pool_with(max_cpu=17, consolidate_after_s=60))
        pods = make_pods(30, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        # most pods finish: the remaining few should repack onto fewer nodes
        for p in pods[4:]:
            env.cluster.delete(p)
        n_before = len(env.cluster.nodes)
        assert n_before >= 2
        env.clock.advance(61)
        env.disruption.reconcile()
        env.step(4)  # drain, rebind onto survivors, settle
        assert not env.cluster.pending_pods()
        assert len(env.cluster.nodes) < n_before
        # cost must not have increased: survivors hold all remaining pods
        assert sum(len(env.cluster.pods_on_node(n)) for n in env.cluster.nodes) == 4

    def test_replace_with_cheaper_single_node(self, env):
        env.apply_defaults(pool_with(consolidate_after_s=60))
        # 3cpu pods pack onto big nodes (best cost-per-slot); shrinking the
        # demand to 2 pods leaves one nearly-empty big node whose pods fit a
        # far cheaper type -> single-node replace
        pods = make_pods(20, "w", {"cpu": "3", "memory": "6Gi"})
        provision(env, pods)
        keep = env.cluster.pods_on_node(
            next(iter(env.cluster.nodes.values())).name
        )[:2]
        for p in pods:
            if p.uid not in {k.uid for k in keep}:
                env.cluster.delete(p)
        price_before = sum(
            env.catalog.pricing.on_demand_price(env.catalog.get(n.instance_type()))
            for n in env.cluster.nodes.values()
        )
        env.clock.advance(61)
        env.disruption.reconcile()
        env.step(4)
        assert not env.cluster.pending_pods()
        price_after = sum(
            env.catalog.pricing.on_demand_price(env.catalog.get(n.instance_type()))
            for n in env.cluster.nodes.values()
        )
        assert price_after < price_before
        assert any("replace" in r or "delete" in r for _, r in env.disruption.disrupted)

    def test_do_not_disrupt_respected(self, env):
        env.apply_defaults(pool_with(max_cpu=17, consolidate_after_s=60))
        pods = make_pods(
            2, "w", {"cpu": "1", "memory": "2Gi"},
            annotations={lbl.ANNOTATION_DO_NOT_DISRUPT: "true"},
        )
        provision(env, pods)
        env.clock.advance(61)
        env.disruption.reconcile()
        assert not any(c.deleted for c in env.cluster.nodeclaims.values())


class TestInterruption:
    def _spot_claim(self, env):
        env.apply_defaults(pool_with(consolidate_after_s=None))
        provision(env, make_pods(3, "w", {"cpu": "1", "memory": "2Gi"}))
        for claim in env.cluster.nodeclaims.values():
            if claim.labels.get(lbl.CAPACITY_TYPE) == "spot":
                return claim
        return next(iter(env.cluster.nodeclaims.values()))

    def test_spot_interruption_drains_and_masks(self, env):
        claim = self._spot_claim(env)
        iid = claim.status.provider_id.rsplit("/", 1)[-1]
        env.queue.send({
            "source": "aws.ec2",
            "detail-type": "EC2 Spot Instance Interruption Warning",
            "detail": {"instance-id": iid},
        })
        env.interruption.reconcile()
        assert claim.deleted
        itype = claim.labels[lbl.INSTANCE_TYPE_LABEL]
        zone = claim.labels[lbl.TOPOLOGY_ZONE]
        assert env.catalog.unavailable.is_unavailable(itype, zone, "spot")
        assert len(env.queue) == 0

    def test_rebalance_is_no_action(self, env):
        claim = self._spot_claim(env)
        iid = claim.status.provider_id.rsplit("/", 1)[-1]
        env.queue.send({
            "source": "aws.ec2",
            "detail-type": "EC2 Instance Rebalance Recommendation",
            "detail": {"instance-id": iid},
        })
        env.interruption.reconcile()
        assert not claim.deleted
        assert len(env.queue) == 0

    def test_state_change_terminated_drains(self, env):
        claim = self._spot_claim(env)
        iid = claim.status.provider_id.rsplit("/", 1)[-1]
        env.queue.send({
            "source": "aws.ec2",
            "detail-type": "EC2 Instance State-change Notification",
            "detail": {"instance-id": iid, "state": "shutting-down"},
        })
        env.interruption.reconcile()
        assert claim.deleted

    def test_health_event_drains(self, env):
        claim = self._spot_claim(env)
        iid = claim.status.provider_id.rsplit("/", 1)[-1]
        env.queue.send({
            "source": "aws.health",
            "detail-type": "AWS Health Event",
            "detail": {"affectedEntities": [{"entityValue": iid}]},
        })
        env.interruption.reconcile()
        assert claim.deleted

    def test_unparseable_message_deleted(self, env):
        env.apply_defaults(pool_with())
        env.queue.send({"source": "junk", "detail-type": "garbage"})
        env.queue.send("not even json {{{")
        env.interruption.reconcile()
        assert len(env.queue) == 0

    def test_end_to_end_interruption_replacement(self, env):
        claim = self._spot_claim(env)
        pods_on = env.cluster.pods_on_node(claim.status.node_name)
        iid = claim.status.provider_id.rsplit("/", 1)[-1]
        env.queue.send({
            "source": "aws.ec2",
            "detail-type": "EC2 Spot Instance Interruption Warning",
            "detail": {"instance-id": iid},
        })
        env.step(5)
        assert not env.cluster.pending_pods()
        for p in pods_on:
            assert p.node_name and p.node_name != f"node-{claim.name}"


class TestConsolidationKernel:
    def test_repack_check_matches_numpy(self, env):
        from karpenter_provider_aws_tpu.ops.consolidate import (
            consolidatable,
            encode_cluster,
            repack_feasible_numpy,
        )

        env.apply_defaults(pool_with(consolidate_after_s=3600))
        pods = make_pods(20, "w", {"cpu": "1", "memory": "2Gi"}) + make_pods(
            6, "big", {"cpu": "8", "memory": "24Gi"}
        )
        provision(env, pods)
        for p in pods[10:20]:
            env.cluster.delete(p)
        ct = encode_cluster(env.cluster, env.catalog)
        if ct is None:
            pytest.skip("no nodes")
        can_device = consolidatable(ct)
        for i in range(len(ct.node_names)):
            host = repack_feasible_numpy(ct, ct.free, i) is not None
            if not ct.blocked[i]:
                assert bool(can_device[i]) == host, f"node {i}"


class TestRAID0Consolidation:
    """The replacement screens must use the NODECLASS's ephemeral rules
    (review regression: provisioning got the RAID0 capacity override but
    consolidation compared pods against the nodeclass-blind 20GiB tensor,
    permanently excluding storage-heavy RAID0 nodes from replace)."""

    def test_cheaper_replacement_sees_raid0_ephemeral(self, env):
        from karpenter_provider_aws_tpu.models.nodeclass import NodeClass
        from karpenter_provider_aws_tpu.ops.consolidate import (
            cheaper_replacement,
            encode_cluster,
        )

        nodeclass = NodeClass(
            name="default", role="node-role", instance_store_policy="RAID0"
        )
        env.cluster.apply(nodeclass)
        pool = pool_with(consolidate_after_s=None)
        pool.requirements = [
            Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m", "r", "i", "d"))
        ]
        env.cluster.apply(pool)
        env.nodeclass_status.reconcile()
        env.nodeclass_hash.reconcile()
        provision(env, make_pods(2, "scratch", {
            "cpu": "1", "memory": "2Gi", "ephemeral-storage": "150Gi",
        }))
        ct = encode_cluster(env.cluster, env.catalog)
        assert ct is not None
        pools = {pool.name: pool}
        ncmap = {pool.name: nodeclass}
        # With the nodeclass threaded, candidate fits exist (NVMe types can
        # hold 150Gi); nodeclass-blind, every type capped at 20Gi and the
        # screen returns nothing structurally fit-capable.
        rows_blind = cheaper_replacement(
            ct, env.catalog, nodepools=pools, margin=-10.0
        )
        rows_aware = cheaper_replacement(
            ct, env.catalog, nodepools=pools, margin=-10.0,
            nodeclass_by_pool=ncmap,
        )
        assert not rows_blind
        assert rows_aware
