"""Disruption + interruption behavior (reference: designs/consolidation.md,
pkg/controllers/interruption suite, scale deprovisioning suites)."""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.models import Disruption, NodePool, Operator, Requirement
from karpenter_provider_aws_tpu.models import labels as lbl
from karpenter_provider_aws_tpu.models.pod import make_pods
from karpenter_provider_aws_tpu.testenv import new_environment


@pytest.fixture(scope="module")
def env():
    return new_environment()


@pytest.fixture(autouse=True)
def _reset(env):
    env.reset()
    yield


def pool_with(max_cpu=None, **disruption_kwargs):
    disruption_kwargs.setdefault("budgets", ["100%"])
    disruption_kwargs.setdefault("consolidate_after_s", None)
    reqs = [Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m", "r"))]
    if max_cpu is not None:
        # the real catalog carries 192-448 vCPU giants; tests asserting
        # multi-node plans pin the node size so pods cannot all land on one
        reqs.append(Requirement(lbl.INSTANCE_CPU, Operator.LT, (str(max_cpu),)))
    return NodePool(
        name="default",
        requirements=reqs,
        disruption=Disruption(**disruption_kwargs),
    )


def provision(env, pods):
    for p in pods:
        env.cluster.apply(p)
    env.step(3)
    assert not env.cluster.pending_pods()


class TestTermination:
    def test_claim_delete_drains_and_terminates(self, env):
        env.apply_defaults(pool_with())
        pods = make_pods(5, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        claim = next(
            c for c in env.cluster.nodeclaims.values()
            if env.cluster.pods_on_node(c.status.node_name)
        )
        provider_id = claim.status.provider_id
        drained = env.cluster.pods_on_node(claim.status.node_name)
        assert drained
        env.cluster.delete(claim)
        env.termination.reconcile()
        # pods evicted back to pending, instance gone, claim finalized
        assert claim.name not in env.cluster.nodeclaims
        with pytest.raises(Exception):
            env.cloudprovider.get(provider_id)
        assert all(p.is_pending() for p in drained)

    def test_drained_pods_reprovisioned(self, env):
        env.apply_defaults(pool_with())
        pods = make_pods(5, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        claim = next(iter(env.cluster.nodeclaims.values()))
        env.cluster.delete(claim)
        env.step(4)
        assert not env.cluster.pending_pods()
        assert len(env.cluster.nodes) >= 1


class TestScheduler:
    def test_pending_pod_lands_on_existing_free_node(self, env):
        env.apply_defaults(pool_with())
        # a 6cpu pod lands on an 8-vcpu-class node, leaving headroom
        provision(env, make_pods(1, "big", {"cpu": "6", "memory": "6Gi"}))
        n_nodes = len(env.cluster.nodes)
        extra = make_pods(2, "extra", {"cpu": "500m", "memory": "1Gi"})
        for p in extra:
            env.cluster.apply(p)
        env.scheduling.reconcile()
        assert all(not p.is_pending() for p in extra)
        assert len(env.cluster.nodes) == n_nodes  # no new nodes

    def test_scheduler_respects_taints_and_labels(self, env):
        from karpenter_provider_aws_tpu.models import Taint

        env.apply_defaults(pool_with())
        provision(env, make_pods(2, "w", {"cpu": "1", "memory": "2Gi"}))
        for node in env.cluster.nodes.values():
            node.taints = [Taint(key="quarantine", effect="NoSchedule")]
        p = make_pods(1, "x", {"cpu": "100m"})[0]
        env.cluster.apply(p)
        env.scheduling.reconcile()
        assert p.is_pending()  # not tolerated -> not bound


class TestEmptiness:
    def test_empty_node_deleted_after_consolidate_after(self, env):
        env.apply_defaults(pool_with(consolidation_policy="WhenEmpty", consolidate_after_s=30))
        pods = make_pods(3, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        for p in pods:  # all pods finish
            env.cluster.delete(p)
        env.disruption.reconcile()
        assert not any(c.deleted for c in env.cluster.nodeclaims.values())  # too soon
        env.clock.advance(31)
        env.disruption.reconcile()
        assert all(c.deleted for c in env.cluster.nodeclaims.values())


class TestExpiration:
    def test_expired_claims_disrupted(self, env):
        env.apply_defaults(pool_with(expire_after_s=3600, consolidate_after_s=None))
        provision(env, make_pods(3, "w", {"cpu": "1", "memory": "2Gi"}))
        env.disruption.reconcile()
        assert not any(c.deleted for c in env.cluster.nodeclaims.values())
        env.clock.advance(3601)
        env.disruption.reconcile()
        assert all(c.deleted for c in env.cluster.nodeclaims.values())


class TestDriftDisruption:
    def test_static_drift_triggers_disruption(self, env):
        env.apply_defaults(pool_with(consolidate_after_s=None))
        provision(env, make_pods(2, "w", {"cpu": "1", "memory": "2Gi"}))
        env.cluster.nodeclasses["default"].user_data = "changed"
        env.disruption.reconcile()
        assert any("drifted" in r for _, r in env.disruption.disrupted)

    def test_nodepool_template_drift_triggers_disruption(self, env):
        """Editing the pool TEMPLATE (labels/taints/requirements) drifts
        claims stamped from the old template (core NodePool static drift);
        non-template knobs (weight, budgets) must not."""
        pool, _ = env.apply_defaults(pool_with(consolidate_after_s=None))
        provision(env, make_pods(2, "w", {"cpu": "1", "memory": "2Gi"}))
        pool.weight = 7  # decision-steering field: NOT drift
        env.disruption.reconcile()
        assert not any("NodePool" in r for _, r in env.disruption.disrupted)
        pool.labels = {"team": "b"}  # template field: drift
        env.disruption.reconcile()
        assert any("NodePoolHashDrifted" in r for _, r in env.disruption.disrupted)


class TestValidationWindow:
    @pytest.fixture(autouse=True)
    def _window(self, env):
        env.disruption.validation_period_s = 15.0
        yield
        env.disruption.validation_period_s = 0.0

    def _thin_out(self, env, pods):
        """Delete most pods but keep one per stretch, so every node retains
        a pod — emptiness (which has no validation window) must not fire."""
        for i, p in enumerate(pods):
            if i % 8 != 0:
                env.cluster.delete(p)

    def test_candidate_must_persist_before_commit(self, env):
        """Core consolidation validation: a node must stay consolidatable
        across the validation window before any delete commits — a
        transient dip never kills a node on first sight."""
        env.apply_defaults(pool_with(max_cpu=17, consolidate_after_s=10))
        pods = make_pods(30, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        self._thin_out(env, pods)
        env.clock.advance(61)
        env.disruption.reconcile()  # first sight: starts the window
        assert not any(
            r.startswith("consolidatable") for _, r in env.disruption.disrupted
        )
        env.clock.advance(16)
        env.disruption.reconcile()  # window passed: commits
        assert any(
            r.startswith("consolidatable") for _, r in env.disruption.disrupted
        )

    def test_flapping_candidate_restarts_window(self, env):
        env.apply_defaults(pool_with(max_cpu=17, consolidate_after_s=10))
        pods = make_pods(30, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        self._thin_out(env, pods)
        env.clock.advance(61)
        env.disruption.reconcile()  # window starts
        # load returns: candidates vanish, first-seen entries prune
        refill = make_pods(26, "w2", {"cpu": "1", "memory": "2Gi"})
        provision(env, refill)
        env.clock.advance(16)
        env.disruption.reconcile()
        assert not any(
            r.startswith("consolidatable") for _, r in env.disruption.disrupted
        )


class TestBudgets:
    def test_budget_caps_disruptions_per_pass(self, env):
        pool = pool_with(max_cpu=100, expire_after_s=60, consolidate_after_s=None)
        pool.disruption.budgets = ["1"]
        env.apply_defaults(pool)
        # several nodes: one pod each, big enough that each pod needs its own node
        provision(env, make_pods(4, "w", {"cpu": "60", "memory": "120Gi"}))
        assert len(env.cluster.nodeclaims) >= 3
        env.clock.advance(61)
        env.disruption.reconcile()
        assert sum(1 for c in env.cluster.nodeclaims.values() if c.deleted) == 1


class TestConsolidation:
    def test_underutilized_nodes_consolidated(self, env):
        # consolidate only after a quiet window, so provisioning settles first
        env.apply_defaults(pool_with(max_cpu=17, consolidate_after_s=60))
        pods = make_pods(30, "w", {"cpu": "1", "memory": "2Gi"})
        provision(env, pods)
        # most pods finish: the remaining few should repack onto fewer nodes
        for p in pods[4:]:
            env.cluster.delete(p)
        n_before = len(env.cluster.nodes)
        assert n_before >= 2
        env.clock.advance(61)
        env.disruption.reconcile()
        env.step(4)  # drain, rebind onto survivors, settle
        assert not env.cluster.pending_pods()
        assert len(env.cluster.nodes) < n_before
        # cost must not have increased: survivors hold all remaining pods
        assert sum(len(env.cluster.pods_on_node(n)) for n in env.cluster.nodes) == 4

    def test_replace_with_cheaper_single_node(self, env):
        env.apply_defaults(pool_with(consolidate_after_s=60))
        # 3cpu pods pack onto big nodes (best cost-per-slot); shrinking the
        # demand to 2 pods leaves one nearly-empty big node whose pods fit a
        # far cheaper type -> single-node replace
        pods = make_pods(20, "w", {"cpu": "3", "memory": "6Gi"})
        provision(env, pods)
        keep = env.cluster.pods_on_node(
            next(iter(env.cluster.nodes.values())).name
        )[:2]
        for p in pods:
            if p.uid not in {k.uid for k in keep}:
                env.cluster.delete(p)
        price_before = sum(
            env.catalog.pricing.on_demand_price(env.catalog.get(n.instance_type()))
            for n in env.cluster.nodes.values()
        )
        env.clock.advance(61)
        env.disruption.reconcile()
        env.step(4)
        assert not env.cluster.pending_pods()
        price_after = sum(
            env.catalog.pricing.on_demand_price(env.catalog.get(n.instance_type()))
            for n in env.cluster.nodes.values()
        )
        assert price_after < price_before
        assert any("replace" in r or "delete" in r for _, r in env.disruption.disrupted)

    def test_do_not_disrupt_respected(self, env):
        env.apply_defaults(pool_with(max_cpu=17, consolidate_after_s=60))
        pods = make_pods(
            2, "w", {"cpu": "1", "memory": "2Gi"},
            annotations={lbl.ANNOTATION_DO_NOT_DISRUPT: "true"},
        )
        provision(env, pods)
        env.clock.advance(61)
        env.disruption.reconcile()
        assert not any(c.deleted for c in env.cluster.nodeclaims.values())


class TestInterruption:
    def _spot_claim(self, env):
        env.apply_defaults(pool_with(consolidate_after_s=None))
        provision(env, make_pods(3, "w", {"cpu": "1", "memory": "2Gi"}))
        for claim in env.cluster.nodeclaims.values():
            if claim.labels.get(lbl.CAPACITY_TYPE) == "spot":
                return claim
        return next(iter(env.cluster.nodeclaims.values()))

    def test_spot_interruption_drains_and_masks(self, env):
        claim = self._spot_claim(env)
        iid = claim.status.provider_id.rsplit("/", 1)[-1]
        env.queue.send({
            "source": "aws.ec2",
            "detail-type": "EC2 Spot Instance Interruption Warning",
            "detail": {"instance-id": iid},
        })
        env.interruption.reconcile()
        assert claim.deleted
        itype = claim.labels[lbl.INSTANCE_TYPE_LABEL]
        zone = claim.labels[lbl.TOPOLOGY_ZONE]
        assert env.catalog.unavailable.is_unavailable(itype, zone, "spot")
        assert len(env.queue) == 0

    def test_rebalance_is_no_action(self, env):
        claim = self._spot_claim(env)
        iid = claim.status.provider_id.rsplit("/", 1)[-1]
        env.queue.send({
            "source": "aws.ec2",
            "detail-type": "EC2 Instance Rebalance Recommendation",
            "detail": {"instance-id": iid},
        })
        env.interruption.reconcile()
        assert not claim.deleted
        assert len(env.queue) == 0

    def test_state_change_terminated_drains(self, env):
        claim = self._spot_claim(env)
        iid = claim.status.provider_id.rsplit("/", 1)[-1]
        env.queue.send({
            "source": "aws.ec2",
            "detail-type": "EC2 Instance State-change Notification",
            "detail": {"instance-id": iid, "state": "shutting-down"},
        })
        env.interruption.reconcile()
        assert claim.deleted

    def test_health_event_drains(self, env):
        claim = self._spot_claim(env)
        iid = claim.status.provider_id.rsplit("/", 1)[-1]
        env.queue.send({
            "source": "aws.health",
            "detail-type": "AWS Health Event",
            "detail": {"affectedEntities": [{"entityValue": iid}]},
        })
        env.interruption.reconcile()
        assert claim.deleted

    def test_unparseable_message_deleted(self, env):
        env.apply_defaults(pool_with())
        env.queue.send({"source": "junk", "detail-type": "garbage"})
        env.queue.send("not even json {{{")
        env.interruption.reconcile()
        assert len(env.queue) == 0

    def test_end_to_end_interruption_replacement(self, env):
        claim = self._spot_claim(env)
        pods_on = env.cluster.pods_on_node(claim.status.node_name)
        iid = claim.status.provider_id.rsplit("/", 1)[-1]
        env.queue.send({
            "source": "aws.ec2",
            "detail-type": "EC2 Spot Instance Interruption Warning",
            "detail": {"instance-id": iid},
        })
        env.step(5)
        assert not env.cluster.pending_pods()
        for p in pods_on:
            assert p.node_name and p.node_name != f"node-{claim.name}"


class TestConsolidationKernel:
    def test_repack_check_matches_numpy(self, env):
        from karpenter_provider_aws_tpu.ops.consolidate import (
            consolidatable,
            encode_cluster,
            repack_feasible_numpy,
        )

        env.apply_defaults(pool_with(consolidate_after_s=3600))
        pods = make_pods(20, "w", {"cpu": "1", "memory": "2Gi"}) + make_pods(
            6, "big", {"cpu": "8", "memory": "24Gi"}
        )
        provision(env, pods)
        for p in pods[10:20]:
            env.cluster.delete(p)
        ct = encode_cluster(env.cluster, env.catalog)
        if ct is None:
            pytest.skip("no nodes")
        can_device = consolidatable(ct)
        for i in range(len(ct.node_names)):
            host = repack_feasible_numpy(ct, ct.free, i) is not None
            if not ct.blocked[i]:
                assert bool(can_device[i]) == host, f"node {i}"


class TestRAID0Consolidation:
    """The replacement screens must use the NODECLASS's ephemeral rules
    (review regression: provisioning got the RAID0 capacity override but
    consolidation compared pods against the nodeclass-blind 20GiB tensor,
    permanently excluding storage-heavy RAID0 nodes from replace)."""

    def test_cheaper_replacement_sees_raid0_ephemeral(self, env):
        from karpenter_provider_aws_tpu.models.nodeclass import NodeClass
        from karpenter_provider_aws_tpu.ops.consolidate import (
            cheaper_replacement,
            encode_cluster,
        )

        nodeclass = NodeClass(
            name="default", role="node-role", instance_store_policy="RAID0"
        )
        env.cluster.apply(nodeclass)
        pool = pool_with(consolidate_after_s=None)
        pool.requirements = [
            Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m", "r", "i", "d"))
        ]
        env.cluster.apply(pool)
        env.nodeclass_status.reconcile()
        env.nodeclass_hash.reconcile()
        provision(env, make_pods(2, "scratch", {
            "cpu": "1", "memory": "2Gi", "ephemeral-storage": "150Gi",
        }))
        ct = encode_cluster(env.cluster, env.catalog)
        assert ct is not None
        pools = {pool.name: pool}
        ncmap = {pool.name: nodeclass}
        # With the nodeclass threaded, candidate fits exist (NVMe types can
        # hold 150Gi); nodeclass-blind, every type capped at 20Gi and the
        # screen returns nothing structurally fit-capable.
        rows_blind = cheaper_replacement(
            ct, env.catalog, nodepools=pools, margin=-10.0
        )
        rows_aware = cheaper_replacement(
            ct, env.catalog, nodepools=pools, margin=-10.0,
            nodeclass_by_pool=ncmap,
        )
        assert not rows_blind
        assert rows_aware


class TestDirtySweepContract:
    """The change-journal-driven dirty-set sweep (_DirtyScan) must return
    the IDENTICAL disruption decision set as the legacy full O(claims)
    walk — the same contract style as the PR 7 sharded-vs-unsharded
    ``canonical_equal`` property test, here over the controller's commit
    log instead of tensors. Claim names come from a process-global
    sequence, so decisions are compared by creation ORDINAL (stable across
    the two runs), never by raw name."""

    STEPS = 6
    N_NODES = 48

    def _churn(self, cl, names, rng, step):
        from karpenter_provider_aws_tpu.models import labels as lbl

        for _ in range(6):
            r = rng.rand()
            if r < 0.40:  # bind a new pod somewhere
                p = make_pods(
                    1, f"dsc{step}", {"cpu": "250m", "memory": "512Mi"}
                )[0]
                cl.apply(p)
                cl.bind_pod(p.uid, names[rng.randint(len(names))])
            elif r < 0.70:  # evict one bound pod
                bound = [pp for pp in cl.pods.values() if pp.node_name]
                if bound:
                    bound.sort(key=lambda pp: pp.name)
                    cl.unbind_pod(bound[rng.randint(len(bound))].uid)
            elif r < 0.85:  # drain a whole node (arms emptiness)
                nd = cl.nodes.get(names[rng.randint(len(names))])
                if nd is not None:
                    for pp in list(cl.pods_on_node(nd.name)):
                        cl.unbind_pod(pp.uid)
            else:  # flip a do-not-disrupt annotation IN PLACE (a direct
                # node write the journal never sees — the defensive
                # node-version scan must catch it in both modes)
                nd = cl.nodes.get(names[rng.randint(len(names))])
                if nd is not None:
                    cur = nd.annotations.get(lbl.ANNOTATION_DO_NOT_DISRUPT)
                    anns = dict(nd.annotations)
                    anns[lbl.ANNOTATION_DO_NOT_DISRUPT] = (
                        "false" if cur == "true" else "true"
                    )
                    nd.annotations = anns  # __setattr__ bumps the version

    def _run_mode(self, mode: str, seed: int):
        import os

        from benchmarks.solve_configs import _synth_cluster

        prev = os.environ.get("KARPENTER_TPU_DISRUPTION_DIRTY")
        os.environ["KARPENTER_TPU_DISRUPTION_DIRTY"] = mode
        env = None
        try:
            env = _synth_cluster(n_nodes=self.N_NODES, pods_per_node=3)
            cl = env.cluster
            pool = cl.nodepools["default"]
            pool.disruption.consolidation_policy = "WhenUnderutilized"
            pool.disruption.consolidate_after_s = 60.0
            pool.disruption.expire_after_s = 500.0  # fires in late steps
            pool.disruption.budgets = ["10%"]
            d = env.disruption
            d.validation_period_s = 0.0
            # creation-ordinal normalization: synth claims first, any
            # replacement launched during the run next, in first-seen order
            ordinal = {
                name: f"c{i}" for i, name in enumerate(cl.nodeclaims)
            }

            def norm(name):
                if name not in ordinal:
                    ordinal[name] = f"r{len(ordinal)}"
                return ordinal[name]

            rng = np.random.RandomState(seed)
            # CREATION order, NOT sorted(): node names embed the process-
            # global claim sequence, so lexicographic order is different in
            # the two runs while insertion order is identical
            names = [n.name for n in cl.snapshot_nodes()]
            log = []
            for step in range(self.STEPS):
                self._churn(cl, names, rng, step)
                env.clock.advance(100.0)
                before = len(d.disrupted)
                d.reconcile()
                # visit claims in creation order so replacement ordinals
                # assign deterministically
                for cname in cl.nodeclaims:
                    norm(cname)
                log.append(tuple(
                    (norm(cn), reason) for cn, reason in d.disrupted[before:]
                ))
            deleted = tuple(sorted(
                norm(c.name) for c in cl.nodeclaims.values() if c.deleted
            ))
            return tuple(log), deleted
        finally:
            if env is not None:
                env.close()
            if prev is None:
                os.environ.pop("KARPENTER_TPU_DISRUPTION_DIRTY", None)
            else:
                os.environ["KARPENTER_TPU_DISRUPTION_DIRTY"] = prev

    @pytest.mark.parametrize("seed", [3, 11, 42])
    def test_property_randomized_churn_same_decisions(self, seed):
        dirty = self._run_mode("1", seed)
        full = self._run_mode("0", seed)
        assert dirty == full, (
            f"seed {seed}: dirty-set decisions diverged from the full walk"
            f"\n dirty: {dirty}\n full:  {full}"
        )

    def test_property_decisions_are_nonempty_somewhere(self):
        """Guard against the property test passing vacuously: at least one
        seed's run must actually disrupt something (expiration at 500s is
        armed by construction — 6 steps x 100s crosses it)."""
        log, deleted = self._run_mode("1", 3)
        assert any(log) or deleted

    def test_overflow_rebuild_path(self):
        """Rolling the change journal between passes must force the
        epoch-guarded rebuild (a NEW _DirtyScan), and a real change buried
        in the overflowed window — a node drained empty — must still be
        seen by the rebuilt scan."""
        import os

        from benchmarks.solve_configs import _synth_cluster

        prev = os.environ.get("KARPENTER_TPU_DISRUPTION_DIRTY")
        os.environ["KARPENTER_TPU_DISRUPTION_DIRTY"] = "1"
        env = None
        try:
            env = _synth_cluster(n_nodes=24, pods_per_node=2)
            cl = env.cluster
            pool = cl.nodepools["default"]
            pool.disruption.consolidation_policy = "WhenEmpty"
            pool.disruption.consolidate_after_s = 0.0
            d = env.disruption
            d.reconcile()
            ds0 = d._ds
            assert ds0 is not None
            rev0 = cl.rev
            # drain one node empty, then roll the journal right past it
            victim = next(
                n.name for n in cl.snapshot_nodes()
                if cl.pods_on_node(n.name)
            )
            empty_claim = next(
                c.name for c in cl.nodeclaims.values()
                if c.status.node_name == victim
            )
            for pp in list(cl.pods_on_node(victim)):
                cl.unbind_pod(pp.uid)
            spin = make_pods(1, "ovf", {"cpu": "100m", "memory": "128Mi"})[0]
            cl.apply(spin)
            other = next(
                n.name for n in cl.snapshot_nodes() if n.name != victim
            )
            for _ in range(3000):
                cl.bind_pod(spin.uid, other)
                cl.unbind_pod(spin.uid)
            assert cl.changes_since(rev0) is None  # the window really rolled
            env.clock.advance(30.0)
            d.reconcile()
            assert d._ds is not ds0  # overflow forced a full rebuild
            assert any(
                cn == empty_claim and reason == "empty"
                for cn, reason in d.disrupted
            ), d.disrupted
        finally:
            if env is not None:
                env.close()
            if prev is None:
                os.environ.pop("KARPENTER_TPU_DISRUPTION_DIRTY", None)
            else:
                os.environ["KARPENTER_TPU_DISRUPTION_DIRTY"] = prev


class TestExpiryHeapSupersededEntries:
    def test_live_deadline_survives_duplicate_due_entries(self):
        """A claim with TWO due heap entries (its deadline moved earlier
        while a stale entry was still queued — e.g. budget-blocked, then
        the pool's expire_after shortened) must expire via the LIVE
        entry: the per-name collapse used to keep whichever popped last
        (the stale one) and silently consume the live entry without a
        repush."""
        import os

        from benchmarks.solve_configs import _synth_cluster

        prev = os.environ.get("KARPENTER_TPU_DISRUPTION_DIRTY")
        os.environ["KARPENTER_TPU_DISRUPTION_DIRTY"] = "1"
        env = None
        try:
            env = _synth_cluster(n_nodes=4, pods_per_node=1)
            cl = env.cluster
            pool = cl.nodepools["default"]
            pool.disruption.consolidation_policy = None
            pool.disruption.expire_after_s = 1000.0
            d = env.disruption
            d.reconcile()
            ds = d._ds
            assert ds is not None and ds.expiry_at
            name = next(iter(ds.expiry_at))
            import heapq

            stale_dl = ds.expiry_at[name]
            live_dl = stale_dl - 900.0  # deadline moved EARLIER
            ds.expiry_at[name] = live_dl
            heapq.heappush(ds.expiry, (live_dl, name))
            # both entries due; the stale one pops last (larger deadline)
            env.clock.advance(1001.0)
            d.reconcile()
            assert any(
                cn == name and reason == "expired"
                for cn, reason in d.disrupted
            ), d.disrupted
        finally:
            if env is not None:
                env.close()
            if prev is None:
                os.environ.pop("KARPENTER_TPU_DISRUPTION_DIRTY", None)
            else:
                os.environ["KARPENTER_TPU_DISRUPTION_DIRTY"] = prev
