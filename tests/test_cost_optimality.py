"""Round-5 cost work: commit-downsize, refine skip, LP lower bounds
(designs/cost-optimality.md)."""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.models import Disruption, NodePool, Operator, Requirement
from karpenter_provider_aws_tpu.models import labels as lbl
from karpenter_provider_aws_tpu.models.pod import make_pods
from karpenter_provider_aws_tpu.ops.encode import encode_problem
from karpenter_provider_aws_tpu.scheduling import HostSolver, TPUSolver
from karpenter_provider_aws_tpu.scheduling.solver import lp_lower_bound


def _pool(cats=("c", "m", "r")):
    return NodePool(
        name="default",
        requirements=[Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, tuple(cats))],
        disruption=Disruption(consolidate_after_s=None),
    )


class TestLpLowerBound:
    def test_bound_is_below_every_plan(self, session_catalog):
        """VALIDITY: the bound must under-cut both solvers on assorted
        workloads (an invalid bound was caught this way in round 5)."""
        rng = np.random.RandomState(5)
        for trial in range(3):
            pods = []
            for i in range(12):
                cpu = int(rng.choice([250, 500, 1000, 3000, 7000]))
                mem = cpu * int(rng.choice([1, 2, 4, 8]))
                pods += make_pods(
                    int(rng.randint(1, 40)), f"t{trial}s{i}",
                    {"cpu": f"{cpu}m", "memory": f"{mem}Mi"},
                )
            pool = _pool()
            problem = encode_problem(pods, session_catalog, pool)
            bound = lp_lower_bound(problem)
            assert bound > 0
            host = HostSolver().solve(pods, [pool], session_catalog)
            tpu = TPUSolver().solve(pods, [pool], session_catalog)
            assert host.total_cost >= bound - 1e-6, (trial, host.total_cost, bound)
            assert tpu.total_cost >= bound - 1e-6, (trial, tpu.total_cost, bound)

    def test_empty_problem(self, session_catalog):
        problem = encode_problem([], session_catalog, _pool())
        assert lp_lower_bound(problem) == 0.0


class TestCommitDownsize:
    def test_tail_node_downsizes_when_granularity_allows(self, session_catalog):
        """A tail far smaller than the group's opening type re-commits to
        a cheaper type that still fits; the greedy baseline keeps paying
        the open-time choice."""
        # 33 pods of 2cpu: opener picks a large $/slot-optimal type; the
        # tail node carries 1 pod and should drop to a small type
        pods = make_pods(33, "w", {"cpu": "2", "memory": "4Gi"})
        pool = _pool()
        tpu = TPUSolver().solve(pods, [pool], session_catalog)
        host = HostSolver().solve(pods, [pool], session_catalog)
        assert tpu.pods_placed() == 33
        assert tpu.total_cost <= host.total_cost + 1e-6
        # the cheapest spec's committed type fits its pods but not the
        # full-node count — i.e. an actual downsize happened somewhere,
        # OR granularity made it impossible; assert the invariant that
        # every spec's committed type covers its own pods
        for spec in tpu.node_specs:
            it = session_catalog.get(spec.instance_type_options[0])
            total = sum((p.requests.v for p in spec.pods))
            alloc = session_catalog.allocatable(it)
            assert (total <= alloc.v + 1e-4).all(), spec.instance_type_options[0]

    def test_downsize_never_raises_cost(self, session_catalog):
        import os

        pods = make_pods(150, "w", {"cpu": "750m", "memory": "1.5Gi"})
        pool = _pool()
        on = TPUSolver().solve(pods, [pool], session_catalog).total_cost
        os.environ["KARPENTER_TPU_DOWNSIZE"] = "0"
        try:
            off = TPUSolver().solve(pods, [pool], session_catalog).total_cost
        finally:
            os.environ.pop("KARPENTER_TPU_DOWNSIZE", None)
        assert on <= off + 1e-6


class TestPipelinedMultiPool:
    """The dispatch-pipelined multi-pool solve (round-5): pool k+1 is
    dispatched on pool k's host-certain leftovers; NON-certain leftovers
    (limits/minValues rejections) catch up sequentially."""

    def test_limits_stragglers_catch_up(self, session_catalog):
        from karpenter_provider_aws_tpu.models.nodepool import Limits

        p1 = NodePool(
            name="limited", weight=10,
            requirements=[Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m"))],
            limits=Limits.of(cpu="8"),
            disruption=Disruption(consolidate_after_s=None),
        )
        p2 = NodePool(
            name="overflow",
            requirements=[Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m"))],
            disruption=Disruption(consolidate_after_s=None),
        )
        # 50 cpu of demand against an 8-cpu limit: most pods are
        # limits-REJECTED (not host-certain — the device solve places
        # them, the host constraint pass rejects), so they must reach
        # pool2 via the sequential catch-up, not the speculation
        pods = make_pods(100, "w", {"cpu": "500m", "memory": "1Gi"})
        res = TPUSolver().solve(pods, [p1, p2], session_catalog)
        assert res.pods_placed() == 100
        assert not res.unschedulable
        by_pool: dict = {}
        for s in res.node_specs:
            by_pool[s.nodepool_name] = by_pool.get(s.nodepool_name, 0) + len(s.pods)
        assert by_pool.get("limited", 0) > 0, "limited pool took its share"
        assert by_pool.get("overflow", 0) >= 90, by_pool
        # equivalence: sequential host solver lands the same split
        host = HostSolver().solve(pods, [p1, p2], session_catalog)
        assert host.pods_placed() == 100

    def test_gpu_pods_speculate_to_accel_pool(self, session_catalog):
        """Host-certain leftovers (no usable type in pool1) take the
        SPECULATIVE path: both pools' programs in flight before a fetch."""
        from karpenter_provider_aws_tpu.models.nodepool import Taint
        from karpenter_provider_aws_tpu.models.pod import Toleration

        p1 = NodePool(
            name="default",
            requirements=[Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m", "r"))],
            disruption=Disruption(consolidate_after_s=None),
        )
        p2 = NodePool(
            name="accel",
            requirements=[Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("g", "p"))],
            taints=[Taint(key="accel", value="true")],
            disruption=Disruption(consolidate_after_s=None),
        )
        pods = make_pods(60, "cpu", {"cpu": "1", "memory": "2Gi"})
        pods += make_pods(
            8, "gpu", {"cpu": "2", "memory": "8Gi", "nvidia.com/gpu": 1},
            tolerations=[Toleration(key="accel", value="true")],
        )
        res = TPUSolver().solve(pods, [p1, p2], session_catalog)
        assert res.pods_placed() == 68
        gpu_specs = [
            s for s in res.node_specs
            if any(p.requests.get("nvidia.com/gpu") > 0 for p in s.pods)
        ]
        assert gpu_specs
        assert all(s.nodepool_name == "accel" for s in gpu_specs)


class TestSparsePlanSelfSizing:
    """Round-5 config2 fix: an overflowing sparse-plan buffer silently
    cost a dense-fallback fetch every solve (the overflow->dense-fallback
    CORRECTNESS is pinned in test_solve_caches.py; here we pin the
    history->buffer-size plumbing, which only matters above the static
    floor and so can't be reached by a naturally-sized hermetic plan).

    FFD-only: the optimizer lane sizes its own compact_plan buffer, which
    would interleave extra entries into the spy below."""

    @pytest.fixture(autouse=True)
    def _ffd_only(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_OPTIMIZER", "0")

    def test_observed_nonzeros_grow_the_buffer(self, session_catalog, monkeypatch):
        from karpenter_provider_aws_tpu.ops import ffd as ffd_mod

        orig_compact = ffd_mod.compact_plan
        calls: list = []

        def spy_compact(placed, max_entries):
            calls.append(max_entries)
            return orig_compact(placed, max_entries)

        # solver imports compact_plan from ops.ffd inside dispatch —
        # patching the source module is the one effective patch point
        monkeypatch.setattr(ffd_mod, "compact_plan", spy_compact)

        pods = make_pods(96, "w", {"cpu": "500m", "memory": "1Gi"})
        pool = _pool()
        tpu = TPUSolver()
        tpu.solve(pods, [pool], session_catalog)
        assert calls, "dispatch must size a sparse buffer"
        floor = calls[-1]
        # a prior solve that observed MANY nonzeros (a config2-scale plan)
        # must size the next buffer past the static floor
        key = next(iter(tpu._nz_hist))
        tpu._nz_hist[key] = 50_000
        tpu.solve(pods, [pool], session_catalog)
        assert calls[-1] >= 75_000, (calls[-1], floor)
        assert calls[-1] > floor


class TestRefineSkip:
    # FFD-only: the optimizer arbitration runs _refine_plan on the lane's
    # own plan, which would interleave extra spy calls / skip-state here
    @pytest.fixture(autouse=True)
    def _ffd_only(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_OPTIMIZER", "0")

    def test_skip_engages_only_after_noop_refines(self, session_catalog, monkeypatch):
        import karpenter_provider_aws_tpu.scheduling.solver as S

        calls = []
        orig = S._refine_plan

        def spy(*a, **k):
            out = orig(*a, **k)
            calls.append(bool(out[0].any()))
            return out

        monkeypatch.setattr(S, "_refine_plan", spy)
        pods = make_pods(300, "w", {"cpu": "500m", "memory": "1Gi"})
        pool = _pool()
        tpu = TPUSolver()
        for _ in range(6):
            tpu.solve(pods, [pool], session_catalog)
        # refine ran at least twice (to observe the no-op streak), then
        # skipped: fewer calls than solves
        assert 2 <= len(calls) < 6
        assert not any(calls)  # dense workload: refine never drops

    def test_skip_never_engages_when_refine_wins(self, session_catalog, monkeypatch):
        from benchmarks.solve_configs import config6_mixed_tail

        import karpenter_provider_aws_tpu.scheduling.solver as S

        calls = []
        orig = S._refine_plan

        def spy(*a, **k):
            out = orig(*a, **k)
            calls.append(bool(out[0].any()))
            return out

        monkeypatch.setattr(S, "_refine_plan", spy)
        pods, pools = config6_mixed_tail()
        tpu = TPUSolver()
        for _ in range(5):
            tpu.solve(pods, pools, session_catalog)
        assert len(calls) == 5  # every solve refined
        assert all(calls)       # and every refine dropped something
