"""Topology-spread / (anti-)affinity behavior (BASELINE config #3:
zone+hostname topology-spread + pod anti-affinity)."""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.catalog import CatalogProvider
from karpenter_provider_aws_tpu.models import NodePool
from karpenter_provider_aws_tpu.models import labels as lbl
from karpenter_provider_aws_tpu.models.pod import (
    PodAffinityTerm,
    TopologySpreadConstraint,
    make_pods,
)
from karpenter_provider_aws_tpu.scheduling import HostSolver, TPUSolver


@pytest.fixture(scope="module")
def catalog():
    return CatalogProvider()


@pytest.fixture(scope="module")
def pool():
    return NodePool(name="default")


def zone_spread(max_skew=1):
    return TopologySpreadConstraint(
        topology_key=lbl.TOPOLOGY_ZONE, max_skew=max_skew,
        label_selector={"app": "web"},
    )


def host_spread(max_skew=1):
    return TopologySpreadConstraint(
        topology_key=lbl.HOSTNAME, max_skew=max_skew,
        label_selector={"app": "web"},
    )


def self_anti_affinity(key=lbl.HOSTNAME):
    return PodAffinityTerm(topology_key=key, label_selector={"app": "web"})


@pytest.mark.parametrize("solver_cls", [TPUSolver, HostSolver])
class TestZoneSpread:
    def test_pods_balanced_across_zones(self, catalog, pool, solver_cls):
        pods = make_pods(12, "w", {"cpu": "1", "memory": "2Gi"},
                         labels={"app": "web"}, topology_spread=[zone_spread()])
        res = solver_cls().solve(pods, [pool], catalog)
        assert res.pods_placed() == 12
        by_zone = {}
        for spec in res.node_specs:
            assert len(spec.zone_options) == 1
            by_zone[spec.zone_options[0]] = by_zone.get(spec.zone_options[0], 0) + len(spec.pods)
        counts = sorted(by_zone.values())
        assert len(by_zone) == 4  # all four zones used
        assert counts[-1] - counts[0] <= 1  # skew <= max_skew

    def test_spread_within_allowed_zones_only(self, catalog, pool, solver_cls):
        pods = make_pods(6, "w", {"cpu": "1"}, labels={"app": "web"},
                         topology_spread=[zone_spread()],
                         node_affinity=[])
        for p in pods:
            p.node_selector = {lbl.TOPOLOGY_ZONE: "zone-a"}
        # zone-pinned + spread: everything lands in zone-a
        res = solver_cls().solve(pods, [pool], catalog)
        assert res.pods_placed() == 6
        for spec in res.node_specs:
            assert list(spec.zone_options) == ["zone-a"]


@pytest.mark.parametrize("solver_cls", [TPUSolver, HostSolver])
class TestHostnameColocation:
    def _pods(self, n, cpu="1"):
        return make_pods(
            n, "co", {"cpu": cpu, "memory": "2Gi"}, labels={"app": "db"},
            affinity=[
                PodAffinityTerm(topology_key=lbl.HOSTNAME,
                                label_selector={"app": "db"})
            ],
        )

    def test_group_lands_on_one_node(self, catalog, pool, solver_cls):
        pods = self._pods(4)
        res = solver_cls().solve(pods, [pool], catalog)
        assert res.pods_placed() == 4
        with_pods = [s for s in res.node_specs if s.pods]
        assert len(with_pods) == 1, "co-located group split across nodes"
        assert len(with_pods[0].pods) == 4
        it = catalog.get(with_pods[0].instance_type_options[0])
        assert it.vcpus >= 4  # must hold the whole unit

    def test_unfittable_unit_is_unschedulable_together(self, catalog, pool, solver_cls):
        # 4 x 200cpu = 800cpu: no single type holds the unit
        pods = self._pods(4, cpu="200")
        res = solver_cls().solve(pods, [pool], catalog)
        assert len(res.unschedulable) == 4
        assert res.pods_placed() == 0

    def test_scale_up_joins_seeded_node(self, catalog, pool, solver_cls):
        """New replicas of an already-running co-located group JOIN its node
        via the rebinder instead of launching a splitting node."""
        from karpenter_provider_aws_tpu.testenv import new_environment

        env = new_environment(
            solver=solver_cls() if solver_cls is HostSolver else None
        )
        from karpenter_provider_aws_tpu.models import NodePool, Operator, Requirement

        # pin node size so the seeded node has slack for joiners (the FFD
        # otherwise sizes the node tightly to the first unit — joining
        # replicas would pend, which is kube-consistent but not this test)
        env.apply_defaults(NodePool(
            name="default",
            requirements=[
                Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m")),
                Requirement(lbl.INSTANCE_CPU, Operator.IN, ("16",)),
            ],
        ))
        first = self._pods(2)
        for p in first:
            env.cluster.apply(p)
        env.step(3)
        assert not env.cluster.pending_pods()
        seeded = {env.cluster.pods[p.uid].node_name for p in first}
        assert len(seeded) == 1
        claims_before = set(env.cluster.nodeclaims)
        # scale up: 2 more replicas of the same group
        more = self._pods(2)
        for p in more:
            env.cluster.apply(p)
        env.step(3)
        assert not env.cluster.pending_pods()
        assert set(env.cluster.nodeclaims) == claims_before, "split the group"
        assert {env.cluster.pods[p.uid].node_name for p in more} == seeded

    def test_colocated_and_plain_pods_mix(self, catalog, pool, solver_cls):
        plain = make_pods(6, "p", {"cpu": "1", "memory": "2Gi"})
        res = solver_cls().solve(self._pods(3) + plain, [pool], catalog)
        assert res.pods_placed() == 9
        co_nodes = {
            id(s) for s in res.node_specs
            if any(p.labels.get("app") == "db" for p in s.pods)
        }
        assert len(co_nodes) == 1


def soft_zone_spread(max_skew=1):
    return TopologySpreadConstraint(
        topology_key=lbl.TOPOLOGY_ZONE, max_skew=max_skew,
        when_unsatisfiable="ScheduleAnyway", label_selector={"app": "web"},
    )


@pytest.mark.parametrize("solver_cls", [TPUSolver, HostSolver])
class TestSoftZoneSpread:
    def test_balances_when_possible(self, catalog, pool, solver_cls):
        pods = make_pods(12, "w", {"cpu": "1", "memory": "2Gi"},
                         labels={"app": "web"},
                         topology_spread=[soft_zone_spread()])
        res = solver_cls().solve(pods, [pool], catalog)
        assert res.pods_placed() == 12
        by_zone = {}
        for spec in res.node_specs:
            by_zone[spec.zone_options[0]] = (
                by_zone.get(spec.zone_options[0], 0) + len(spec.pods)
            )
        counts = sorted(by_zone.values())
        assert len(by_zone) == 4
        assert counts[-1] - counts[0] <= 1

    def test_never_unschedulable_when_constrained_to_one_zone(
        self, catalog, pool, solver_cls
    ):
        """The defining difference from DoNotSchedule: pinning every pod to
        one zone violates any skew, but ScheduleAnyway relaxes instead of
        pending."""
        pods = make_pods(6, "w", {"cpu": "1", "memory": "2Gi"},
                         labels={"app": "web"},
                         topology_spread=[soft_zone_spread()])
        for p in pods:
            p.node_selector = {lbl.TOPOLOGY_ZONE: "zone-a"}
        res = solver_cls().solve(pods, [pool], catalog)
        assert res.pods_placed() == 6
        assert not res.unschedulable
        for spec in res.node_specs:
            assert list(spec.zone_options) == ["zone-a"]

    def test_hard_spread_wins_when_both_present(self, catalog, pool, solver_cls):
        pods = make_pods(8, "w", {"cpu": "1", "memory": "2Gi"},
                         labels={"app": "web"},
                         topology_spread=[zone_spread(), soft_zone_spread(3)])
        res = solver_cls().solve(pods, [pool], catalog)
        assert res.pods_placed() == 8
        by_zone = {}
        for spec in res.node_specs:
            by_zone[spec.zone_options[0]] = (
                by_zone.get(spec.zone_options[0], 0) + len(spec.pods)
            )
        counts = sorted(by_zone.values())
        assert counts[-1] - counts[0] <= 1  # the HARD term's skew holds


@pytest.mark.parametrize("solver_cls", [TPUSolver, HostSolver])
class TestHostnameTopology:
    def test_anti_affinity_one_pod_per_node(self, catalog, pool, solver_cls):
        pods = make_pods(5, "w", {"cpu": "500m", "memory": "1Gi"},
                         labels={"app": "web"},
                         anti_affinity=[self_anti_affinity()])
        res = solver_cls().solve(pods, [pool], catalog)
        assert res.pods_placed() == 5
        assert len(res.node_specs) == 5
        for spec in res.node_specs:
            assert len(spec.pods) == 1

    def test_hostname_spread_caps_per_node(self, catalog, pool, solver_cls):
        pods = make_pods(9, "w", {"cpu": "250m", "memory": "512Mi"},
                         labels={"app": "web"},
                         topology_spread=[host_spread(max_skew=3)])
        res = solver_cls().solve(pods, [pool], catalog)
        assert res.pods_placed() == 9
        for spec in res.node_specs:
            assert len(spec.pods) <= 3

    def test_zone_anti_affinity_one_per_zone(self, catalog, pool, solver_cls):
        pods = make_pods(6, "w", {"cpu": "1"}, labels={"app": "web"},
                         anti_affinity=[self_anti_affinity(lbl.TOPOLOGY_ZONE)])
        res = solver_cls().solve(pods, [pool], catalog)
        # only 4 zones exist: 4 placed, 2 unschedulable with a clear reason
        assert res.pods_placed() == 4
        assert len(res.unschedulable) == 2
        assert "zone anti-affinity" in res.unschedulable[0][1]
        zones = [spec.zone_options[0] for spec in res.node_specs]
        assert len(zones) == len(set(zones))

    def test_zone_affinity_co_locates(self, catalog, pool, solver_cls):
        pods = make_pods(4, "w", {"cpu": "1"}, labels={"app": "web"},
                         affinity=[self_anti_affinity(lbl.TOPOLOGY_ZONE)])
        res = solver_cls().solve(pods, [pool], catalog)
        assert res.pods_placed() == 4
        zones = {spec.zone_options[0] for spec in res.node_specs}
        assert len(zones) == 1


class TestCombined:
    def test_config3_mix(self, catalog, pool):
        """Zone spread + hostname anti-affinity together (BASELINE config 3)."""
        pods = make_pods(
            8, "w", {"cpu": "1", "memory": "2Gi"}, labels={"app": "web"},
            topology_spread=[zone_spread()],
            anti_affinity=[self_anti_affinity()],
        )
        pods += make_pods(30, "filler", {"cpu": "500m", "memory": "1Gi"})
        res = TPUSolver().solve(pods, [pool], catalog)
        assert res.pods_placed() == 38
        web_nodes = [s for s in res.node_specs if any(p.labels.get("app") == "web" for p in s.pods)]
        for spec in web_nodes:
            assert sum(1 for p in spec.pods if p.labels.get("app") == "web") == 1
        by_zone = {}
        for spec in web_nodes:
            z = spec.zone_options[0]
            by_zone[z] = by_zone.get(z, 0) + 1
        counts = sorted(by_zone.values())
        assert counts[-1] - counts[0] <= 1


class TestSchedulerTopology:
    def test_rebind_respects_hostname_anti_affinity(self):
        from karpenter_provider_aws_tpu.models import Disruption
        from karpenter_provider_aws_tpu.testenv import new_environment

        env = new_environment(use_tpu_solver=False)
        env.apply_defaults(NodePool(name="default", disruption=Disruption(consolidate_after_s=None)))
        pods = make_pods(3, "w", {"cpu": "500m", "memory": "1Gi"},
                         labels={"app": "web"},
                         anti_affinity=[self_anti_affinity()])
        for p in pods:
            env.cluster.apply(p)
        env.step(2)
        assert not env.cluster.pending_pods()
        # evict one pod; the scheduler must not double it onto a sibling node
        victim = pods[0]
        # through the store so caches/journal observe the eviction
        env.cluster.unbind_pod(victim.uid)
        env.scheduling.reconcile()
        if not victim.is_pending():
            others = {p.node_name for p in pods[1:]}
            assert victim.node_name not in others


class TestHistogramExposition:
    def test_buckets_cumulative_once(self):
        from karpenter_provider_aws_tpu.metrics import Histogram

        h = Histogram("t", buckets=(1.0, 5.0, 10.0))
        h.observe(0.5)
        text = "\n".join(h.expose())
        assert 't_bucket{le="1.0"} 1' in text
        assert 't_bucket{le="5.0"} 1' in text
        assert 't_bucket{le="+Inf"} 1' in text
        assert "t_count 1" in text


class TestWaterFillEquivalence:
    """The batched water_fill/balanced_fill must replicate the sequential
    per-pod rule EXACTLY (it decides zone-spread placement shares)."""

    @staticmethod
    def _seq_water(counts, live, skew, P):
        counts = dict(counts)
        assign = {z: 0 for z in counts}
        placed = 0
        for _ in range(P):
            floor = min(counts.values())
            cands = [z for z in live if counts[z] + 1 - floor <= skew]
            if not cands:
                break
            zi = min(cands, key=lambda z: (counts[z], z))
            counts[zi] += 1
            assign[zi] += 1
            placed += 1
        return counts, assign, placed

    @staticmethod
    def _seq_balanced(counts, live, P):
        counts = dict(counts)
        assign = {}
        placed = 0
        for _ in range(P):
            if not live:
                break
            zi = min(live, key=lambda z: (counts[z], z))
            counts[zi] += 1
            assign[zi] = assign.get(zi, 0) + 1
            placed += 1
        return assign, placed

    def test_water_fill_matches_sequential(self):
        import numpy as np

        from karpenter_provider_aws_tpu.ops.encode import water_fill

        rng = np.random.RandomState(0)
        for trial in range(300):
            nz = rng.randint(1, 7)
            counts = {z: int(rng.randint(0, 6)) for z in range(nz)}
            live = {z for z in range(nz) if rng.rand() < 0.7}
            skew = int(rng.randint(1, 4))
            P = int(rng.randint(0, 40))
            want = self._seq_water(counts, live, skew, P)
            got = water_fill(counts, live, skew, P)
            assert got[1] == want[1] and got[2] == want[2], (
                trial, counts, live, skew, P, got, want
            )
            assert got[0] == want[0]

    def test_water_fill_single_live_zone_jump(self):
        from karpenter_provider_aws_tpu.ops.encode import water_fill

        # lone live zone below the rest: the fast path must not overshoot
        counts = {0: 0, 1: 9, 2: 9}
        want = self._seq_water(counts, {0}, 2, 30)
        got = water_fill(counts, {0}, 2, 30)
        assert got[1] == want[1] and got[2] == want[2]

    def test_balanced_fill_matches_sequential(self):
        import numpy as np

        from karpenter_provider_aws_tpu.ops.encode import balanced_fill

        rng = np.random.RandomState(1)
        for trial in range(300):
            nz = rng.randint(1, 7)
            counts = {z: int(rng.randint(0, 8)) for z in range(nz)}
            live = {z for z in range(nz) if rng.rand() < 0.7}
            P = int(rng.randint(0, 50))
            want = self._seq_balanced(counts, live, P)
            got = balanced_fill(counts, live, P)
            assert got[0] == want[0] and got[1] == want[1], (
                trial, counts, live, P, got, want
            )
