"""PodDisruptionBudget-aware draining (core parity: the termination
controller drains via the eviction API, which enforces PDBs — disruption
rolls through covered workloads instead of taking them down at once)."""

import pytest

from karpenter_provider_aws_tpu.models import Disruption, NodePool, Operator, Requirement
from karpenter_provider_aws_tpu.models import labels as lbl
from karpenter_provider_aws_tpu.models.pdb import PodDisruptionBudget
from karpenter_provider_aws_tpu.models.pod import make_pods
from karpenter_provider_aws_tpu.testenv import new_environment


@pytest.fixture(scope="module")
def env():
    return new_environment()


@pytest.fixture(autouse=True)
def _reset(env):
    env.reset()
    yield


def cmr_pool():
    return NodePool(
        name="default",
        requirements=[Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m"))],
        disruption=Disruption(consolidate_after_s=None),
    )


class TestDisruptionsAllowed:
    def _pods(self, n_running, n_pending=0):
        pods = make_pods(n_running + n_pending, "w", {"cpu": "1"}, labels={"app": "web"})
        for p in pods[:n_running]:
            p.node_name = "n1"
            p.phase = "Running"
        return pods

    def test_min_available_int(self):
        pdb = PodDisruptionBudget(name="pdb", selector={"app": "web"}, min_available=3)
        assert pdb.disruptions_allowed(self._pods(5)) == 2
        assert pdb.disruptions_allowed(self._pods(3)) == 0

    def test_min_available_percent_rounds_up(self):
        pdb = PodDisruptionBudget(name="pdb", selector={"app": "web"}, min_available="50%")
        # 5 pods: need ceil(2.5) = 3 -> 2 allowed
        assert pdb.disruptions_allowed(self._pods(5)) == 2

    def test_max_unavailable(self):
        pdb = PodDisruptionBudget(name="pdb", selector={"app": "web"}, max_unavailable=1)
        assert pdb.disruptions_allowed(self._pods(4)) == 1
        # one already pending (unavailable): no more allowed
        assert pdb.disruptions_allowed(self._pods(3, n_pending=1)) == 0

    def test_selector_scoping(self):
        pdb = PodDisruptionBudget(name="pdb", selector={"app": "db"}, min_available=1)
        others = self._pods(4)  # app=web: not covered
        assert all(not pdb.matches(p) for p in others)


class TestRollingDrain:
    def test_drain_respects_min_available(self, env):
        """6 covered pods, minAvailable=4: terminating their node evicts at
        most 2 per pass; the drain completes only as replacements go
        Running elsewhere, and coverage never drops below the budget."""
        env.apply_defaults(cmr_pool())
        pods = make_pods(
            6, "web", {"cpu": "1", "memory": "2Gi"}, labels={"app": "web"}
        )
        for p in pods:
            env.cluster.apply(p)
        env.step(3)
        assert not env.cluster.pending_pods()
        env.cluster.apply(
            PodDisruptionBudget(name="web-pdb", selector={"app": "web"},
                                min_available=4)
        )
        # delete every claim: worst case, the whole fleet drains at once
        for claim in list(env.cluster.nodeclaims.values()):
            env.cluster.delete(claim)
        for _ in range(12):
            running = sum(
                1 for p in env.cluster.pods.values()
                if p.node_name and p.phase == "Running"
            )
            assert running >= 4, f"budget violated: {running} running"
            env.step(1)
        # eventually everything reschedules onto replacement nodes
        assert not env.cluster.pending_pods()
        assert sum(
            1 for p in env.cluster.pods.values() if p.phase == "Running"
        ) == 6

    def test_termination_grace_force_drains(self, env):
        """terminationGracePeriod: a fully-blocking PDB holds the drain only
        until the grace deadline, then eviction force-completes (core
        v1 NodePool.spec.template.spec.terminationGracePeriod)."""
        pool = cmr_pool()
        pool.termination_grace_period_s = 300
        env.apply_defaults(pool)
        pods = make_pods(2, "db", {"cpu": "1", "memory": "2Gi"}, labels={"app": "db"})
        for p in pods:
            env.cluster.apply(p)
        env.step(3)
        env.cluster.apply(
            PodDisruptionBudget(name="db-pdb", selector={"app": "db"},
                                min_available=2)
        )
        for c in list(env.cluster.nodeclaims.values()):
            env.cluster.delete(c)
        env.step(2)
        assert any(c.deleted for c in env.cluster.nodeclaims.values())  # held
        env.clock.advance(301)
        env.step(3)
        # grace expired: claims finalized despite the blocking budget
        assert not any(c.deleted for c in env.cluster.nodeclaims.values())

    def test_fully_blocking_pdb_holds_finalizer(self, env):
        env.apply_defaults(cmr_pool())
        pods = make_pods(2, "db", {"cpu": "1", "memory": "2Gi"}, labels={"app": "db"})
        for p in pods:
            env.cluster.apply(p)
        env.step(3)
        env.cluster.apply(
            PodDisruptionBudget(name="db-pdb", selector={"app": "db"},
                                min_available=2)
        )
        claims = [c for c in env.cluster.nodeclaims.values()]
        for c in claims:
            env.cluster.delete(c)
        env.step(3)
        # pods untouched; claims still draining (finalizer held)
        assert all(p.phase == "Running" for p in env.cluster.pods.values())
        held = [c for c in env.cluster.nodeclaims.values() if c.deleted]
        assert held, "fully-blocked drain must hold the claim finalizer"
        # budget released -> drain completes
        env.cluster.delete(env.cluster.pdbs["db-pdb"])
        env.step(4)
        assert not any(c.deleted for c in env.cluster.nodeclaims.values())
