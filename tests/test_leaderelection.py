"""Leader election: single-writer + failover across two Operator replicas.

Round-3 VERDICT missing #2: the shipped ``deploy/deployment.yaml`` runs 2
replicas with ``--leader-elect=true``; without election both replicas would
double-launch nodes. These tests run two full Operator instances against
ONE fake cloud and ONE shared cluster store (the two-replicas-one-apiserver
shape) and prove exactly one writes, with takeover after leader death.
Reference: the controller-runtime manager lease, cmd/controller/main.go:34.
"""

from __future__ import annotations

import threading

from karpenter_provider_aws_tpu.fake import FakeCloud
from karpenter_provider_aws_tpu.models import Disruption, NodePool
from karpenter_provider_aws_tpu.models.nodeclass import NodeClass
from karpenter_provider_aws_tpu.models.pod import make_pods
from karpenter_provider_aws_tpu.operator.leaderelection import LeaderElector
from karpenter_provider_aws_tpu.operator.operator import new_operator
from karpenter_provider_aws_tpu.operator.options import Options
from karpenter_provider_aws_tpu.state.cluster import Cluster
from karpenter_provider_aws_tpu.utils.clock import FakeClock


def _pair():
    """Two operator replicas over one cloud + one cluster store."""
    clock = FakeClock()
    cloud = FakeCloud(clock=clock)
    cluster = Cluster(clock=clock)
    opts = dict(
        solver_backend="host", metrics_port=0, leader_elect=True,
        interruption_queue="",
    )
    a = new_operator(
        Options(leader_identity="replica-a", **opts),
        cloud=cloud, clock=clock, cluster=cluster,
    )
    b = new_operator(
        Options(leader_identity="replica-b", **opts),
        cloud=cloud, clock=clock, cluster=cluster,
    )
    return clock, cloud, cluster, a, b


class TestLease:
    def test_cas_acquire_renew_steal(self):
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        assert cloud.try_acquire_lease("l", "a", 15.0) == "a"
        # contender cannot take a live lease
        assert cloud.try_acquire_lease("l", "b", 15.0) == "a"
        # holder renews, pushing expiry forward
        clock.advance(10)
        assert cloud.try_acquire_lease("l", "a", 15.0) == "a"
        clock.advance(10)  # 20s after start, but only 10s after renew
        assert cloud.try_acquire_lease("l", "b", 15.0) == "a"
        # expiry lets the contender steal
        clock.advance(6)
        assert cloud.try_acquire_lease("l", "b", 15.0) == "b"

    def test_release_hands_off_immediately(self):
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        cloud.try_acquire_lease("l", "a", 15.0)
        cloud.release_lease("l", "a")
        assert cloud.try_acquire_lease("l", "b", 15.0) == "b"

    def test_non_holder_cannot_release(self):
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        cloud.try_acquire_lease("l", "a", 15.0)
        cloud.release_lease("l", "b")
        assert cloud.try_acquire_lease("l", "c", 15.0) == "a"


class TestSingleWriter:
    def test_only_leader_launches(self):
        clock, cloud, cluster, a, b = _pair()
        cluster.apply(NodeClass(name="default", role="node-role"))
        a.apply(NodePool(name="default", disruption=Disruption(consolidate_after_s=None)))
        for p in make_pods(8, "w", {"cpu": "1", "memory": "2Gi"}):
            cluster.apply(p)
        # both replicas tick; replica-a wins the first CAS
        for _ in range(6):
            a.manager.reconcile_all_once()
            b.manager.reconcile_all_once()
            clock.advance(1)
        assert a.manager.elector.is_leader()
        assert not b.manager.elector.is_leader()
        launched = len(cloud.instances)
        assert launched > 0
        assert not cluster.pending_pods()
        # a second follower-side sweep must not add instances
        for _ in range(3):
            b.manager.reconcile_all_once()
        assert len(cloud.instances) == launched

    def test_failover_after_leader_death(self):
        clock, cloud, cluster, a, b = _pair()
        cluster.apply(NodeClass(name="default", role="node-role"))
        a.apply(NodePool(name="default", disruption=Disruption(consolidate_after_s=None)))
        for _ in range(2):
            a.manager.reconcile_all_once()
            b.manager.reconcile_all_once()
        assert a.manager.elector.is_leader()
        # replica-a dies silently (no release): b takes over after the TTL
        clock.advance(16)
        b.manager.reconcile_all_once()
        assert b.manager.elector.is_leader()
        # and the new leader actually operates: pending pods get capacity
        for p in make_pods(4, "w", {"cpu": "1", "memory": "2Gi"}):
            cluster.apply(p)
        for _ in range(6):
            b.manager.reconcile_all_once()
            clock.advance(1)
        assert not cluster.pending_pods()
        assert len(cloud.instances) > 0

    def test_clean_shutdown_hands_off(self):
        clock, cloud, cluster, a, b = _pair()
        for _ in range(2):
            a.manager.reconcile_all_once()
            b.manager.reconcile_all_once()
        assert a.manager.elector.is_leader()
        a.manager.stop()  # releases the lease — no TTL wait
        b.manager.reconcile_all_once()
        assert b.manager.elector.is_leader()

    def test_contended_cas_is_single_winner_under_threads(self):
        """Stress: many electors hammering one lease concurrently; at every
        observation exactly one holder exists."""
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        electors = [
            LeaderElector(cloud, identity=f"r{i}", ttl_s=15.0, clock=clock)
            for i in range(8)
        ]
        stop = threading.Event()
        errors = []

        def spin(e):
            while not stop.is_set():
                try:
                    e.reconcile()
                except Exception as exc:  # pragma: no cover
                    errors.append(exc)

        threads = [threading.Thread(target=spin, args=(e,)) for e in electors]
        for t in threads:
            t.start()
        try:
            for _ in range(200):
                leaders = [e.identity for e in electors if e.is_leader()]
                assert len(leaders) <= 1, leaders
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors
        assert sum(1 for e in electors if e.is_leader()) == 1


class TestRenewDeadline:
    def test_failed_renewals_drop_leadership_locally(self):
        """Review finding: a leader whose CAS renewals FAIL must stop
        considering itself leader once the TTL passes — otherwise a
        contender steals the expired lease and both write (split-brain)."""
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        a = LeaderElector(cloud, identity="a", ttl_s=15.0, clock=clock)
        b = LeaderElector(cloud, identity="b", ttl_s=15.0, clock=clock)
        a.reconcile()
        assert a.is_leader()
        # the cloud starts failing every CAS from replica a
        import pytest as _pytest

        for _ in range(8):
            cloud.next_errors.append(RuntimeError("api down"))
            clock.advance(2.5)
            with _pytest.raises(RuntimeError):
                a.reconcile()  # Manager would swallow this; the state matters
        # >15s without a successful renew: a must drop leadership locally
        assert not a.is_leader()
        # and b can steal the expired lease; never two leaders
        b.reconcile()
        assert b.is_leader() and not a.is_leader()

    def test_stop_with_stuck_thread_keeps_lease(self):
        """Review finding: Manager.stop must NOT release the lease while a
        controller thread is still mid-reconcile."""
        import time as _time

        from karpenter_provider_aws_tpu.controllers.base import Manager

        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        elector = LeaderElector(cloud, identity="a", ttl_s=15.0, clock=clock)

        release = threading.Event()

        class Stuck:
            name = "stuck"
            interval_s = 0.01

            def reconcile(self):
                release.wait(10.0)

        mgr = Manager([Stuck()], elector=elector)
        mgr.start()
        deadline = _time.monotonic() + 5
        while not elector.is_leader() and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert elector.is_leader()
        mgr.stop(timeout=0.2)  # stuck thread cannot join in time
        # the lease must still be held: a contender cannot take it
        assert cloud.try_acquire_lease(elector.lease_name, "b", 15.0) == "a"
        release.set()


class TestBoundaries:
    """Clock-skew / exact-TTL-boundary contract (PR 9 satellite): the
    renew deadline sits STRICTLY inside the TTL, renewals are dated from
    BEFORE the CAS round-trip, boundary ties go to safety, and identity
    collisions cannot mint two leaders."""

    def test_exact_renew_deadline_boundary_is_stale(self):
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        a = LeaderElector(cloud, identity="a", ttl_s=15.0, clock=clock)
        a.reconcile()
        assert a.is_leader()
        clock.advance(10.0)  # exactly ttl * 2/3
        assert not a.is_leader()  # AT the deadline is already too late

    def test_just_inside_deadline_still_leader(self):
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        a = LeaderElector(cloud, identity="a", ttl_s=15.0, clock=clock)
        a.reconcile()
        clock.advance(9.999)
        assert a.is_leader()

    def test_renewal_dated_before_the_cas_call(self):
        """A slow lease host must not inflate local freshness: the renew
        timestamp is captured BEFORE the CAS, so 3s of call latency eats
        INTO the deadline window instead of extending it."""
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)

        class SlowCloud:
            def try_acquire_lease_fenced(self, name, holder, ttl_s, nonce=""):
                out = cloud.try_acquire_lease_fenced(name, holder, ttl_s,
                                                     nonce=nonce)
                clock.advance(3.0)  # the call itself took 3 virtual secs
                return out

            def release_lease(self, name, holder):
                cloud.release_lease(name, holder)

        a = LeaderElector(SlowCloud(), identity="a", ttl_s=15.0, clock=clock)
        a.reconcile()
        # 3s already elapsed inside the call; 7s more reaches the 10s
        # deadline measured from the PRE-call instant
        clock.advance(7.0)
        assert not a.is_leader()

    def test_paused_leader_resume_within_ttl_keeps_lease(self):
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        a = LeaderElector(cloud, identity="a", ttl_s=15.0, clock=clock)
        b = LeaderElector(cloud, identity="b", ttl_s=15.0, clock=clock)
        a.reconcile()
        clock.advance(9.0)  # paused, but inside the TTL
        b.reconcile()       # contender cannot steal a live lease
        assert not b.is_leader()
        a.reconcile()       # resume: renews its own lease
        assert a.is_leader() and not b.is_leader()

    def test_paused_leader_resume_past_ttl_no_double_leader(self):
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        a = LeaderElector(cloud, identity="a", ttl_s=15.0, clock=clock)
        b = LeaderElector(cloud, identity="b", ttl_s=15.0, clock=clock)
        a.reconcile()
        clock.advance(16.0)     # paused past the TTL
        assert not a.is_leader()  # local deadline stood it down long ago
        b.reconcile()
        assert b.is_leader()
        a.reconcile()           # resumed leader sees the new holder
        assert not a.is_leader()
        assert b.is_leader()

    def test_identity_collision_single_leader(self):
        """Two elector INSTANCES misconfigured with one identity string:
        the fenced lease host distinguishes them by nonce, so exactly one
        leads (the legacy identity-only CAS would have made both
        leaders — the split-brain this satellite closes)."""
        clock = FakeClock()
        cloud = FakeCloud(clock=clock)
        a1 = LeaderElector(cloud, identity="x", ttl_s=15.0, clock=clock)
        a2 = LeaderElector(cloud, identity="x", ttl_s=15.0, clock=clock)
        a1.reconcile()
        a2.reconcile()
        leaders = [e for e in (a1, a2) if e.is_leader()]
        assert len(leaders) == 1
        # and the twin takes over only after the real holder's TTL lapses
        clock.advance(16.0)
        a2.reconcile()
        assert a2.is_leader() and not a1.is_leader()

    def test_bounded_clock_skew_never_two_leaders(self):
        """A leader whose local clock runs SLOW under-counts its elapsed
        time — the renewDeadline margin (2/3 of the TTL) tolerates rate
        skew up to ttl/deadline = 1.5x. At 25% slow (well inside the
        bound) the old leader must stand down strictly before the host
        would let a contender steal."""
        host_clock = FakeClock()
        slow_clock = FakeClock()
        cloud = FakeCloud(clock=host_clock)
        a = LeaderElector(cloud, identity="a", ttl_s=15.0, clock=slow_clock)
        b = LeaderElector(cloud, identity="b", ttl_s=15.0, clock=host_clock)
        a.reconcile()
        assert a.is_leader()
        # host time marches to just before expiry; a's clock saw only 75%
        for _ in range(15):
            host_clock.advance(0.999)
            slow_clock.advance(0.749)
            b.reconcile()
            # never two leaders at any observation
            assert not (a.is_leader() and b.is_leader())
        # past expiry on the host: b steals; a's local deadline (10s at
        # 0.75 rate = 13.3 host secs < 15) already stood it down
        host_clock.advance(0.1)
        b.reconcile()
        assert b.is_leader() and not a.is_leader()
