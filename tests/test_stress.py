"""Concurrency stress tier (parity: the reference's `make deflake` —
ginkgo --until-it-fails --race, Makefile:66-73).

Python has no -race, so these tests manufacture contention instead: many
threads hammering the shared substrates (batcher, cluster store) while
assertions check linearizable outcomes. Each test is deterministic in its
assertions — only the interleavings vary run to run.
"""

import threading
import time

import numpy as np
import pytest

from karpenter_provider_aws_tpu.utils.batcher import Batcher, BatcherOptions


class TestBatcherFanOut:
    def test_slow_batch_does_not_serialize_other_buckets(self):
        """A stuck create_fleet for bucket A must not delay bucket B's
        flush (batcher.go:71-95 worker fan-out; round-1/2 finding: the
        executor ran inline on the shared timer thread)."""
        release_a = threading.Event()

        def executor(reqs):
            if reqs[0][0] == "a":
                release_a.wait(timeout=10)
            return [f"done-{r}" for r in reqs]

        b = Batcher(
            executor,
            hasher=lambda r: r[0],
            options=BatcherOptions(idle_timeout_s=0.01, max_timeout_s=0.1),
        )
        try:
            results: dict[str, object] = {}

            def call(tag):
                results[tag] = b.add((tag[0], tag))

            ta = threading.Thread(target=call, args=("a1",))
            ta.start()
            time.sleep(0.05)  # bucket A flushed and stuck in its worker
            t0 = time.monotonic()
            tb = threading.Thread(target=call, args=("b1",))
            tb.start()
            tb.join(timeout=5)
            b_latency = time.monotonic() - t0
            assert not tb.is_alive()
            assert results["b1"] == "done-('b', 'b1')"
            # inline execution would have pinned B behind A's 10s wait
            assert b_latency < 2.0, f"bucket B serialized behind A: {b_latency:.1f}s"
            release_a.set()
            ta.join(timeout=5)
            assert results["a1"] == "done-('a', 'a1')"
        finally:
            release_a.set()
            b.close()

    def test_hammer_add_while_executor_sleeps(self):
        """32 threads x 25 adds against a sleepy executor: every caller gets
        exactly its own result, nothing lost, nothing crossed."""
        def executor(reqs):
            time.sleep(0.002)
            return [("echo", r) for r in reqs]

        b = Batcher(
            executor,
            hasher=lambda r: r % 4,
            options=BatcherOptions(idle_timeout_s=0.005, max_timeout_s=0.05, max_items=64),
        )
        try:
            out: dict[int, object] = {}
            errors: list[Exception] = []
            lock = threading.Lock()

            def worker(base):
                for i in range(25):
                    v = base * 100 + i
                    try:
                        r = b.add(v)
                    except Exception as e:  # pragma: no cover
                        with lock:
                            errors.append(e)
                        return
                    with lock:
                        out[v] = r

            threads = [threading.Thread(target=worker, args=(t,)) for t in range(32)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert not errors
            assert len(out) == 32 * 25
            for v, r in out.items():
                assert r == ("echo", v), (v, r)
            # coalescing actually happened (not one wire call per request)
            assert b.batches_executed < 32 * 25
        finally:
            b.close()

    def test_executor_failure_fans_out_to_its_batch_only(self):
        def executor(reqs):
            if any(r < 0 for r in reqs):
                raise RuntimeError("poisoned batch")
            return list(reqs)

        b = Batcher(
            executor,
            hasher=lambda r: r < 0,
            options=BatcherOptions(idle_timeout_s=0.005, max_timeout_s=0.05),
        )
        try:
            oks: list[int] = []
            fails: list[int] = []
            lock = threading.Lock()

            def call(v):
                try:
                    r = b.add(v)
                    with lock:
                        oks.append(r)
                except RuntimeError:
                    with lock:
                        fails.append(v)

            threads = [threading.Thread(target=call, args=(v,)) for v in (-1, -2, 1, 2, 3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            assert sorted(oks) == [1, 2, 3]
            assert sorted(fails) == [-2, -1]
        finally:
            b.close()


class TestClusterStoreChurn:
    def test_bind_delete_churn_vs_bulk_views(self):
        """Writers bind/delete pods while readers take bulk views. During the
        churn the readers exercise concurrent access (each view is one locked
        pass — crashes/torn iteration would surface here); equality between
        node_usage() and pods_by_node() is asserted once the writers stop
        (two separate snapshots can't be compared mid-churn)."""
        from karpenter_provider_aws_tpu.models.pod import make_pods
        from karpenter_provider_aws_tpu.state.cluster import Cluster, Node

        cluster = Cluster()
        for i in range(8):
            cluster.apply(Node(name=f"n{i}", ready=True))
        stop = threading.Event()
        errors: list[Exception] = []

        def writer(wid):
            rng = np.random.RandomState(wid)
            while not stop.is_set():
                pods = make_pods(5, f"w{wid}", {"cpu": "100m", "memory": "128Mi"})
                for p in pods:
                    cluster.apply(p)
                    cluster.bind_pod(p.uid, f"n{rng.randint(8)}")
                # leave the last pod of every 10th batch bound, so the final
                # consistency check sees a non-trivial state
                keep = rng.randint(10) == 0
                for p in (pods[:-1] if keep else pods):
                    cluster.delete(p)

        def reader():
            try:
                while not stop.is_set():
                    usage = cluster.node_usage()
                    by_node = cluster.pods_by_node()
                    for name, pods in by_node.items():
                        assert all(p.node_name == name for p in pods)
                    for name in usage:
                        assert name.startswith("n")
            except Exception as e:  # pragma: no cover
                errors.append(e)

        writers = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
        readers = [threading.Thread(target=reader) for _ in range(2)]
        for t in writers + readers:
            t.start()
        time.sleep(0.5)
        stop.set()
        for t in writers + readers:
            t.join(timeout=10)
        assert not errors
        # quiesced: the two bulk views must agree exactly
        usage = cluster.node_usage()
        by_node = cluster.pods_by_node()
        assert set(usage) == set(by_node)
        for name, pods in by_node.items():
            expect = sum(p.requests.v for p in pods)
            np.testing.assert_allclose(usage[name], expect, rtol=1e-6)


class TestControllerChurnLoop:
    def test_provision_disrupt_churn(self):
        """Drive the full control plane through pod churn: apply pending
        pods, step controllers, delete half, step again — repeatedly. The
        invariant after every round: no pod bound onto a node past its
        allocatable, no claim leaked without a pool."""
        from karpenter_provider_aws_tpu.models import Disruption, NodePool, Operator, Requirement
        from karpenter_provider_aws_tpu.models import labels as lbl
        from karpenter_provider_aws_tpu.models.pod import make_pods
        from karpenter_provider_aws_tpu.testenv import new_environment

        env = new_environment()
        env.apply_defaults(
            NodePool(
                name="default",
                requirements=[
                    Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m"))
                ],
                disruption=Disruption(consolidate_after_s=1, budgets=["100%"]),
            )
        )
        rng = np.random.RandomState(3)
        live_pods = []
        for round_i in range(5):
            newp = make_pods(
                20, f"r{round_i}", {"cpu": f"{int(rng.choice([250, 500, 1000]))}m", "memory": "512Mi"}
            )
            for p in newp:
                env.cluster.apply(p)
            live_pods.extend(newp)
            env.step(3)
            # kill a random half of the running pods
            rng.shuffle(live_pods)
            drop, live_pods = live_pods[: len(live_pods) // 2], live_pods[len(live_pods) // 2:]
            for p in drop:
                env.cluster.delete(p)
            env.clock.advance(2)
            env.step(2)
            usage = env.cluster.node_usage()
            for node in env.cluster.nodes.values():
                used = usage.get(node.name)
                if used is None:
                    continue
                assert (used <= node.allocatable.v + 1e-6).all(), node.name
            for claim in env.cluster.nodeclaims.values():
                assert claim.nodepool_name in env.cluster.nodepools
