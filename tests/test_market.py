"""Market-engine suite: moving prices, reserved-capacity windows, cost
under volatility (designs/market-engine.md).

Pins the four load-bearing properties of the market engine:

1. **Determinism** — a seeded :class:`MarketModel` is a pure function of
   ``(seed, coordinates, tick)``: same seed => byte-identical price
   traces, different seed => different market (3-seed property test).
2. **Kill switch** — ``KARPENTER_TPU_MARKET=0`` restores the static
   catalog bit-for-bit: tensors, cache key, and the FFD plan are
   identical to a provider that never constructed market state.
3. **Plan quality** — every optimizer-lane-ADOPTED plan under a MARKET
   scenario places all pods and is STRICTLY cheaper than the FFD oracle
   at the current tick's prices (adoption implies host validation).
4. **Offering windows** — expired or slot-exhausted reservation windows
   never win a price sort (the ``cheapest_price`` regression) and never
   light the reserved tensor column.

Plus the staleness probe: ``karpenter_pricing_age_seconds{source}`` and
the ``PricingStale`` Warning once a refreshed source crosses the TTL.
"""

from __future__ import annotations

import numpy as np
import pytest

from karpenter_provider_aws_tpu.catalog.instancetypes import Offering
from karpenter_provider_aws_tpu.catalog.pricing import (
    PRICING_STALE_TTL_S,
    MarketModel,
    PricingProvider,
)
from karpenter_provider_aws_tpu.catalog.provider import CatalogProvider
from karpenter_provider_aws_tpu.catalog.reservations import Reservation
from karpenter_provider_aws_tpu.market import (
    OfferingWindow,
    apply_window_columns,
    windows_cache_key,
    windows_from_reservations,
)
from karpenter_provider_aws_tpu.market.offerings import EXPIRED, OPEN, PENDING
from karpenter_provider_aws_tpu.market.scenarios import market_catalog
from karpenter_provider_aws_tpu.models import labels as lbl
from karpenter_provider_aws_tpu.utils.clock import FakeClock

SEEDS = (0, 1, 2)


def _price_trace(seed: int, ticks: int = 6) -> list:
    """The full walked market for ``seed``: every (type, zone) spot price
    and reclaim probability at each of ``ticks`` hourly steps."""
    catalog, model = market_catalog(seed, "market-day")
    out = []
    for t in range(ticks):
        if t:
            catalog._clock.advance(3600.0)
            model.apply(catalog)
        now = catalog._clock.now()
        for it in catalog.list():
            for o in it.offerings:
                if o.capacity_type != lbl.CAPACITY_TYPE_SPOT:
                    continue
                out.append((
                    t, it.name, o.zone,
                    catalog.pricing.spot_price(it, o.zone),
                    round(model.reclaim_probability(it.name, o.zone, now), 9),
                ))
    return out


class TestMarketDeterminism:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_same_seed_byte_identical(self, seed):
        a, b = _price_trace(seed), _price_trace(seed)
        assert repr(a) == repr(b)  # byte-identical, not just approx-equal

    def test_different_seeds_differ(self):
        assert repr(_price_trace(0)) != repr(_price_trace(1))

    def test_walk_moves_and_stays_bounded(self):
        catalog, model = market_catalog(0, "market-day")
        it = catalog.list()[0]
        zone = it.offerings[0].zone
        base = catalog.pricing.base_spot_price(it, zone)
        mults = set()
        for h in range(24):
            m = model.spot_multiplier(it.name, zone, h * 3600.0)
            assert m >= 0.2
            mults.add(round(m, 6))
        assert len(mults) > 1, "a market that never moves is a still photo"
        assert base > 0

    def test_apply_never_compounds(self):
        # two applies at the same instant are idempotent: the walk rides
        # the OVERRIDE-IGNORING base table, so ticks compose as
        # base x multiplier, never walked x multiplier
        catalog, model = market_catalog(1, "market-day")
        it = catalog.list()[0]
        zone = it.offerings[0].zone
        p1 = catalog.pricing.spot_price(it, zone)
        model.apply(catalog)
        assert catalog.pricing.spot_price(it, zone) == p1


class TestKillSwitch:
    @staticmethod
    def _virgin(clk: FakeClock, reservations):
        """A provider that NEVER constructed market state (the pre-PR
        shape): same clock, same reservation rows, no model."""
        cat = CatalogProvider(clock=clk, pricing=PricingProvider(clock=clk))
        if reservations:
            cat.reservations.update(reservations)
        return cat

    @pytest.mark.parametrize("seed", SEEDS)
    def test_tensors_and_key_byte_identical(self, seed, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_MARKET", "0")
        catalog, model = market_catalog(seed, "market-day")
        catalog._clock.advance(7200.0)
        assert model.apply(catalog) == 0  # the switch gates the walk too
        virgin = self._virgin(catalog._clock, catalog.reservations.list())
        mt, vt = catalog.tensors(), virgin.tensors()
        assert np.array_equal(mt.price, vt.price)
        assert np.array_equal(mt.available, vt.available)
        assert np.array_equal(mt.capacity, vt.capacity)
        # the market fragment must degrade to (): the cache key is the
        # exact pre-market tuple shape
        assert catalog._market_fragment() == ()

    def test_plan_byte_identical_when_off(self, monkeypatch):
        from benchmarks.optimizer_bench import _pool, frag_workload

        from karpenter_provider_aws_tpu.ops.encode import encode_problem
        from karpenter_provider_aws_tpu.scheduling.oracle import ffd_oracle

        monkeypatch.setenv("KARPENTER_TPU_MARKET", "0")
        catalog, _model = market_catalog(0, "market-day")
        virgin = self._virgin(catalog._clock, catalog.reservations.list())
        pods = frag_workload(0)
        pool = _pool()

        def plan(cat):
            nodes, un = ffd_oracle(encode_problem(pods, cat, nodepool=pool))
            return [
                (n.type_index, n.price, n.window.tobytes(),
                 sorted(n.group_counts.items()))
                for n in nodes
            ], un

        assert plan(catalog) == plan(virgin)

    def test_market_on_actually_moves_prices(self):
        # the converse guard: with the switch ON (default), the walked
        # catalog differs from the virgin one — otherwise the kill-switch
        # test above is vacuously green
        catalog, _model = market_catalog(0, "market-day")
        virgin = self._virgin(catalog._clock, catalog.reservations.list())
        assert not np.array_equal(
            catalog.tensors().price, virgin.tensors().price
        )


class TestAdoptedPlansUnderMarket:
    def test_adopted_plans_place_all_and_beat_oracle(self):
        """Every lane-ADOPTED plan under a MARKET scenario host-validates
        (all pods placed, nothing unschedulable) and is STRICTLY cheaper
        than the FFD oracle at the CURRENT tick's prices."""
        from benchmarks.optimizer_bench import _pool, frag_workload

        from karpenter_provider_aws_tpu.ops.encode import encode_problem
        from karpenter_provider_aws_tpu.scheduling import TPUSolver
        from karpenter_provider_aws_tpu.scheduling.oracle import (
            ffd_oracle,
            oracle_cost,
        )

        pool = _pool()
        tpu = TPUSolver()
        adopted = 0
        for seed in (0, 1):
            catalog, model = market_catalog(seed, "market-day")
            pods = frag_workload(seed)
            for tick in range(2):
                if tick:
                    catalog._clock.advance(3600.0)
                    model.apply(catalog)
                res = tpu.solve(pods, [pool], catalog)
                nodes, un = ffd_oracle(
                    encode_problem(pods, catalog, nodepool=pool))
                assert not un, "oracle itself must place the workload"
                assert res.pods_placed() == len(pods)
                assert not res.unschedulable
                base = oracle_cost(nodes)
                if tpu.timings.get("opt_lane") == "adopted":
                    adopted += 1
                    assert res.total_cost < base, (
                        f"adopted plan not cheaper at tick {tick}: "
                        f"{res.total_cost} >= {base}"
                    )
                else:
                    assert res.total_cost <= base * (1 + 1e-9)
        assert adopted >= 1, "no MARKET sample adopted the optimizer plan"


class TestOfferingWindows:
    def test_lifecycle(self):
        w = OfferingWindow(id="w", instance_type="c7g.xlarge", zone="z",
                           slots=4, committed_price=0.1,
                           start_s=100.0, end_s=200.0)
        assert w.state_at(50.0) == PENDING and not w.open_at(50.0)
        assert w.state_at(100.0) == OPEN and w.open_at(100.0)
        assert w.state_at(200.0) == EXPIRED and not w.open_at(200.0)
        # slot exhaustion closes an otherwise-open window
        full = OfferingWindow(id="f", instance_type="t", zone="z",
                              slots=2, used=2)
        assert full.state_at(0.0) == OPEN and not full.open_at(0.0)

    def test_apply_window_columns(self):
        names, zones = ("a", "b"), ("z1",)
        T, Z, C = len(names), len(zones), lbl.NUM_CAPACITY_TYPES
        ci = lbl.RESERVED_INDEX
        price = np.full((T, Z, C), np.inf, dtype=np.float32)
        avail = np.zeros((T, Z, C), dtype=bool)
        windows = [
            OfferingWindow(id="open", instance_type="a", zone="z1",
                           slots=2, committed_price=0.5),
            # cheaper window on the same cell must win the min
            OfferingWindow(id="cheaper", instance_type="a", zone="z1",
                           slots=1, committed_price=0.2),
            OfferingWindow(id="expired", instance_type="b", zone="z1",
                           slots=2, committed_price=0.0, end_s=10.0),
            OfferingWindow(id="exhausted", instance_type="b", zone="z1",
                           slots=2, used=2, committed_price=0.0),
        ]
        lit = apply_window_columns(price, avail, names, zones, windows,
                                   now=100.0)
        assert lit == 2  # both live windows land on the same cell
        assert avail[0, 0, ci] and price[0, 0, ci] == np.float32(0.2)
        assert not avail[1, 0, ci] and price[1, 0, ci] == np.inf

    def test_cache_key_tracks_bounded_windows_only(self):
        odcr = OfferingWindow(id="odcr", instance_type="a", zone="z",
                              slots=2)
        block = OfferingWindow(id="blk", instance_type="a", zone="z",
                               slots=2, start_s=100.0, end_s=200.0)
        assert windows_cache_key([odcr], 0.0) == ()
        assert windows_cache_key([odcr, block], 50.0) == (("blk", PENDING),)
        assert windows_cache_key([odcr, block], 150.0) == (("blk", OPEN),)
        assert windows_cache_key([odcr, block], 250.0) == (("blk", EXPIRED),)

    def test_expiry_darkens_the_tensor_column(self):
        clk = FakeClock()
        catalog = CatalogProvider(clock=clk,
                                  pricing=PricingProvider(clock=clk))
        itype = catalog.list()[0].name
        zone = catalog.zones[0]
        catalog.reservations.update([Reservation(
            id="r", instance_type=itype, zone=zone, count=4,
            end_s=1000.0,
        )])
        ti = catalog.tensors().names.index(itype)
        ci = lbl.RESERVED_INDEX
        assert catalog.tensors().available[ti, 0, ci]
        clk.advance(1000.0)  # the window dies; only the CLOCK moved
        t2 = catalog.tensors()
        assert not t2.available[ti, 0, ci]
        assert t2.price[ti, 0, ci] == np.inf


class TestCheapestPriceRegression:
    def _it(self, offerings):
        catalog = CatalogProvider()
        it = catalog.list()[0]
        import dataclasses

        return dataclasses.replace(it, offerings=offerings)

    def test_exhausted_window_cannot_win_the_sort(self):
        it = self._it([
            Offering(zone="z1", capacity_type=lbl.CAPACITY_TYPE_ON_DEMAND,
                     price=1.0, available=True),
            # price 0, available=True, but zero slots remain: pre-fix this
            # won every cheapest-price sort while selling nothing
            Offering(zone="z1", capacity_type=lbl.CAPACITY_TYPE_RESERVED,
                     price=0.0, available=True, remaining=0),
        ])
        assert it.cheapest_price() == 1.0

    def test_expired_window_cannot_win_the_sort(self):
        it = self._it([
            Offering(zone="z1", capacity_type=lbl.CAPACITY_TYPE_ON_DEMAND,
                     price=1.0, available=True),
            Offering(zone="z1", capacity_type=lbl.CAPACITY_TYPE_RESERVED,
                     price=0.0, available=True, remaining=3,
                     expires_at=500.0),
        ])
        assert it.cheapest_price(now=600.0) == 1.0
        # ... but the same window IS the cheapest while it lives
        assert it.cheapest_price(now=400.0) == 0.0

    def test_open_ended_reserved_still_wins(self):
        it = self._it([
            Offering(zone="z1", capacity_type=lbl.CAPACITY_TYPE_ON_DEMAND,
                     price=1.0, available=True),
            Offering(zone="z1", capacity_type=lbl.CAPACITY_TYPE_RESERVED,
                     price=0.0, available=True, remaining=3),
        ])
        assert it.cheapest_price() == 0.0


class TestStaleness:
    def test_gauge_and_stale_event(self):
        from karpenter_provider_aws_tpu.events import EventRecorder
        from karpenter_provider_aws_tpu.metrics import PRICING_AGE

        clk = FakeClock()
        pricing = PricingProvider(clock=clk)
        catalog = CatalogProvider(clock=clk, pricing=pricing)
        rec = EventRecorder(clock=clk)
        # never refreshed: static-catalog processes must not report/page
        assert pricing.observe_staleness(recorder=rec) == {}
        it = catalog.list()[0]
        zone = it.offerings[0].zone
        pricing.update_spot({(it.name, zone): 0.123})
        clk.advance(10.0)
        ages = pricing.observe_staleness(recorder=rec)
        assert ages == {"spot": 10.0}
        assert PRICING_AGE.value(source="spot") == 10.0
        assert not [e for e in rec.events() if e.reason == "PricingStale"]
        clk.advance(PRICING_STALE_TTL_S)
        ages = pricing.observe_staleness(recorder=rec)
        assert ages["spot"] > PRICING_STALE_TTL_S
        stale = [e for e in rec.events() if e.reason == "PricingStale"]
        assert stale and stale[0].type == "Warning"
        assert PRICING_AGE.value(source="spot") == ages["spot"]

    def test_reservation_windows_ride_discovery(self):
        """The fake cloud's CapacityReservation window fields survive the
        nodeclass-status publish into the reservation store — the path a
        real capacity block takes into the tensors."""
        res = Reservation(id="cb", instance_type="c7g.xlarge", zone="z",
                          count=4, start_s=50.0, end_s=150.0,
                          committed_price=0.25)
        (w,) = windows_from_reservations([res])
        assert (w.start_s, w.end_s, w.committed_price) == (50.0, 150.0, 0.25)
        assert w.state_at(0.0) == PENDING
        assert w.state_at(100.0) == OPEN
        assert w.state_at(150.0) == EXPIRED
