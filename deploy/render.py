"""Render deploy manifests from values.yaml (the Helm-template analogue).

Usage: python deploy/render.py [--values deploy/values.yaml] [--out -]
Substitutes ${key} / ${a.b} placeholders; no external deps (tiny flat-YAML
reader, sufficient for values.yaml's two-level structure).
"""

from __future__ import annotations

import argparse
import pathlib
import re
import sys

HERE = pathlib.Path(__file__).resolve().parent
MANIFESTS = ("rbac.yaml", "deployment.yaml", "pdb-and-service.yaml", "webhooks.yaml")


def load_values(path: pathlib.Path) -> dict[str, str]:
    """Flatten two-level yaml into {'a': x, 'a.b': y} string values."""
    out: dict[str, str] = {}
    stack: list[str] = []
    for raw in path.read_text().splitlines():
        line = raw.split("#", 1)[0].rstrip()
        if not line.strip():
            continue
        indent = len(line) - len(line.lstrip())
        key, _, val = line.strip().partition(":")
        raw_val = val.strip()
        level = indent // 2
        stack = stack[:level]
        if raw_val:  # '""' is an explicit empty scalar, not a section
            out[".".join(stack + [key])] = raw_val.strip("\"'")
        else:
            stack.append(key)
    return out


def render(text: str, values: dict[str, str]) -> str:
    def sub(m: re.Match) -> str:
        k = m.group(1)
        if k not in values:
            raise SystemExit(f"no value for ${{{k}}}")
        return values[k]

    return re.sub(r"\$\{([a-zA-Z0-9_.]+)\}", sub, text)


def _import_crds():
    sys.path.insert(0, str(HERE.parent))
    from karpenter_provider_aws_tpu.operator import crds

    return crds


def webhook_cert_values(service: str = "karpenter-tpu",
                        namespace: str = "karpenter") -> dict[str, str]:
    """Generate the webhook serving cert at render time: a fresh
    self-signed pair whose SAN covers the webhook Service DNS names, plus
    the caBundle the registrations embed — so the rendered manifests work
    as applied with no external cert manager (the reference instead runs a
    knative cert injector at runtime; re-render to rotate here)."""
    import base64
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                         f"{service}.{namespace}.svc")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=3650))
        .add_extension(x509.SubjectAlternativeName([
            x509.DNSName(service),
            x509.DNSName(f"{service}.{namespace}"),
            x509.DNSName(f"{service}.{namespace}.svc"),
            x509.DNSName(f"{service}.{namespace}.svc.cluster.local"),
        ]), critical=False)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption(),
    )
    b64 = lambda b: base64.b64encode(b).decode()  # noqa: E731
    return {
        "webhookCertData": b64(cert_pem),
        "webhookKeyData": b64(key_pem),
        # self-signed: the serving cert IS the trust anchor
        "webhookCaBundle": b64(cert_pem),
    }


def _crd_docs() -> list[str]:
    """CRD artifacts with the admission rules encoded (parity: the
    reference bundles pkg/apis/crds/ into its chart). JSON is valid YAML,
    so the docs concatenate into the same stream."""
    import json

    crds = _import_crds()
    return [
        json.dumps(crds.nodeclass_crd(), indent=1),
        json.dumps(crds.nodepool_crd(), indent=1),
    ]


KEY_PLACEHOLDER = "RENDERED-TO-FILE-SEE-STDERR"


def _write_private(path: pathlib.Path, data: bytes) -> None:
    """Write key-bearing content 0600. fchmod, not just the open mode:
    the mode argument only applies at CREATION, so re-rendering over a
    file a pre-hardening run left 0644 must still tighten it."""
    import os

    path.parent.mkdir(parents=True, exist_ok=True)
    fd = os.open(str(path), os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    os.fchmod(fd, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(data)


def _write_key_file(path: pathlib.Path, key_b64: str) -> None:
    """Key material lands in a 0600 file, never in a pipe: stdout gets
    captured by shells, CI logs, and `kubectl apply -f -` transcripts —
    none of which should hold a TLS private key."""
    import base64

    _write_private(path, base64.b64decode(key_b64))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--values", default=str(HERE / "values.yaml"))
    ap.add_argument("--out", default="-", help="'-' for stdout, else a directory")
    ap.add_argument(
        "--key-out", default=str(HERE / "webhook-tls.key"),
        help="where the generated TLS private key is written (0600) when "
             "rendering to stdout; the streamed Secret carries a "
             "placeholder to patch from this file",
    )
    args = ap.parse_args()
    values = load_values(pathlib.Path(args.values))
    values.update(webhook_cert_values())
    key_b64 = values["webhookKeyData"]
    if args.out == "-":
        # the private key NEVER reaches stdout: it goes to --key-out and
        # the rendered Secret carries a placeholder the operator patches
        # (kubectl create secret tls ... --key deploy/webhook-tls.key)
        key_path = pathlib.Path(args.key_out)
        _write_key_file(key_path, key_b64)
        import base64

        values["webhookKeyData"] = base64.b64encode(
            KEY_PLACEHOLDER.encode()
        ).decode()
        docs = [render((HERE / m).read_text(), values) for m in MANIFESTS]
        sys.stdout.write("\n---\n".join(_crd_docs() + docs))
        print(
            f"webhook TLS private key written to {key_path} (0600); the "
            "streamed Secret's tls.key is a placeholder — patch it from "
            "that file before applying",
            file=sys.stderr,
        )
    else:
        docs = [render((HERE / m).read_text(), values) for m in MANIFESTS]
        outdir = pathlib.Path(args.out)
        outdir.mkdir(parents=True, exist_ok=True)
        for name, doc in zip(MANIFESTS, docs):
            if name == "webhooks.yaml":
                # this manifest embeds the serving key — 0600 like the
                # key file, not the umask default a backup/artifact
                # upload would sweep up world-readable
                _write_private(outdir / name, doc.encode())
            else:
                (outdir / name).write_text(doc)
        _write_key_file(outdir / "webhook-tls.key", key_b64)
        written = _import_crds().write_crds(outdir / "crds")
        print(
            f"rendered {len(MANIFESTS)} manifests + {len(written)} CRDs to {outdir}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
