# Developer entry points (parity: the reference's Makefile targets —
# presubmit/test at Makefile:59-64, deflake at :66-73, e2etests at :75-88,
# benchmark at :90-91).

PYTEST ?= python -m pytest

.PHONY: presubmit test deflake stress e2etests benchmark interruption-bench verify multichip native soak sidecar-client sim-smoke sim-sweep sim-cliff-smoke bench-gate bench-optimizer bench-market bench-gang market-smoke gang-smoke chaos-smoke sim-replica-smoke sim-provision-smoke fleet-obs-smoke device-obs-smoke warmup-smoke why-smoke

presubmit: test multichip  ## everything CI gates on

test:  ## hermetic unit/behavior suites (CPU, no cloud)
	$(PYTEST) tests/ -q

deflake:  ## re-run the concurrency-sensitive suites until they fail (Ctrl-C to stop)
	@i=1; while $(PYTEST) tests/test_stress.py tests/test_multichip.py \
		tests/test_events.py -q; do \
		echo "deflake pass $$i clean"; i=$$((i+1)); done

stress:  ## one pass over the concurrency stress tier
	$(PYTEST) tests/test_stress.py -q

e2etests:  ## end-to-end suites against the fake cloud (serial, like the reference)
	$(PYTEST) tests/e2e/ -q -p no:randomly

benchmark:  ## the one-JSON-line bench on whatever accelerator is live
	python bench.py

interruption-bench:  ## reference tiers: 100/1k/5k/15k messages
	python -c "from benchmarks.interruption_bench import run_all; run_all()"

multichip:  ## the driver's multi-chip dry run on a virtual 8-device mesh
	python -c "import os; os.environ['XLA_FLAGS']='--xla_force_host_platform_device_count=8'; \
		import jax; jax.config.update('jax_platforms','cpu'); \
		import __graft_entry__ as g; fn,a=g.entry(); jax.jit(fn)(*a); \
		g.dryrun_multichip(8); print('multichip OK')"

native: sidecar-client  ## build the C++ artifacts (FFD kernel lib + gRPC sidecar client)
	python -c "from karpenter_provider_aws_tpu.scheduling.native import native_available; \
		assert native_available(), 'native FFD build failed'; print('libffd OK')"

sidecar-client: native/build/sidecar_client  ## the zero-Python gRPC client

native/build/sidecar_client: tools/sidecar_client.cpp
	mkdir -p native/build
	g++ -O2 -o native/build/sidecar_client tools/sidecar_client.cpp -ldl -lz
	@echo sidecar_client OK

soak:  ## randomized churn with convergence invariants (SOAK_ROUNDS scales)
	SOAK_ROUNDS=$${SOAK_ROUNDS:-150} $(PYTEST) tests/test_soak.py -q

sim-smoke:  ## 500-node 2-simulated-hour fleet run under the SLO regression gate
	JAX_PLATFORMS=cpu python -m karpenter_provider_aws_tpu.sim run \
		--trace smoke --seed 0 --report /tmp/fleet_report_smoke.json
	python tools/fleet_gate.py /tmp/fleet_report_smoke.json \
		--baseline karpenter_provider_aws_tpu/sim/baselines/smoke-500.json

sim-sweep:  ## scale-tier ladder + cliff detector (slow; SIM_TIERS overrides)
	JAX_PLATFORMS=cpu python -m karpenter_provider_aws_tpu.sim sweep \
		--trace smoke --seed 0 --tiers $${SIM_TIERS:-500,1000,2000}

sim-cliff-smoke:  ## small tier pair through the cliff detector — zero findings required
	JAX_PLATFORMS=cpu python -m karpenter_provider_aws_tpu.sim sweep \
		--trace smoke --seed 0 --tiers 300,600

bench-gate:  ## steady-state perf budgets (config9 tick + disruption quiet pass + optimizer lane) vs measured rows
	python tools/bench_gate.py BENCH_DETAIL.jsonl \
		--budgets benchmarks/baselines/steady-state.json

bench-optimizer:  ## optimizer-lane evidence rows (config6 family) -> BENCH_DETAIL.jsonl, then the gate
	JAX_PLATFORMS=cpu BENCH_FORCE_CPU=1 python bench.py --child=optimizer
	$(MAKE) bench-gate

bench-market:  ## cost-vs-oracle-under-moving-prices rows (cost_vs_oracle_market_* family) -> BENCH_DETAIL.jsonl, then the gate
	JAX_PLATFORMS=cpu BENCH_FORCE_CPU=1 python bench.py --child=market
	$(MAKE) bench-gate

bench-gang:  ## gang-day fleet row (config10_gang_day: wall/day + zero partial gangs + fairness + zero retraces) -> BENCH_DETAIL.jsonl, then the gate
	JAX_PLATFORMS=cpu BENCH_FORCE_CPU=1 python bench.py --child=gang
	$(MAKE) bench-gate

market-smoke:  ## 500-node market day (moving prices + a reserved-capacity window) fleet-gated: oracle-relative cost, zero sentinel findings, zero retraces after warmup
	JAX_PLATFORMS=cpu python -m karpenter_provider_aws_tpu.sim run \
		--trace market-day --seed 0 --report /tmp/fleet_report_market.json
	python tools/fleet_gate.py /tmp/fleet_report_market.json \
		--baseline karpenter_provider_aws_tpu/sim/baselines/market-500.json

gang-smoke:  ## 500-node gang day (all-or-nothing training gangs + HA pairs + DaemonSet overhead + noisy tenant) fleet-gated: zero partial gangs, fairness ratio <= 2x, zero retraces after warmup
	JAX_PLATFORMS=cpu python -m karpenter_provider_aws_tpu.sim run \
		--trace gang-day --seed 0 --report /tmp/fleet_report_gang.json
	python tools/fleet_gate.py /tmp/fleet_report_gang.json \
		--baseline karpenter_provider_aws_tpu/sim/baselines/gang-500.json

chaos-smoke:  ## every canned chaos scenario (incl. replica-loss), run twice, determinism diffed
	JAX_PLATFORMS=cpu python -m karpenter_provider_aws_tpu.chaos --all --seed 0

sim-replica-smoke:  ## 2-replica sharded-control-plane day with a replica-loss overlay, fleet-gated
	JAX_PLATFORMS=cpu python -m karpenter_provider_aws_tpu.sim run \
		--trace smoke --nodes 200 --seed 0 --replicas 2 \
		--overlay replica-loss@1800 \
		--report /tmp/fleet_report_replica.json
	python tools/fleet_gate.py /tmp/fleet_report_replica.json \
		--baseline karpenter_provider_aws_tpu/sim/baselines/replica-loss-2r.json

fleet-obs-smoke:  ## 2-replica smoke day through the flight recorder: correlation coverage >= 99%, zero sentinel false positives, obs-fleet CLI round-trip
	JAX_PLATFORMS=cpu python tools/fleet_obs_smoke.py

device-obs-smoke:  ## smoke-500 day with jitwatch armed: per-family compile counts, 0 retraces after warmup, obs-device CLI round-trip of the ledger snapshot
	JAX_PLATFORMS=cpu python tools/device_obs_smoke.py

warmup-smoke:  ## smoke-500 day warmed from the checked-in AOT manifest: first solve compiles=0 (first_solve_after_restart) + 0 retraces, fleet-gated
	JAX_PLATFORMS=cpu python tools/warmup_smoke.py

why-smoke:  ## deliberately-starving why-day with the why-not engine armed: why_coverage == 1.0 + 0 retraces (fleet-gated vs why-500.json), kill-switch byte-identity, stamped why_overhead row < 5% p99
	JAX_PLATFORMS=cpu python tools/why_smoke.py

sim-provision-smoke:  ## 4-replica sharded-provisioning flood day (GLOBAL holder killed mid-flood; work-stealing + packing-envelope-parity), fleet-gated
	JAX_PLATFORMS=cpu python -m karpenter_provider_aws_tpu.sim run \
		--trace flood-day --nodes 250 --hours 2 --seed 0 --replicas 4 \
		--overlay provisioning-replica-loss@1800 \
		--report /tmp/fleet_report_provision.json
	python tools/fleet_gate.py /tmp/fleet_report_provision.json \
		--baseline karpenter_provider_aws_tpu/sim/baselines/provisioning-4r.json
