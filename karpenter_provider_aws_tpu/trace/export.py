"""Span export: Chrome trace-event JSON + the metrics bridge.

Chrome export makes the flight recorder's tape loadable in
``chrome://tracing`` / Perfetto / ``about:tracing`` — complete "X" (duration)
events on one process lane, thread lanes per recording thread, span attrs
as ``args``. The format is the Trace Event Format's JSON-object flavor
(``{"traceEvents": [...]}``), timestamps in microseconds.

The metrics bridge closes the loop with ``metrics.py``: span durations feed
the per-phase ``Histogram`` families on finish, so ``/metrics`` exposes the
same latencies the tape records — one instrumentation layer, two consumers.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Iterable, Optional

from .spans import TRACER, Span, Tracer

# span-name prefix -> (histogram attr in metrics.py, label key). The bridge
# resolves histograms lazily so importing trace/ never forces the metrics
# registry (and its well-known families) to exist first.
_PHASE_PREFIX = "solve."
_CONTROLLER_PREFIX = "controller."
_AWS_PREFIX = "aws."
_CONSOLIDATE_PREFIX = "consolidate."
_JIT_PREFIX = "jit."


def to_chrome_trace(spans: Iterable[Span], pid: Optional[int] = None) -> dict:
    """Spans -> Trace Event Format dict (JSON-object flavor).

    ``ts``/``dur`` are microseconds on the perf_counter timebase — absolute
    values are meaningless across processes, deltas are exact within one.
    """
    pid = os.getpid() if pid is None else pid
    events = []
    for s in spans:
        events.append({
            "name": s.name,
            "ph": "X",                       # complete event: ts + dur
            "ts": s.t0_ns / 1e3,
            "dur": s.dur_ns / 1e3,
            "pid": pid,
            "tid": s.tid,
            "cat": s.name.split(".", 1)[0],
            "args": {
                **{k: _jsonable(v) for k, v in s.attrs.items()},
                "span_id": s.span_id,
                "parent_id": s.parent_id,
            },
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def write_chrome_trace(path: str, spans: Optional[Iterable[Span]] = None,
                       tracer: Tracer = TRACER) -> str:
    """Dump spans (default: the tracer's current tape) to ``path``."""
    doc = to_chrome_trace(tracer.snapshot() if spans is None else spans)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def validate_chrome_trace(doc) -> list[str]:
    """Structural validation of a trace-event document (what the tests —
    and a doubting reviewer — run against an exported 2k-pod solve).
    Returns a list of problems; empty == valid."""
    problems: list[str] = []
    if isinstance(doc, (str, bytes)):
        try:
            doc = json.loads(doc)
        except json.JSONDecodeError as e:
            return [f"not JSON: {e}"]
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["missing traceEvents"]
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        for key in ("name", "ph", "ts", "pid", "tid"):
            if key not in ev:
                problems.append(f"event {i}: missing {key!r}")
        if ev.get("ph") == "X":
            if not isinstance(ev.get("dur"), (int, float)) or ev["dur"] < 0:
                problems.append(f"event {i}: bad dur {ev.get('dur')!r}")
        if not isinstance(ev.get("ts"), (int, float)) or ev.get("ts", -1) < 0:
            problems.append(f"event {i}: bad ts {ev.get('ts')!r}")
    return problems


class MetricsBridge:
    """on_finish hook feeding span durations into the metrics registry.

    Name taxonomy -> histogram family + label:

    - ``solve.<phase>``        -> SOLVE_PHASE_SECONDS{phase=...}
    - ``consolidate.<phase>``  -> SOLVE_PHASE_SECONDS{phase=consolidate.<phase>}
    - ``controller.<name>``    -> RECONCILE_SECONDS{controller=...}
    - ``aws.<service>``        -> AWS_REQUEST_SECONDS{service=...} (+ the
      retry counter when the span carries a ``retries`` attr > 0)
    - ``jit.compile``          -> JIT_COMPILE_SECONDS{family=...} (the
      jitwatch ledger records one such span per new trace signature, so
      compile walls land in Chrome export AND /metrics from one spot)

    Installed once per process (idempotent via ``install``).
    """

    _installed_lock = threading.Lock()
    _installed: Optional["MetricsBridge"] = None

    def __call__(self, span: Span) -> None:
        from .. import metrics as m

        if span.name.startswith(_PHASE_PREFIX):
            m.SOLVE_PHASE_SECONDS.observe(
                span.duration_s, phase=span.name[len(_PHASE_PREFIX):]
            )
        elif span.name.startswith(_CONSOLIDATE_PREFIX):
            m.SOLVE_PHASE_SECONDS.observe(span.duration_s, phase=span.name)
        elif span.name.startswith(_CONTROLLER_PREFIX):
            labels = {"controller": span.name[len(_CONTROLLER_PREFIX):]}
            # N-replica processes (testenv.new_replicaset) stamp the
            # replica identity on reconcile spans: without the label,
            # every replica's series silently summed into one
            replica = span.attrs.get("replica")
            if replica:
                labels["replica"] = replica
            m.RECONCILE_SECONDS.observe(span.duration_s, **labels)
        elif span.name.startswith(_AWS_PREFIX):
            m.AWS_REQUEST_SECONDS.observe(
                span.duration_s, service=span.name[len(_AWS_PREFIX):]
            )
            retries = span.attrs.get("retries", 0)
            if retries:
                m.AWS_REQUEST_RETRIES.inc(
                    retries, service=span.name[len(_AWS_PREFIX):]
                )
        elif span.name.startswith(_JIT_PREFIX):
            m.JIT_COMPILE_SECONDS.observe(
                span.duration_s,
                family=str(span.attrs.get("family", "?")),
            )

    @classmethod
    def install(cls, tracer: Tracer = TRACER) -> "MetricsBridge":
        with cls._installed_lock:
            if cls._installed is None:
                cls._installed = cls()
                tracer.on_finish(cls._installed)
            return cls._installed


class SpanAggregator:
    """Streaming wall-time attribution: an ``on_finish`` hook folding
    every completed span into per-name totals as it lands.

    The flight recorder's ring is bounded (8192 spans), so a consumer
    that wants a WHOLE run's attribution — the fleet simulator's
    "where did the simulated day's wall time go" profile — cannot
    snapshot the tape at the end: a day of reconciles overflows it many
    times over. Aggregating at finish time is O(1) per span and misses
    nothing. Root spans (``parent_id == 0``) are totaled separately so a
    driver that wraps all of its work in top-level spans can state what
    fraction of its wall clock the profile accounts for (nested spans
    would double-count if summed naively).

    Install with ``tracer.on_finish(agg)``; remove with
    ``tracer.remove_on_finish(agg)``; read :meth:`profile`.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._by_name: dict[str, list] = {}    # name -> [count, total_ns]
        self._roots: dict[str, list] = {}

    def __call__(self, span: Span) -> None:
        with self._lock:
            cell = self._by_name.setdefault(span.name, [0, 0])
            cell[0] += 1
            cell[1] += span.dur_ns
            if span.parent_id == 0:
                cell = self._roots.setdefault(span.name, [0, 0])
                cell[0] += 1
                cell[1] += span.dur_ns

    def profile(self) -> dict:
        """``{"spans": {name: {count, total_ms}}, "roots": {...}}``,
        totals rounded to microsecond-ms for stable JSON."""
        with self._lock:
            return {
                "spans": {
                    name: {"count": c, "total_ms": round(ns / 1e6, 3)}
                    for name, (c, ns) in sorted(self._by_name.items())
                },
                "roots": {
                    name: {"count": c, "total_ms": round(ns / 1e6, 3)}
                    for name, (c, ns) in sorted(self._roots.items())
                },
            }


def aggregate_spans(spans: Iterable[Span]) -> dict:
    """One-shot :class:`SpanAggregator` over an in-memory span list
    (tests, small tapes). Same output shape as ``SpanAggregator.profile``."""
    agg = SpanAggregator()
    for s in spans:
        agg(s)
    return agg.profile()


# Auto-install on first import of the trace package: every instrumented
# layer that records a span also populates /metrics, with no wiring step
# for operators to forget.
MetricsBridge.install()
