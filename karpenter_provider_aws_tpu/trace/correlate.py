"""Causal correlation across replicas: one id per pod/claim lifecycle.

The sharded control plane (PRs 9/12) split one pod's lifecycle across
processes: replica A routes it, replica B claims it from the GLOBAL work
queue after A dies, replica C registers the node and the launcher binds
the nomination. Every per-process observability plane (spans, audit,
events) sees only its own hops — answering "why did pod X take 500s to
bind" meant manually joining N rings with no shared causality.

This module is the joining key (designs/fleet-flight-recorder.md):

- :func:`correlation_id` — a **pure function** of the object's identity
  (``c-<sha256(kind:ident)[:12]>``). No mint RPC, no coordination: every
  replica derives the same id from the same pod/claim independently,
  which is what makes cross-replica correlation work with zero protocol.
- :class:`Hop` — one lifecycle step, stamped with the correlation id,
  the store-clock time, the **replica identity** that performed it
  (resolved from the ambient sharding ownership scope), and — for hops
  sanctioned by a partition lease — the lease's fencing token, so the
  merged timeline can order cross-replica hops on tenancy epochs, not
  just timestamps.
- :class:`CorrelationLedger` — a bounded, thread-safe hop ring with a
  per-correlation-id index and a ``(subject kind, name) -> cid`` alias
  map. ``record_once`` dedupes idempotent hops (a pod stays pending for
  ten passes; its ``route`` hop is minted exactly once), so steady state
  can never grow the ledger through re-reconciles.

The ledger lives on the ``Obs`` bundle (one per hermetic environment; in
a ReplicaSet every replica writes to the shared world's ledger exactly
like the shared audit ring — the N-processes-one-store shape is the
testenv seam, and real deployments serialize per-process ledgers through
``/debug/flight`` for :class:`~..obs.fleet.FleetRecorder` to merge).
Hooks never call back into the cluster store: ``record`` may run under
its lock.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional

#: bounded hop history (a steady-state fleet dedupes to ~8 hops per pod
#: lifecycle; 64k hops covers a multi-thousand-pod simulated day)
LEDGER_CAP = 65536

#: the replica stamp used when no sharding ownership scope is ambient
SINGLE_REPLICA = "single"

#: a COMPLETE pod chain (correlation coverage) carries a lifecycle START
#: hop — ``pending`` (first sight) or ``evict`` (a drained pod re-enters
#: pending; its original pending hop may predate the recorder) — and the
#: terminal ``bind``; everything between (route, queue claim, solve,
#: launch, nominate) depends on how the pod landed
START_POD_HOPS = ("pending", "evict")
REQUIRED_POD_HOPS = ("pending", "bind")  # kept for back-compat docs


def chain_complete(kinds) -> bool:
    """Is a pod chain complete? (the coverage gate's one rule)"""
    kinds = set(kinds)
    return "bind" in kinds and any(k in kinds for k in START_POD_HOPS)


import functools


@functools.lru_cache(maxsize=65536)
def correlation_id(kind: str, ident: str) -> str:
    """Deterministic correlation id for one object identity. Pods key on
    their uid, claims on their name — both stable for the object's whole
    lifetime and identical on every replica. Memoized: the provisioner
    and the 1s host binder re-derive ids for every still-pending pod on
    every pass."""
    digest = hashlib.sha256(f"{kind}:{ident}".encode()).hexdigest()
    return f"c-{digest[:12]}"


def current_replica() -> str:
    """The replica identity to stamp on a hop: the ambient sharding
    ownership's replica when a scope is active (Manager-wrapped
    reconciles in an N-replica deployment), else ``single``."""
    from ..operator import sharding

    own = sharding.current()
    return own.replica if own is not None else SINGLE_REPLICA


@dataclass(frozen=True)
class Hop:
    """One lifecycle step of one correlated object."""

    seq: int                   # ledger-local, monotonic (merge tiebreak)
    cid: str                   # correlation id
    at: float                  # store-clock timestamp
    replica: str               # identity of the replica performing the hop
    kind: str                  # pending | route | claim | steal | solve | ...
    subject_kind: str = ""     # Pod | NodeClaim
    subject: str = ""          # object name
    detail: dict = field(default_factory=dict)
    fence: Optional[tuple] = None  # (lease name, token) sanctioning the hop

    def as_dict(self) -> dict:
        d = {
            "seq": self.seq,
            "cid": self.cid,
            "at": round(float(self.at), 3),
            "replica": self.replica,
            "kind": self.kind,
            "subject_kind": self.subject_kind,
            "subject": self.subject,
        }
        if self.detail:
            d["detail"] = dict(self.detail)
        if self.fence:
            d["fence"] = [self.fence[0], int(self.fence[1])]
        return d

    @staticmethod
    def from_dict(d: dict) -> "Hop":
        fence = d.get("fence")
        return Hop(
            seq=int(d.get("seq", 0)),
            cid=str(d.get("cid", "")),
            at=float(d.get("at", 0.0)),
            replica=str(d.get("replica", SINGLE_REPLICA)),
            kind=str(d.get("kind", "")),
            subject_kind=str(d.get("subject_kind", "")),
            subject=str(d.get("subject", "")),
            detail=dict(d.get("detail") or {}),
            fence=tuple(fence) if fence else None,
        )


def merge_key(hop: Hop) -> tuple:
    """The cross-replica merge order (designs/fleet-flight-recorder.md):
    store-clock time first (all replicas share the store's clock base —
    the lease-audit tick base), then the ledger sequence (within one
    shared-world ledger, append order IS causal order — the common
    testenv/sim/chaos shape), then the fencing-token epoch (the
    remaining tiebreak when N per-process ledgers are concatenated and
    seq streams interleave: an adopt under tenancy 3 sorts after a
    launch under tenancy 2)."""
    return (round(hop.at, 6), hop.seq, hop.fence[1] if hop.fence else 0)


class CorrelationLedger:
    """Bounded thread-safe hop ring + per-cid index + name alias map."""

    def __init__(self, capacity: int = LEDGER_CAP, clock=None):
        self.clock = clock
        self._lock = threading.Lock()
        self._ring: deque[Hop] = deque(maxlen=capacity)
        self._seq = itertools.count(1)
        # cid -> list of hops (pruned lazily against the ring's tail)
        self._by_cid: "OrderedDict[str, list[Hop]]" = OrderedDict()
        # (subject kind, subject name) -> cid — the CLI looks objects up
        # by name; correlation ids key on uids for pods
        self._alias: dict[tuple, str] = {}
        # (cid, kind, dedupe key) already recorded (record_once)
        self._seen: set = set()

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.now()
        import time

        return time.monotonic()

    # -- minting -----------------------------------------------------------
    def mint(self, subject_kind: str, ident: str,
             name: Optional[str] = None) -> str:
        """Resolve (and alias) the correlation id for one object. Pure on
        ``(subject_kind, ident)``; registering the human name makes the
        object findable by ``<kind>/<name>``."""
        cid = correlation_id(subject_kind, ident)
        key = (subject_kind, name or ident)
        if self._alias.get(key) == cid:  # steady-state fast path
            return cid
        with self._lock:
            self._alias[key] = cid
            if name is not None and name != ident:
                self._alias[(subject_kind, ident)] = cid
        return cid

    def resolve(self, subject_kind: str, name: str) -> Optional[str]:
        with self._lock:
            return self._alias.get((subject_kind, name))

    # -- recording ---------------------------------------------------------
    def record(self, cid: str, kind: str, subject_kind: str = "",
               subject: str = "", detail: Optional[dict] = None,
               at: Optional[float] = None, replica: Optional[str] = None,
               fence: Optional[tuple] = None) -> Hop:
        hop = Hop(
            seq=next(self._seq),
            cid=cid,
            at=self._now() if at is None else at,
            replica=current_replica() if replica is None else replica,
            kind=kind,
            subject_kind=subject_kind,
            subject=subject,
            detail=detail or {},
            fence=tuple(fence) if fence else None,
        )
        with self._lock:
            evicted = (
                self._ring[0]
                if len(self._ring) == self._ring.maxlen else None
            )
            self._ring.append(hop)
            self._by_cid.setdefault(cid, []).append(hop)
            if evicted is not None:
                hops = self._by_cid.get(evicted.cid)
                if hops:
                    hops.remove(evicted)
                    if not hops:
                        self._by_cid.pop(evicted.cid, None)
        try:
            from ..metrics import CORRELATION_HOPS

            CORRELATION_HOPS.inc(kind=kind)
        except Exception:
            pass
        return hop

    def has_recorded(self, cid: str, kind: str, key: str = "") -> bool:
        """Lock-free peek at the :meth:`record_once` dedupe set — the
        hot controller loops check this FIRST and skip the per-pod
        mint/partition work for objects already narrated."""
        return (cid, kind, key) in self._seen

    def record_once(self, cid: str, kind: str, key: str = "",
                    **kw) -> Optional[Hop]:
        """Record unless an identical ``(cid, kind, key)`` hop exists —
        the idempotence contract that lets every reconcile pass re-route
        a still-pending pod without growing its chain."""
        token = (cid, kind, key)
        with self._lock:
            if token in self._seen:
                return None
            if len(self._seen) >= 4 * (self._ring.maxlen or LEDGER_CAP):
                # bounded like the ring: once enough lifecycles have
                # passed to wrap it several times over, the evicted
                # chains' dedupe tokens are dead weight — drop the set
                # (live chains at worst re-record one idempotent hop)
                self._seen.clear()
            self._seen.add(token)
        return self.record(cid, kind, **kw)

    # -- reading -----------------------------------------------------------
    def hops(self, cid: str) -> list[Hop]:
        """One object's hops in cross-replica merge order."""
        with self._lock:
            out = list(self._by_cid.get(cid, ()))
        return sorted(out, key=merge_key)

    def all_hops(self) -> list[Hop]:
        with self._lock:
            return list(self._ring)

    def cids(self) -> list[str]:
        with self._lock:
            return list(self._by_cid)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- persistence (the /debug/flight + CLI offline surface) -------------
    def snapshot(self) -> dict:
        with self._lock:
            hops = list(self._ring)
            alias = {
                f"{kind}/{name}": cid
                for (kind, name), cid in self._alias.items()
            }
        return {
            "hops": [h.as_dict() for h in hops],
            "alias": alias,
        }

    @staticmethod
    def from_snapshot(data: dict, clock=None) -> "CorrelationLedger":
        ledger = CorrelationLedger(clock=clock)
        for key, cid in (data.get("alias") or {}).items():
            kind, _, name = key.partition("/")
            ledger._alias[(kind, name)] = cid
        for d in data.get("hops", ()):
            hop = Hop.from_dict(d)
            ledger._ring.append(hop)
            ledger._by_cid.setdefault(hop.cid, []).append(hop)
        return ledger

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_cid.clear()
            self._alias.clear()
            self._seen.clear()
