"""AOT warmup: the jitwatch ledger serialized into a manifest, replayed
through ``lower().compile()`` before a process serves its first solve.

PR 14's ledger priced the cold-start tax precisely: a config6 cold solve
is 4,355.9ms of which 4,242.3ms is XLA compile (``optimizer.lanes`` alone
~3.4s) against 51.6ms warm — so every restarted sidecar/replica wins its
leases in seconds and then stalls its first real solve behind compiles it
has paid a thousand times before. This module closes that cliff with the
classic serving-stack pair:

- **The manifest** — the ledger IS the record of which trace signatures a
  fleet of this exact workload actually compiles (ladder buckets, static
  axes, dtypes). :func:`build_manifest` serializes every live
  ``tracked_jit`` wrapper's replay specs (captured at first trace as
  ``ShapeDtypeStruct`` pytrees) into a versioned JSON document;
  :func:`warm_from_manifest` replays it through ``lower().compile()`` in
  a fixed priority order — FFD + screen first, so the solve-serving path
  is warm before the ~3.4s optimizer lane program even starts; the lane
  programs may finish warming on a background thread while FFD already
  serves — under a deadline budget with per-family wall/skip accounting.
- **The persistent compile cache** — :func:`ensure_compile_cache` points
  jax's persistent compilation cache at a fleet-shared directory (with a
  uid-/pid-keyed fallback when the shared path is not writable), so a
  warmup on a restarted process is a cache *read*, not a re-compile: the
  first process pays XLA once and writes executables the whole fleet
  reuses.

Entry points, threaded through every place a process learns its shapes:
:func:`startup_warm` (sim driver fleet build, ``bench.py`` children,
sidecar startup), :func:`warm_on_adoption` (``ShardElector`` — a
successor warms the dead launcher's manifest before its first owned
pass), :func:`maybe_save` (end of a run, env-gated).

Knobs::

    KARPENTER_TPU_WARMUP_MANIFEST     path to load + warm at startup
    KARPENTER_TPU_WARMUP_SAVE         path to write the manifest at exit
    KARPENTER_TPU_WARMUP_DEADLINE_S   foreground warmup budget (0 = none)
    KARPENTER_TPU_COMPILE_CACHE_DIR   shared cache dir ("0" disables)

A corrupt, version-skewed, or simply missing manifest degrades to a plain
cold start: every loader/decoder error is caught, accounted, and never
crosses into the serving path.
"""

from __future__ import annotations

import importlib
import json
import logging
import os
import threading
import time
from typing import Optional

from . import jitwatch

log = logging.getLogger("karpenter.tpu.warmup")

MANIFEST_VERSION = 1

ENV_MANIFEST = "KARPENTER_TPU_WARMUP_MANIFEST"
ENV_SAVE = "KARPENTER_TPU_WARMUP_SAVE"
ENV_DEADLINE = "KARPENTER_TPU_WARMUP_DEADLINE_S"
ENV_CACHE = "KARPENTER_TPU_COMPILE_CACHE_DIR"
DEFAULT_CACHE_DIR = "/tmp/karpenter_tpu_jit_cache"

#: only our own containers may be re-materialized by the spec decoder —
#: a manifest is fleet-internal data, not a pickle
_PKG = "karpenter_provider_aws_tpu"


class ManifestError(ValueError):
    """The manifest file is unusable (corrupt JSON, wrong version, wrong
    shape) — callers degrade to a plain cold start."""


class WarmupTopologySkew(Warning):
    """The manifest was recorded on a different device topology (platform
    or device count) than this process runs on. Replaying it would warm
    wrong-shaped programs — sharded lanes trace against the live device
    axis — so every entry is skipped and the process runs cold instead.
    Heterogeneous fleets should point each topology class at its own
    manifest (ROADMAP: per-topology manifests)."""


class SpecCodecError(ValueError):
    """One replay spec cannot be (de)serialized — that entry is skipped
    with a recorded reason, never fatal."""


# ---------------------------------------------------------------------------
# spec codec: restricted JSON pytrees (no pickle)
# ---------------------------------------------------------------------------

def _encode(x) -> dict:
    import jax

    if isinstance(x, jax.ShapeDtypeStruct):
        return {"t": "arr", "shape": list(x.shape), "dtype": str(x.dtype)}
    if x is None or isinstance(x, (bool, int, float, str)):
        return {"t": "py", "v": x}
    if isinstance(x, tuple) and hasattr(x, "_fields"):      # NamedTuple
        cls = type(x)
        return {
            "t": "nt",
            "cls": f"{cls.__module__}:{cls.__qualname__}",
            "items": [_encode(v) for v in x],
        }
    if isinstance(x, tuple):
        return {"t": "tuple", "items": [_encode(v) for v in x]}
    if isinstance(x, list):
        return {"t": "list", "items": [_encode(v) for v in x]}
    if isinstance(x, dict):
        if not all(isinstance(k, str) for k in x):
            raise SpecCodecError("non-string dict keys")
        return {
            "t": "dict",
            "items": [[k, _encode(v)] for k, v in sorted(x.items())],
        }
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:   # stray concrete array
        return {"t": "arr", "shape": list(shape), "dtype": str(dtype)}
    raise SpecCodecError(f"unserializable leaf {type(x).__name__}")


def _decode(d: dict):
    import numpy as np

    import jax

    t = d.get("t")
    if t == "arr":
        return jax.ShapeDtypeStruct(tuple(d["shape"]), np.dtype(d["dtype"]))
    if t == "py":
        return d["v"]
    if t == "tuple":
        return tuple(_decode(v) for v in d["items"])
    if t == "list":
        return [_decode(v) for v in d["items"]]
    if t == "dict":
        return {k: _decode(v) for k, v in d["items"]}
    if t == "nt":
        modname, _, qual = d["cls"].partition(":")
        if not modname.startswith(_PKG):
            raise SpecCodecError(f"refusing foreign class {d['cls']!r}")
        obj = importlib.import_module(modname)
        for part in qual.split("."):
            obj = getattr(obj, part)
        return obj(*(_decode(v) for v in d["items"]))
    raise SpecCodecError(f"unknown spec tag {t!r}")


# ---------------------------------------------------------------------------
# family materialization: find-or-build the wrapper a spec replays through
# ---------------------------------------------------------------------------

#: module-level families: importing the home module registers the wrapper
_FAMILY_MODULES = {
    "ffd.solve": f"{_PKG}.ops.ffd",
    "ffd.solve_chained": f"{_PKG}.ops.ffd",
    "ffd.compact_plan": f"{_PKG}.ops.ffd",
    "ffd.rank_launch_options": f"{_PKG}.ops.ffd",
    "ffd.pallas": f"{_PKG}.ops.ffd_pallas",
    "screen.repack": f"{_PKG}.ops.consolidate",
    "screen.pallas": f"{_PKG}.ops.repack_pallas",
    "gangs.feasible": f"{_PKG}.scheduling.groups",
}


def _materialize(family: str, params: Optional[dict]):
    """The live wrapper for ``family`` — factory families rebuild through
    their (cached) builder with the manifest's recorded params, module
    families import their home module and read the registry."""
    params = params or {}
    # NOTE: lru_cache keys keyword calls separately from positional ones —
    # every builder below must be called POSITIONALLY, exactly like its
    # runtime dispatch site, or the warm replay lands on a second cache
    # entry and the fleet's first solve still compiles.
    if family == "optimizer.lanes":
        from ..scheduling.optimizer import _program_cached

        return _program_cached(int(params["max_nodes"]), int(params["lanes"]))
    if family == "device_state.patch":
        from ..ops.device_state import _patch_fn

        return _patch_fn(bool(params["donate"]))
    if family == "mesh.lanes":
        from ..parallel.mesh import _lanes_vmap_fn

        return _lanes_vmap_fn(int(params["max_nodes"]))
    if family == "mesh.lanes_shard":
        from ..parallel.mesh import _lanes_shard_fn, make_mesh

        return _lanes_shard_fn(make_mesh(), int(params["max_nodes"]))
    if family == "mesh.solve_shard":
        from ..parallel.mesh import make_mesh, sharded_solve_fn

        return sharded_solve_fn(make_mesh(), int(params["max_nodes"]))
    if family == "mesh.screen":
        from ..parallel.mesh import make_mesh, sharded_screen_fn

        return sharded_screen_fn(make_mesh())
    if family == "why.eliminate":
        from ..obs.why import _kernel

        return _kernel()
    mod = _FAMILY_MODULES.get(family)
    if mod is not None:
        importlib.import_module(mod)
    wrappers = jitwatch.wrappers_for(family)
    if not wrappers:
        raise SpecCodecError(f"no wrapper for family {family!r}")
    return wrappers[0]


# ---------------------------------------------------------------------------
# manifest build / save / load
# ---------------------------------------------------------------------------

def build_manifest() -> dict:
    """Serialize every live wrapper's replay specs into a manifest dict.
    Unserializable specs are recorded under ``unserializable`` (family +
    reason) rather than failing the build."""
    import jax

    entries: list[dict] = []
    unserializable: list[dict] = []
    for w in jitwatch.all_wrappers():
        for spec in w.replay_specs():
            try:
                args, kwargs = spec
                entries.append({
                    "family": w.family,
                    "params": w.warmup_params,
                    "args": [_encode(a) for a in args],
                    "kwargs": {k: _encode(v) for k, v in kwargs.items()},
                })
            except SpecCodecError as e:
                unserializable.append({"family": w.family, "reason": str(e)})
    return {
        "version": MANIFEST_VERSION,
        "jax": jax.__version__,
        # the topology key (per-topology manifests): replay specs trace
        # against THIS process's device axis — the sharded mesh lanes
        # bake the device count into their programs — so a manifest is
        # only valid on the topology that recorded it. Manifests without
        # the key (pre-skew-gate fleets) warm unconditionally.
        "topology": _live_topology(),
        "entries": entries,
        "unserializable": unserializable,
    }


def _live_topology() -> dict:
    """The (platform, device_count) pair the manifest's programs were —
    or would be — traced against."""
    import jax

    return {
        "platform": str(jax.default_backend()),
        "device_count": int(jax.device_count()),
    }


def save_manifest(manifest: dict, path: str) -> str:
    """Atomic write (tmp + rename): a reader never sees a torn file."""
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_manifest(path: str) -> dict:
    """Parse + validate one manifest file. Raises :class:`ManifestError`
    on corrupt JSON, a version skew, or a structurally wrong document —
    callers catch it and run cold."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise ManifestError(f"unreadable manifest {path!r}: {e}") from e
    if not isinstance(doc, dict):
        raise ManifestError(f"manifest {path!r} is not an object")
    if doc.get("version") != MANIFEST_VERSION:
        raise ManifestError(
            f"manifest {path!r} version {doc.get('version')!r} != "
            f"{MANIFEST_VERSION}"
        )
    if not isinstance(doc.get("entries"), list):
        raise ManifestError(f"manifest {path!r} has no entries list")
    return doc


# ---------------------------------------------------------------------------
# the warmup sweep
# ---------------------------------------------------------------------------

#: foreground priority: the solve-serving path (FFD + screen + patch +
#: gangs) warms first; the lane programs — including the ~3.4s
#: optimizer.lanes compile — rank last and may finish in the background
_PRIORITY = {fam: i for i, fam in enumerate((
    "ffd.solve", "ffd.solve_chained", "ffd.rank_launch_options",
    "ffd.compact_plan", "screen.repack", "screen.pallas", "ffd.pallas",
    "device_state.patch", "gangs.feasible", "why.eliminate",
    "mesh.solve_shard", "mesh.screen",
))}
_LATE = {"mesh.lanes": 100, "mesh.lanes_shard": 101, "optimizer.lanes": 200}


def _rank(family: str) -> int:
    return _PRIORITY.get(family, _LATE.get(family, 50))


_bg_lock = threading.Lock()
_bg_thread: Optional[threading.Thread] = None


def _warm_entry(entry: dict, acct: dict, lock: threading.Lock) -> None:
    family = entry.get("family", "?")
    try:
        wrapper = _materialize(family, entry.get("params"))
        args = tuple(_decode(a) for a in entry.get("args", []))
        kwargs = {k: _decode(v) for k, v in entry.get("kwargs", {}).items()}
        wall = wrapper.warm((args, kwargs))
        with lock:
            cell = acct["families"].setdefault(
                family, {"warmed": 0, "wall_ms": 0.0}
            )
            cell["warmed"] += 1
            cell["wall_ms"] = round(cell["wall_ms"] + wall, 1)
    except Exception as e:
        with lock:
            acct["skipped"].append({
                "family": family,
                "reason": f"{type(e).__name__}: {e}",
            })


def warm_from_manifest(manifest: dict, deadline_s: Optional[float] = None,
                       background: bool = True) -> dict:
    """Replay every manifest entry through ``lower().compile()`` in
    priority order under a deadline budget; returns the accounting dict
    ({families: {name: {warmed, wall_ms}}, skipped: [{family, reason}],
    deadline_hit, background_families, wall_ms}).

    When the deadline fires, remaining late-ranked entries (the lane
    programs) continue on a daemon thread if ``background`` — FFD serves
    warm while the 3.4s lane compile finishes off-path; other remaining
    entries are skipped with reason ``deadline``."""
    global _bg_thread
    if deadline_s is None:
        deadline_s = float(os.environ.get(ENV_DEADLINE, "0") or 0)
    t0 = time.perf_counter()
    lock = threading.Lock()
    acct: dict = {
        "families": {},
        "skipped": [],
        "deadline_hit": False,
        "background_families": [],
        "wall_ms": 0.0,
    }
    entries = sorted(
        manifest.get("entries", []),
        key=lambda e: _rank(e.get("family", "?")),
    )
    # per-topology gate: a manifest recorded on a different platform or
    # device count must not be replayed — its specs would warm (and on
    # sharded families, FAIL against) wrong-shaped programs. Every entry
    # is skipped with an explicit reason and a WarmupTopologySkew Warning
    # so operators see WHY the process ran cold. Manifests without the
    # key (recorded before the gate existed) warm unconditionally.
    recorded = manifest.get("topology")
    if isinstance(recorded, dict) and entries:
        live = _live_topology()
        if (
            str(recorded.get("platform", "")) != live["platform"]
            or int(recorded.get("device_count", 0)) != live["device_count"]
        ):
            import warnings

            msg = (
                "warmup manifest topology "
                f"{recorded.get('platform')}/{recorded.get('device_count')} "
                f"!= live {live['platform']}/{live['device_count']}; "
                f"skipping all {len(entries)} entries (running cold)"
            )
            warnings.warn(WarmupTopologySkew(msg))
            log.warning("%s", msg)
            acct["skipped"] = [
                {"family": e.get("family", "?"), "reason": "topology-skew"}
                for e in entries
            ]
            acct["wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
            return acct
    deferred: list[dict] = []
    for entry in entries:
        if deadline_s and (time.perf_counter() - t0) > deadline_s:
            acct["deadline_hit"] = True
            fam = entry.get("family", "?")
            if background and _rank(fam) >= 100:
                deferred.append(entry)
            else:
                acct["skipped"].append({"family": fam, "reason": "deadline"})
            continue
        _warm_entry(entry, acct, lock)
    if deferred:
        acct["background_families"] = sorted(
            {e.get("family", "?") for e in deferred}
        )

        def _bg():
            for e in deferred:
                _warm_entry(e, acct, lock)

        with _bg_lock:
            t = threading.Thread(
                target=_bg, name="warmup-lanes", daemon=True
            )
            _bg_thread = t
            t.start()
    acct["wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    return acct


def join_background(timeout: Optional[float] = None) -> bool:
    """Wait for a deferred background lane warmup (tests / smoke tools).
    True when no background work remains."""
    with _bg_lock:
        t = _bg_thread
    if t is None:
        return True
    t.join(timeout)
    return not t.is_alive()


# ---------------------------------------------------------------------------
# persistent compile cache wiring
# ---------------------------------------------------------------------------

def ensure_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache at the fleet-shared dir
    (``KARPENTER_TPU_COMPILE_CACHE_DIR``, default a shared /tmp path),
    falling back to a uid-keyed then pid-keyed sibling when the shared
    path is not writable. ``"0"`` disables. Returns the dir in use."""
    raw = path or os.environ.get(ENV_CACHE) or DEFAULT_CACHE_DIR
    if raw in ("0", "off", "none"):
        return None
    uid = getattr(os, "getuid", lambda: 0)()
    for candidate in (raw, f"{raw}-u{uid}", f"{raw}-p{os.getpid()}"):
        try:
            os.makedirs(candidate, exist_ok=True)
        except OSError:
            continue
        if not os.access(candidate, os.W_OK):
            continue
        from ..utils.observability import enable_compilation_cache

        enable_compilation_cache(candidate)
        if candidate != raw:
            log.warning(
                "shared compile cache %s not writable; using "
                "process-keyed fallback %s", raw, candidate,
            )
        return candidate
    log.warning("no writable compile cache dir under %s; cache disabled", raw)
    return None


# ---------------------------------------------------------------------------
# process entry points
# ---------------------------------------------------------------------------

_state = {
    "context": False,        # a warmup-managed cold start is in progress
    "did_warm": False,       # a sweep actually ran
    "accounting": None,
    "adoption_attempted": False,
}
_state_lock = threading.Lock()


def cold_start_context() -> bool:
    """True once this process opted into warmup-managed cold start (a
    manifest path was given) — the solver's lazy optimizer-lane admission
    keys on this in its default ``auto`` mode."""
    return _state["context"]


def did_warm() -> bool:
    """True once a warmup sweep actually ran in this process — the sim
    report only emits ``first_solve_after_restart`` when it did."""
    return _state["did_warm"]


def accounting() -> Optional[dict]:
    return _state["accounting"]


def startup_warm(manifest_path: Optional[str] = None,
                 deadline_s: Optional[float] = None,
                 cache_dir: Optional[str] = None,
                 background: bool = True) -> Optional[dict]:
    """The one-call process warmup: enable the persistent compile cache,
    load the manifest (explicit path or ``KARPENTER_TPU_WARMUP_MANIFEST``),
    replay it. Returns the sweep accounting, or None when no manifest is
    configured or anything degrades — NEVER raises: a broken manifest is
    a plain cold start, not an outage."""
    path = manifest_path or os.environ.get(ENV_MANIFEST)
    if not path:
        return None
    with _state_lock:
        _state["context"] = True
    try:
        ensure_compile_cache(cache_dir)
        manifest = load_manifest(path)
        acct = warm_from_manifest(
            manifest, deadline_s=deadline_s, background=background
        )
        with _state_lock:
            _state["did_warm"] = True
            _state["accounting"] = acct
        warmed = sum(c["warmed"] for c in acct["families"].values())
        log.info(
            "warmup: %d specs warmed in %.0fms (%d skipped%s)",
            warmed, acct["wall_ms"], len(acct["skipped"]),
            ", lanes finishing in background"
            if acct["background_families"] else "",
        )
        return acct
    except Exception as e:
        log.warning("warmup degraded to cold start: %s: %s",
                    type(e).__name__, e)
        return None


def warm_on_adoption() -> None:
    """``ShardElector`` adoption hook: the successor of a dead launcher
    warms the fleet manifest before its first owned pass. No-op — and
    jax-import-free — unless ``KARPENTER_TPU_WARMUP_MANIFEST`` is set
    (electors run in hundreds of plain unit tests); at most one attempt
    per process; never raises."""
    if not os.environ.get(ENV_MANIFEST):
        return
    with _state_lock:
        if _state["did_warm"] or _state["adoption_attempted"]:
            return
        _state["adoption_attempted"] = True
    try:
        startup_warm()
    except Exception:       # startup_warm already never raises; belt+braces
        pass


def maybe_save(path: Optional[str] = None) -> Optional[str]:
    """Write this process's manifest when asked (explicit path or
    ``KARPENTER_TPU_WARMUP_SAVE``). Never raises."""
    p = path or os.environ.get(ENV_SAVE)
    if not p:
        return None
    try:
        return save_manifest(build_manifest(), p)
    except Exception as e:
        log.warning("manifest save failed: %s: %s", type(e).__name__, e)
        return None
