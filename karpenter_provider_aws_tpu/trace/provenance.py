"""Per-solve provenance records: who computed this, on what, how fast.

The round-5 verdict's core complaint was that latency claims went stale
invisibly: a ``BENCH_DETAIL.jsonl`` row could not say what device, backend,
or scale produced it, so "config2 225->143 ms" survived long after the
measurement did. A ``ProvenanceRecord`` makes that impossible going
forward:

- every ``Solver.solve`` result carries one (``SolveResult.provenance``)
  naming the device kind, the kernel backend that actually ran (including
  whether a fallback fired), the problem scale, and per-phase wall times;
- the consolidation screen records one per ``consolidatable`` sweep;
- ``bench.py`` REFUSES to emit a row without a stamp, and the summary
  generator surfaces the device/backend label next to every number.

Records are intentionally plain data (``as_dict`` is JSON-ready) with a
``schema`` version so downstream tooling can evolve.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

SCHEMA_VERSION = 1

_git_sha_cache: Optional[str] = None
_git_sha_lock = threading.Lock()


def git_sha() -> str:
    """The source revision of the running code, best-effort and cached:
    KARPENTER_GIT_SHA env (baked into images) wins, then ``git rev-parse``
    on the package's repo, then "unknown" (never an exception — provenance
    must not take down the path it describes)."""
    global _git_sha_cache
    if _git_sha_cache is not None:
        return _git_sha_cache
    with _git_sha_lock:
        if _git_sha_cache is not None:
            return _git_sha_cache
        sha = os.environ.get("KARPENTER_GIT_SHA", "")
        if not sha:
            try:
                repo = os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))))
                sha = subprocess.run(
                    ["git", "-C", repo, "rev-parse", "--short=12", "HEAD"],
                    capture_output=True, text=True, timeout=5,
                ).stdout.strip()
            except Exception:
                sha = ""
        _git_sha_cache = sha or "unknown"
    return _git_sha_cache


def device_info() -> tuple[str, int]:
    """(platform, device_count) WITHOUT forcing a jax import/initialization:
    a HostSolver-only deployment (or the bench parent process, which must
    never import jax) reports ("host", 0) instead of paying — or wedging
    on — accelerator runtime init."""
    jax = sys.modules.get("jax")
    if jax is None:
        return "host", 0
    try:
        devices = jax.devices()
        return jax.default_backend(), len(devices)
    except Exception:
        return "host", 0


@dataclass
class ProvenanceRecord:
    """What produced a result: device, backend, scale, timings, revision."""

    kind: str                          # "solve" | "consolidate.screen" | "bench"
    device: str = "host"               # jax platform ("tpu"/"cpu"/"gpu") or "host"
    device_count: int = 0
    backend: str = "host"              # xla-scan | pallas | pallas-interpret |
    #                                    host | sidecar | vmap | native | mesh
    fallback: str = ""                 # non-empty = a fallback fired (reason)
    # Where the input tensors lived when the kernel ran (ops/device_state.py):
    #   resident — served from device-resident state (hit or scatter patch;
    #              no host re-upload of the big buffers)
    #   upload   — the pass paid a full host->device upload
    #   fallback — the device-residency layer was off/unusable; the legacy
    #              host-buffer path ran
    # Empty on paths that predate (or don't use) the residency layer.
    residency: str = ""
    scale: dict = field(default_factory=dict)    # pods/groups/nodes/rows...
    phases_ms: dict = field(default_factory=dict)  # encode/upload/device/decode
    wall_ms: float = 0.0
    git_sha: str = field(default_factory=git_sha)
    created_unix: float = field(default_factory=time.time)
    schema: int = SCHEMA_VERSION
    # ambient context stamped at record() time (e.g. the chaos harness's
    # scenario/seed/active-fault set); empty outside special regimes
    context: dict = field(default_factory=dict)
    # answer-quality telemetry stamped by the obs/ subsystem: packing
    # efficiency per resource, cost-vs-oracle gap, unschedulable rate —
    # so a latency number can never again be silent about whether the
    # fast answer was also a good one
    quality: dict = field(default_factory=dict)
    # jitwatch ledger compiles that fired DURING this record's window
    # (trace/jitwatch.py): 0 proves the measurement ran warm — a bench row
    # can no longer launder a cold compile into a steady-state number.
    # None = jitwatch disabled / the producer predates the ledger.
    compiles: Optional[int] = None
    # why-engine attribution summary (obs/why.py): the decoded reason
    # histogram over this solve's unschedulable remainder, e.g.
    # {"reasons": {"capacity": 3, "zone": 1}, "attributed": 4}. Empty on
    # clean solves and whenever KARPENTER_TPU_WHY=0 (the kill switch must
    # keep the record byte-identical to the legacy shape).
    why: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        d = {
            "kind": self.kind,
            "device": self.device,
            "device_count": self.device_count,
            "backend": self.backend,
            "fallback": self.fallback,
            "scale": dict(self.scale),
            "phases_ms": {
                k: round(float(v), 3) for k, v in self.phases_ms.items()
            },
            "wall_ms": round(float(self.wall_ms), 3),
            "git_sha": self.git_sha,
            "created_unix": int(self.created_unix),
            "schema": self.schema,
        }
        if self.residency:
            d["residency"] = self.residency
        if self.context:
            d["context"] = dict(self.context)
        if self.quality:
            d["quality"] = dict(self.quality)
        if self.compiles is not None:
            d["compiles"] = int(self.compiles)
        if self.why:
            d["why"] = dict(self.why)
        return d

    def label(self) -> str:
        """Short human label for summaries: ``tpu/pallas@abc123``."""
        base = f"{self.device}/{self.backend}"
        if self.fallback:
            base += "(fallback)"
        return f"{base}@{self.git_sha}"


# Bounded per-kind registry of recent records, for consumers that cannot
# thread a record through a return value (the consolidation screen returns
# a bare mask; the bench reads the last screen's provenance after the call).
_RECENT: dict[str, deque] = {}
_RECENT_LOCK = threading.Lock()
_RECENT_CAP = 64


# Ambient context providers: a running subsystem (the chaos harness) can
# register a callable whose dict is merged into every record's ``context``
# at creation — a solve that happened under an active fault says so in its
# provenance forever, without the solver knowing chaos exists. Provider
# failures are swallowed: provenance must not take down the path it stamps.
_ambient_providers: list = []


def register_ambient_provider(provider) -> None:
    _ambient_providers.append(provider)


def unregister_ambient_provider(provider) -> None:
    if provider in _ambient_providers:
        _ambient_providers.remove(provider)


def record(rec: ProvenanceRecord) -> ProvenanceRecord:
    for provider in list(_ambient_providers):
        try:
            rec.context.update(provider() or {})
        except Exception:
            pass
    with _RECENT_LOCK:
        _RECENT.setdefault(rec.kind, deque(maxlen=_RECENT_CAP)).append(rec)
    return rec


def last_record(kind: str) -> Optional[ProvenanceRecord]:
    with _RECENT_LOCK:
        q = _RECENT.get(kind)
        return q[-1] if q else None


def solve_record(
    backend: str,
    timings: Optional[dict] = None,
    num_pods: int = 0,
    wall_ms: float = 0.0,
    fallback: str = "",
    extra_scale: Optional[dict] = None,
    residency: str = "",
) -> ProvenanceRecord:
    """Build + register the provenance for one end-to-end solve."""
    device, count = device_info()
    timings = timings or {}
    phases = {
        k[:-3]: float(v)
        for k, v in timings.items()
        if k.endswith("_ms") and isinstance(v, (int, float))
    }
    scale = {"pods": int(num_pods)}
    for k in ("n_rows", "n_open", "upload_bytes"):
        if k in timings:
            scale[k] = int(timings[k])
    scale.update(extra_scale or {})
    if not fallback:
        # breaker-driven skips and device failures outrank the in-solve
        # pallas->xla note: an open breaker must be visible in every
        # ``obs explain`` output (resilience/breaker.py)
        for key in ("breaker_fallback", "sidecar_fallback",
                    "device_fallback", "pallas_fallback"):
            v = timings.get(key)
            if isinstance(v, str) and v:
                fallback = v
                break
    if not residency:
        # solvers note their input residency in timings (TPUSolver: the
        # content-addressed device cache; degraded/host paths: "fallback")
        v = timings.get("residency")
        if isinstance(v, str):
            residency = v
    compiles = timings.get("compiles")
    return record(ProvenanceRecord(
        kind="solve", device=device, device_count=count, backend=backend,
        fallback=fallback, scale=scale, phases_ms=phases, wall_ms=wall_ms,
        residency=residency,
        compiles=int(compiles) if isinstance(compiles, int) else None,
    ))


def screen_record(
    backend: str,
    nodes: int,
    wall_ms: float,
    fallback: str = "",
    phases_ms: Optional[dict] = None,
    residency: str = "",
) -> ProvenanceRecord:
    """Build + register the provenance for one consolidation screen sweep."""
    device, count = device_info()
    return record(ProvenanceRecord(
        kind="consolidate.screen", device=device, device_count=count,
        backend=backend, fallback=fallback, scale={"nodes": int(nodes)},
        phases_ms=dict(phases_ms or {}), wall_ms=wall_ms, residency=residency,
    ))


def stamp_row(row: dict, provenance: Optional[ProvenanceRecord] = None,
              **overrides) -> dict:
    """Attach a provenance stamp to a bench row (in place, returned).

    With an explicit record (e.g. ``SolveResult.provenance``) the stamp IS
    that record; otherwise a minimal ambient stamp (device, git sha) is
    built — ``bench.py`` requires SOME stamp on every row, so even error
    rows say what host/revision produced them."""
    if provenance is not None:
        stamp = provenance.as_dict()
    else:
        device, count = device_info()
        stamp = ProvenanceRecord(
            kind="bench", device=device, device_count=count,
            backend=str(row.get("backend", "") or "unknown"),
        ).as_dict()
        stamp.pop("scale", None)
        stamp.pop("phases_ms", None)
        stamp.pop("wall_ms", None)
    stamp.update(overrides)
    row["provenance"] = stamp
    return row
