"""Monotonic-clock span recorder: the flight-recorder core.

Design constraints (designs/tracing.md):

- **Steady-state safe.** Completed spans land in a bounded ring buffer
  (``collections.deque(maxlen=...)``); a controller loop running for weeks
  can never grow memory through the recorder.
- **Near-zero when disabled.** ``tracer.span(...)`` returns one shared
  no-op context manager and allocates nothing — call sites never branch
  on whether tracing is on.
- **Exception safe.** ``__exit__`` always pops the thread-local stack and
  stamps an ``error`` attr; a raising solve leaves no dangling parent for
  the next span on the thread.
- **Nestable across threads.** The span stack is thread-local, so the
  Manager's per-controller threads and the launch worker pool each get
  correct parent/child edges; ids are process-unique.

The clock is ``time.perf_counter_ns`` — monotonic, immune to NTP steps,
and the same family the solver's existing stage timings use, so span
durations and ``TPUSolver.timings`` agree.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

_ids = itertools.count(1)


@dataclass
class Span:
    name: str
    t0_ns: int                  # perf_counter_ns at __enter__
    dur_ns: int = 0             # filled at __exit__
    tid: int = 0                # thread ident (Chrome export lane)
    span_id: int = 0
    parent_id: int = 0          # 0 = root
    attrs: dict = field(default_factory=dict)

    @property
    def duration_ms(self) -> float:
        return self.dur_ns / 1e6

    @property
    def duration_s(self) -> float:
        return self.dur_ns / 1e9


class _NoopSpan:
    """Shared do-nothing context manager for the disabled path — one
    module-level instance, so a disabled-tracer call site allocates
    nothing and costs one attribute check + one method call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> "_NoopSpan":
        return self


_NOOP = _NoopSpan()


class _SpanCtx:
    """One live span: context manager handed out by ``Tracer.span``."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self.span = span

    def set(self, **attrs) -> "_SpanCtx":
        self.span.attrs.update(attrs)
        return self

    def __enter__(self) -> "_SpanCtx":
        stack = self._tracer._stack()
        if stack:
            self.span.parent_id = stack[-1].span_id
        stack.append(self.span)
        self.span.t0_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.span.dur_ns = time.perf_counter_ns() - self.span.t0_ns
        stack = self._tracer._stack()
        # pop OUR span even if an inner span leaked (belt and braces: a
        # generator-held span abandoned mid-iteration must not corrupt
        # every later parent edge on this thread)
        while stack:
            top = stack.pop()
            if top is self.span:
                break
        if exc_type is not None:
            self.span.attrs["error"] = exc_type.__name__
        self._tracer._finish(self.span)
        return False


class Tracer:
    """Span recorder with a bounded ring buffer and finish hooks.

    ``capacity`` bounds retained completed spans (the flight recorder's
    tape length); ``on_finish`` callbacks run synchronously at span end —
    the metrics bridge (export.py) rides this to feed histograms with no
    second timing layer. Callback failures are swallowed: observability
    must never take down the path it observes.
    """

    def __init__(self, capacity: int = 8192, enabled: bool = True):
        self._buf: deque[Span] = deque(maxlen=capacity)
        self._enabled = enabled
        self._local = threading.local()
        self._callbacks: list[Callable[[Span], None]] = []

    # -- state -------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **attrs):
        """Context manager for one timed region. ``with tracer.span("x")
        as s: s.set(k=v)``; returns the shared no-op when disabled."""
        if not self._enabled:
            return _NOOP
        return _SpanCtx(
            self, Span(
                name=name, t0_ns=0, tid=threading.get_ident(),
                span_id=next(_ids), attrs=attrs,
            )
        )

    def traced(self, name: Optional[str] = None, **attrs):
        """Decorator form: ``@tracer.traced("solve.decode")``."""

        def deco(fn):
            label = name or fn.__qualname__

            @functools.wraps(fn)
            def wrapper(*a, **kw):
                with self.span(label, **attrs):
                    return fn(*a, **kw)

            return wrapper

        return deco

    def annotate(self, **attrs) -> None:
        """Attach attrs to the INNERMOST live span on this thread (no-op
        without one) — how deep layers add detail (e.g. the AWS retry
        count) without threading a span object through every signature."""
        if not self._enabled:
            return
        stack = getattr(self._local, "stack", None)
        if stack:
            stack[-1].attrs.update(attrs)

    def current(self) -> Optional[Span]:
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    def _finish(self, span: Span) -> None:
        self._buf.append(span)
        for cb in self._callbacks:
            try:
                cb(span)
            except Exception:
                pass

    # -- consumption -------------------------------------------------------

    def snapshot(self) -> list[Span]:
        """Completed spans, oldest first (non-destructive)."""
        return list(self._buf)

    def drain(self) -> list[Span]:
        """Snapshot and clear the tape."""
        out = list(self._buf)
        self._buf.clear()
        return out

    def clear(self) -> None:
        self._buf.clear()

    def on_finish(self, cb: Callable[[Span], None]) -> Callable[[Span], None]:
        self._callbacks.append(cb)
        return cb

    def remove_on_finish(self, cb: Callable[[Span], None]) -> None:
        if cb in self._callbacks:
            self._callbacks.remove(cb)


# The process-wide default tracer. Enabled by default: the per-span cost is
# two perf_counter_ns reads + one small object, paid a handful of times per
# reconcile/solve — and the metrics bridge depends on it. ``TRACER.disable()``
# turns every instrumentation point into the shared no-op.
TRACER = Tracer()


def span(name: str, **attrs):
    return TRACER.span(name, **attrs)


def traced(name: Optional[str] = None, **attrs):
    return TRACER.traced(name, **attrs)


def annotate(**attrs) -> None:
    TRACER.annotate(**attrs)
