"""Flight-recorder tracing & provenance for the solve hot path, the
controller loops, and the AWS wire layer.

Three pieces (designs/tracing.md):

- ``spans``      — a low-overhead monotonic-clock span recorder: context
                   manager + decorator API, thread-local span stack,
                   bounded ring buffer, near-zero cost when disabled.
- ``export``     — Chrome trace-event JSON export of the ring buffer plus
                   the bridge that feeds span durations into the
                   ``metrics.py`` histograms (so ``/metrics`` exposes
                   per-phase latency without a second instrumentation
                   layer).
- ``provenance`` — the per-solve provenance record (device kind, chosen
                   kernel backend, scale, per-phase timings, git sha)
                   attached to every solver result and stamped into every
                   bench row, so no measurement can be silent about what
                   hardware/backend produced it.

The round-5 verdict motivated this: headline latency claims went stale
because nothing in the system stamped bench rows with device/backend, and
the end-to-end p99 could not be decomposed into encode / transfer /
device-solve / decode authoritatively. Every future perf claim is now a
machine-checkable artifact.
"""

from .export import (
    MetricsBridge,
    SpanAggregator,
    aggregate_spans,
    to_chrome_trace,
    validate_chrome_trace,
    write_chrome_trace,
)
from .provenance import ProvenanceRecord, git_sha, last_record, stamp_row
from .spans import TRACER, Span, Tracer, annotate, span, traced

__all__ = [
    "TRACER",
    "Span",
    "Tracer",
    "span",
    "traced",
    "annotate",
    "ProvenanceRecord",
    "stamp_row",
    "git_sha",
    "last_record",
    "MetricsBridge",
    "SpanAggregator",
    "aggregate_spans",
    "to_chrome_trace",
    "write_chrome_trace",
    "validate_chrome_trace",
]
