"""jitwatch: the compile/retrace ledger behind the device-plane observatory.

The entire device hot path is built on a "one compiled program per ladder
bucket" discipline — asserted in comments (``ops/ffd.py``,
``ops/encode.py``, ``scheduling/optimizer.py``, ``parallel/mesh.py``) but
never *observed*: two prior compile cliffs (the ~270ms vmap-screen re-jit
the PR 8 simulator surfaced, the 245.8ms cold lane solve in ``config9``)
were diagnosed indirectly from wall-clock anomalies. This module makes
compiles first-class telemetry:

- :func:`tracked_jit` — a drop-in ``jax.jit`` replacement used at every
  jit/shard_map callsite in the tree. Each wrapped function belongs to a
  **program family** (``ffd.solve``, ``screen.repack``, ``mesh.lanes`` …);
  every call derives the abstract *trace signature* of its arguments
  (pytree structure + per-leaf shape/dtype + static-arg values — the same
  key axes ``jax.jit``'s cache uses) and folds the outcome into the
  process-wide :class:`JitLedger`: cache hits, compiles, **retrace
  attribution** (which signature axis changed vs. the previous trace —
  the ladder's whole point is that steady state retraces zero times),
  first-compile wall and callsite, and per-family dispatch bytes.
- :class:`JitLedger` — bounded, thread-safe, process-wide. ``seq()`` is a
  monotonic compile counter: any consumer (the solver's provenance stamp,
  the sim driver's warmup cursor, the retrace sentinel, the bench gates)
  can prove a window ran warm by reading it twice.
- :func:`install_monitoring` — hooks ``jax.monitoring`` duration events
  where the runtime exposes them, so compiles from *un-wrapped* callsites
  (library internals, future code that forgets the wrapper) are counted
  rather than silently missed.

Each compile/retrace also lands as a ``jit.compile`` span on the flight
recorder (Chrome-trace export + the metrics bridge feeds
``karpenter_jit_compile_seconds``), and bumps
``karpenter_jit_compiles_total{family,kind}``.

Compile wall is measured as the first call with a new signature — trace +
compile + one execution. That overstates pure-XLA-compile time by one
kernel run, which is noise at the ~100ms-to-seconds compile scale this
ledger exists to attribute; the ``jax.monitoring`` hook reports the
runtime's own backend-compile durations beside it where available.

``KARPENTER_TPU_JITWATCH=0`` kills the layer: wrapped functions forward
straight to their plain jitted form (one env read of overhead), nothing
is recorded, and the metric families stay absent from ``/metrics``.
"""

from __future__ import annotations

import inspect
import os
import threading
import time
import traceback
import weakref
from collections import deque
from typing import Optional

#: bounded compile-event history (a healthy process compiles tens of
#: programs, not thousands; a runaway retrace storm must not grow memory)
EVENTS_CAP = 1024


def enabled() -> bool:
    return os.environ.get("KARPENTER_TPU_JITWATCH", "1") != "0"


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

class FamilyRecord:
    """Per-program-family accounting. Plain mutable holder; the ledger's
    lock guards every access."""

    __slots__ = ("name", "callsite", "compiles", "retraces", "hits",
                 "compile_ms_total", "last_compile_ms", "signatures",
                 "last_sig", "last_change", "dispatch_bytes_total",
                 "last_arg_bytes", "warmed", "warm_ms_total")

    def __init__(self, name: str, callsite: str):
        self.name = name
        self.callsite = callsite
        self.compiles = 0          # first trace of a brand-new family
        self.retraces = 0          # additional signatures after the first
        self.hits = 0              # calls served by an already-traced sig
        self.compile_ms_total = 0.0
        self.last_compile_ms = 0.0
        self.signatures: dict = {}  # sig -> call count
        self.last_sig = None
        self.last_change = ""      # retrace attribution of the last trace
        self.dispatch_bytes_total = 0
        self.last_arg_bytes = 0
        self.warmed = 0            # AOT warmup replays (trace/warmup.py)
        self.warm_ms_total = 0.0

    def as_dict(self) -> dict:
        return {
            "family": self.name,
            "callsite": self.callsite,
            "compiles": self.compiles,
            "retraces": self.retraces,
            "hits": self.hits,
            "signatures": len(self.signatures),
            "compile_ms_total": round(self.compile_ms_total, 1),
            "last_compile_ms": round(self.last_compile_ms, 1),
            "last_change": self.last_change,
            "dispatch_bytes_total": int(self.dispatch_bytes_total),
            "last_arg_bytes": int(self.last_arg_bytes),
            "warmed": self.warmed,
            "warm_ms_total": round(self.warm_ms_total, 1),
        }


class JitLedger:
    """Process-wide compile/retrace ledger (one per process, like the
    metrics registry). Thread-safe; every read returns plain data."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, FamilyRecord] = {}
        self._events: deque = deque(maxlen=EVENTS_CAP)
        self._seq = 0               # monotonic compile counter
        #: jax.monitoring observations: event key -> [count, total_secs]
        self._monitor: dict[str, list] = {}
        #: AOT warmup replays (trace/warmup.py) — kept OUT of the compile
        #: event ring: a warmup is not a compile the serving path paid,
        #: and the zero-retrace gates must not count it
        self._warm_events: deque = deque(maxlen=EVENTS_CAP)

    # -- recording ----------------------------------------------------------
    def family(self, name: str, callsite: str = "") -> FamilyRecord:
        with self._lock:
            rec = self._families.get(name)
            if rec is None:
                rec = self._families[name] = FamilyRecord(name, callsite)
            elif callsite and not rec.callsite:
                rec.callsite = callsite
            return rec

    def record_hit(self, name: str, sig, nbytes: int = 0) -> None:
        with self._lock:
            rec = self._families.get(name)
            if rec is None:
                rec = self._families[name] = FamilyRecord(name, "")
            rec.hits += 1
            if sig is not None:
                rec.signatures[sig] = rec.signatures.get(sig, 0) + 1
            rec.dispatch_bytes_total += nbytes
            if nbytes:
                rec.last_arg_bytes = nbytes

    def record_compile(self, name: str, sig, wall_ms: float, changed: str,
                       nbytes: int = 0, callsite: str = "") -> dict:
        """One new trace of ``name``: returns the event dict (also kept in
        the bounded event ring and counted on the metric family)."""
        with self._lock:
            rec = self._families.get(name)
            if rec is None:
                rec = self._families[name] = FamilyRecord(name, callsite)
            kind = "compile" if not rec.signatures else "retrace"
            if kind == "compile":
                rec.compiles += 1
            else:
                rec.retraces += 1
            rec.signatures[sig] = 1
            rec.last_sig = sig
            rec.last_change = changed
            rec.compile_ms_total += wall_ms
            rec.last_compile_ms = wall_ms
            rec.dispatch_bytes_total += nbytes
            if nbytes:
                rec.last_arg_bytes = nbytes
            self._seq += 1
            event = {
                "seq": self._seq,
                "family": name,
                "kind": kind,
                "wall_ms": round(wall_ms, 1),
                "changed": changed,
                "at_unix": round(time.time(), 3),
            }
            self._events.append(event)
        _TLS.compiles = getattr(_TLS, "compiles", 0) + 1
        try:
            from ..metrics import JIT_COMPILES

            JIT_COMPILES.inc(family=name, kind=kind)
        except Exception:
            pass
        return event

    def record_warm(self, name: str, sig, wall_ms: float) -> None:
        """One AOT warmup replay of ``name`` (``lower().compile()`` —
        trace/warmup.py). Claims the signature so the first REAL call with
        these shapes records a *hit*, and keeps the warm wall in its own
        accounting: ``_seq`` does not move, ``thread_compiles()`` does not
        move, and no event lands in the compile ring — a warmed family is
        exactly as invisible to the retrace gates as a warm one."""
        with self._lock:
            rec = self._families.get(name)
            if rec is None:
                rec = self._families[name] = FamilyRecord(name, "")
            if sig is not None and sig not in rec.signatures:
                rec.signatures[sig] = 0
                rec.last_sig = sig
            rec.warmed += 1
            rec.warm_ms_total += wall_ms
            self._warm_events.append({
                "family": name,
                "wall_ms": round(wall_ms, 1),
                "at_unix": round(time.time(), 3),
            })

    def family_signatures(self, name: str) -> int:
        """How many trace signatures ``name`` has (compiled OR warmed) —
        0 means the family is still cold in this process."""
        with self._lock:
            rec = self._families.get(name)
            return len(rec.signatures) if rec else 0

    def warm_summary(self) -> dict:
        """{family: {count, wall_ms}} of AOT warmup replays so far."""
        with self._lock:
            out: dict[str, dict] = {}
            for e in self._warm_events:
                cell = out.setdefault(
                    e["family"], {"count": 0, "wall_ms": 0.0}
                )
                cell["count"] += 1
                cell["wall_ms"] = round(cell["wall_ms"] + e["wall_ms"], 1)
            return out

    def note_monitor(self, key: str, secs: float) -> None:
        with self._lock:
            cell = self._monitor.setdefault(key, [0, 0.0])
            cell[0] += 1
            cell[1] += secs

    # -- reading ------------------------------------------------------------
    def seq(self) -> int:
        """The monotonic compile counter: reading it twice bounds a
        window's compile count (0 delta == the window ran warm)."""
        with self._lock:
            return self._seq

    def events_since(self, seq: int) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._events if e["seq"] > seq]

    def compiles_total(self) -> int:
        with self._lock:
            return sum(
                r.compiles + r.retraces for r in self._families.values()
            )

    def snapshot(self) -> dict:
        """JSON-ready ledger state: the ``/debug/device`` page's core, the
        ``obs device`` CLI's input, and the sim report's device plane."""
        with self._lock:
            return {
                "enabled": enabled(),
                "seq": self._seq,
                "families": {
                    name: rec.as_dict()
                    for name, rec in sorted(self._families.items())
                },
                "events": [dict(e) for e in self._events],
                "monitoring": {
                    k: {"count": c, "total_s": round(s, 3)}
                    for k, (c, s) in sorted(self._monitor.items())
                },
                "warmups": [dict(e) for e in self._warm_events],
            }

    def live_arg_bytes(self) -> dict:
        """{family: last_arg_bytes} for families with a footprint — the
        cheap accessor the per-tick gauge export uses (no event-ring
        copy; ``snapshot()`` is for pages and artifacts)."""
        with self._lock:
            return {
                name: rec.last_arg_bytes
                for name, rec in self._families.items()
                if rec.last_arg_bytes
            }

    def dispatch_bytes(self) -> dict:
        """{family: cumulative dispatch bytes}, nonzero families only."""
        with self._lock:
            return {
                name: rec.dispatch_bytes_total
                for name, rec in self._families.items()
                if rec.dispatch_bytes_total
            }

    def top_retracers(self, n: int = 8) -> list[dict]:
        with self._lock:
            recs = sorted(
                self._families.values(),
                key=lambda r: (-r.retraces, -r.compiles, r.name),
            )
            return [r.as_dict() for r in recs[:n] if r.retraces or r.compiles]

    def reset(self) -> None:
        """Tests only: a fresh process-equivalent ledger."""
        with self._lock:
            self._families.clear()
            self._events.clear()
            self._seq = 0
            self._monitor.clear()
            self._warm_events.clear()


_LEDGER = JitLedger()

#: per-thread compile counter: a solve's provenance stamp must count ITS
#: OWN compiles, not a concurrent screen's on another thread (the ledger
#: seq is process-global; a warm solve overlapping someone else's compile
#: would otherwise stamp compiles>0 and read as cold)
_TLS = threading.local()


def ledger() -> JitLedger:
    return _LEDGER


def thread_compiles() -> int:
    """Compiles recorded on the CALLING thread so far — read twice to
    bound one code window's own compile count."""
    return getattr(_TLS, "compiles", 0)


# ---------------------------------------------------------------------------
# jax.monitoring hook: compiles from un-wrapped callsites
# ---------------------------------------------------------------------------

_monitor_installed = False
_monitor_lock = threading.Lock()


def install_monitoring() -> bool:
    """Register a ``jax.monitoring`` duration listener that folds every
    compile-flavored runtime event into the ledger. Idempotent; returns
    whether a listener is installed (older runtimes without the API
    return False — the tracked_jit signature ledger still works)."""
    global _monitor_installed
    with _monitor_lock:
        if _monitor_installed:
            return True
        try:
            from jax import monitoring as _m

            register = getattr(
                _m, "register_event_duration_secs_listener", None
            )
            if register is None:
                return False

            def _listener(key: str, secs: float, **kw) -> None:
                if not enabled():
                    return
                if "compil" in key or "trace" in key.split("/")[-1]:
                    _LEDGER.note_monitor(key, float(secs))

            register(_listener)
            _monitor_installed = True
            return True
        except Exception:
            return False


# ---------------------------------------------------------------------------
# wrapper registry: every live tracked_jit wrapper, by family
# ---------------------------------------------------------------------------

_registry_lock = threading.Lock()
#: family -> [weakref to _TrackedJit]. Weak: factory-built wrappers
#: (optimizer lane programs, mesh lane fns) live in lru_caches and may be
#: evicted; the registry must not pin them.
_registry: dict[str, list] = {}


def _register(wrapper: "_TrackedJit") -> None:
    with _registry_lock:
        refs = _registry.setdefault(wrapper.family, [])
        refs[:] = [r for r in refs if r() is not None]
        refs.append(weakref.ref(wrapper))


def wrappers_for(family: str) -> list:
    """The LIVE tracked wrappers registered under ``family`` (a factory
    family like ``optimizer.lanes`` can have several — one per builder
    parameterization)."""
    with _registry_lock:
        refs = _registry.get(family, ())
        return [w for w in (r() for r in refs) if w is not None]


def all_wrappers() -> list:
    """Every live tracked wrapper in the process — the warmup manifest
    builder walks this."""
    with _registry_lock:
        out = []
        for refs in _registry.values():
            out.extend(w for w in (r() for r in refs) if w is not None)
        return out


# ---------------------------------------------------------------------------
# tracked_jit
# ---------------------------------------------------------------------------

def _trace_state_clean() -> bool:
    """True when the calling thread is NOT inside a jax trace. Runtimes
    without the API report clean (recording proceeds; nested phantom
    events are then only guarded by the enclosing wrapper's own event)."""
    try:
        import jax

        return bool(jax.core.trace_state_clean())
    except Exception:
        return True


def _abstract_spec(args, kwargs):
    """The abstract twin of one call's arguments: array-likes become
    ``jax.ShapeDtypeStruct`` (only shape/dtype survive — exactly the axes
    ``_leaf_sig`` keys on, so a replay produces the identical signature),
    python scalars and static values stay concrete. Captured BEFORE the
    dispatch runs — donated buffers are invalid after it."""
    import jax

    def leaf(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            return jax.ShapeDtypeStruct(tuple(shape), dtype)
        return x

    return jax.tree_util.tree_map(leaf, (args, kwargs))


def _leaf_sig(leaf) -> tuple:
    shape = getattr(leaf, "shape", None)
    if shape is not None:
        return (tuple(shape), str(getattr(leaf, "dtype", "?")))
    # dynamic python scalars trace by dtype, not value (weak types): the
    # signature must not call a changing n_pre int a retrace
    return (type(leaf).__name__,)


def _leaf_bytes(leaf) -> int:
    n = getattr(leaf, "nbytes", None)
    return int(n) if isinstance(n, (int,)) else 0


def _describe_change(prev, cur) -> str:
    """Human-readable retrace attribution: WHICH signature axis moved.
    ``prev``/``cur`` are (treedef, leaf_sigs, statics) triples."""
    if prev is None:
        return "first trace"
    if prev[0] != cur[0]:
        return "pytree structure changed"
    bits: list[str] = []
    pl, cl = prev[1], cur[1]
    if len(pl) != len(cl):
        return f"leaf count {len(pl)} -> {len(cl)}"
    for i, (a, b) in enumerate(zip(pl, cl)):
        if a == b:
            continue
        if len(a) == 2 and len(b) == 2 and a[1] != b[1]:
            bits.append(f"leaf{i}.dtype {a[1]} -> {b[1]}")
        elif len(a) == 2 and len(b) == 2:
            sa, sb = a[0], b[0]
            if len(sa) == len(sb):
                for ax, (da, db) in enumerate(zip(sa, sb)):
                    if da != db:
                        bits.append(f"leaf{i}.shape[{ax}] {da} -> {db}")
            else:
                bits.append(f"leaf{i}.shape {sa} -> {sb}")
        else:
            bits.append(f"leaf{i} {a} -> {b}")
    ps, cs = dict(prev[2]), dict(cur[2])
    for k in sorted(set(ps) | set(cs)):
        if ps.get(k) != cs.get(k):
            bits.append(f"static {k}: {ps.get(k)!r} -> {cs.get(k)!r}")
    return "; ".join(bits[:6]) or "signature changed"


def _callsite_of(fn) -> str:
    try:
        code = fn.__code__
        return f"{os.path.basename(code.co_filename)}:{code.co_firstlineno}"
    except Exception:
        return ""


def _compile_backtrace(depth: int = 4) -> str:
    """Short summary of who triggered the first compile (the ledger's
    first-compile backtrace): the innermost non-jitwatch frames."""
    frames = traceback.extract_stack()[:-2]
    keep = [
        f"{os.path.basename(f.filename)}:{f.lineno}:{f.name}"
        for f in frames
        if "jitwatch" not in f.filename
    ]
    return " <- ".join(reversed(keep[-depth:]))


class _TrackedJit:
    """The wrapper ``tracked_jit`` returns: behaves exactly like the
    jitted function, with the ledger fold on every call."""

    def __init__(self, fn, family: str, jit_kwargs: dict):
        import jax

        self.family = family
        self.__wrapped__ = fn
        self._jit = jax.jit(fn, **jit_kwargs)
        self._static = tuple(jit_kwargs.get("static_argnames") or ())
        # bound lazily: inspect.signature pays once, only when statics can
        # arrive positionally (compact_plan(placed, E) style calls)
        self._pysig = inspect.signature(fn) if self._static else None
        self._lock = threading.Lock()
        self._seen: set = set()
        self._last_sig = None
        self._callsite = _callsite_of(fn)
        #: sig -> abstract (args, kwargs) replay spec (ShapeDtypeStruct
        #: leaves, concrete python scalars) captured at first trace — the
        #: warmup manifest's raw material (trace/warmup.py)
        self._replay: dict = {}
        #: builder parameters for factory-made wrappers (set by the
        #: factory: optimizer._program, device_state._patch_fn, the mesh
        #: lane builders) so a fresh process can re-materialize THIS
        #: wrapper before replaying its specs; None for module-level fns
        self.warmup_params: Optional[dict] = None
        _register(self)

    # jax's jitted functions expose lower/trace etc.; forward unknowns so
    # the wrapper stays a drop-in
    def __getattr__(self, name):
        return getattr(self._jit, name)

    def _signature(self, args, kwargs) -> tuple[tuple, int]:
        import jax

        if self._static:
            bound = self._pysig.bind(*args, **kwargs)
            bound.apply_defaults()
            statics = tuple(
                (k, bound.arguments.get(k)) for k in self._static
            )
            dynamic = {
                k: v for k, v in bound.arguments.items()
                if k not in self._static
            }
            leaves, treedef = jax.tree_util.tree_flatten(dynamic)
        else:
            statics = ()
            leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sigs = tuple(_leaf_sig(leaf) for leaf in leaves)
        nbytes = sum(_leaf_bytes(leaf) for leaf in leaves)
        return (treedef, sigs, statics), nbytes

    def __call__(self, *args, **kwargs):
        if not enabled():
            return self._jit(*args, **kwargs)
        if not _trace_state_clean():
            # called UNDER an enclosing jax trace (mesh.solve_shard
            # tracing calls ffd_solve with tracers): recording here would
            # log a phantom compile whose wall is already inside the
            # enclosing family's event AND poison the signature set — a
            # later REAL standalone compile of the same shapes would then
            # read as a hit and the zero-retrace gates would pass falsely.
            # The enclosing tracked wrapper owns this compile's event.
            return self._jit(*args, **kwargs)
        if not _monitor_installed:      # lock-free fast path; see install
            install_monitoring()
        try:
            sig, nbytes = self._signature(args, kwargs)
            hashable = True
            # check-and-claim in ONE lock block: two threads racing the
            # same new signature must produce exactly one compile event —
            # the loser records a hit (a doubled event would fail the
            # hard zero-retrace gates as a phantom retrace)
            with self._lock:
                known = sig in self._seen
                if not known:
                    prev = self._last_sig
                    self._seen.add(sig)
                    self._last_sig = sig
        except Exception:
            # an unhashable static / exotic pytree must never take down the
            # dispatch it observes
            sig, nbytes, known, hashable = None, 0, True, False
        if known:
            _LEDGER.record_hit(self.family, sig if hashable else None, nbytes)
            return self._jit(*args, **kwargs)
        # new signature: this call traces (and compiles on a cache miss
        # of jax's own); time it and attribute the changed axis
        changed = _describe_change(prev, sig)
        try:
            spec = _abstract_spec(args, kwargs)
            with self._lock:
                self._replay[sig] = spec
        except Exception:
            pass  # an exotic pytree loses its manifest entry, not the call
        from .spans import span as _span

        t0 = time.perf_counter()
        with _span("jit.compile", family=self.family,
                   kind=("compile" if prev is None else "retrace"),
                   changed=changed):
            out = self._jit(*args, **kwargs)
        wall_ms = (time.perf_counter() - t0) * 1e3
        _LEDGER.record_compile(
            self.family, sig, wall_ms, changed, nbytes=nbytes,
            callsite=self._callsite or _compile_backtrace(),
        )
        return out

    # -- AOT warmup (trace/warmup.py drives these) --------------------------
    def replay_specs(self) -> list:
        """The abstract (args, kwargs) specs this wrapper has traced —
        one per signature, manifest-ready."""
        with self._lock:
            return list(self._replay.values())

    def warm(self, spec) -> float:
        """AOT-compile one replay spec (``lower().compile()``) and claim
        its signature: the next real call with these shapes records a
        ledger *hit*, and jax serves the executable from its own (persistent
        cache backed) compile cache. Returns the warmup wall in ms."""
        args, kwargs = spec
        try:
            sig, _ = self._signature(args, kwargs)
            with self._lock:
                if sig in self._seen:   # already traced/warmed: idempotent
                    return 0.0
        except Exception:
            sig = None
        t0 = time.perf_counter()
        self._jit.lower(*args, **kwargs).compile()
        wall_ms = (time.perf_counter() - t0) * 1e3
        if sig is not None:
            with self._lock:
                self._seen.add(sig)
                self._last_sig = sig
                self._replay.setdefault(sig, spec)
        _LEDGER.record_warm(self.family, sig, wall_ms)
        return wall_ms


def tracked_jit(fn=None, *, family: Optional[str] = None, **jit_kwargs):
    """``jax.jit`` with the ledger fold. Use as a decorator
    (``@tracked_jit(family="screen.repack")``), a decorator factory with
    jit options (``static_argnames`` / ``donate_argnums`` pass through),
    or a direct call (``tracked_jit(impl, family="ffd.solve", ...)``)."""
    if fn is None:
        return lambda f: tracked_jit(f, family=family, **jit_kwargs)
    fam = family or getattr(fn, "__name__", "anonymous")
    return _TrackedJit(fn, fam, jit_kwargs)


def note_dispatch(family: str, nbytes: int) -> None:
    """Fold link bytes a non-jit path shipped for ``family`` (the sidecar's
    server-side device cache, the solver's upload path) into the ledger's
    per-family dispatch accounting. No-op when jitwatch is off."""
    if not enabled():
        return
    _LEDGER.record_hit(family, None, int(nbytes))
