"""Pod model: the unit of scheduling demand.

Carries exactly what the scheduler needs: resource requests, scheduling
constraints (nodeSelector, required node affinity, tolerations, topology
spread, pod anti-affinity), and disruption-cost inputs (priority,
deletion cost, do-not-disrupt). Reference parity: the core scheduler's pod
view plus ``designs/consolidation.md:24-36`` cost inputs.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence

from .requirements import Operator, Requirement, Requirements
from .resources import ResourceVector
from . import labels as lbl

_uid_counter = itertools.count()
# scheduling_key -> interned token (see Pod.scheduling_token). Never cleared:
# re-interning a key under a fresh number would hand equal-key pods different
# tokens, and the encoder grouping by token would then SPLIT a constraint-
# coupled group (atomic co-location, self-matching anti-affinity, spread) —
# a correctness bug, not an efficiency loss. Growth is bounded by the number
# of distinct scheduling shapes seen over the process lifetime (~1KB each).
_TOKEN_INTERN: dict[tuple, int] = {}
_TOKEN_LOCK = threading.Lock()
_token_counter = itertools.count()
# (scheduling_token, labels) -> interned consolidation-group token (see
# Pod.group_token). Same never-renumber rule as _TOKEN_INTERN.
_GROUP_INTERN: dict[tuple, int] = {}
_group_counter = itertools.count()
# gang name -> 1-based ordinal (0 = "no gang", the zero-fill-safe sentinel
# for the encoders' node_gang column). Never renumbered, same rule as the
# token interns: a gang re-interned under a fresh ordinal would make two
# encodes of the same cluster disagree about node_gang.
_GANG_INTERN: dict[str, int] = {}


def gangs_enabled() -> bool:
    """Kill switch for the gang-scheduling plane (scheduling/groups.py):
    ``KARPENTER_TPU_GANGS=0`` makes every gang annotation inert — grouping,
    encoding, solve enforcement, and disruption locking all read this, so a
    disarmed run is byte-identical to pre-gang behavior."""
    import os

    return os.environ.get("KARPENTER_TPU_GANGS", "1") == "1"


def gang_ordinal(name: str) -> int:
    """Process-interned 1-based ordinal for a gang name (0 for none)."""
    if not name:
        return 0
    with _TOKEN_LOCK:
        o = _GANG_INTERN.get(name)
        if o is None:
            o = _GANG_INTERN[name] = len(_GANG_INTERN) + 1
    return o


class _Seq:
    """Process-wide write-sequence cell (a mutable int). Shared with
    state/cluster.py's NODE_WRITE_SEQ — one definition for both."""

    __slots__ = ("v",)

    def __init__(self):
        self.v = 0


#: Bumped by every scheduling-relevant Pod field write, process-wide. The
#: O(1) revision token the provisioning loop hands the encoded-problem
#: cache folds this in: a direct ``pod.requests = ...`` reassignment bumps
#: Pod._version but NOT the cluster revision, and without this sequence the
#: revision-keyed cache would serve the pod's stale encoding (the legacy
#: per-pod (id, _version) key caught exactly that).
POD_WRITE_SEQ = _Seq()

#: Bumped by every Pod ``phase`` / ``node_name`` write, process-wide. The
#: cluster store's pending-pod index resyncs against it: the sanctioned
#: mutation surface (bind/unbind/apply/delete) maintains the index
#: incrementally and snapshots this sequence, so a DIRECT ``pod.phase =``
#: write anywhere else makes the next ``pending_pods()`` read fall back to
#: one full rescan (over-invalidation, never a stale answer).
POD_BIND_SEQ = _Seq()


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects

    def tolerates(self, taint: "Taint") -> bool:  # noqa: F821 (forward ref)
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass(frozen=True)
class TopologySpreadConstraint:
    topology_key: str  # e.g. topology.kubernetes.io/zone or kubernetes.io/hostname
    max_skew: int = 1
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    label_selector: Mapping[str, str] = field(default_factory=dict)

    def __hash__(self):
        return hash((self.topology_key, self.max_skew, self.when_unsatisfiable,
                     tuple(sorted(self.label_selector.items()))))


@dataclass(frozen=True)
class PodAffinityTerm:
    """Required pod (anti-)affinity term (label selector + topology key)."""

    topology_key: str
    label_selector: Mapping[str, str] = field(default_factory=dict)

    def __hash__(self):
        return hash((self.topology_key, tuple(sorted(self.label_selector.items()))))

    def matches(self, pod: "Pod") -> bool:
        return all(pod.labels.get(k) == v for k, v in self.label_selector.items())


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    requests: ResourceVector = field(default_factory=ResourceVector)
    node_selector: dict[str, str] = field(default_factory=dict)
    # Required-during-scheduling node affinity, flattened to requirement terms
    # (OR across terms is not yet supported; terms are ANDed like nodeSelector).
    node_affinity: list[Requirement] = field(default_factory=list)
    # Preferred-during-scheduling node affinity (soft): the solver tries to
    # honor these, then relaxes them for pods that would otherwise pend
    # (karpenter's preference-relaxation; weights collapse to all-or-nothing
    # — one relaxation round drops them together).
    preferred_node_affinity: list[Requirement] = field(default_factory=list)
    tolerations: list[Toleration] = field(default_factory=list)
    topology_spread: list[TopologySpreadConstraint] = field(default_factory=list)
    anti_affinity: list[PodAffinityTerm] = field(default_factory=list)
    affinity: list[PodAffinityTerm] = field(default_factory=list)
    priority: int = 0
    node_name: str = ""  # bound node, empty = pending
    phase: str = "Pending"
    owner_key: str = ""  # ReplicaSet/Deployment identity for grouping
    # lazily computed by scheduling_key(); excluded from comparisons
    _scheduling_key: Optional[tuple] = field(default=None, repr=False, compare=False)
    _scheduling_token: Optional[int] = field(default=None, repr=False, compare=False)
    # (version, token) memo for group_token(); version-guarded because
    # labels participate and labels bump _version on reassignment
    _group_token: Optional[tuple] = field(default=None, repr=False, compare=False)
    # bumped on every scheduling-relevant field assignment; cross-solve
    # caches (ops.encode._PROBLEM_CACHE) key on (id, _version) pairs so a
    # sanctioned field reassignment can never serve a stale encoding
    _version: int = field(default=0, repr=False, compare=False)

    # Fields covered by scheduling_key(); assigning any of them invalidates
    # the cached key. (In-place mutation of a field's container — e.g.
    # ``pod.node_selector["k"] = v`` — is not detectable; assign a fresh
    # value instead, which is what all in-tree callers do.)
    _KEY_FIELDS = frozenset({
        "requests", "node_selector", "node_affinity", "preferred_node_affinity",
        "tolerations", "topology_spread", "anti_affinity", "affinity",
    })
    # Fields that invalidate cross-solve encodings: the key fields plus
    # labels (selector-matching input for topology terms).
    _VERSION_FIELDS = _KEY_FIELDS | {"labels"}

    def __post_init__(self):
        if not self.uid:
            self.uid = f"pod-{next(_uid_counter)}"
        # One pod slot is always consumed.
        if self.requests.get("pods") == 0:
            self.requests.set("pods", 1)

    def __setattr__(self, name, value):
        if name in Pod._KEY_FIELDS:
            if getattr(self, "_scheduling_key", None) is not None:
                object.__setattr__(self, "_scheduling_key", None)
            # token clears UNCONDITIONALLY: a racing scheduling_token() may
            # have memoized a token from the pre-assignment key while
            # _scheduling_key was transiently None (review round-3)
            if getattr(self, "_scheduling_token", None) is not None:
                object.__setattr__(self, "_scheduling_token", None)
        # RE-assignment only (the field already exists): dataclass __init__
        # assigns every field once, and construction must not look like a
        # pendingness flip to the store's index
        rebind = (
            (name == "phase" or name == "node_name") and name in self.__dict__
        )
        object.__setattr__(self, name, value)
        # version bumps AFTER the field write: a reader that keys on the new
        # version has then necessarily seen (or will re-read) the new value,
        # so caches can only over-invalidate, never pin a stale encoding
        # under a fresh version
        if name in Pod._VERSION_FIELDS:
            object.__setattr__(self, "_version", getattr(self, "_version", 0) + 1)
            POD_WRITE_SEQ.v += 1
        elif rebind:
            POD_BIND_SEQ.v += 1

    def bump_version(self) -> None:
        """Explicit invalidation after IN-PLACE mutation of a scheduling
        field's container (e.g. ``pod.labels[k] = v`` — a common k8s
        idiom). ``__setattr__`` only sees reassignment; a caller that
        mutates in place must call this (or reassign a fresh container) or
        cross-solve caches may serve the pod's stale encoding."""
        object.__setattr__(self, "_scheduling_key", None)
        object.__setattr__(self, "_scheduling_token", None)
        object.__setattr__(self, "_version", getattr(self, "_version", 0) + 1)
        POD_WRITE_SEQ.v += 1

    # -- scheduling views --------------------------------------------------
    def requirements(self) -> Requirements:
        """nodeSelector + required node affinity as one requirement set."""
        reqs = Requirements.from_node_selector(self.node_selector)
        for r in self.node_affinity:
            reqs.add(r)
        return reqs

    def tolerates(self, taint) -> bool:
        return any(t.tolerates(taint) for t in self.tolerations)

    def tolerates_all(self, taints) -> bool:
        return all(self.tolerates(t) for t in taints if t.effect in ("NoSchedule", "NoExecute"))

    def do_not_disrupt(self) -> bool:
        return self.annotations.get(lbl.ANNOTATION_DO_NOT_DISRUPT) == "true"

    def deletion_cost(self) -> float:
        try:
            return float(self.annotations.get("controller.kubernetes.io/pod-deletion-cost", "0"))
        except ValueError:
            return 0.0

    def is_pending(self) -> bool:
        return self.phase == "Pending" and not self.node_name

    # -- gang views (designs/gang-scheduling.md) ---------------------------
    def gang_name(self) -> str:
        """Gang identity, or "" — annotation-carried, scheduling-key-inert."""
        return self.annotations.get(lbl.ANNOTATION_POD_GROUP, "")

    def gang_min(self) -> int:
        """All-or-nothing floor: a gang with fewer than this many members
        placed must place NONE (scheduling/groups.enforce_gangs)."""
        try:
            return int(self.annotations.get(lbl.ANNOTATION_POD_GROUP_MIN, "0"))
        except ValueError:
            return 0

    def gang_ordinal(self) -> int:
        """Interned gang ordinal (0 = no gang) for the node_gang tensor
        column; intentionally NOT gated on ``gangs_enabled()`` so the
        column is a pure function of cluster content (the kill switch
        gates consumers, not the encoding of identity)."""
        return gang_ordinal(self.gang_name())

    def gang_locked(self) -> bool:
        """True when disruption must treat this pod's node atomically: a
        live gang member may never be consolidated out from under its
        gang. Shares the blocked-predicate seam with do_not_disrupt()."""
        return bool(self.annotations.get(lbl.ANNOTATION_POD_GROUP)) and gangs_enabled()

    # -- topology views ----------------------------------------------------
    def hostname_cap(self) -> int:
        """Max replicas of this pod's group per node: 1 under self-matching
        hostname anti-affinity, max_skew under a DoNotSchedule hostname
        topology spread, else unbounded."""
        cap = 1 << 30
        for c in self.topology_spread:
            if c.topology_key == lbl.HOSTNAME and c.when_unsatisfiable == "DoNotSchedule":
                cap = min(cap, max(c.max_skew, 1))
        for a in self.anti_affinity:
            if a.topology_key == lbl.HOSTNAME and a.matches(self):
                cap = min(cap, 1)
        return cap

    def hostname_colocated(self) -> bool:
        """Required SELF-matching hostname pod affinity: every replica of
        the group must land on ONE node (the "pack my replicas together"
        co-location case; the encoder turns the group atomic)."""
        return any(
            a.topology_key == lbl.HOSTNAME and a.matches(self)
            for a in self.affinity
        )

    def zone_topology(self) -> Optional[tuple[str, int]]:
        """('spread', max_skew) | ('anti', 1) | ('affinity', 0) | None for the
        zone axis."""
        term = self.zone_topology_term()
        return term[:2] if term is not None else None

    def zone_topology_term(self) -> Optional[tuple[str, int, dict]]:
        """(mode, max_skew, label_selector) for the zone axis, or None.

        The selector is what existing cluster pods are counted against when
        the encoder/rebinder account for zone occupancy."""
        for a in self.anti_affinity:
            if a.topology_key == lbl.TOPOLOGY_ZONE and a.matches(self):
                return ("anti", 1, dict(a.label_selector))
        for c in self.topology_spread:
            if c.topology_key == lbl.TOPOLOGY_ZONE and c.when_unsatisfiable == "DoNotSchedule":
                return ("spread", max(c.max_skew, 1), dict(c.label_selector))
        for a in self.affinity:
            if a.topology_key == lbl.TOPOLOGY_ZONE and a.matches(self):
                return ("affinity", 0, dict(a.label_selector))
        # ScheduleAnyway: a PREFERENCE — balance when possible, relax
        # instead of going unschedulable (lowest precedence: a required
        # term above always wins the zone axis)
        for c in self.topology_spread:
            if c.topology_key == lbl.TOPOLOGY_ZONE and c.when_unsatisfiable == "ScheduleAnyway":
                return ("soft_spread", max(c.max_skew, 1), dict(c.label_selector))
        return None

    # -- grouping (dedup) key ----------------------------------------------
    def scheduling_token(self) -> int:
        """Process-interned integer standing for scheduling_key(): equal keys
        share one token. Grouping 50k pods hashes 50k large nested tuples
        per solve through the dict; the token reduces that to one tuple hash
        per pod LIFETIME (the token memoizes alongside the key and
        __setattr__ invalidation clears both)."""
        t = self._scheduling_token
        if t is None:
            key = self.scheduling_key()
            with _TOKEN_LOCK:  # atomic check-then-insert: concurrent solves
                t = _TOKEN_INTERN.get(key)  # must never mint two tokens for
                if t is None:               # one key (group-splitting bug)
                    t = _TOKEN_INTERN[key] = next(_token_counter)
            # memoize only if the key is still current: a racing KEY-field
            # assignment cleared _scheduling_key, and storing a token
            # derived from the old key would be PERMANENTLY stale (the
            # __setattr__ clear already happened). The identity check makes
            # the store atomic-enough: same object => same key content.
            if self._scheduling_key is key:
                self._scheduling_token = t
        return t

    def group_token(self) -> int:
        """Interned token for the CONSOLIDATION grouping identity:
        (scheduling shape, exact labels). Labels ride along because the
        repack validator matches selectors against a group representative's
        labels — two pods with equal scheduling keys but different labels
        must not share a group. Memoized per (pod, _version): labels
        reassignment (or ``bump_version()`` after in-place mutation) bumps
        the version and forces a re-intern."""
        memo = self._group_token
        if memo is not None and memo[0] == self._version:
            return memo[1]
        # capture the version BEFORE reading labels: a concurrent labels
        # reassignment between key computation and the store must leave a
        # memo that the version guard rejects, never a permanently-stale
        # token under the new version (same race _scheduling_token fixed)
        v = self._version
        key = (self.scheduling_token(), tuple(sorted(self.labels.items())))
        with _TOKEN_LOCK:
            t = _GROUP_INTERN.get(key)
            if t is None:
                t = _GROUP_INTERN[key] = next(_group_counter)
        object.__setattr__(self, "_group_token", (v, t))
        return t

    def scheduling_key(self) -> tuple:
        """Pods with equal keys are interchangeable to the solver; the
        encoder collapses them into one group with a count (the TPU-native
        replacement for the reference's per-pod loop — SURVEY.md section 7).

        Cached after first computation (admission-time keying): the fields it
        covers are fixed at pod creation in this model, and the encoder calls
        this once per pod per solve — at 50k pods the recompute would be the
        single biggest host-side cost in the hot path."""
        k = self._scheduling_key
        if k is None:
            k = self._scheduling_key = (
                self.requests.v.tobytes(),
                tuple(sorted(self.node_selector.items())),
                tuple(sorted((r.key, r.operator.value, r.values, r.min_values) for r in self.node_affinity)),
                tuple(sorted((r.key, r.operator.value, r.values, r.min_values) for r in self.preferred_node_affinity)),
                tuple(sorted((t.key, t.operator, t.value, t.effect) for t in self.tolerations)),
                tuple(sorted(self.topology_spread, key=lambda c: c.topology_key)),
                tuple(sorted(self.anti_affinity, key=lambda a: a.topology_key)),
                tuple(sorted(self.affinity, key=lambda a: a.topology_key)),
            )
        return k


def make_pods(
    count: int,
    name_prefix: str,
    requests: Mapping[str, object],
    **kwargs,
) -> list[Pod]:
    """Convenience constructor for test/bench workloads."""
    rv = ResourceVector.from_map(requests)
    pods = [
        Pod(name=f"{name_prefix}-{i}", requests=rv.copy(), **{k: (v.copy() if hasattr(v, "copy") else v) for k, v in kwargs.items()})
        for i in range(count)
    ]
    # Clones share one spec: stamp the dedup key once (admission-time keying)
    if pods:
        key = pods[0].scheduling_key()
        for p in pods[1:]:
            p._scheduling_key = key
    return pods
