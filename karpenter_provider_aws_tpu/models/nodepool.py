"""NodePool: the provisioning template + disruption policy + limits.

Owns what the reference consumes from the core library's NodePool API
(SURVEY.md section 2.2): template requirements/taints pointing at a
NodeClass, resource limits, weight, and the disruption block
(consolidationPolicy / consolidateAfter / expireAfter).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .requirements import Requirement, Requirements
from .resources import ResourceVector
from . import labels as lbl
from .nodeclass import KubeletConfiguration, SPEC_WRITE_SEQ


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class Limits:
    """Aggregate resource caps across a NodePool's nodes (core NodePool.spec.limits)."""

    resources: ResourceVector = field(default_factory=lambda: ResourceVector.from_map({}))
    unlimited: bool = True

    @staticmethod
    def of(**resources) -> "Limits":
        return Limits(resources=ResourceVector.from_map({k.replace("_", "-"): v for k, v in resources.items()}), unlimited=False)

    def exceeded_by(self, in_use: ResourceVector) -> bool:
        if self.unlimited:
            return False
        import numpy as np
        mask = self.resources.v > 0
        return bool((in_use.v[mask] > self.resources.v[mask]).any())


# Budget reason classes (core DisruptionReason vocabulary).
DISRUPTION_REASONS = ("Underutilized", "Empty", "Drifted", "Expired")


@dataclass
class Budget:
    """One disruption budget (core NodePool.spec.disruption.budgets entry):
    a node cap, optionally scoped to reasons and/or a cron-scheduled window.

    ``nodes`` is "N" or "P%". ``reasons`` empty = every reason. ``schedule``
    (5-field cron, UTC) + ``duration_s`` restrict the budget to
    [match, match+duration) windows — outside them the budget does not
    apply at all (core semantics: a schedule-gated "0" budget blocks
    disruption only during its window)."""

    nodes: str = "10%"
    reasons: tuple[str, ...] = ()
    schedule: Optional[str] = None
    duration_s: Optional[float] = None

    def applies(self, reason: str, now: Optional[float]) -> bool:
        if self.reasons and reason not in self.reasons:
            return False
        if self.schedule is not None:
            if now is None:
                return True  # no clock: be conservative, apply
            from ..utils.cron import CronSchedule

            return CronSchedule(self.schedule).active_within(
                now, self.duration_s or 60.0
            )
        return True

    def cap(self, total_nodes: int) -> int:
        import math

        if self.nodes.endswith("%"):
            # percentages round UP (k8s GetScaledValueFromIntOrPercent
            # semantics as used by karpenter budgets): "10%" of 3 nodes
            # allows 1 disruption, not 0
            return math.ceil(total_nodes * float(self.nodes[:-1]) / 100.0)
        return int(self.nodes)


@dataclass
class Disruption:
    """NodePool.spec.disruption (core): consolidation + expiration policy."""

    def __setattr__(self, name, value):
        object.__setattr__(self, name, value)
        SPEC_WRITE_SEQ.v += 1  # see SPEC_WRITE_SEQ (policy edits in place)

    consolidation_policy: str = "WhenUnderutilized"  # or WhenEmpty
    consolidate_after_s: Optional[float] = 0.0  # None = Never
    expire_after_s: Optional[float] = None  # None = Never
    # disruption budgets: plain "20%"/"5" strings (apply always, to every
    # reason) or Budget objects with reasons/schedule scoping
    budgets: list = field(default_factory=lambda: ["10%"])

    def _budget_objs(self) -> list[Budget]:
        return [b if isinstance(b, Budget) else Budget(nodes=b) for b in self.budgets]

    def max_disruptions(
        self, total_nodes: int, reason: str = "", now: Optional[float] = None
    ) -> int:
        """Disruptable-node cap for ``reason`` at ``now``: the minimum over
        every budget that applies (reason in scope, schedule window active).
        No applicable budget = no cap beyond the node count."""
        allowed = total_nodes
        for b in self._budget_objs():
            if not b.applies(reason, now):
                continue
            allowed = min(allowed, b.cap(total_nodes))
        return max(allowed, 0)


@dataclass
class NodePool:
    name: str
    nodeclass_name: str = "default"
    requirements: list[Requirement] = field(default_factory=list)
    taints: list[Taint] = field(default_factory=list)
    startup_taints: list[Taint] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    limits: Limits = field(default_factory=Limits)
    disruption: Disruption = field(default_factory=Disruption)
    weight: int = 0  # higher = preferred, like core NodePool.spec.weight
    # terminationGracePeriod (core): after this long in Deleting, the drain
    # force-completes — blocking PDBs and do-not-disrupt pods no longer
    # hold the node. None = wait forever.
    termination_grace_period_s: Optional[float] = None
    # Kubelet knobs templated onto every node of this pool (parity: the
    # v1beta1 NodePool.spec.template.spec.kubelet block).
    kubelet: "Optional[KubeletConfiguration]" = None

    def __setattr__(self, name, value):
        # process-wide spec write signal: a direct field reassignment on a
        # live pool (tests and ad-hoc operators edit in place instead of
        # re-applying) is invisible to the store's change journal, and the
        # disruption controller's dirty-set walk re-scans on this sequence
        # exactly like the encoders do on NODE_WRITE_SEQ
        object.__setattr__(self, name, value)
        SPEC_WRITE_SEQ.v += 1

    def scheduling_requirements(self) -> Requirements:
        """Template requirements + identity labels as a requirement set."""
        reqs = Requirements(self.requirements)
        reqs = reqs.union(Requirements.from_labels(self.labels))
        reqs = reqs.union(Requirements.from_labels({lbl.NODEPOOL: self.name}))
        return reqs

    # Fields excluded from the template-drift hash: they steer future
    # decisions (which node to open next, when to disrupt), they don't
    # change what is stamped onto an already-launched node. Everything
    # else is included BY DEFAULT so a newly added template field drifts
    # without anyone remembering to list it here (fail-safe; same pattern
    # as NodeClass._HASH_EXCLUDE).
    _HASH_EXCLUDE = ("name", "weight", "limits", "disruption")

    def hash(self) -> str:
        """Stable hash over the node TEMPLATE: everything stamped onto a
        launched node. A claim whose stamped hash diverges is drifted and
        gets replaced (the core's NodePool static-drift analogue)."""
        import hashlib
        import json
        from dataclasses import asdict

        spec = {}
        for k, v in self.__dict__.items():
            if k in self._HASH_EXCLUDE or k.startswith("_"):
                continue
            if hasattr(v, "__dataclass_fields__"):
                v = asdict(v)
            elif isinstance(v, list):
                v = [
                    asdict(x) if hasattr(x, "__dataclass_fields__") else x
                    for x in v
                ]
            spec[k] = v
        blob = json.dumps(spec, sort_keys=True, default=str).encode()
        return hashlib.sha256(blob).hexdigest()[:16]
