"""Label-requirement engine: operators, intersection, compatibility.

This owns the semantics the reference consumes from the core library's
scheduling requirements engine (used at
``pkg/cloudprovider/cloudprovider.go:258-263`` and
``pkg/providers/instancetype/types.go:76-161``): requirement sets keyed by
label, with operators In / NotIn / Exists / DoesNotExist / Gt / Lt, pairwise
intersection, ``Compatible()`` checks, and minValues support.

Design note (TPU-first): requirements are *host-side* objects. They are
evaluated once per (pod-group x instance-type) pair to produce the boolean
compatibility mask that ships to the device (see ``ops/encode.py``); nothing
in this module runs under jit.
"""

from __future__ import annotations

import enum
import math
from dataclasses import dataclass, field
from typing import Iterable, Mapping, Optional, Sequence


class Operator(str, enum.Enum):
    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"
    LT = "Lt"


@dataclass(frozen=True)
class Requirement:
    """A single label requirement, as on pods/NodePools (k8s NodeSelectorRequirement)."""

    key: str
    operator: Operator
    values: tuple[str, ...] = ()
    # Karpenter extension: at least this many distinct values must remain
    # after all intersections (spec.template.spec.requirements[].minValues).
    min_values: Optional[int] = None

    def __post_init__(self):
        object.__setattr__(self, "values", tuple(self.values))
        if self.operator in (Operator.GT, Operator.LT):
            if len(self.values) != 1:
                raise ValueError(f"{self.operator.value} requires exactly one value")
            float(self.values[0])  # must be numeric
        if self.operator in (Operator.EXISTS, Operator.DOES_NOT_EXIST) and self.values:
            raise ValueError(f"{self.operator.value} takes no values")


class ValueSet:
    """The set of label values a key may take, closed under intersection.

    One of four shapes:
      - complement=False: a finite allowed set (possibly empty -> unsatisfiable)
      - complement=True:  everything except ``values`` (NotIn / Exists)
    plus an optional numeric interval (gt, lt) intersected on top, and an
    ``allow_undefined`` bit: whether the *absence* of the label satisfies the
    requirement (DoesNotExist, or no constraint at all).
    """

    __slots__ = ("values", "complement", "gt", "lt", "allow_undefined", "allow_defined")

    def __init__(
        self,
        values: frozenset[str] = frozenset(),
        complement: bool = True,
        gt: float = -math.inf,
        lt: float = math.inf,
        allow_undefined: bool = False,
        allow_defined: bool = True,
    ):
        self.values = values
        self.complement = complement
        self.gt = gt
        self.lt = lt
        self.allow_undefined = allow_undefined
        self.allow_defined = allow_defined

    # -- constructors ------------------------------------------------------
    @staticmethod
    def any() -> "ValueSet":
        """No constraint: any value, or absence, is fine."""
        return ValueSet(allow_undefined=True)

    @staticmethod
    def from_requirement(req: Requirement) -> "ValueSet":
        op = req.operator
        if op == Operator.IN:
            return ValueSet(values=frozenset(req.values), complement=False)
        if op == Operator.NOT_IN:
            # k8s semantics: NotIn is satisfied when the label is absent
            # (nodeaffinity NotIn matches nodes without the key).
            return ValueSet(values=frozenset(req.values), complement=True, allow_undefined=True)
        if op == Operator.EXISTS:
            return ValueSet()
        if op == Operator.DOES_NOT_EXIST:
            return ValueSet(allow_undefined=True, allow_defined=False)
        if op == Operator.GT:
            return ValueSet(gt=float(req.values[0]))
        if op == Operator.LT:
            return ValueSet(lt=float(req.values[0]))
        raise ValueError(op)

    # -- algebra -----------------------------------------------------------
    def intersect(self, other: "ValueSet") -> "ValueSet":
        if not self.complement and not other.complement:
            vals = self.values & other.values
            comp = False
        elif not self.complement:
            vals, comp = self.values - other.values, False
        elif not other.complement:
            vals, comp = other.values - self.values, False
        else:
            vals, comp = self.values | other.values, True
        return ValueSet(
            values=vals,
            complement=comp,
            gt=max(self.gt, other.gt),
            lt=min(self.lt, other.lt),
            allow_undefined=self.allow_undefined and other.allow_undefined,
            allow_defined=self.allow_defined and other.allow_defined,
        )

    def _numeric_ok(self, value: str) -> bool:
        if self.gt == -math.inf and self.lt == math.inf:
            return True
        try:
            f = float(value)
        except ValueError:
            return False
        return self.gt < f < self.lt

    def contains(self, value: Optional[str]) -> bool:
        """Does a concrete label value (None = label absent) satisfy this set?"""
        if value is None:
            return self.allow_undefined
        if not self.allow_defined:
            return False
        if not self._numeric_ok(value):
            return False
        if self.complement:
            return value not in self.values
        return value in self.values

    def is_satisfiable(self) -> bool:
        if self.allow_undefined:
            return True
        if not self.allow_defined:
            return False
        if self.gt >= self.lt:
            return False
        if not self.complement:
            return any(self._numeric_ok(v) for v in self.values)
        return True  # complement of a finite set is infinite

    def finite_values(self) -> Optional[frozenset[str]]:
        """The allowed finite set, or None if unbounded."""
        if self.complement:
            return None
        return frozenset(v for v in self.values if self._numeric_ok(v))

    def __repr__(self):
        parts = []
        if not self.complement:
            parts.append(f"in={sorted(self.values)}")
        elif self.values:
            parts.append(f"notin={sorted(self.values)}")
        if self.gt != -math.inf:
            parts.append(f"gt={self.gt}")
        if self.lt != math.inf:
            parts.append(f"lt={self.lt}")
        if self.allow_undefined:
            parts.append("undef-ok")
        if not self.allow_defined:
            parts.append("must-be-undef")
        return f"ValueSet({', '.join(parts) or 'any-defined'})"


class Requirements:
    """A conjunction of per-key ValueSets, the unit of compatibility checks.

    Mirrors the core library's ``scheduling.Requirements`` (NewRequirements /
    Add / Compatible / Intersects) as consumed by the reference.
    """

    def __init__(self, reqs: Iterable[Requirement] = ()):
        self._sets: dict[str, ValueSet] = {}
        self._min_values: dict[str, int] = {}
        for r in reqs:
            self.add(r)

    # -- construction ------------------------------------------------------
    def add(self, req: Requirement) -> None:
        vs = ValueSet.from_requirement(req)
        cur = self._sets.get(req.key)
        self._sets[req.key] = vs if cur is None else cur.intersect(vs)
        if req.min_values is not None:
            self._min_values[req.key] = max(
                self._min_values.get(req.key, 0), req.min_values
            )

    @staticmethod
    def from_labels(labels: Mapping[str, str]) -> "Requirements":
        """Requirements equivalent to a concrete label set (one In per key)."""
        return Requirements(
            Requirement(k, Operator.IN, (v,)) for k, v in labels.items()
        )

    @staticmethod
    def from_node_selector(selector: Mapping[str, str]) -> "Requirements":
        return Requirements.from_labels(selector)

    def union(self, other: "Requirements") -> "Requirements":
        """Conjunction of both requirement sets (intersecting shared keys)."""
        out = Requirements()
        out._sets = dict(self._sets)
        out._min_values = dict(self._min_values)
        for k, vs in other._sets.items():
            cur = out._sets.get(k)
            out._sets[k] = vs if cur is None else cur.intersect(vs)
        for k, mv in other._min_values.items():
            out._min_values[k] = max(out._min_values.get(k, 0), mv)
        return out

    # -- queries -----------------------------------------------------------
    def keys(self) -> Sequence[str]:
        return list(self._sets.keys())

    def get(self, key: str) -> ValueSet:
        return self._sets.get(key, ValueSet.any())

    def min_values(self, key: str) -> int:
        return self._min_values.get(key, 0)

    def is_satisfiable(self) -> bool:
        return all(vs.is_satisfiable() for vs in self._sets.values())

    def compatible(self, other: "Requirements") -> bool:
        """Can some label assignment satisfy both requirement sets?

        Semantics of the core engine: for every key constrained by either
        side, the intersection of the two ValueSets must be satisfiable.
        A key unconstrained on one side is treated as unbounded there.
        """
        for k in set(self._sets) | set(other._sets):
            if not self.get(k).intersect(other.get(k)).is_satisfiable():
                return False
        return True

    def satisfied_by_labels(self, lbl: Mapping[str, str]) -> bool:
        """Do concrete labels (a launched node) satisfy every requirement?"""
        return all(vs.contains(lbl.get(k)) for k, vs in self._sets.items())

    def min_values_satisfied(self, other: "Requirements") -> bool:
        """After intersecting with ``other`` (an instance-type set's labels),
        does every minValues-bearing key retain enough distinct values?

        The caller intersects against the union of candidate types; see
        ``scheduling/solver.py``. Keys whose intersection is unbounded
        trivially satisfy minValues.
        """
        for k, need in self._min_values.items():
            inter = self.get(k).intersect(other.get(k))
            finite = inter.finite_values()
            if finite is not None and len(finite) < need:
                return False
        return True

    def __iter__(self):
        return iter(self._sets.items())

    def __len__(self):
        return len(self._sets)

    def __repr__(self):
        return f"Requirements({self._sets!r})"
