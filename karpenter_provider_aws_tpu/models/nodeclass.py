"""NodeClass: cloud-specific node configuration (EC2NodeClass analogue).

Reference parity: ``pkg/apis/v1beta1/ec2nodeclass.go:29-120`` (spec: selector
terms, AMI family, role/instanceProfile, userData, block devices, metadata
options, tags) and ``ec2nodeclass_status.go:56-92`` (status: resolved
subnets/security-groups/images/instance-profile + conditions), plus the
static drift hash (``ec2nodeclass.go:340``, ``hash/controller.go:47-70``).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field, asdict
from typing import Optional

from . import labels as lbl
from .pod import _Seq

#: Bumped by every NodePool / NodeClass field reassignment, process-wide.
#: Direct in-place spec edits on live objects never reach the store's
#: change journal; consumers that cache per-spec derivations (the
#: disruption controller's dirty-set drift/expiry state) re-scan when this
#: sequence moves — the same over-invalidation contract as NODE_WRITE_SEQ.
SPEC_WRITE_SEQ = _Seq()


@dataclass(frozen=True)
class SelectorTerm:
    """Discovery selector for subnets / security groups / images
    (parity: SubnetSelectorTerm / SecurityGroupSelectorTerm / AMISelectorTerm).

    ``owner`` (AMISelectorTerm.Owner parity) scopes the WIRE discovery
    call (DescribeImages Owner param) — it narrows what the cloud returns
    rather than what ``matches`` accepts host-side, since discovered
    resource models carry no owner field to check against."""

    tags: tuple[tuple[str, str], ...] = ()
    id: str = ""
    name: str = ""
    owner: str = ""

    @staticmethod
    def of(id: str = "", name: str = "", owner: str = "", **tags) -> "SelectorTerm":
        return SelectorTerm(
            tags=tuple(sorted(tags.items())), id=id, name=name, owner=owner
        )

    def matches(self, resource) -> bool:
        if self.id:
            return resource.id == self.id
        if self.name:
            rname = getattr(resource, "name", "")
            if "*" in self.name or "?" in self.name:
                # EC2 DescribeImages name filters take shell-style
                # wildcards; the host-side enforcement point must accept
                # exactly what the scoped wire call matched
                import fnmatch

                if not fnmatch.fnmatchcase(rname, self.name):
                    return False
            elif rname != self.name:
                return False
        rtags = getattr(resource, "tags", {})
        for k, v in self.tags:
            if v == "*":
                if k not in rtags:
                    return False
            elif rtags.get(k) != v:
                return False
        # an owner-only term constrains at the wire (Owner param); host-side
        # it accepts whatever that scoped discovery returned
        return bool(self.tags) or bool(self.name) or bool(self.owner)


@dataclass(frozen=True)
class KubeletConfiguration:
    """Kubelet knobs surfaced through node bootstrap
    (parity: v1beta1 KubeletConfiguration consumed at bootstrap.go:36-64)."""

    max_pods: Optional[int] = None
    pods_per_core: Optional[int] = None
    cluster_dns: tuple[str, ...] = ()
    system_reserved: tuple[tuple[str, str], ...] = ()
    kube_reserved: tuple[tuple[str, str], ...] = ()
    eviction_hard: tuple[tuple[str, str], ...] = ()
    eviction_soft: tuple[tuple[str, str], ...] = ()
    # signal -> duration string, e.g. ("memory.available", "1m0s")
    # (parity: bootstrap.go:64 --eviction-soft-grace-period)
    eviction_soft_grace_period: tuple[tuple[str, str], ...] = ()
    # parity: bootstrap.go:66-68 --eviction-max-pod-grace-period
    eviction_max_pod_grace_period: Optional[int] = None
    image_gc_high_threshold_percent: Optional[int] = None
    image_gc_low_threshold_percent: Optional[int] = None
    cpu_cfs_quota: Optional[bool] = None

    def extra_args(self) -> list[str]:
        """--flag=value kubelet arguments (parity: kubeletExtraArgs)."""
        args: list[str] = []
        if self.max_pods is not None:
            args.append(f"--max-pods={self.max_pods}")
        if self.pods_per_core is not None:
            args.append(f"--pods-per-core={self.pods_per_core}")
        if self.cluster_dns:
            args.append("--cluster-dns=" + ",".join(self.cluster_dns))
        for flag, pairs in (
            ("--system-reserved", self.system_reserved),
            ("--kube-reserved", self.kube_reserved),
            ("--eviction-hard", self.eviction_hard),
            ("--eviction-soft", self.eviction_soft),
            ("--eviction-soft-grace-period", self.eviction_soft_grace_period),
        ):
            if pairs:
                args.append(flag + "=" + ",".join(f"{k}={v}" for k, v in pairs))
        if self.eviction_max_pod_grace_period is not None:
            args.append(
                f"--eviction-max-pod-grace-period={self.eviction_max_pod_grace_period}"
            )
        if self.image_gc_high_threshold_percent is not None:
            args.append(f"--image-gc-high-threshold={self.image_gc_high_threshold_percent}")
        if self.image_gc_low_threshold_percent is not None:
            args.append(f"--image-gc-low-threshold={self.image_gc_low_threshold_percent}")
        if self.cpu_cfs_quota is not None:
            args.append(f"--cpu-cfs-quota={str(self.cpu_cfs_quota).lower()}")
        return args


@dataclass(frozen=True)
class BlockDevice:
    device_name: str = "/dev/xvda"
    volume_size_gib: int = 20
    volume_type: str = "gp3"
    iops: Optional[int] = None
    throughput: Optional[int] = None
    encrypted: bool = True
    delete_on_termination: bool = True
    # at most one mapping may be the root volume (CEL rule parity:
    # ec2nodeclass.go:89 "must have only one blockDeviceMappings with
    # rootVolume")
    root_volume: bool = False


@dataclass(frozen=True)
class MetadataOptions:
    """IMDS options (parity: ec2nodeclass.go MetadataOptions defaults)."""

    http_endpoint: str = "enabled"
    http_protocol_ipv6: str = "disabled"
    http_put_response_hop_limit: int = 2
    http_tokens: str = "required"


@dataclass
class Condition:
    type: str
    status: bool
    reason: str = ""
    message: str = ""
    transition_seq: int = 0


@dataclass
class NodeClassStatus:
    def __setattr__(self, name, value):
        object.__setattr__(self, name, value)
        SPEC_WRITE_SEQ.v += 1  # discovery updates move drift answers

    subnets: list = field(default_factory=list)           # resolved Subnet objects
    security_groups: list = field(default_factory=list)   # resolved SecurityGroup objects
    images: list = field(default_factory=list)            # resolved Image objects
    capacity_reservations: list = field(default_factory=list)  # resolved reservations
    instance_profile: str = ""
    conditions: dict[str, Condition] = field(default_factory=dict)

    def set_condition(self, ctype: str, status: bool, reason: str = "", message: str = "") -> None:
        self.conditions[ctype] = Condition(ctype, status, reason, message)

    def is_ready(self) -> bool:
        c = self.conditions.get("Ready")
        return c is not None and c.status


@dataclass
class NodeClass:
    def __setattr__(self, name, value):
        # see SPEC_WRITE_SEQ: direct spec edits must wake journal-driven
        # consumers (the disruption drift sweep) without a store apply()
        object.__setattr__(self, name, value)
        SPEC_WRITE_SEQ.v += 1

    name: str
    image_family: str = "standard"  # parity with AMIFamily: standard|minimal|gpu|custom
    image_selector: list[SelectorTerm] = field(default_factory=list)
    subnet_selector: list[SelectorTerm] = field(default_factory=list)
    security_group_selector: list[SelectorTerm] = field(default_factory=list)
    # Capacity-reservation discovery (ODCR analogue): reservations matching
    # any term become 'reserved' capacity-type offerings at price 0.
    capacity_reservation_selector: list[SelectorTerm] = field(default_factory=list)
    role: str = ""
    instance_profile: str = ""  # mutually exclusive with role
    user_data: str = ""
    block_devices: list[BlockDevice] = field(default_factory=lambda: [BlockDevice()])
    metadata_options: MetadataOptions = field(default_factory=MetadataOptions)
    tags: dict[str, str] = field(default_factory=dict)
    vm_memory_overhead_percent: float = 0.075  # options.go VMMemoryOverheadPercent default
    detailed_monitoring: bool = False
    # How instance-store (local NVMe) disks are used. "RAID0" makes them the
    # node's ephemeral-storage (capacity = total instance-store size) and the
    # bootstrap configures the RAID (parity: ec2nodeclass.go:93-95 +
    # types.go:218-224 ephemeralStorage + eksbootstrap.go:80-82 /
    # nodeadm.go:86-88). None leaves ephemeral-storage on the EBS root.
    instance_store_policy: Optional[str] = None  # None | "RAID0"
    # Explicit public-IP override (parity: ec2nodeclass.go:45-47). None =
    # infer from the resolved subnets (subnet.go:119-130); True/False wins.
    associate_public_ip: Optional[bool] = None
    # Reserved EC2 launch context, passed through to the fleet request
    # verbatim (parity: ec2nodeclass.go:116-119 + instance.go:220).
    context: str = ""
    status: NodeClassStatus = field(default_factory=NodeClassStatus)
    finalizers: set[str] = field(default_factory=set)
    deleted: bool = False

    # Fields excluded from the static drift hash because they are resolved
    # dynamically (parity: hash tags on ec2nodeclass.go spec fields).
    _HASH_EXCLUDE = ("status", "finalizers", "deleted", "image_selector",
                     "subnet_selector", "security_group_selector",
                     "capacity_reservation_selector")

    def hash(self) -> str:
        """Static drift hash over immutable spec fields
        (parity: ec2nodeclass.go:340 Hash via hashstructure)."""
        spec = {}
        for k, v in self.__dict__.items():
            if k in self._HASH_EXCLUDE or k.startswith("_"):
                continue
            if hasattr(v, "__dataclass_fields__"):
                v = asdict(v)
            elif isinstance(v, list):
                v = [asdict(x) if hasattr(x, "__dataclass_fields__") else x for x in v]
            spec[k] = v
        blob = json.dumps(spec, sort_keys=True, default=str).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def root_volume_size_gib(self) -> int:
        """Size of the root EBS volume: the device marked rootVolume, else
        the first mapping, else the 20 GiB family default. ONE home for the
        rule — claim capacity (cloudprovider) and the solve tensor (encode)
        must agree on it (parity: types.go:225-244 block-device resolution)."""
        root = next(
            (b for b in self.block_devices if b.root_volume),
            self.block_devices[0] if self.block_devices else None,
        )
        return root.volume_size_gib if root else 20

    def capacity_kwargs(self) -> dict:
        """kwargs for InstanceType.capacity()/CatalogProvider.allocatable()
        derived from this nodeclass — the ONE home for how a nodeclass
        shapes node capacity (fit accounting, limits accounting, and claim
        status must agree)."""
        return {
            "ephemeral_gib": self.root_volume_size_gib(),
            "instance_store_policy": self.instance_store_policy,
        }

    def hash_annotations(self) -> dict[str, str]:
        return {
            lbl.ANNOTATION_NODECLASS_HASH: self.hash(),
            lbl.ANNOTATION_NODECLASS_HASH_VERSION: lbl.NODECLASS_HASH_VERSION,
        }
