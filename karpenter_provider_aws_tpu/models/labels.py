"""Well-known labels (reference parity: pkg/apis/v1beta1/labels.go:22-110).

The framework's label namespace is ``karpenter.tpu`` (the reference uses
``karpenter.k8s.aws``); core-library labels keep their upstream names so
existing pod specs work unchanged.
"""

GROUP = "karpenter.tpu"

# Core (upstream karpenter.sh / kubernetes.io) labels.
NODEPOOL = "karpenter.sh/nodepool"
CAPACITY_TYPE = "karpenter.sh/capacity-type"
ARCH = "kubernetes.io/arch"
OS = "kubernetes.io/os"
INSTANCE_TYPE_LABEL = "node.kubernetes.io/instance-type"
TOPOLOGY_ZONE = "topology.kubernetes.io/zone"
# availability-zone | local-zone (parity: the localzone e2e suite selecting
# zones by type via DescribeAvailabilityZones)
ZONE_TYPE = f"{GROUP}/zone-type"
TOPOLOGY_REGION = "topology.kubernetes.io/region"
HOSTNAME = "kubernetes.io/hostname"

CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_RESERVED = "reserved"  # capacity-reservation-backed (pre-paid)
CAPACITY_TYPES = (CAPACITY_TYPE_ON_DEMAND, CAPACITY_TYPE_SPOT, CAPACITY_TYPE_RESERVED)
NUM_CAPACITY_TYPES = len(CAPACITY_TYPES)
SPOT_INDEX = CAPACITY_TYPES.index(CAPACITY_TYPE_SPOT)
RESERVED_INDEX = CAPACITY_TYPES.index(CAPACITY_TYPE_RESERVED)
CAPACITY_RESERVATION_ID = f"{GROUP}/capacity-reservation-id"

# Instance-property labels (reference: labels.go:87-98 — 19 instance labels).
INSTANCE_HYPERVISOR = f"{GROUP}/instance-hypervisor"
INSTANCE_ENCRYPTION_IN_TRANSIT = f"{GROUP}/instance-encryption-in-transit-supported"
INSTANCE_CATEGORY = f"{GROUP}/instance-category"
INSTANCE_FAMILY = f"{GROUP}/instance-family"
INSTANCE_GENERATION = f"{GROUP}/instance-generation"
INSTANCE_LOCAL_NVME = f"{GROUP}/instance-local-nvme"
INSTANCE_SIZE = f"{GROUP}/instance-size"
INSTANCE_CPU = f"{GROUP}/instance-cpu"
INSTANCE_CPU_MANUFACTURER = f"{GROUP}/instance-cpu-manufacturer"
INSTANCE_MEMORY = f"{GROUP}/instance-memory"
INSTANCE_EBS_BANDWIDTH = f"{GROUP}/instance-ebs-bandwidth"
INSTANCE_NETWORK_BANDWIDTH = f"{GROUP}/instance-network-bandwidth"
INSTANCE_GPU_NAME = f"{GROUP}/instance-gpu-name"
INSTANCE_GPU_MANUFACTURER = f"{GROUP}/instance-gpu-manufacturer"
INSTANCE_GPU_COUNT = f"{GROUP}/instance-gpu-count"
INSTANCE_GPU_MEMORY = f"{GROUP}/instance-gpu-memory"
INSTANCE_ACCELERATOR_NAME = f"{GROUP}/instance-accelerator-name"
INSTANCE_ACCELERATOR_MANUFACTURER = f"{GROUP}/instance-accelerator-manufacturer"
INSTANCE_ACCELERATOR_COUNT = f"{GROUP}/instance-accelerator-count"

# Annotations.
ANNOTATION_NODECLASS_HASH = f"{GROUP}/nodeclass-hash"
ANNOTATION_NODEPOOL_HASH = f"{GROUP}/nodepool-hash"
ANNOTATION_NODECLASS_HASH_VERSION = f"{GROUP}/nodeclass-hash-version"
ANNOTATION_INSTANCE_TAGGED = f"{GROUP}/tagged"
ANNOTATION_DO_NOT_DISRUPT = "karpenter.sh/do-not-disrupt"

# Gang scheduling (designs/gang-scheduling.md). The gang identity rides
# ANNOTATIONS, never the scheduling key: an annotation write bumps neither
# Pod._version nor the interned scheduling token, so a disarmed run
# (``KARPENTER_TPU_GANGS=0``) is byte-identical to a world where the
# annotations were never stamped.
ANNOTATION_POD_GROUP = f"{GROUP}/pod-group"
ANNOTATION_POD_GROUP_MIN = f"{GROUP}/pod-group-min"
# Tenant identity for per-tenant fairness SLOs (a LABEL: selectors and the
# sim's fairness accounting both match on it; stamped at pod creation).
TENANT_LABEL = f"{GROUP}/tenant"

# Bump whenever a field joins the NodeClass static hash: the hash
# controller then RE-STAMPS existing claims' annotations instead of
# letting the new field's presence falsely drift-flag the whole fleet
# (parity: hash/controller.go:83-120 hash-version migration).
# v2: instance_store_policy; v3: associate_public_ip + context.
NODECLASS_HASH_VERSION = "v3"

# Labels whose values are numeric and thus support Gt/Lt requirements.
NUMERIC_LABELS = frozenset(
    {
        INSTANCE_CPU,
        INSTANCE_MEMORY,
        INSTANCE_GENERATION,
        INSTANCE_GPU_COUNT,
        INSTANCE_GPU_MEMORY,
        INSTANCE_ACCELERATOR_COUNT,
        INSTANCE_EBS_BANDWIDTH,
        INSTANCE_NETWORK_BANDWIDTH,
    }
)

# Restricted: users may not set these directly on NodePools (parity with
# labels.go RestrictedLabels).
RESTRICTED_LABELS = frozenset({HOSTNAME, f"{GROUP}/nodeclass"})
