"""NodeClaim: a request for one node, tracked from launch to registration.

Owns what the reference consumes from the core NodeClaim API + lifecycle
(SURVEY.md section 2.2): requirements snapshot, resource request, provider-ID
binding, and Launched/Registered/Initialized conditions. The cloud provider
converts a launched instance into NodeClaim status
(parity: pkg/cloudprovider/cloudprovider.go:294-337 instanceToNodeClaim).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

from .requirements import Requirement, Requirements
from .resources import ResourceVector
from .nodeclass import Condition

_seq = itertools.count()


@dataclass
class NodeClaimStatus:
    provider_id: str = ""
    image_id: str = ""
    internal_ip: str = ""
    node_name: str = ""
    capacity: ResourceVector = field(default_factory=ResourceVector)
    allocatable: ResourceVector = field(default_factory=ResourceVector)
    conditions: dict[str, Condition] = field(default_factory=dict)

    def set_condition(self, ctype: str, status: bool, reason: str = "") -> None:
        self.conditions[ctype] = Condition(ctype, status, reason)

    def condition(self, ctype: str) -> bool:
        c = self.conditions.get(ctype)
        return c is not None and c.status


@dataclass
class NodeClaim:
    name: str
    nodepool_name: str = ""
    nodeclass_name: str = "default"
    requirements: list[Requirement] = field(default_factory=list)
    resources: ResourceVector = field(default_factory=ResourceVector)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    taints: list = field(default_factory=list)
    startup_taints: list = field(default_factory=list)
    created_at: float = 0.0
    deleted: bool = False
    deleted_at: float = 0.0  # clock time of the delete mark (grace periods)
    # snapshotted from the pool at launch (core copies it onto the claim):
    # the deadline must survive the pool being edited/deleted mid-drain
    termination_grace_period_s: "Optional[float]" = None
    finalizers: set[str] = field(default_factory=set)
    status: NodeClaimStatus = field(default_factory=NodeClaimStatus)
    # Solver hints: candidate instance-type names ranked by the solve, passed
    # to the launch path (parity: the scheduler passing instance-type options
    # into CloudProvider.Create, truncated at instance.go:52-53).
    instance_type_options: list[str] = field(default_factory=list)
    capacity_type_options: list[str] = field(default_factory=list)
    zone_options: list[str] = field(default_factory=list)
    offering_options: list[tuple] = field(default_factory=list)  # joint (zone, captype)

    @staticmethod
    def fresh(nodepool_name: str, nodeclass_name: str = "default", **kw) -> "NodeClaim":
        return NodeClaim(name=f"{nodepool_name}-{next(_seq):x}", nodepool_name=nodepool_name,
                         nodeclass_name=nodeclass_name, **kw)

    def scheduling_requirements(self) -> Requirements:
        return Requirements(self.requirements).union(Requirements.from_labels(self.labels))

    def is_launched(self) -> bool:
        return self.status.condition("Launched")

    def is_registered(self) -> bool:
        return self.status.condition("Registered")

    def is_initialized(self) -> bool:
        return self.status.condition("Initialized")
