"""Data model: the framework's own NodePool/NodeClass/NodeClaim/Pod types.

Reference parity: ``pkg/apis/v1beta1`` (EC2NodeClass CRD, labels.go) and the
core library's NodePool/NodeClaim APIs + scheduling requirements engine
(SURVEY.md section 2.2).
"""

from .requirements import (  # noqa: F401
    Operator,
    Requirement,
    Requirements,
    ValueSet,
)
from .resources import ResourceVector, RESOURCE_AXES  # noqa: F401
from .pod import Pod, Toleration, TopologySpreadConstraint  # noqa: F401
from .nodepool import Budget, Disruption, Limits, NodePool, Taint  # noqa: F401
from .nodeclass import NodeClass, SelectorTerm, BlockDevice, MetadataOptions  # noqa: F401
from .nodeclaim import NodeClaim, NodeClaimStatus, Condition  # noqa: F401
from . import labels  # noqa: F401
