"""Canonical resource axes and the fixed-width resource vector.

TPU-first design decision: every resource quantity in the system lives on a
fixed, ordered axis of length ``NUM_RESOURCES`` so that pods, capacities, and
overheads are plain float32 vectors and the whole scheduling problem is a set
of dense matrices (SURVEY.md section 7.1). This replaces the reference's
``corev1.ResourceList`` maps (used throughout
``pkg/providers/instancetype/types.go:182-416``).

Units: cpu in millicores, memory/ephemeral-storage in MiB, everything else in
counts. Parsing accepts k8s quantity strings ("100m", "2", "4Gi", "512Mi").
"""

from __future__ import annotations

import re
from typing import Mapping, Union

import numpy as np

# The fixed resource axis. Order matters: it is the last dim of every tensor.
RESOURCE_AXES: tuple[str, ...] = (
    "cpu",                      # millicores
    "memory",                   # MiB
    "pods",                     # count (per-node pod slots, ENI-limited)
    "ephemeral-storage",        # MiB
    "nvidia.com/gpu",           # count
    "amd.com/gpu",              # count
    "aws.amazon.com/neuron",    # count
    "habana.ai/gaudi",          # count (dl1 family accelerators)
    "vpc.amazonaws.com/efa",    # count
    "vpc.amazonaws.com/pod-eni",  # count (branch interfaces, security-group-per-pod)
)
NUM_RESOURCES = len(RESOURCE_AXES)
_AXIS_INDEX = {name: i for i, name in enumerate(RESOURCE_AXES)}

CPU, MEMORY, PODS, EPHEMERAL = 0, 1, 2, 3
NVIDIA_GPU, AMD_GPU, NEURON, GAUDI, EFA, POD_ENI = 4, 5, 6, 7, 8, 9

# Extended-resource label parity: pkg/apis/v1beta1/labels.go:87-98 resources.
EXTENDED_RESOURCES = RESOURCE_AXES[4:]

_QUANTITY_RE = re.compile(r"^([0-9.]+)([a-zA-Z]*)$")
_SUFFIX = {
    "": 1.0,
    "m": 1e-3,
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
}


def parse_quantity(q: Union[str, int, float]) -> float:
    """Parse a k8s quantity string to a raw float (bytes for byte-suffixed)."""
    if isinstance(q, (int, float)):
        return float(q)
    m = _QUANTITY_RE.match(q.strip())
    if not m:
        raise ValueError(f"bad quantity: {q!r}")
    num, suf = m.groups()
    if suf not in _SUFFIX:
        raise ValueError(f"bad quantity suffix: {q!r}")
    return float(num) * _SUFFIX[suf]


def _to_axis_units(name: str, raw: float, q: Union[str, int, float]) -> float:
    if name == "cpu":
        # raw is cores (possibly fractional via "m"); axis unit is millicores.
        return raw * 1000.0
    if name in ("memory", "ephemeral-storage"):
        # Bare numbers are bytes per k8s semantics; axis unit is MiB.
        return raw / 2**20
    return raw


class ResourceVector:
    """A point on the resource axis; wraps a float32 numpy vector."""

    __slots__ = ("v",)

    def __init__(self, v: np.ndarray | None = None):
        self.v = np.zeros(NUM_RESOURCES, dtype=np.float32) if v is None else np.asarray(v, dtype=np.float32)

    @staticmethod
    def from_map(m: Mapping[str, Union[str, int, float]]) -> "ResourceVector":
        out = ResourceVector()
        for k, q in m.items():
            if k not in _AXIS_INDEX:
                raise KeyError(f"unknown resource {k!r}; axes are {RESOURCE_AXES}")
            out.v[_AXIS_INDEX[k]] = _to_axis_units(k, parse_quantity(q), q)
        return out

    def to_map(self) -> dict[str, float]:
        return {name: float(self.v[i]) for i, name in enumerate(RESOURCE_AXES) if self.v[i] != 0}

    def to_quantities(self) -> dict[str, str]:
        """Unit-faithful k8s quantity strings: the inverse of ``from_map``
        (``to_map`` exports raw AXIS units — millicores/MiB — which
        ``from_map`` would re-parse as cores/bytes)."""
        def fmt(val: float) -> str:
            # never exponent notation: parse_quantity's grammar is plain
            # digits (a 1000-core limit as "1e+06m" would not re-parse)
            if val == int(val):
                return str(int(val))
            return f"{val:f}".rstrip("0").rstrip(".")

        out: dict[str, str] = {}
        for i, name in enumerate(RESOURCE_AXES):
            val = float(self.v[i])
            if val == 0:
                continue
            if name == "cpu":
                out[name] = fmt(val) + "m"       # axis unit IS millicores
            elif name in ("memory", "ephemeral-storage"):
                out[name] = fmt(val) + "Mi"      # axis unit IS MiB
            else:
                out[name] = fmt(val)
        return out

    def get(self, name: str) -> float:
        return float(self.v[_AXIS_INDEX[name]])

    def set(self, name: str, value: float) -> "ResourceVector":
        self.v[_AXIS_INDEX[name]] = value
        return self

    # -- arithmetic (all elementwise on the fixed axis) --------------------
    def __add__(self, other: "ResourceVector") -> "ResourceVector":
        return ResourceVector(self.v + other.v)

    def __sub__(self, other: "ResourceVector") -> "ResourceVector":
        return ResourceVector(self.v - other.v)

    def __mul__(self, s: float) -> "ResourceVector":
        return ResourceVector(self.v * s)

    def clip_min_zero(self) -> "ResourceVector":
        return ResourceVector(np.maximum(self.v, 0))

    def fits_in(self, capacity: "ResourceVector") -> bool:
        return bool(np.all(self.v <= capacity.v + 1e-6))

    def is_zero(self) -> bool:
        return bool(np.all(self.v == 0))

    def dominant_share(self, capacity: "ResourceVector") -> float:
        """Max over axes of request/capacity — the FFD sort key
        (designs/bin-packing.md:29-31 sorts pods by decreasing size)."""
        with np.errstate(divide="ignore", invalid="ignore"):
            shares = np.where(capacity.v > 0, self.v / capacity.v, 0.0)
        return float(np.max(shares))

    def copy(self) -> "ResourceVector":
        return ResourceVector(self.v.copy())

    def __eq__(self, other):
        return isinstance(other, ResourceVector) and bool(np.all(self.v == other.v))

    def __hash__(self):
        return hash(self.v.tobytes())

    def __repr__(self):
        return f"ResourceVector({self.to_map()})"


def stack(vectors: list[ResourceVector]) -> np.ndarray:
    """[len(vectors), NUM_RESOURCES] float32 matrix."""
    if not vectors:
        return np.zeros((0, NUM_RESOURCES), dtype=np.float32)
    return np.stack([rv.v for rv in vectors]).astype(np.float32)
