"""PodDisruptionBudget: voluntary-eviction limits the drain path honors.

Parity: the core termination controller drains through the eviction API,
which enforces PDBs — a karpenter disruption never takes more replicas of
a covered workload down than the budget allows; blocked evictions retry
until replacements are Ready elsewhere.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Union


def _resolve(value: Union[int, str], total: int, round_up: bool) -> int:
    """K8s intstr semantics: minAvailable percentages round UP,
    maxUnavailable percentages round DOWN (the conservative direction for
    each field — the caller states which). Integer math, like
    GetScaledValueFromIntOrPercent — float rounding diverges at exact
    boundaries (ceil(50*0.14) = 8, but k8s' ceil(14*50/100) = 7)."""
    if isinstance(value, str) and value.endswith("%"):
        pct = int(float(value[:-1]))
        if round_up:
            return (pct * total + 99) // 100
        return pct * total // 100
    return int(value)


@dataclass
class PodDisruptionBudget:
    name: str
    selector: Mapping[str, str] = field(default_factory=dict)
    # exactly one of the two must be set (enforced in __post_init__)
    min_available: Optional[Union[int, str]] = None
    max_unavailable: Optional[Union[int, str]] = None

    def __post_init__(self):
        if (self.min_available is None) == (self.max_unavailable is None):
            raise ValueError(
                "PodDisruptionBudget needs exactly one of minAvailable / "
                "maxUnavailable"
            )

    def matches(self, pod) -> bool:
        return all(pod.labels.get(k) == v for k, v in self.selector.items())

    def disruptions_allowed(self, pods) -> int:
        """How many of ``pods`` (all pods matching the selector,
        cluster-wide) may be evicted right now. ``healthy`` = bound and
        Running; everything else already counts as disrupted."""
        matching = [p for p in pods if self.matches(p)]
        total = len(matching)
        healthy = sum(1 for p in matching if p.node_name and p.phase == "Running")
        if self.min_available is not None:
            need = _resolve(self.min_available, total, round_up=True)
            allowed = healthy - need
        else:
            cap = _resolve(self.max_unavailable, total, round_up=False)
            allowed = cap - (total - healthy)
        return max(allowed, 0)
