"""Prometheus-compatible metrics registry (text exposition format).

Parity surface: the reference's prometheus instrumentation — instance-type
gauges (pkg/providers/instancetype/metrics.go), batcher histograms
(pkg/batcher/metrics.go), interruption counters
(pkg/controllers/interruption/metrics.go), and the CloudProvider method
decorator (cmd/controller/main.go:44 metrics.Decorate).
"""

from __future__ import annotations

import threading
import time
from http.server import ThreadingHTTPServer
from typing import Optional


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        # read under the lock: a concurrent inc() resizing the dict must
        # not race this lookup (CPython dicts don't tear, but the
        # lock-free read was still an unordered peek at a mid-update map)
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def total(self) -> float:
        """Sum across every label set (e.g. all-services retry volume)."""
        with self._lock:
            return float(sum(self._values.values()))

    def sum(self, **labels) -> float:
        """Sum across label sets MATCHING the given subset — e.g.
        ``ENCODE_CACHE.sum(path="cluster", outcome="full")`` totals every
        ``cause`` series of the full outcome. ``value()`` stays an exact
        label-set lookup."""
        want = set(labels.items())
        with self._lock:
            return float(sum(
                v for key, v in self._values.items() if want <= set(key)
            ))

    def _snapshot(self) -> list[tuple]:
        with self._lock:
            return sorted(self._values.items())

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for key, v in self._snapshot():
            out.append(f"{self.name}{_fmt_labels(dict(key))} {v}")
        return out


class Gauge(Counter):
    def set(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for key, v in self._snapshot():
            out.append(f"{self.name}{_fmt_labels(dict(key))} {v}")
        return out


class Histogram:
    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0)

    def __init__(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(buckets)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            self._sums[key] = self._sums.get(key, 0.0) + value
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            counts[-1] += 1  # +Inf (total observations)

    def time(self, **labels):
        hist = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                hist.observe(time.perf_counter() - self.t0, **labels)

        return _Timer()

    def expose(self) -> list[str]:
        # snapshot under the lock: a concurrent observe() appends bucket
        # rows and mutates count lists in place — expose must render a
        # coherent point-in-time view, not a mid-update one
        with self._lock:
            snap = sorted(
                (key, list(counts), self._sums[key])
                for key, counts in self._counts.items()
            )
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for key, counts, total in snap:
            labels = dict(key)
            for i, b in enumerate(self.buckets):
                lab = dict(labels, le=str(b))
                out.append(f"{self.name}_bucket{_fmt_labels(lab)} {sum(counts[: i + 1])}")
            lab = dict(labels, le="+Inf")
            out.append(f"{self.name}_bucket{_fmt_labels(lab)} {counts[-1]}")
            out.append(f"{self.name}_sum{_fmt_labels(labels)} {total}")
            out.append(f"{self.name}_count{_fmt_labels(labels)} {counts[-1]}")
        return out


class Registry:
    def __init__(self):
        self._metrics: list = []
        self._lock = threading.Lock()
        self._http: Optional[ThreadingHTTPServer] = None
        # /debug/* page providers: path -> zero-arg callable returning a
        # JSON-serializable object (the obs/ subsystem registers /debug/slo,
        # /debug/decisions, /debug/cluster here)
        self._debug_pages: dict = {}

    def register(self, metric):
        with self._lock:
            self._metrics.append(metric)
        return metric

    def register_debug_page(self, path: str, provider) -> None:
        """Expose ``provider()`` as JSON at ``path`` (must start with
        /debug/) on the metrics HTTP server. Re-registration replaces —
        a fresh hermetic environment owns the pages."""
        if not path.startswith("/debug/"):
            raise ValueError(f"debug pages live under /debug/: {path!r}")
        with self._lock:
            self._debug_pages[path] = provider

    def debug_page(self, path: str):
        """Render one registered page to a JSON-ready object (None when
        unregistered). Provider errors surface as an error payload — an
        introspection endpoint must never take down the scrape server."""
        with self._lock:
            provider = self._debug_pages.get(path)
        if provider is None:
            return None
        try:
            return provider()
        except Exception as e:  # pragma: no cover - defensive
            return {"error": f"{type(e).__name__}: {e}"}

    def metric_names(self) -> set[str]:
        """Registered family names (the docs schema-drift guard's source)."""
        with self._lock:
            return {m.name for m in self._metrics}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self.register(Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self.register(Gauge(name, help_))

    def histogram(self, name: str, help_: str = "", buckets=Histogram.DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_, buckets))

    def expose(self) -> str:
        with self._lock:
            lines: list[str] = []
            for m in self._metrics:
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"

    # -- /metrics + health endpoints ---------------------------------------
    def serve(self, port: int, readiness=None) -> int:
        """Serve /metrics, /healthz (liveness: the process answers), and
        /readyz (readiness: the shipped deployment.yaml probes it —
        ``readiness`` is an optional callable the operator wires to "the
        manager is running"; a follower replica IS ready: it serves as a
        hot standby and must not be restarted by the kubelet)."""
        registry = self
        from .utils.httpserve import QuietHandler, serve_http

        class Handler(QuietHandler):
            def do_GET(self):  # noqa: N802
                if self.path == "/metrics":
                    self.reply(
                        200, registry.expose().encode(),
                        "text/plain; version=0.0.4",
                    )
                elif self.path == "/healthz":
                    self.reply(200, b"ok\n", "text/plain; version=0.0.4")
                elif self.path.startswith("/debug/"):
                    page = registry.debug_page(self.path)
                    if page is None:
                        self.reply(404, b"unknown debug page\n")
                    else:
                        import json

                        self.reply(
                            200,
                            json.dumps(page, indent=2, default=str).encode()
                            + b"\n",
                            "application/json",
                        )
                elif self.path == "/readyz":
                    ready = True
                    if readiness is not None:
                        try:
                            ready = bool(readiness())
                        except Exception:
                            ready = False
                    self.reply(
                        200 if ready else 503,
                        b"ok\n" if ready else b"not ready\n",
                        "text/plain; version=0.0.4",
                    )
                else:
                    self.reply(404, b"")

        self._http = serve_http(Handler, port)
        return self._http.server_address[1]

    def stop(self) -> None:
        from .utils.httpserve import stop_server

        stop_server(self._http)
        self._http = None


# The default process-wide registry + well-known metrics (created lazily by
# components; names mirror the reference's metric families).
REGISTRY = Registry()

SOLVE_DURATION = REGISTRY.histogram(
    "karpenter_solver_solve_duration_seconds", "End-to-end Solve() latency"
)
# Per-phase solve latency, fed by the trace/ flight recorder's metrics
# bridge (trace/export.py): encode / dispatch / device / decode spans land
# here with a phase label, so /metrics can attribute a slow solve without
# a profiler attach. Buckets skew low: phases are ms-scale where the
# end-to-end solve is tens-to-hundreds of ms.
SOLVE_PHASE_SECONDS = REGISTRY.histogram(
    "karpenter_solver_phase_duration_seconds",
    "Solve latency by phase (encode/dispatch/device/decode), from trace spans",
    buckets=(0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0),
)
RECONCILE_SECONDS = REGISTRY.histogram(
    "karpenter_controller_reconcile_duration_seconds",
    "Controller reconcile latency by controller, from trace spans "
    "(parity: controller-runtime's controller_runtime_reconcile_time_seconds)",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0),
)
AWS_REQUEST_SECONDS = REGISTRY.histogram(
    "karpenter_aws_request_duration_seconds",
    "Signed AWS API call latency by service, from trace spans (includes "
    "retries; the retry count rides the span and the counter below)",
    buckets=(0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0, 30.0),
)
AWS_REQUEST_RETRIES = REGISTRY.counter(
    "karpenter_aws_request_retries_total",
    "AWS API retry attempts by service (DefaultRetryer parity)",
)
AWS_REQUEST_RETRY_REASONS = REGISTRY.counter(
    "karpenter_aws_request_retry_reason_total",
    "AWS API retry attempts by service and cause class "
    "(throttle / server / connection) — what chaos runs assert on",
)
SOLVE_PODS = REGISTRY.counter("karpenter_solver_pods_total", "Pods passed through Solve()")
NODES_CREATED = REGISTRY.counter("karpenter_nodes_created_total", "Nodes launched")
NODES_TERMINATED = REGISTRY.counter("karpenter_nodes_terminated_total", "Nodes terminated")
DISRUPTION_ACTIONS = REGISTRY.counter(
    "karpenter_disruption_actions_total", "Disruption actions by reason"
)
INTERRUPTION_MESSAGES = REGISTRY.counter(
    "karpenter_interruption_messages_total", "Interruption queue messages by kind"
)
INTERRUPTION_MESSAGE_ERRORS = REGISTRY.counter(
    "karpenter_interruption_message_errors_total",
    "Interruption messages whose handler raised; the message is still "
    "deleted (documented at-least-once semantics) instead of poisoning "
    "the queue with eternal redelivery",
)
CHAOS_FAULTS_INJECTED = REGISTRY.counter(
    "karpenter_chaos_faults_injected_total",
    "Chaos faults injected by kind (chaos/ subsystem)",
)
ICE_CACHE_SIZE = REGISTRY.gauge(
    "karpenter_ice_cache_size",
    "Offerings currently masked by the unavailable-offerings (ICE) cache "
    "— chaos scenarios assert its growth under storms and decay after",
)
ENCODE_CACHE = REGISTRY.counter(
    "karpenter_encode_cache_total",
    "Encode-cache outcomes by path (cluster = consolidation ClusterTensors, "
    "problem = provisioning EncodedProblem, occupancy = bound-pod zone "
    "snapshot) and outcome (hit = served unchanged, patch = delta-patched, "
    "full = rebuilt from scratch)",
)
ENCODE_PATCH_ROWS = REGISTRY.counter(
    "karpenter_encode_patch_rows_total",
    "Node rows rewritten by incremental cluster-encode patches",
)
ENCODE_PARTITIONS = REGISTRY.gauge(
    "karpenter_encode_partitions",
    "Live (nodepool, zone) partitions tracked by the partitioned cluster "
    "encoder (ops/encode_partition.py); 0 while the single-chain encoder "
    "serves the cluster",
)
PARTITION_SOLVE_LANES = REGISTRY.counter(
    "karpenter_partition_solve_lanes_total",
    "FFD partition lanes executed by the mesh-parallel multi-pool solve, "
    "by mode (vmap = single-program vmapped lanes, shard_map = lanes "
    "sharded across the device axis, fallback = per-pool dispatch)",
)
# -- ops/device_state.py: device-resident cluster state ---------------------
DEVICE_STATE = REGISTRY.counter(
    "karpenter_device_state_total",
    "Device-resident cluster-state outcomes by path (screen = the "
    "consolidation repack tensors) and outcome (hit = device buffers "
    "served unchanged, patch = scatter-patched on device from the change "
    "journal delta, upload = full host->device upload, fallback = the "
    "residency layer was off/unusable and the host-buffer path ran)",
)
DEVICE_STATE_PATCH_ROWS = REGISTRY.counter(
    "karpenter_device_state_patch_rows_total",
    "Node rows rewritten on device by scatter patches (the link carries "
    "only these rows' bytes instead of the full ladder-padded buffers)",
)
DEVICE_STATE_BYTES = REGISTRY.counter(
    "karpenter_device_state_bytes_total",
    "Bytes shipped host->device by the residency layer, by kind (upload = "
    "full buffer uploads, patch = scatter-patch row payloads)",
)
BATCH_SIZE = REGISTRY.histogram(
    "karpenter_batcher_batch_size", "Requests per coalesced batch",
    buckets=(1, 2, 5, 10, 50, 100, 500, 1000),
)
ICE_EVENTS = REGISTRY.counter(
    "karpenter_insufficient_capacity_errors_total", "ICE occurrences"
)
EVENTS = REGISTRY.counter(
    "karpenter_events_total",
    "Events published by controllers, by type and reason (parity: the core "
    "event recorder behind interruption controller.go:219-238)",
)
SIDECAR_RPC_SECONDS = REGISTRY.histogram(
    "karpenter_sidecar_rpc_duration_seconds",
    "Solver-sidecar RPC latency by method, server side",
    buckets=(0.001, 0.005, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 5.0, 30.0),
)
SIDECAR_ERRORS = REGISTRY.counter(
    "karpenter_sidecar_rpc_errors_total", "Solver-sidecar RPC failures by method"
)
BATCH_WINDOW = REGISTRY.histogram(
    "karpenter_batcher_window_seconds",
    "Time from a batch's first request to execution (parity: batcher window histograms, metrics.go:37-47)",
    buckets=(0.001, 0.005, 0.01, 0.035, 0.1, 0.3, 1.0, 3.0),
)
# -- obs/ subsystem: lifecycle SLIs, solver quality, SLOs, audit ----------
POD_SCHEDULING_SECONDS = REGISTRY.histogram(
    "karpenter_pod_scheduling_duration_seconds",
    "Pod lifecycle SLI by phase: nominate = pending->nominated, "
    "bind = pending->bound (parity: the reference's pod-startup "
    "histograms). Fed by the obs/ cluster observer on every sanctioned "
    "bind, in the store clock's time base",
    buckets=(0.5, 1, 5, 15, 30, 60, 120, 300, 600, 1800),
)
NODECLAIM_LIFECYCLE_SECONDS = REGISTRY.histogram(
    "karpenter_nodeclaim_lifecycle_duration_seconds",
    "NodeClaim phase transitions: launch = created->launched, register = "
    "launched->registered, ready = registered->initialized, total = "
    "created->initialized (obs/ lifecycle SLI)",
    buckets=(1, 5, 15, 30, 60, 120, 300, 600, 900, 1800),
)
SLO_BUDGET_REMAINING = REGISTRY.gauge(
    "karpenter_slo_error_budget_remaining",
    "Fraction of the SLO's error budget left over its compliance window "
    "(1 = untouched, 0 = exhausted), per declared SLO (obs/slo.py)",
)
SLO_BURN_RATE = REGISTRY.gauge(
    "karpenter_slo_burn_rate",
    "Error-budget burn rate per SLO and rule window (1.0 = burning "
    "exactly the sustainable rate; fast-burn Warning events fire when "
    "both windows of a rule exceed its factor)",
)
SOLVE_PACKING_EFFICIENCY = REGISTRY.gauge(
    "karpenter_solver_packing_efficiency",
    "Requested/allocatable per resource across the nodes the last solve "
    "committed to launch (1.0 = perfectly packed; solver-quality SLI)",
)
CLUSTER_PACKING_EFFICIENCY = REGISTRY.gauge(
    "karpenter_cluster_packing_efficiency",
    "Bound-pod requests / node allocatable per resource across live "
    "nodes, refreshed by each consolidation screen sweep",
)
SOLVE_COST_VS_ORACLE = REGISTRY.gauge(
    "karpenter_solver_cost_vs_oracle",
    "Committed launch cost / FFD-oracle cost for the sampled solve "
    "(scheduling/oracle.py; sampled off the hot path, pure-launch "
    "passes only — ~1.0 means the device plan matches the oracle)",
)
OPTIMIZER_LANE = REGISTRY.counter(
    "karpenter_optimizer_lane_total",
    "Optimizer-lane outcomes per solve (scheduling/optimizer.py): "
    "adopted, rejected, skipped_tight (FFD within 1% of the LP bound), "
    "skipped_existing (plan binds live slack), skipped_large (group axis "
    "past the dispatch ceiling), breaker_open, error, and "
    "consolidation_adopted (the multi-replace subset chooser)",
)
UNSCHEDULABLE_PODS = REGISTRY.counter(
    "karpenter_solver_unschedulable_pods_total",
    "Pods a solve pass left unschedulable (solver-quality SLI; the "
    "per-pod reasons ride the audit log and FailedScheduling events)",
)
GANG_PLACEMENTS = REGISTRY.counter(
    "karpenter_gang_placements_total",
    "Pod groups committed atomically — every member placed in one solve "
    "(the all-or-nothing gate in scheduling/groups.enforce_gangs)",
)
GANG_WITHHELD = REGISTRY.counter(
    "karpenter_gang_withheld_total",
    "Pod groups stripped WHOLE by the all-or-nothing commit gate because "
    "fewer than min_count members were placeable this solve",
)
UNSCHEDULABLE_REASONS = REGISTRY.counter(
    "karpenter_unschedulable_reason_total",
    "Unschedulable pods by decoded why-engine verdict (obs/why.py: "
    "capacity / shape / requirements / zone / hostname / ice / limits / "
    "market:* / reservation:expired / gang:atomicity-shortfall) — the "
    "aggregated frontier view of WHY pending work is pending",
)
CONSOLIDATION_REJECTED = REGISTRY.counter(
    "karpenter_consolidation_rejected_total",
    "Consolidation / optimizer proposals rejected, by decoded reason "
    "(budget:<class> at the disruption budget gate, lane:validator and "
    "lane:not-cheaper at the optimizer adoption contract) — obs/why.py",
)
LEADER = REGISTRY.gauge(
    "karpenter_leader",
    "1 when this replica holds the leader lease, else 0 (by identity). "
    "docs/troubleshooting.md points operators here for split-brain triage "
    "— the docs referenced it before it existed; the obs/ schema-drift "
    "guard caught that",
)
AUDIT_RECORDS = REGISTRY.counter(
    "karpenter_audit_records_total",
    "Decision audit records appended, by kind (placement / disruption / "
    "interruption / eviction / lifecycle — obs/audit.py)",
)
# -- resilience/ subsystem: circuit breakers, crash-loop supervision ------
CIRCUIT_STATE = REGISTRY.gauge(
    "karpenter_circuit_state",
    "Circuit-breaker state per dependency (0 = closed, 1 = half-open, "
    "2 = open); keyed instances guard each solver backend "
    "(solver.pallas / solver.xla-scan / solver.mesh / solver.sidecar) "
    "and each AWS service (aws.<service>) — resilience/breaker.py",
)
CIRCUIT_TRANSITIONS = REGISTRY.counter(
    "karpenter_circuit_transitions_total",
    "Circuit-breaker state transitions by breaker name and target state "
    "(to = closed / half-open / open)",
)
CONTROLLER_STUCK = REGISTRY.gauge(
    "karpenter_controller_stuck",
    "1 while a controller's in-flight reconcile has exceeded N x its "
    "interval (the Manager watchdog; a Warning event fires on the edge), "
    "else 0",
)
CRASHLOOP_BACKOFFS = REGISTRY.counter(
    "karpenter_controller_crashloop_backoff_total",
    "Crash-loop backoffs armed by consecutive reconcile failures, per "
    "controller (reset on the first successful reconcile)",
)

# -- operator/sharding.py: horizontally sharded control plane ---------------
SHARD_LEASES_HELD = REGISTRY.gauge(
    "karpenter_shard_leases_held",
    "Partition leases this replica currently holds (by replica identity); "
    "the GLOBAL lease counts as one — a healthy N-replica deployment sums "
    "to the partition count + 1 across replicas",
)
SHARD_REBALANCES = REGISTRY.counter(
    "karpenter_shard_rebalances_total",
    "Partition-lease ownership changes by reason (acquired = new tenancy, "
    "rebalance = voluntary hand-off to the rendezvous target, lost = a "
    "definitive foreign holder dropped the lease, renew-failed = an "
    "indeterminate CAS renew error; the lease rides its old renew date "
    "to the renew deadline)",
)
FENCED_WRITES_REJECTED = REGISTRY.counter(
    "karpenter_fenced_writes_rejected_total",
    "Cloud-side writes rejected because their fencing token belonged to a "
    "superseded lease tenancy (a deposed replica's in-flight launch/"
    "terminate bounced instead of racing the successor), by api",
)
PROVISIONING_STEALS = REGISTRY.counter(
    "karpenter_provisioning_steals_total",
    "Work-stealing GLOBAL-queue claim outcomes (sharded provisioning), by "
    "outcome: claimed = the GLOBAL-lease holder's normal batch, stolen = a "
    "partition holder picked up unclaimed/expired global pods, contended = "
    "items lost to another live claimant's CAS, fenced = the whole claim "
    "attempt bounced on a superseded fencing token (deposed replica)",
)
LEASE_OWNERSHIP = REGISTRY.gauge(
    "karpenter_lease_ownership",
    "Partition leases (incl. GLOBAL) held per replica identity as seen on "
    "the lease host — the fleet-wide twin of karpenter_shard_leases_held "
    "(which each replica sets for itself); the rendezvous-imbalance gauge "
    "below is derived from this distribution",
)
RENDEZVOUS_IMBALANCE = REGISTRY.gauge(
    "karpenter_rendezvous_imbalance",
    "max/mean partition leases held across live replicas (1.0 = perfectly "
    "balanced rendezvous hash; the ROADMAP's 16-keys/8-replicas skew made "
    "this measured, not anecdotal)",
)
PROVISIONING_SHARDED_PODS = REGISTRY.counter(
    "karpenter_provisioning_sharded_pods_total",
    "Pending pods routed by the sharded provisioner, by scope: local = "
    "pinned to an owned (nodepool, zone) partition and solved on this "
    "replica's device mirror, global = through the work-stealing GLOBAL "
    "queue, foreign = pinned to a partition another replica owns (skipped "
    "here, solved there)",
)

# -- fleet flight recorder (trace/correlate.py + obs/fleet.py) --------------
POD_QUEUE_WAIT_SECONDS = REGISTRY.histogram(
    "karpenter_pod_queue_wait_seconds",
    "GLOBAL work-queue wait per pod (enqueue -> claim), by outcome "
    "(claimed = the GLOBAL-lease holder's normal batch, stolen = picked "
    "up by a partition holder after the GLOBAL holder died) — the "
    "steal-latency SLI (obs/sli.py)",
    buckets=(0.5, 1, 5, 15, 30, 60, 120, 300, 600, 1800),
)
CORRELATION_HOPS = REGISTRY.counter(
    "karpenter_correlation_hops_total",
    "Lifecycle hops recorded in the correlation ledger, by hop kind: "
    "pod-side pending / route / claim / steal / solve / launch / "
    "nominate / bind / evict, claim-side launched / launch-for / "
    "register / ready / adopt / disrupt (trace/correlate.py; the hop "
    "table in designs/fleet-flight-recorder.md is the vocabulary)",
)

# -- trace/jitwatch.py + obs/device.py: device-plane observatory ------------
JIT_COMPILES = REGISTRY.counter(
    "karpenter_jit_compiles_total",
    "Program (re)traces recorded by the jitwatch ledger, by program family "
    "and kind (compile = a family's first trace, retrace = an additional "
    "signature after it — the ladder discipline demands steady state "
    "retraces ZERO times; the retrace sentinel pages on this edge)",
)
JIT_COMPILE_SECONDS = REGISTRY.histogram(
    "karpenter_jit_compile_seconds",
    "Wall seconds of each jitwatch-recorded trace (first call with a new "
    "signature: trace + compile + one execution), by program family — fed "
    "by the metrics bridge from jit.compile spans",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0),
)
DEVICE_LIVE_BYTES = REGISTRY.gauge(
    "karpenter_device_live_bytes",
    "Estimated device-resident bytes per program family (last dispatch's "
    "abstract input sizes; the device_state.mirror family is the "
    "holder-LRU's actual buffer bytes) — the DeviceAccountant's "
    "HBM-watermark source (obs/device.py)",
)

# -- obs/sentinel.py: live steady-state regression sentinel -----------------
SENTINEL_TICK_WALL = REGISTRY.gauge(
    "karpenter_sentinel_tick_wall_ms",
    "Wall milliseconds of span time attributed to the most recent "
    "sentinel tick (the liveness-cadence delta over the cumulative "
    "span profile)",
)
SENTINEL_SHARE = REGISTRY.gauge(
    "karpenter_sentinel_share",
    "Per-subsystem share of the most recent sentinel tick's wall profile "
    "(controller.* spans keep their name; other spans fold to their "
    "family) — the live twin of the cliff detector's attribution shares",
)
SENTINEL_REGRESSIONS = REGISTRY.counter(
    "karpenter_sentinel_regressions_total",
    "Edge-triggered SteadyStateRegression findings by named subsystem "
    "and kind (attribution-shift = one family's share jumped past the "
    "cliff thresholds, tick-superlinear = the whole tick blew past its "
    "rolling baseline)",
)

# -- sim/ subsystem: deterministic fleet simulator --------------------------
SIM_EVENTS = REGISTRY.counter(
    "karpenter_sim_events_total",
    "Workload-trace events applied by the fleet simulator, by kind "
    "(wave / flood / churn / expire / overlay-activate / overlay-deactivate)",
)
SIM_PASSES = REGISTRY.counter(
    "karpenter_sim_controller_passes_total",
    "Full controller-manager reconcile passes driven by the fleet "
    "simulator (micro-bursts after events + steady heartbeat)",
)
SIM_VIRTUAL_SECONDS = REGISTRY.gauge(
    "karpenter_sim_virtual_seconds",
    "Virtual seconds elapsed in the current (or most recent) fleet-"
    "simulator run; /debug/sim serves the full last-run summary",
)

# Catalog gauges (parity: instancetype metrics.go:32-75 — vCPU/memory per
# type, offering price/availability per (type, zone, capacity type)).
INSTANCE_TYPE_VCPU = REGISTRY.gauge(
    "karpenter_instance_type_cpu_cores", "vCPU cores per instance type"
)
INSTANCE_TYPE_MEMORY = REGISTRY.gauge(
    "karpenter_instance_type_memory_bytes", "Memory per instance type"
)
OFFERING_PRICE = REGISTRY.gauge(
    "karpenter_instance_type_offering_price_estimate", "Offering $/hr"
)
OFFERING_AVAILABLE = REGISTRY.gauge(
    "karpenter_instance_type_offering_available", "Offering availability (0/1)"
)
PRICING_AGE = REGISTRY.gauge(
    "karpenter_pricing_age_seconds",
    "Seconds since the live pricing backend last refreshed, per source "
    "(spot / on-demand); only published once a source has refreshed at "
    "least once — past the TTL a PricingStale Warning event fires "
    "(catalog/pricing.py observe_staleness)",
)


def publish_catalog_metrics(types) -> None:
    """Refresh-time gauge publication (instancetype metrics.go parity)."""
    for it in types:
        INSTANCE_TYPE_VCPU.set(float(it.vcpus), instance_type=it.name)
        INSTANCE_TYPE_MEMORY.set(float(it.memory_mib) * 1024 * 1024, instance_type=it.name)
        for o in it.offerings:
            labels = dict(
                instance_type=it.name, zone=o.zone, capacity_type=o.capacity_type
            )
            OFFERING_PRICE.set(float(o.price), **labels)
            OFFERING_AVAILABLE.set(1.0 if o.available else 0.0, **labels)
