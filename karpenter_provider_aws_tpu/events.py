"""Event recorder: the kube-Event analogue for operational visibility.

Parity: the reference publishes an event for every interruption message,
disruption decision, launch, and unschedulable pod through the core
events.Recorder (`/root/reference/pkg/controllers/interruption/controller.go:219-238`
uses recorder.Publish; the core decorates it with dedupe). Here the sink is
an in-memory ring with TTL dedupe + a counter metric — the control plane has
no apiserver, so "publishing" means: queryable by operators/tests, counted
in metrics, logged once per dedupe window.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger("karpenter.tpu.events")

NORMAL = "Normal"
WARNING = "Warning"


@dataclass(frozen=True)
class Event:
    kind: str        # object kind: NodeClaim | Pod | Node | NodePool
    name: str        # object name
    type: str        # Normal | Warning
    reason: str      # CamelCase machine key (Launched, Disrupted, ...)
    message: str
    at: float = 0.0
    count: int = 1   # occurrences within the dedupe window


class EventRecorder:
    """Thread-safe bounded event sink with per-(object, reason, message)
    TTL dedupe — repeats within the window bump a count instead of
    appending (the core recorder's dedupe semantics)."""

    def __init__(self, clock=None, dedupe_ttl_s: float = 120.0, capacity: int = 4096):
        self.clock = clock
        self.dedupe_ttl_s = dedupe_ttl_s
        self._lock = threading.Lock()
        self._ring: deque[Event] = deque(maxlen=capacity)
        self._last: dict[tuple, list] = {}  # key -> [first_at, Event, count]
        self._next_sweep = 0.0

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.now()
        import time

        return time.monotonic()

    def publish(
        self,
        kind: str,
        name: str,
        reason: str,
        message: str,
        type: str = NORMAL,
    ) -> bool:
        """Record one event; returns False when deduped into a prior one."""
        key = (kind, name, reason, message)
        now = self._now()
        with self._lock:
            hit = self._last.get(key)
            if hit is not None and now - hit[0] < self.dedupe_ttl_s:
                # count in place — no ring mutation (a deque.remove scan per
                # hot deduped event would serialize publishers)
                hit[2] += 1
                return False
            ev = Event(kind, name, type, reason, message, at=now)
            self._last[key] = [now, ev, 1]
            self._ring.append(ev)
            # opportunistic eviction: the dedupe map would otherwise grow
            # one entry per unique (object, reason, message) forever (claim
            # names are unique per launch — weeks of churn = a leak).
            # Time-gated to at most one O(map) sweep per half-TTL, so an
            # event storm cannot make every publish pay a rebuild under the
            # lock, and expired storm entries are reclaimed within ~TTL/2
            # of expiring instead of lingering behind a growth ratchet.
            if len(self._last) > 2 * self._ring.maxlen and now >= self._next_sweep:
                self._sweep_locked(now)
        try:
            from .metrics import EVENTS

            EVENTS.inc(type=type, reason=reason)
        except Exception:
            pass
        log.info("%s %s/%s: %s (%s)", type, kind, name, reason, message)
        return True

    def _sweep_locked(self, now: float) -> int:
        """Drop expired dedupe entries (caller holds the lock). Before an
        entry goes, its live repeat count is written back onto the ring
        Event it shadows, so ``events()`` keeps reporting the true count
        after the dedupe map forgets the key."""
        cutoff = now - self.dedupe_ttl_s
        expired = [k for k, v in self._last.items() if v[0] < cutoff]
        for k in expired:
            v = self._last.pop(k)
            if v[2] != v[1].count:
                object.__setattr__(v[1], "count", v[2])
        self._next_sweep = now + self.dedupe_ttl_s / 2
        return len(expired)

    def sweep(self, now: Optional[float] = None) -> int:
        """Idle-cluster memory hygiene: evict expired dedupe entries even
        when no new events arrive (publish only sweeps opportunistically,
        so a quiet cluster after an event storm would otherwise hold the
        whole map until the NEXT storm). Called from the obs/ engine tick;
        returns the number of entries dropped."""
        now = self._now() if now is None else now
        with self._lock:
            return self._sweep_locked(now)

    def query(
        self,
        kind: Optional[str] = None,
        name: Optional[str] = None,
        reason: Optional[str] = None,
    ) -> list[Event]:
        """Filterable accessor over the retained ring (the ``obs explain``
        CLI's join surface) — alias of :meth:`events` with the filter
        semantics spelled out: every non-None argument must match."""
        return self.events(kind=kind, name=name, reason=reason)

    def events(
        self,
        kind: Optional[str] = None,
        name: Optional[str] = None,
        reason: Optional[str] = None,
    ) -> list[Event]:
        with self._lock:
            out = []
            for e in self._ring:
                hit = self._last.get((e.kind, e.name, e.reason, e.message))
                n = hit[2] if hit is not None and hit[1] is e else e.count
                out.append(e if n == e.count else Event(
                    e.kind, e.name, e.type, e.reason, e.message, at=e.at, count=n
                ))
        if kind is not None:
            out = [e for e in out if e.kind == kind]
        if name is not None:
            out = [e for e in out if e.name == name]
        if reason is not None:
            out = [e for e in out if e.reason == reason]
        return out

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._last.clear()
            self._next_sweep = 0.0


_default = EventRecorder()


def default_recorder() -> EventRecorder:
    return _default
