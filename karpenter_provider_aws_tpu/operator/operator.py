"""Operator: compose every component from Options and start the manager.

Parity: ``cmd/controller/main.go:32-73`` + ``pkg/operator/operator.go`` —
build the cloud session (here: the cloud backend handle), construct the ten
providers, wrap the cloud provider in the metrics decorator, register core
+ cloud-specific controllers (interruption only when a queue is configured),
and start the reconcile loops.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from ..catalog.provider import CatalogProvider, OverheadOptions
from ..cloudprovider.cloudprovider import CloudProvider
from ..controllers import (
    DisruptionController,
    GarbageCollectionController,
    LivenessController,
    InterruptionController,
    Manager,
    NodeClassHashController,
    NodeClassStatusController,
    NodeClassTerminationController,
    ProvisioningController,
    RegistrationController,
    SchedulingController,
    TaggingController,
    TerminationController,
)
from ..controllers.refresh import (
    CatalogRefreshController,
    PricingRefreshController,
    VersionRefreshController,
)
from ..catalog.pricing import PricingProvider
from ..scheduling.solver import HostSolver, TPUSolver
from ..state.cluster import Cluster
from ..utils.batcher import BatcherOptions
from ..utils.clock import Clock, RealClock
from ..metrics import REGISTRY
from .options import Options

log = logging.getLogger("karpenter.tpu.operator")


@dataclass
class Operator:
    options: Options
    cluster: Cluster
    catalog: CatalogProvider
    cloudprovider: CloudProvider
    manager: Manager
    metrics_port: int = 0
    version_provider: object = None
    admission: object = None
    admission_port: int = 0

    def start(self) -> None:
        # Freeze the construction-time object graph out of the collector's
        # working set (measured: a gen-2 pass over a 50k-pod graph injects
        # ~100ms spikes straight into solve p99 — the bench freezes for the
        # same reason, solve_configs._timed_solves). Long-lived operators
        # never free this graph anyway; freezing just stops re-scanning it.
        # stop() unfreezes, so embedders cycling operators in one process
        # do not accumulate permanently-uncollectable heap.
        if self.options.gc_freeze:
            import gc

            gc.collect()
            gc.freeze()
            self._gc_frozen = True
        if self.options.metrics_port:
            # readiness = "the manager's reconcile threads are up" (a
            # follower replica is ready standby — leadership is NOT part
            # of readiness, or the kubelet would restart followers)
            self.metrics_port = REGISTRY.serve(
                self.options.metrics_port,
                readiness=self.manager.is_running,
            )
            log.info("metrics on 127.0.0.1:%d/metrics", self.metrics_port)
        if self.options.admission_port:
            from .admission_server import AdmissionServer

            self.admission = AdmissionServer()
            self.admission_port = self.admission.serve(
                self.options.admission_port,
                tls_dir=self.options.admission_tls_dir,
            )
        self.manager.start()

    def stop(self) -> None:
        if getattr(self, "_gc_frozen", False):
            import gc

            gc.unfreeze()
            self._gc_frozen = False
        self.manager.stop()
        self.cloudprovider.close()  # join batcher worker pools
        if self.admission is not None:
            self.admission.stop()
        REGISTRY.stop()

    def apply(self, obj):
        """Admission-checked apply (webhook chain parity)."""
        from .webhooks import admit

        return self.cluster.apply(admit(obj))


def _build_solver(options: Options):
    if options.solver_backend == "host":
        return HostSolver()
    if options.solver_backend == "native":
        from ..scheduling.native import NativeSolver

        return NativeSolver()
    if options.solver_backend == "grpc":
        from ..runtime.sidecar import RemoteSolver, SolverClient

        return RemoteSolver(SolverClient(options.solver_sidecar_target))
    return TPUSolver(max_nodes=options.max_nodes_per_solve or None)


def new_operator(
    options: Optional[Options] = None,
    cloud=None,
    queue=None,
    clock: Optional[Clock] = None,
    cluster: Optional[Cluster] = None,
    lease_host=None,
) -> Operator:
    """Build the full control plane. ``cloud`` is the cloud backend handle
    (the fake for tests; a real adapter in production). ``cluster`` lets
    multi-replica tests share one state store the way two replicas share
    one apiserver. ``lease_host`` is where ``--shard-elect`` /
    ``--leader-elect`` leases live: defaults to the cloud backend when it
    hosts leases (the fake does); production shard deployments pass an
    ``operator.leasehost.KubeLeaseHost`` over their apiserver transport."""
    options = options or Options.from_env_and_args()
    clock = clock or RealClock()
    if not options.prune_types:
        # the encoder reads the env knob (it has no Options handle); the
        # flag is the discoverable spelling of the same switch
        import os

        os.environ["KARPENTER_TPU_PRUNE_TYPES"] = "0"
    from ..utils.observability import Profiler, enable_xla_dump, setup_logging

    setup_logging(options.log_level)
    if options.xla_dump_dir:
        enable_xla_dump(options.xla_dump_dir)  # before the first jit compile
    if options.compilation_cache_dir:
        from ..utils.observability import enable_compilation_cache

        enable_compilation_cache(options.compilation_cache_dir)
    profiler = Profiler(options.profile_dir)
    if cloud is None:
        if options.cloud_backend == "aws":
            # production wiring (operator.go:92-106): one signed session —
            # credential chain, optional STS assume-role, retryer,
            # user-agent — behind the CloudBackend Protocol
            from ..providers.aws import AwsCloudBackend, Session

            from ..resilience import breakers as _breakers

            session = Session(
                region=options.aws_region,
                assume_role_arn=options.assume_role_arn,
                # the process registry: per-service aws.* breakers show
                # up on /debug/health next to the solver breakers
                breakers=_breakers,
            )
            cloud = AwsCloudBackend(session, cluster_name=options.cluster_name)
            if queue is None and options.interruption_queue:
                from ..providers.aws import SqsQueueProvider

                queue = SqsQueueProvider.from_queue_name(
                    session, options.interruption_queue
                )
        else:
            # hermetic default: any object satisfying cloudprovider.backend
            # .CloudBackend slots in here (parity: the reference's tier-1
            # strategy — real clouds are adapters injected at this seam)
            from ..fake import FakeCloud

            cloud = FakeCloud(clock=clock)

    # Cloud-connectivity preflight FIRST (parity: operator.go:205-212
    # CheckEC2Connectivity's dry-run DescribeInstanceTypes): a broken
    # backend/credentials must fail operator construction loudly, before
    # any provider consumes (or swallows) the first error.
    try:
        zone_types = cloud.describe_availability_zones()
    except Exception as e:
        raise RuntimeError(
            f"cloud backend connectivity preflight failed: {type(e).__name__}: {e}"
        ) from e

    pricing = PricingProvider(isolated_vpc=options.isolated_vpc)
    # The catalog's zone axis ADOPTS the backend's zones (the preflight
    # already fetched them): live feeds key spot prices and offerings by
    # the cloud's real AZ names, and a catalog stuck on its synthetic
    # defaults would silently never match them (round-5 live-pricing drive
    # caught exactly this).
    # availability zones only: local/wavelength zones carry a tiny subset
    # of types (cloudprovider.py zone-type gating handles launches there);
    # putting them on the synthetic-catalog zone axis would fabricate
    # offerings that don't exist
    zones = tuple(sorted(
        z for z, zt in zone_types.items() if zt == "availability-zone"
    )) if zone_types else None
    if zone_types and not zones:
        # falling back to synthetic defaults here would recreate the
        # silent zone-name mismatch this adoption exists to fix — fail
        # like the preflight does
        raise RuntimeError(
            "cloud backend reported zones but none typed "
            f"'availability-zone': {zone_types!r}"
        )
    catalog = CatalogProvider(
        **({"zones": zones} if zones else {}),
        pricing=pricing,
        overhead=OverheadOptions(
            vm_memory_overhead_percent=options.vm_memory_overhead_percent,
            reserved_enis=options.reserved_enis,
        ),
        clock=clock,
    )
    cluster = cluster if cluster is not None else Cluster(clock=clock)
    from ..providers.bootstrap import ClusterInfo
    from ..providers.launchtemplates import resolve_service_cidr as _cidr

    cloudprovider = CloudProvider(
        cloud,
        catalog,
        cluster,
        clock=clock,
        batcher_options=BatcherOptions(
            idle_timeout_s=options.batch_idle_seconds,
            max_timeout_s=options.batch_max_seconds,
        ),
        cluster_info=ClusterInfo(
            name=options.cluster_name,
            endpoint=options.cluster_endpoint,
            ip_family=options.ip_family,
            # KubeDNSIP discovery parity (operator.go:247-260): the kube-dns
            # service IP is the 10th address of the service range — modeled
            # here as family-typed defaults overridable by --cluster-dns-ip
            dns_ip=options.cluster_dns_ip
            or ("fd00:10::a" if options.ip_family == "ipv6" else "10.100.0.10"),
            # service-CIDR discovery (launchtemplate.go:429-450
            # ResolveClusterCIDR): a startup failure leaves it empty and the
            # launch-template provider retries from the launch path
            service_cidr=_cidr(cloud, options.ip_family),
        ),
    )
    # Metrics decorator around the plugin boundary (parity: main.go:44).
    from ..cloudprovider.decorator import decorate
    from ..providers.version import VersionProvider

    cloudprovider = decorate(cloudprovider)
    version_provider = VersionProvider(cluster, clock=clock)
    version_provider.get()  # support-window preflight

    solver = _build_solver(options)

    from ..events import EventRecorder

    recorder = EventRecorder(clock=clock)
    # the observability bundle: lifecycle SLIs on this cluster, SLO engine
    # on this recorder, /debug/{slo,decisions,cluster} on the metrics server
    from .. import obs as obs_mod

    obs_bundle = obs_mod.install(cluster=cluster, recorder=recorder, clock=clock)
    provisioning = ProvisioningController(
        cluster, solver, cloudprovider, profiler=profiler, recorder=recorder,
        obs=obs_bundle,
    )
    scheduling = SchedulingController(cluster, provisioning, clock=clock)
    registration = RegistrationController(cluster, provisioning, clock=clock)
    termination = TerminationController(cluster, cloudprovider, clock=clock)
    disruption = DisruptionController(
        cluster,
        cloudprovider,
        clock=clock,
        drift_enabled=options.drift_enabled and options.gate("Drift", True),
        provisioning=provisioning,
        recorder=recorder,
        spot_to_spot=options.gate("SpotToSpot", False),
        obs=obs_bundle,
    )
    from ..providers.aws.backend import AwsCloudBackend

    live_pricing = None
    pricing_region = "us-east-1"
    if isinstance(cloud, AwsCloudBackend) and not options.isolated_vpc:
        from ..providers.aws import PricingClient

        live_pricing = PricingClient(cloud.session, cloud.ec2)
        # injected backends may carry a region-less session; options fill
        # in, and only a true fallback to the default gets the warning
        pricing_region = cloud.session.region or options.aws_region
        if not pricing_region:
            pricing_region = "us-east-1"
            log.warning(
                "no AWS region configured; pricing refresh filters by %s",
                pricing_region,
            )
    controllers = [
        NodeClassStatusController(cluster, cloudprovider),
        NodeClassHashController(cluster),
        termination,
        registration,
        scheduling,
        provisioning,
        TaggingController(cluster, cloudprovider),
        disruption,
        GarbageCollectionController(cluster, cloudprovider, clock=clock),
        LivenessController(cluster, clock=clock, recorder=recorder,
                           obs=obs_bundle),
        NodeClassTerminationController(cluster, cloudprovider),
        CatalogRefreshController(catalog),
        # Live pricing refresh sources when the AWS backend is wired
        # (pricing.go:158-296 parity: GetProducts OD fan-out + spot
        # history BATCHED BY the catalog's own types); isolated-VPC skips
        # entirely (pricing.go:164-170 — don't even build the client).
        PricingRefreshController(
            catalog,
            od_source=live_pricing and (
                lambda: live_pricing.fetch_on_demand(pricing_region)
            ),
            spot_source=live_pricing and (
                lambda: live_pricing.fetch_spot(
                    [t.name for t in catalog.list()]
                )
            ),
        ),
        VersionRefreshController(version_provider),
    ]
    # parity: interruption controller registered iff a queue is configured
    # (pkg/controllers/controllers.go:67-71)
    if options.interruption_queue and queue is not None:
        controllers.insert(
            2,
            InterruptionController(cluster, cloudprovider, queue,
                                   recorder=recorder, obs=obs_bundle),
        )

    elector = None
    if lease_host is None and hasattr(cloud, "try_acquire_lease"):
        # the fake hosts leases (fenced AND plain); a plain-lease backend
        # still serves the single LeaderElector path below
        lease_host = cloud
    if options.shard_elect:
        # horizontally sharded control plane: per-partition leases with
        # fenced writes (operator/sharding.py); N replicas built over one
        # shared cluster store each wire their own ShardElector. Outside
        # the fake (the AWS backend hosts no leases) the caller supplies a
        # kube-Lease-backed host (operator/leasehost.KubeLeaseHost) so
        # --shard-elect works against a real apiserver.
        from .sharding import ShardElector

        if lease_host is None or not hasattr(
            lease_host, "try_acquire_lease_fenced"
        ):
            raise RuntimeError(
                "--shard-elect needs a FENCED lease host: the cloud "
                "backend does not host fenced leases — pass new_operator("
                "lease_host=KubeLeaseHost(transport)) (operator/leasehost.py)"
            )
        elector = ShardElector(
            lease_host, cluster, identity=options.leader_identity,
            clock=clock,
        )
        # the provisioner's work-stealing GLOBAL queue lives on the same
        # lease host (netsplit seam included)
        provisioning.elector = elector
    elif options.leader_elect:
        from .leaderelection import LeaderElector

        if lease_host is None:
            raise RuntimeError(
                "--leader-elect needs a lease host: the cloud backend "
                "does not host leases — pass new_operator(lease_host=...)"
            )
        elector = LeaderElector(
            lease_host, identity=options.leader_identity, clock=clock
        )

    return Operator(
        options=options,
        cluster=cluster,
        catalog=catalog,
        cloudprovider=cloudprovider,
        manager=Manager(controllers, elector=elector, clock=clock,
                        recorder=recorder),
        version_provider=version_provider,
    )
