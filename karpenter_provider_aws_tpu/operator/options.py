"""Options: the layered flag/env configuration system.

Parity: ``pkg/operator/options/options.go:35-86`` — every knob has a flag
form and an env fallback (FLAG --cluster-name <-> env CLUSTER_NAME), values
validate on load, and the resolved Options object is injected into every
component constructor (the context-injection analogue).
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field, fields
from typing import Optional


def _env(name: str, default, cast):
    raw = os.environ.get(name)
    if raw is None:
        return default
    if cast is bool:
        return raw.lower() in ("1", "true", "yes")
    return cast(raw)


@dataclass
class Options:
    cluster_name: str = "cluster-1"
    cluster_endpoint: str = ""
    isolated_vpc: bool = False                   # skips live pricing refresh
    vm_memory_overhead_percent: float = 0.075
    interruption_queue: str = ""                 # empty = controller disabled
    reserved_enis: int = 0
    batch_idle_seconds: float = 0.035            # createfleet.go:35
    batch_max_seconds: float = 1.0
    solver_backend: str = "tpu"                  # tpu | host | native | grpc
    solver_sidecar_target: str = ""              # for solver_backend=grpc
    max_nodes_per_solve: int = 0                 # 0 = auto bucket
    metrics_port: int = 8080                     # 0 = disabled
    admission_port: int = 0                      # webhook-server analogue; 0 = disabled
    # dir with tls.crt/tls.key (mounted kubernetes.io/tls Secret); non-empty
    # serves the admission endpoint over HTTPS, as the apiserver requires
    admission_tls_dir: str = ""
    drift_enabled: bool = True
    feature_gates: str = ""                      # "Drift=true,SpotToSpot=false"
    log_level: str = "INFO"
    profile_dir: str = ""                        # JAX profiler captures; "" = off
    xla_dump_dir: str = ""                       # compiled-HLO dumps; "" = off
    # persistent jit cache: restarts skip the ~20-40s per-shape-bucket
    # compile (keyed on HLO + compiler version; staleness impossible)
    compilation_cache_dir: str = ""              # "" = off
    ip_family: str = "ipv4"                      # ipv4 | ipv6 (cluster address family)
    cluster_dns_ip: str = ""                     # "" = discover (KubeDNSIP parity)
    # single-writer gating for multi-replica deployments (parity: the
    # controller-runtime manager lease, cmd/controller/main.go:34; the
    # shipped deployment.yaml runs 2 replicas behind this flag)
    leader_elect: bool = False
    leader_identity: str = ""                    # "" = hostname + random suffix
    # horizontally sharded control plane (operator/sharding.py): N
    # active-active replicas each own a partition of (nodepool, zone)
    # leases with fenced writes, instead of the all-or-nothing single
    # leader lease above. Mutually exclusive with --leader-elect.
    shard_elect: bool = False
    # freeze the startup object graph out of the GC working set (gen-2
    # passes over large pod graphs inject ~100ms spikes into solve p99)
    gc_freeze: bool = True
    # type-axis compaction: drop catalog types no pod group can use from
    # the device tensors (the encode also honors the raw
    # KARPENTER_TPU_PRUNE_TYPES env var for non-operator callers)
    prune_types: bool = True
    # which cloud backend to wire when none is injected: the in-memory
    # fake (hermetic default) or the production AWS adapter
    # (providers/aws/, signed stdlib clients)
    cloud_backend: str = "fake"                  # fake | aws
    # STS assume-role for the AWS backend (operator.go:96-100 parity;
    # base credentials then only ever sign AssumeRole)
    assume_role_arn: str = ""
    aws_region: str = ""                         # "" = AWS_REGION env

    @staticmethod
    def from_env_and_args(argv: Optional[list[str]] = None) -> "Options":
        defaults = Options()
        parser = argparse.ArgumentParser(prog="karpenter-tpu")
        for f in fields(Options):
            flag = "--" + f.name.replace("_", "-")
            env_name = f.name.upper()
            cast = type(getattr(defaults, f.name))
            env_default = _env(env_name, getattr(defaults, f.name), cast)
            if cast is bool:
                parser.add_argument(flag, type=lambda s: s.lower() in ("1", "true", "yes"),
                                    default=env_default)
            else:
                parser.add_argument(flag, type=cast, default=env_default)
        ns = parser.parse_args(argv if argv is not None else [])
        opts = Options(**vars(ns))
        opts.validate()
        return opts

    def validate(self) -> None:
        """Parity: options_validation.go."""
        if not self.cluster_name:
            raise ValueError("cluster-name is required")
        if not 0.0 <= self.vm_memory_overhead_percent < 1.0:
            raise ValueError("vm-memory-overhead-percent must be in [0, 1)")
        if self.solver_backend not in ("tpu", "host", "native", "grpc"):
            raise ValueError(f"unknown solver backend {self.solver_backend!r}")
        if self.solver_backend == "grpc" and not self.solver_sidecar_target:
            raise ValueError("solver-sidecar-target required for the grpc backend")
        if self.batch_idle_seconds <= 0 or self.batch_max_seconds < self.batch_idle_seconds:
            raise ValueError("batch windows must satisfy 0 < idle <= max")
        if self.ip_family not in ("ipv4", "ipv6"):
            raise ValueError(f"ip-family must be ipv4 or ipv6, got {self.ip_family!r}")
        if self.cloud_backend not in ("fake", "aws"):
            raise ValueError(f"unknown cloud backend {self.cloud_backend!r}")
        if self.leader_elect and self.shard_elect:
            raise ValueError(
                "leader-elect and shard-elect are mutually exclusive: the "
                "sharded lease layer subsumes the single leader lease"
            )

    def gate(self, name: str, default: bool = True) -> bool:
        for pair in self.feature_gates.split(","):
            if "=" in pair:
                k, v = pair.split("=", 1)
                if k.strip() == name:
                    return v.strip().lower() in ("1", "true", "yes")
        return default
