"""HTTP admission boundary: the webhook-server analogue.

Parity: ``pkg/webhooks/webhooks.go:30-60`` — the reference serves knative
defaulting + validation admission over HTTPS for the apiserver. This
framework has no apiserver, but an EXTERNAL control plane (the gRPC/Go
split in ``runtime/``) still needs the admission chain as a network
service, not a Python import. One endpoint, AdmissionReview-shaped:

    POST /admit
    {"kind": "NodeClass" | "NodePool", "object": {...}}
      -> 200 {"allowed": true,  "object": {...defaulted...}}
      -> 200 {"allowed": false, "violations": ["...", ...]}

GET /healthz serves readiness. The JSON object schema mirrors the
dataclass fields (`models/nodeclass.py`, `models/nodepool.py`).
"""

from __future__ import annotations

import json
import logging
from dataclasses import asdict
from http.server import ThreadingHTTPServer
from typing import Optional

from ..models.nodeclass import (
    BlockDevice,
    KubeletConfiguration,
    MetadataOptions,
    NodeClass,
    SelectorTerm,
)
from ..models.nodepool import Disruption, Limits, NodePool, Taint
from ..models.requirements import Operator, Requirement
from .webhooks import AdmissionError, admit

log = logging.getLogger("karpenter.tpu.admission")


# -- deserialization ---------------------------------------------------------

def _selector_terms(raw) -> list[SelectorTerm]:
    out = []
    for t in raw or []:
        tags = t.get("tags") or {}
        if isinstance(tags, dict):
            tags = tuple(sorted(tags.items()))
        else:
            tags = tuple(tuple(p) for p in tags)
        out.append(SelectorTerm(tags=tags, id=t.get("id", ""), name=t.get("name", "")))
    return out


def _kubelet(raw) -> Optional[KubeletConfiguration]:
    if not raw:
        return None
    kw = {}
    for k in ("max_pods", "pods_per_core", "image_gc_high_threshold_percent",
              "image_gc_low_threshold_percent", "cpu_cfs_quota",
              "eviction_max_pod_grace_period"):
        if k in raw:
            kw[k] = raw[k]
    for k in ("system_reserved", "kube_reserved", "eviction_hard",
              "eviction_soft", "eviction_soft_grace_period"):
        if k in raw:
            v = raw[k]
            kw[k] = tuple(sorted(v.items())) if isinstance(v, dict) else tuple(
                tuple(p) for p in v
            )
    if "cluster_dns" in raw:
        kw["cluster_dns"] = tuple(raw["cluster_dns"])
    return KubeletConfiguration(**kw)


def nodeclass_from_dict(data: dict) -> NodeClass:
    kw = {"name": data["name"]}
    for k in ("image_family", "role", "instance_profile", "user_data",
              "instance_store_policy", "detailed_monitoring",
              "associate_public_ip", "context"):
        if k in data:
            kw[k] = data[k]
    if "tags" in data:
        kw["tags"] = dict(data["tags"])
    for field_name in ("image_selector", "subnet_selector",
                       "security_group_selector", "capacity_reservation_selector"):
        if field_name in data:
            kw[field_name] = _selector_terms(data[field_name])
    if "block_devices" in data:
        kw["block_devices"] = [BlockDevice(**bd) for bd in data["block_devices"]]
    if "metadata_options" in data:
        kw["metadata_options"] = MetadataOptions(**data["metadata_options"])
    return NodeClass(**kw)


def nodepool_from_dict(data: dict) -> NodePool:
    kw = {"name": data["name"]}
    for k in ("nodeclass_name", "weight"):
        if k in data:
            kw[k] = data[k]
    if "labels" in data:
        kw["labels"] = dict(data["labels"])
    if "annotations" in data:
        kw["annotations"] = dict(data["annotations"])
    if "requirements" in data:
        kw["requirements"] = [
            Requirement(
                key=r["key"],
                operator=Operator(r["operator"]),
                values=tuple(r.get("values") or ()),
                min_values=r.get("min_values"),
            )
            for r in data["requirements"]
        ]
    for k in ("taints", "startup_taints"):
        if k in data:
            kw[k] = [Taint(**t) for t in data[k]]
    if "limits" in data:
        raw = data["limits"]
        kw["limits"] = (
            Limits() if raw.get("unlimited", False) else Limits.of(
                **{k.replace("-", "_"): v for k, v in (raw.get("resources") or {}).items()}
            )
        )
    if "disruption" in data:
        kw["disruption"] = Disruption(**data["disruption"])
    if "kubelet" in data:
        kw["kubelet"] = _kubelet(data["kubelet"])
    return NodePool(**kw)


_KINDS = {"NodeClass": nodeclass_from_dict, "NodePool": nodepool_from_dict}


def review_admission_review(body: dict) -> dict:
    """A REAL apiserver's AdmissionReview v1 envelope (what the chart's
    webhook registration routes here): ``{apiVersion: admission.k8s.io/v1,
    kind: AdmissionReview, request: {uid, kind: {kind}, object: {...}}}``.
    The embedded object is the CRD wire shape (camelCase spec), so it runs
    the CRD schema + CEL gate first, then the admission chain; the reply
    carries the required ``.response.uid`` and, for defaulting, a JSONPatch
    (``patchType: JSONPatch``, base64) replacing the spec — the envelope the
    apiserver demands of both Mutating and Validating configurations."""
    import base64

    from . import crds
    from .manifests import admit_wire_object

    request = body.get("request") or {}
    uid = request.get("uid", "")

    def deny(*messages: str) -> dict:
        return {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": {
                "uid": uid,
                "allowed": False,
                "status": {"message": "; ".join(messages) or "denied"},
            },
        }

    kind = (request.get("kind") or {}).get("kind", "")
    raw = request.get("object") or {}
    # ONE shared gate with manifest ingestion (schema + CEL + defaulting +
    # validation) so the wire path and examples/ loading can never diverge
    admitted, violations = admit_wire_object(kind, raw)
    if violations:
        return deny(*violations)
    defaulted_spec = (
        crds.nodeclass_to_obj(admitted)
        if kind == "NodeClass"
        else crds.nodepool_to_obj(admitted)
    )["spec"]
    patch = json.dumps(
        [{"op": "replace" if "spec" in raw else "add",
          "path": "/spec", "value": defaulted_spec}]
    ).encode()
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": {
            "uid": uid,
            "allowed": True,
            "patchType": "JSONPatch",
            "patch": base64.b64encode(patch).decode(),
        },
    }


def review(body: dict) -> dict:
    """One admission review: parse -> default -> validate -> re-serialize.
    Never raises: every failure mode is a violations response (this is the
    network boundary; callers can't catch Python exceptions)."""
    kind = body.get("kind", "")
    if kind == "AdmissionReview":
        # apiserver envelope: full CRD-schema + admission path, enveloped reply
        return review_admission_review(body)
    parser = _KINDS.get(kind)
    if parser is None:
        return {"allowed": False, "violations": [f"unknown kind {kind!r}"]}
    try:
        obj = parser(body.get("object") or {})
    except Exception as e:  # any malformed shape: lists-as-strings etc.
        return {"allowed": False, "violations": [f"malformed object: {e}"]}
    try:
        admitted = admit(obj)
    except AdmissionError as e:
        return {"allowed": False, "violations": list(e.violations)}
    except Exception as e:  # validator tripped on a shape parse() let through
        return {"allowed": False, "violations": [f"malformed object: {e}"]}
    out = asdict(admitted)
    out.pop("status", None)
    out.pop("finalizers", None)
    if isinstance(admitted, NodePool):
        # Limits holds a ResourceVector (not a dataclass): re-serialize as
        # unit-faithful k8s quantity strings so the object round-trips
        out["limits"] = {
            "unlimited": admitted.limits.unlimited,
            "resources": admitted.limits.resources.to_quantities(),
        }
    return {"allowed": True, "object": json.loads(json.dumps(out, default=str))}


class AdmissionServer:
    """Serves the admission chain on localhost (TLS termination is the
    deployment's job, like the reference's webhook Service)."""

    def __init__(self):
        self._http: Optional[ThreadingHTTPServer] = None

    def serve(self, port: int = 0, tls_dir: str = "") -> int:
        """``tls_dir`` holding tls.crt/tls.key (a mounted kubernetes.io/tls
        Secret, e.g. karpenter-tpu-cert) serves HTTPS — required when the
        apiserver routes to us via the chart's webhook Service."""
        from ..utils.httpserve import QuietHandler, serve_http

        class Handler(QuietHandler):
            def do_GET(self):  # noqa: N802
                if self.path != "/healthz":
                    self.reply(404, b"")
                    return
                self.reply(200, b"ok\n")

            def do_POST(self):  # noqa: N802
                if self.path != "/admit":
                    self.reply(404, b"")
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    result = review(body)
                except Exception as e:  # malformed request must not 500-loop
                    result = {"allowed": False, "violations": [f"bad request: {e}"]}
                self.reply(200, json.dumps(result).encode(), "application/json")

        # pod-IP reachable: the apiserver calls in over the network
        self._http = serve_http(Handler, port, tls_dir=tls_dir)
        log.info(
            "admission server on :%d/admit (%s)",
            self._http.server_address[1], "https" if tls_dir else "http",
        )
        return self._http.server_address[1]

    def stop(self) -> None:
        from ..utils.httpserve import stop_server

        stop_server(self._http)
        self._http = None
