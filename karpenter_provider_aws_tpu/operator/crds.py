"""CRD artifacts: machine-readable API schemas with the admission rules.

Parity: ``pkg/apis/crds/`` — the reference ships CustomResourceDefinitions
whose openAPI v3 schemas carry CEL ``x-kubernetes-validations`` markers
(authored in ``pkg/apis/v1beta1/ec2nodeclass.go:29-120``), so an external
apiserver enforces the same rules the webhooks do. This module emits the
equivalent artifacts for NodeClass and NodePool (written into the deploy
bundle by ``deploy/render.py``), plus:

 - converters from the in-memory models to the CRD spec wire shape, and
 - a validator (`validate_object`) that enforces the schema EXACTLY as
   shipped — structural openAPI constraints plus evaluation of the CEL
   rule strings via a small CEL-subset interpreter — so tests can prove
   the artifact rejects what ``webhooks.admit()`` rejects (the rule
   strings themselves are under test, not a parallel re-implementation).
"""

from __future__ import annotations

import re
from typing import Any, Optional

from ..models import labels as lbl

API_GROUP = "karpenter.tpu"
RESTRICTED_KEYS = sorted(lbl.RESTRICTED_LABELS | {lbl.NODEPOOL})


# ---------------------------------------------------------------------------
# CEL-subset interpreter (the dialect used by the rules below): literals,
# self paths, indexing, ! == != < <= > >= && || ?: in, has(), size(),
# .exists() .exists_one() .all() .startsWith()
# ---------------------------------------------------------------------------

_TOKEN = re.compile(
    r"\s*(?:(?P<num>\d+(?:\.\d+)?)|(?P<str>'[^']*')|(?P<id>[A-Za-z_][A-Za-z0-9_]*)"
    r"|(?P<op>&&|\|\||[!<>=]=|[()\[\],.!<>?:]))"
)


def _tokenize(src: str) -> list[str]:
    out, i = [], 0
    while i < len(src):
        m = _TOKEN.match(src, i)
        if m is None:
            raise ValueError(f"bad CEL at {src[i:]!r}")
        out.append(m.group(m.lastgroup))
        i = m.end()
    return out


def _get_field(obj, name: str):
    if isinstance(obj, dict):
        return obj.get(name)
    return getattr(obj, name)


class _Cel:
    """Compiles the token stream to closures env->value, so `&&`/`||`/`?:`
    short-circuit exactly like CEL (an eager evaluator would error on
    `has(self.x) && self.x > 0` when x is absent)."""

    def __init__(self, tokens: list[str]):
        self.t = tokens
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.t[self.i] if self.i < len(self.t) else None

    def next(self) -> str:
        tok = self.t[self.i]
        self.i += 1
        return tok

    def expect(self, tok: str) -> None:
        got = self.next()
        if got != tok:
            raise ValueError(f"expected {tok!r}, got {got!r}")

    # precedence climbing: ternary < or < and < cmp < unary < member
    def expr(self):
        cond = self.or_()
        if self.peek() == "?":
            self.next()
            a = self.expr()
            self.expect(":")
            b = self.expr()
            return lambda env: a(env) if cond(env) else b(env)
        return cond

    def or_(self):
        v = self.and_()
        while self.peek() == "||":
            self.next()
            lhs, rhs = v, self.and_()
            v = (lambda a, b: lambda env: bool(a(env)) or bool(b(env)))(lhs, rhs)
        return v

    def and_(self):
        v = self.cmp()
        while self.peek() == "&&":
            self.next()
            lhs, rhs = v, self.cmp()
            v = (lambda a, b: lambda env: bool(a(env)) and bool(b(env)))(lhs, rhs)
        return v

    _CMP = {
        "==": lambda a, b: a == b,
        "!=": lambda a, b: a != b,
        "<": lambda a, b: a < b,
        "<=": lambda a, b: a <= b,
        ">": lambda a, b: a > b,
        ">=": lambda a, b: a >= b,
        "in": lambda a, b: a in b,
    }

    def cmp(self):
        v = self.unary()
        while self.peek() in self._CMP:
            fn = self._CMP[self.next()]
            lhs, rhs = v, self.unary()
            v = (lambda f, a, b: lambda env: f(a(env), b(env)))(fn, lhs, rhs)
        return v

    def unary(self):
        if self.peek() == "!":
            self.next()
            inner = self.unary()
            return lambda env: not inner(env)
        return self.member()

    def member(self):
        v = self.atom()
        while True:
            tok = self.peek()
            if tok == ".":
                self.next()
                name = self.next()
                if self.peek() == "(":
                    self.next()
                    v = self.call_method(v, name)
                else:
                    v = (lambda r, n: lambda env: _get_field(r(env), n))(v, name)
            elif tok == "[":
                self.next()
                idx = self.expr()
                self.expect("]")
                v = (lambda r, ix: lambda env: r(env)[ix(env)])(v, idx)
            else:
                return v

    def call_method(self, recv, name: str):
        if name in ("exists", "exists_one", "all"):
            var = self.next()
            self.expect(",")
            body = self.expr()
            self.expect(")")

            def macro(env, recv=recv, var=var, body=body, name=name):
                items = list(recv(env))  # map -> keys, list -> elements
                hits = sum(1 for item in items if body({**env, var: item}))
                if name == "exists":
                    return hits > 0
                if name == "exists_one":
                    return hits == 1
                return hits == len(items)

            return macro
        if name == "startsWith":
            arg = self.expr()
            self.expect(")")
            return (
                lambda r, a: lambda env: isinstance(r(env), str)
                and r(env).startswith(a(env))
            )(recv, arg)
        raise ValueError(f"unknown method {name}")

    def atom(self):
        tok = self.next()
        if tok == "(":
            v = self.expr()
            self.expect(")")
            return v
        if tok == "[":
            items = []
            while self.peek() != "]":
                items.append(self.expr())
                if self.peek() == ",":
                    self.next()
            self.expect("]")
            return lambda env: [it(env) for it in items]
        if tok.startswith("'"):
            s = tok[1:-1]
            return lambda env: s
        if tok and tok[0].isdigit():
            n = float(tok) if "." in tok else int(tok)
            return lambda env: n
        if tok == "true":
            return lambda env: True
        if tok == "false":
            return lambda env: False
        if tok == "has":
            self.expect("(")
            root = self.next()
            parts = []
            while self.peek() == ".":
                self.next()
                parts.append(self.next())
            self.expect(")")

            def has(env, root=root, parts=tuple(parts)):
                base = env[root]
                for p in parts[:-1]:
                    base = _get_field(base, p)
                    if base is None:
                        return False
                return _get_field(base, parts[-1]) is not None

            return has
        if tok == "size":
            self.expect("(")
            v = self.expr()
            self.expect(")")
            return lambda env: len(v(env))
        name = tok
        return lambda env: env[name]


import functools


@functools.lru_cache(maxsize=512)
def _compile_rule(rule: str):
    """Rules are static strings compiled to closures; caching makes the
    admission hot path re-use them instead of re-tokenizing every rule on
    every apiserver write (advisor round-5)."""
    return _Cel(_tokenize(rule)).expr()


def cel_eval(rule: str, self_value) -> bool:
    return bool(_compile_rule(rule)({"self": self_value}))


# ---------------------------------------------------------------------------
# Schema walker: the subset of structural openAPI v3 the CRDs below use.
# ---------------------------------------------------------------------------

_TYPES = {
    "object": dict, "array": list, "string": str,
    "boolean": bool, "integer": (int,), "number": (int, float),
}


def _walk(schema: dict, value, path: str, out: list[str]) -> None:
    t = schema.get("type")
    if t and value is not None:
        expected = _TYPES[t]
        if t == "boolean":
            ok = isinstance(value, bool)
        elif t == "integer":
            ok = isinstance(value, int) and not isinstance(value, bool)
        elif t == "number":
            ok = isinstance(value, (int, float)) and not isinstance(value, bool)
        else:
            ok = isinstance(value, expected)
        if not ok:
            out.append(f"{path}: expected {t}")
            return
    if value is None:
        return
    if "enum" in schema and value not in schema["enum"]:
        out.append(f"{path}: {value!r} not in {schema['enum']}")
    if "minimum" in schema and isinstance(value, (int, float)) and value < schema["minimum"]:
        out.append(f"{path}: {value} below minimum {schema['minimum']}")
    if "maximum" in schema and isinstance(value, (int, float)) and value > schema["maximum"]:
        out.append(f"{path}: {value} above maximum {schema['maximum']}")
    # re.search, not fullmatch: the apiserver's openAPI pattern semantics
    # are PARTIAL match — the validator must agree with what actually
    # ships, so unanchored patterns fail the parity tests here too
    if "pattern" in schema and isinstance(value, str) and not re.search(schema["pattern"], value):
        out.append(f"{path}: {value!r} does not match {schema['pattern']}")
    if isinstance(value, list):
        if "maxItems" in schema and len(value) > schema["maxItems"]:
            out.append(f"{path}: more than {schema['maxItems']} items")
        items = schema.get("items")
        if items:
            for i, item in enumerate(value):
                _walk(items, item, f"{path}[{i}]", out)
    if isinstance(value, dict):
        for req in schema.get("required", ()):
            if value.get(req) is None:
                out.append(f"{path}: missing required field {req!r}")
        props = schema.get("properties", {})
        for k, sub in props.items():
            if k in value:
                _walk(sub, value[k], f"{path}.{k}", out)
        addl = schema.get("additionalProperties")
        if isinstance(addl, dict):
            for k, v in value.items():
                if k not in props:
                    _walk(addl, v, f"{path}.{k}", out)
    for rule in schema.get("x-kubernetes-validations", ()):
        try:
            ok = cel_eval(rule["rule"], value)
        except Exception as e:  # a broken shipped rule must fail loudly
            out.append(f"{path}: rule {rule['rule']!r} errored: {e}")
            continue
        if not ok:
            out.append(f"{path}: {rule.get('message', rule['rule'])}")


def validate_object(crd: dict, obj: dict) -> list[str]:
    """Violations of ``obj`` (a {spec: ...} dict) against the CRD schema."""
    schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
    out: list[str] = []
    _walk(schema, obj, crd["spec"]["names"]["kind"], out)
    return out


# ---------------------------------------------------------------------------
# The CRDs
# ---------------------------------------------------------------------------

def _selector_term_schema() -> dict:
    return {
        "type": "object",
        "properties": {
            "id": {"type": "string"},
            "name": {"type": "string"},
            "tags": {"type": "object", "additionalProperties": {"type": "string"}},
        },
        # Every rule guards optional fields with has(): CEL field access on
        # an absent field ERRORS (apiserver and this evaluator agree), and a
        # rule error rejects the object — an unguarded rule would reject
        # valid manifests that simply omit the field.
        "x-kubernetes-validations": [
            {"rule": "(has(self.id) && self.id != '') || "
                     "(has(self.name) && self.name != '') || "
                     "(has(self.tags) && size(self.tags) > 0)",
             "message": "terms must set id, name, or tags"},
            {"rule": "!(has(self.id) && self.id != '') || "
                     "(!(has(self.name) && self.name != '') && "
                     "(!has(self.tags) || size(self.tags) == 0))",
             "message": "'id' is mutually exclusive with other fields"},
            {"rule": "!has(self.tags) || "
                     "!self.tags.exists(k, k == '' || self.tags[k] == '')",
             "message": "empty tag keys or values aren't supported"},
        ],
    }


def _taint_schema() -> dict:
    return {
        "type": "object",
        "required": ["key", "effect"],
        "properties": {
            "key": {"type": "string", "pattern": r"."},  # non-empty
            "value": {"type": "string"},
            "effect": {"type": "string",
                       "enum": ["NoSchedule", "PreferNoSchedule", "NoExecute"]},
        },
    }


def _crd(kind: str, plural: str, spec_schema: dict) -> dict:
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{API_GROUP}"},
        "spec": {
            "group": API_GROUP,
            "names": {"kind": kind, "plural": plural, "singular": kind.lower()},
            "scope": "Cluster",
            "versions": [{
                "name": "v1",
                "served": True,
                "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "required": ["spec"],
                    "properties": {"spec": spec_schema},
                }},
            }],
        },
    }


def nodeclass_crd() -> dict:
    from ..providers.imagefamily import FAMILIES

    spec = {
        "type": "object",
        "properties": {
            "role": {"type": "string"},
            "instanceProfile": {"type": "string"},
            "imageFamily": {"type": "string", "enum": sorted(FAMILIES)},
            "userData": {"type": "string"},
            "subnetSelectorTerms": {
                "type": "array", "maxItems": 30, "items": _selector_term_schema(),
            },
            "securityGroupSelectorTerms": {
                "type": "array", "maxItems": 30, "items": _selector_term_schema(),
            },
            "imageSelectorTerms": {
                "type": "array", "maxItems": 30, "items": _selector_term_schema(),
            },
            # ODCR discovery terms (parity: capacityReservationSelectorTerms)
            "capacityReservationSelectorTerms": {
                "type": "array", "maxItems": 30, "items": _selector_term_schema(),
            },
            "blockDeviceMappings": {
                "type": "array", "maxItems": 50,
                "items": {
                    "type": "object",
                    "properties": {
                        "deviceName": {"type": "string"},
                        "volumeSizeGiB": {"type": "integer", "minimum": 1},
                        "volumeType": {"type": "string"},
                        "rootVolume": {"type": "boolean"},
                        "encrypted": {"type": "boolean"},
                    },
                },
            },
            "metadataOptions": {
                "type": "object",
                "properties": {
                    "httpEndpoint": {"type": "string", "enum": ["enabled", "disabled"]},
                    "httpProtocolIPv6": {"type": "string", "enum": ["enabled", "disabled"]},
                    "httpPutResponseHopLimit": {"type": "integer", "minimum": 1, "maximum": 64},
                    "httpTokens": {"type": "string", "enum": ["required", "optional"]},
                },
            },
            "tags": {"type": "object", "additionalProperties": {"type": "string"}},
            # parity: ec2nodeclass.go:93-95 kubebuilder Enum=RAID0
            "instanceStorePolicy": {"type": "string", "enum": ["RAID0"]},
            # parity: ec2nodeclass.go:96-98 DetailedMonitoring
            "detailedMonitoring": {"type": "boolean"},
            # parity: ec2nodeclass.go:45-47 / :116-119
            "associatePublicIPAddress": {"type": "boolean"},
            "context": {"type": "string"},
        },
        # has()-guarded throughout: unguarded access to an absent optional
        # field errors (apiserver semantics) and would reject valid objects
        "x-kubernetes-validations": [
            {"rule": "(has(self.role) && self.role != '') != "
                     "(has(self.instanceProfile) && self.instanceProfile != '')",
             "message": "exactly one of role or instanceProfile is required"},
            {"rule": "!has(self.imageFamily) || self.imageFamily != 'custom' || "
                     "(has(self.imageSelectorTerms) && size(self.imageSelectorTerms) > 0)",
             "message": "imageFamily custom requires imageSelector terms"},
            {"rule": "!has(self.imageFamily) || self.imageFamily != 'custom' || "
                     "(has(self.userData) && self.userData != '')",
             "message": "imageFamily custom requires userData"},
            {"rule": "!has(self.tags) || !self.tags.exists(k, k == '')",
             "message": "empty tag keys aren't supported"},
            {"rule": "!has(self.tags) || "
                     "!self.tags.exists(k, k.startsWith('kubernetes.io/cluster'))",
             "message": "tag matches restricted prefix kubernetes.io/cluster/"},
            {"rule": f"!has(self.tags) || "
                     f"!self.tags.exists(k, k.startsWith('{lbl.GROUP}/'))",
             "message": f"tags may not use the {lbl.GROUP}/ namespace"},
            {"rule": "!has(self.blockDeviceMappings) || "
                     "!self.blockDeviceMappings.exists(b, has(b.rootVolume) && b.rootVolume) || "
                     "self.blockDeviceMappings.exists_one(b, has(b.rootVolume) && b.rootVolume)",
             "message": "must have only one blockDeviceMappings with rootVolume"},
        ],
    }
    return _crd("NodeClass", "nodeclasses", spec)


def nodepool_crd() -> dict:
    from ..models.nodepool import DISRUPTION_REASONS

    restricted = "[" + ", ".join(f"'{k}'" for k in RESTRICTED_KEYS) + "]"
    spec = {
        "type": "object",
        "required": ["nodeClassRef"],
        "properties": {
            "nodeClassRef": {
                "type": "object",
                "properties": {"name": {"type": "string"}},
                "x-kubernetes-validations": [
                    {"rule": "has(self.name) && self.name != ''",
                     "message": "nodeClassRef is required"},
                ],
            },
            "weight": {"type": "integer"},
            "labels": {"type": "object", "additionalProperties": {"type": "string"}},
            # parity: core NodePool.spec.limits — resource-name -> quantity
            "limits": {"type": "object", "additionalProperties": {"type": "string"}},
            "taints": {"type": "array", "items": _taint_schema()},
            "startupTaints": {"type": "array", "items": _taint_schema()},
            "requirements": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["key", "operator"],
                    "properties": {
                        "key": {"type": "string"},
                        "operator": {
                            "type": "string",
                            "enum": ["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"],
                        },
                        "values": {"type": "array", "items": {"type": "string"}},
                        "minValues": {"type": "integer", "minimum": 1},
                    },
                    "x-kubernetes-validations": [
                        {"rule": f"!(self.key in {restricted})",
                         "message": "requirement on restricted label"},
                    ],
                },
            },
            "disruption": {
                "type": "object",
                "properties": {
                    "consolidationPolicy": {
                        "type": "string",
                        "enum": ["WhenEmpty", "WhenUnderutilized"],
                    },
                    "consolidateAfter": {"type": "number"},
                    "expireAfter": {"type": "number"},
                    "budgets": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "properties": {
                                "nodes": {
                                    "type": "string",
                                    # anchored: the apiserver evaluates
                                    # openAPI patterns as PARTIAL matches
                                    "pattern": r"^([0-9]+(\.[0-9]+)?%|[0-9]+)$",
                                },
                                "reasons": {
                                    "type": "array",
                                    "items": {"type": "string",
                                              "enum": list(DISRUPTION_REASONS)},
                                },
                                "schedule": {"type": "string"},
                                "duration": {"type": "number"},
                            },
                            "x-kubernetes-validations": [
                                {"rule": "!has(self.schedule) || "
                                         "(has(self.duration) && self.duration > 0)",
                                 "message": "budget schedule requires a positive duration"},
                            ],
                        },
                    },
                },
                "x-kubernetes-validations": [
                    {"rule": "!has(self.consolidateAfter) || self.consolidateAfter >= 0",
                     "message": "consolidateAfter must be >= 0"},
                    {"rule": "!has(self.expireAfter) || self.expireAfter > 0",
                     "message": "expireAfter must be positive"},
                ],
            },
            # parity: the core NodePool CRD's kubelet section, including the
            # evictionSoft <-> evictionSoftGracePeriod pairing XValidations
            "kubelet": {
                "type": "object",
                "properties": {
                    "maxPods": {"type": "integer", "minimum": 0},
                    "podsPerCore": {"type": "integer", "minimum": 0},
                    "clusterDNS": {"type": "array", "items": {"type": "string"}},
                    "systemReserved": {"type": "object",
                                       "additionalProperties": {"type": "string"}},
                    "kubeReserved": {"type": "object",
                                     "additionalProperties": {"type": "string"}},
                    "evictionHard": {"type": "object",
                                     "additionalProperties": {"type": "string"}},
                    "evictionSoft": {"type": "object",
                                     "additionalProperties": {"type": "string"}},
                    "evictionSoftGracePeriod": {
                        "type": "object",
                        "additionalProperties": {"type": "string"},
                    },
                    "evictionMaxPodGracePeriod": {"type": "integer"},
                    "imageGCHighThresholdPercent": {
                        "type": "integer", "minimum": 0, "maximum": 100,
                    },
                    "imageGCLowThresholdPercent": {
                        "type": "integer", "minimum": 0, "maximum": 100,
                    },
                    "cpuCFSQuota": {"type": "boolean"},
                },
                "x-kubernetes-validations": [
                    {"rule": "!has(self.evictionSoft) || "
                             "self.evictionSoft.all(k, "
                             "has(self.evictionSoftGracePeriod) && "
                             "k in self.evictionSoftGracePeriod)",
                     "message": "evictionSoft requires a matching "
                                "evictionSoftGracePeriod"},
                    {"rule": "!has(self.evictionSoftGracePeriod) || "
                             "self.evictionSoftGracePeriod.all(k, "
                             "has(self.evictionSoft) && k in self.evictionSoft)",
                     "message": "evictionSoftGracePeriod requires a matching "
                                "evictionSoft"},
                    {"rule": "!has(self.imageGCHighThresholdPercent) || "
                             "!has(self.imageGCLowThresholdPercent) || "
                             "self.imageGCHighThresholdPercent > "
                             "self.imageGCLowThresholdPercent",
                     "message": "imageGCHighThresholdPercent must be greater "
                                "than imageGCLowThresholdPercent"},
                ],
            },
        },
        "x-kubernetes-validations": [
            {"rule": f"!has(self.labels) || !self.labels.exists(k, k in {restricted})",
             "message": "template label is restricted"},
        ],
    }
    return _crd("NodePool", "nodepools", spec)


# ---------------------------------------------------------------------------
# Model -> wire-shape converters (so one object can take both paths)
# ---------------------------------------------------------------------------

def _terms(terms) -> list[dict]:
    return [
        {"id": t.id, "name": t.name, "tags": {k: v for k, v in t.tags}}
        for t in terms
    ]


def nodeclass_to_obj(nc) -> dict:
    return {"spec": {
        "role": nc.role,
        "instanceProfile": nc.instance_profile,
        "imageFamily": nc.image_family,
        "userData": nc.user_data,
        "subnetSelectorTerms": _terms(nc.subnet_selector),
        "securityGroupSelectorTerms": _terms(nc.security_group_selector),
        "imageSelectorTerms": _terms(nc.image_selector),
        "capacityReservationSelectorTerms": _terms(nc.capacity_reservation_selector),
        "blockDeviceMappings": [
            {
                "deviceName": bd.device_name,
                "volumeSizeGiB": bd.volume_size_gib,
                "volumeType": bd.volume_type,
                "rootVolume": bd.root_volume,
                "encrypted": bd.encrypted,
            }
            for bd in nc.block_devices
        ],
        "metadataOptions": {
            "httpEndpoint": nc.metadata_options.http_endpoint,
            "httpProtocolIPv6": nc.metadata_options.http_protocol_ipv6,
            "httpPutResponseHopLimit": nc.metadata_options.http_put_response_hop_limit,
            "httpTokens": nc.metadata_options.http_tokens,
        },
        "tags": dict(nc.tags),
        "detailedMonitoring": nc.detailed_monitoring,
        **(
            {"associatePublicIPAddress": nc.associate_public_ip}
            if nc.associate_public_ip is not None else {}
        ),
        **({"context": nc.context} if nc.context else {}),
        **(
            {"instanceStorePolicy": nc.instance_store_policy}
            if nc.instance_store_policy is not None else {}
        ),
    }}


def nodepool_to_obj(pool) -> dict:
    from ..models.nodepool import Budget

    budgets = []
    for b in pool.disruption.budgets:
        if not isinstance(b, Budget):
            b = Budget(nodes=b)
        row: dict[str, Any] = {"nodes": b.nodes, "reasons": list(b.reasons)}
        if b.schedule is not None:
            row["schedule"] = b.schedule
        if b.duration_s is not None:
            row["duration"] = b.duration_s
        budgets.append(row)
    d: dict[str, Any] = {
        "consolidationPolicy": pool.disruption.consolidation_policy,
        "budgets": budgets,
    }
    if pool.disruption.consolidate_after_s is not None:
        d["consolidateAfter"] = pool.disruption.consolidate_after_s
    if pool.disruption.expire_after_s is not None:
        d["expireAfter"] = pool.disruption.expire_after_s
    reqs = []
    for r in pool.requirements:
        row = {
            "key": r.key,
            "operator": getattr(r.operator, "value", str(r.operator)),
            "values": [str(v) for v in r.values],
        }
        if r.min_values is not None:
            row["minValues"] = r.min_values
        reqs.append(row)
    spec: dict[str, Any] = {
        "nodeClassRef": {"name": pool.nodeclass_name},
        "weight": pool.weight,
        "labels": dict(pool.labels),
        "requirements": reqs,
        "disruption": d,
    }
    for attr, key in (("taints", "taints"), ("startup_taints", "startupTaints")):
        ts = getattr(pool, attr)
        if ts:
            spec[key] = [
                {"key": t.key, "value": t.value, "effect": t.effect} for t in ts
            ]
    if not pool.limits.unlimited:
        spec["limits"] = pool.limits.resources.to_quantities()
    if pool.kubelet is not None:
        k = pool.kubelet
        kd: dict[str, Any] = {}
        for attr, key in (
            ("max_pods", "maxPods"),
            ("pods_per_core", "podsPerCore"),
            ("eviction_max_pod_grace_period", "evictionMaxPodGracePeriod"),
            ("image_gc_high_threshold_percent", "imageGCHighThresholdPercent"),
            ("image_gc_low_threshold_percent", "imageGCLowThresholdPercent"),
            ("cpu_cfs_quota", "cpuCFSQuota"),
        ):
            val = getattr(k, attr)
            if val is not None:
                kd[key] = val
        if k.cluster_dns:
            kd["clusterDNS"] = list(k.cluster_dns)
        for attr, key in (
            ("system_reserved", "systemReserved"),
            ("kube_reserved", "kubeReserved"),
            ("eviction_hard", "evictionHard"),
            ("eviction_soft", "evictionSoft"),
            ("eviction_soft_grace_period", "evictionSoftGracePeriod"),
        ):
            pairs = getattr(k, attr)
            if pairs:
                kd[key] = dict(pairs)
        if kd:
            spec["kubelet"] = kd
    return {"spec": spec}


def write_crds(outdir) -> list:
    """Write both CRD artifacts as JSON (JSON is valid YAML) — called by
    deploy/render.py alongside the manifests."""
    import json
    import pathlib

    outdir = pathlib.Path(outdir)
    outdir.mkdir(parents=True, exist_ok=True)
    written = []
    for name, crd in (
        (f"{API_GROUP}_nodeclasses.json", nodeclass_crd()),
        (f"{API_GROUP}_nodepools.json", nodepool_crd()),
    ):
        p = outdir / name
        p.write_text(json.dumps(crd, indent=1) + "\n")
        written.append(p)
    return written
