"""Kube-Lease-backed lease host: fenced shard leases on a real apiserver.

The sharded control plane (``operator/sharding.py``) talks to its lease
host through four calls — ``try_acquire_lease_fenced`` / ``release_lease``
/ ``list_leases`` / ``lease_token`` — which the FakeCloud hosts in
memory for every hermetic environment. Outside the fake, ``--shard-elect``
needs the same semantics on what a production control plane actually has:
``coordination.k8s.io/v1`` Lease objects. This module provides that
adapter.

Mapping (designs/sharded-provisioning.md documents the full matrix):

- one Lease object per shard lease. Shard lease NAMES are free-form
  (``karpenter-shard/default/zone-a``, the ``__global__`` sentinel) while
  Kubernetes object names are DNS-1123 subdomains, so the adapter derives
  a deterministic safe object name (sanitized + an 8-hex content hash)
  and stores the ORIGINAL name in the ``karpenter.tpu/lease-key``
  annotation — ``list_leases`` maps back losslessly.
- ``spec.holderIdentity`` / ``spec.leaseDurationSeconds`` /
  ``spec.renewTime`` / ``spec.acquireTime`` carry the client-go-shaped
  tenancy; expiry is ``renewTime + leaseDurationSeconds`` on the
  adapter's injected clock.
- the **fencing token** and **holder nonce** live in annotations
  (``karpenter.tpu/fencing-token``, ``karpenter.tpu/holder-nonce``).
  The token bumps on every HOLDER change — acquire of a fresh, expired,
  or released lease, or a same-identity takeover with a different nonce
  (the identity-collision edge) — and NEVER on a renew, exactly the
  FakeCloud contract. Valid tokens start at 1: token 0 remains the
  explicit never-held sentinel the cloud-side fence check rejects.
- ``release_lease`` clears the holder and backdates ``renewTime`` but
  KEEPS the object (a delete would lose the token annotation and reset
  fencing history — the one divergence from the fake, which hosts tokens
  separately from leases).
- every write is a compare-and-swap on ``metadata.resourceVersion``; on
  ``ConflictError`` the attempt re-reads once and reports the real
  holder, the same "CAS lost = somebody else holds it" answer the fake
  gives without retrying forever inside a reconcile tick.

The transport is injected (``LeaseTransport`` protocol below): unit
tests run a :class:`StubLeaseApi` that models apiserver optimistic
concurrency; a production deployment supplies a thin client over its
kube credentials. The adapter itself is transport-agnostic and carries
no HTTP machinery.
"""

from __future__ import annotations

import hashlib
import re
import threading
from typing import Optional, Protocol

from ..utils.clock import Clock, RealClock

TOKEN_ANNOTATION = "karpenter.tpu/fencing-token"
NONCE_ANNOTATION = "karpenter.tpu/holder-nonce"
KEY_ANNOTATION = "karpenter.tpu/lease-key"

_UNSAFE = re.compile(r"[^a-z0-9.-]+")


class ConflictError(Exception):
    """Optimistic-concurrency failure: the object's resourceVersion moved
    under the write (HTTP 409 from a real apiserver)."""


class LeaseNotFound(Exception):
    """GET/PUT target does not exist (HTTP 404)."""


class LeaseTransport(Protocol):
    """The minimal apiserver surface the adapter needs. All objects are
    plain dicts in the coordination.k8s.io/v1 Lease shape with
    ``metadata.resourceVersion`` strings."""

    def get(self, name: str) -> dict: ...
    def create(self, name: str, obj: dict) -> dict: ...
    def update(self, name: str, obj: dict, resource_version: str) -> dict: ...
    def list(self) -> list[dict]: ...


def k8s_lease_name(key: str) -> str:
    """Deterministic DNS-1123-safe object name for a free-form shard
    lease name: lowercased, unsafe runs collapsed to ``-``, suffixed with
    an 8-hex content hash so two keys can never collide after
    sanitization (``__global__`` and ``--global--`` must stay distinct)."""
    digest = hashlib.sha256(key.encode()).hexdigest()[:8]
    safe = _UNSAFE.sub("-", key.lower()).strip("-.") or "lease"
    return f"{safe[:54]}-{digest}"


class KubeLeaseHost:
    """``try_acquire_lease_fenced`` semantics over Lease objects.

    Duck-types the FakeCloud's lease surface, so ``ShardElector`` (and
    the provisioner's work-queue steal probe via :meth:`list_leases`)
    runs unchanged against a real control plane."""

    def __init__(self, transport: LeaseTransport,
                 clock: Optional[Clock] = None):
        self.transport = transport
        self.clock = clock or RealClock()
        self._lock = threading.Lock()

    # -- object plumbing ----------------------------------------------------
    def _now(self) -> float:
        return self.clock.now()

    @staticmethod
    def _annotations(obj: dict) -> dict:
        return obj.setdefault("metadata", {}).setdefault("annotations", {})

    @staticmethod
    def _token_of(obj: dict) -> int:
        try:
            return int(KubeLeaseHost._annotations(obj).get(
                TOKEN_ANNOTATION, "0"
            ))
        except ValueError:
            return 0

    def _expired(self, obj: dict) -> bool:
        spec = obj.get("spec", {})
        holder = spec.get("holderIdentity") or ""
        if not holder:
            return True
        renew = spec.get("renewTime")
        duration = spec.get("leaseDurationSeconds") or 0
        if renew is None:
            return True
        return self._now() >= float(renew) + float(duration)

    def _fresh_obj(self, name: str, key: str) -> dict:
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {
                "name": name,
                "annotations": {
                    KEY_ANNOTATION: key,
                    TOKEN_ANNOTATION: "0",
                    NONCE_ANNOTATION: "",
                },
            },
            "spec": {},
        }

    # -- the lease-host surface --------------------------------------------
    def try_acquire_lease_fenced(
        self, name: str, holder: str, ttl_s: float, nonce: str = "",
    ) -> tuple[str, int, str]:
        """Fenced CAS acquire-or-renew; returns ``(holder, token, nonce)``
        after the attempt — the FakeCloud contract verbatim. A lost CAS
        (another writer moved the resourceVersion) re-reads once and
        reports the winner instead of spinning."""
        with self._lock:
            return self._acquire_locked(name, holder, ttl_s, nonce)

    def _acquire_locked(self, name, holder, ttl_s, nonce, retried=False):
        obj_name = k8s_lease_name(name)
        try:
            obj = self.transport.get(obj_name)
            resource_version = obj["metadata"].get("resourceVersion", "")
            created = False
        except LeaseNotFound:
            obj = self._fresh_obj(obj_name, name)
            resource_version = None
            created = True
        ann = self._annotations(obj)
        spec = obj.setdefault("spec", {})
        cur_holder = spec.get("holderIdentity") or ""
        cur_nonce = ann.get(NONCE_ANNOTATION, "")
        token = self._token_of(obj)
        ours = cur_holder == holder and cur_nonce == nonce
        if not created and not self._expired(obj) and not ours:
            # live foreign tenancy (including the identity-collision edge:
            # same holder string, different elector nonce = a CONTENDER)
            return cur_holder, token, cur_nonce
        if created or not ours or self._expired(obj):
            # new tenancy (fresh, expired, released, or takeover): the
            # fencing token advances; never on a renew
            token += 1
            ann[TOKEN_ANNOTATION] = str(token)
            spec["acquireTime"] = self._now()
        ann[NONCE_ANNOTATION] = nonce
        spec["holderIdentity"] = holder
        spec["leaseDurationSeconds"] = float(ttl_s)
        spec["renewTime"] = self._now()
        try:
            if created:
                self.transport.create(obj_name, obj)
            else:
                self.transport.update(obj_name, obj, resource_version)
        except ConflictError:
            if retried:
                raise
            # somebody else won the CAS: one re-read names the winner
            return self._acquire_locked(name, holder, ttl_s, nonce,
                                        retried=True)
        return holder, token, nonce

    def release_lease(self, name: str, holder: str) -> None:
        """Voluntary hand-off; only the holder may release. The Lease
        OBJECT (and its token annotation) survives — the next acquire
        bumps the token, fencing the released tenancy out."""
        with self._lock:
            obj_name = k8s_lease_name(name)
            try:
                obj = self.transport.get(obj_name)
            except LeaseNotFound:
                return
            if (obj.get("spec", {}).get("holderIdentity") or "") != holder:
                return
            resource_version = obj["metadata"].get("resourceVersion", "")
            obj["spec"]["holderIdentity"] = ""
            obj["spec"]["renewTime"] = None
            try:
                self.transport.update(obj_name, obj, resource_version)
            except ConflictError:
                pass  # a contender already took it; nothing to release

    def list_leases(self, prefix: str = "") -> dict[str, tuple[str, float, str]]:
        """Live (unexpired) leases by ORIGINAL shard-lease name,
        prefix-filtered — the elector's membership discovery and the
        provisioner's GLOBAL-holder liveness probe read this."""
        out: dict[str, tuple[str, float, str]] = {}
        for obj in self.transport.list():
            ann = self._annotations(obj)
            key = ann.get(KEY_ANNOTATION, "")
            if not key.startswith(prefix) or self._expired(obj):
                continue
            spec = obj.get("spec", {})
            expires = float(spec.get("renewTime") or 0.0) + float(
                spec.get("leaseDurationSeconds") or 0.0
            )
            out[key] = (
                spec.get("holderIdentity") or "", expires,
                ann.get(NONCE_ANNOTATION, ""),
            )
        return out

    def lease_token(self, name: str) -> int:
        """Current fencing token (0 = never acquired); survives release."""
        try:
            return self._token_of(self.transport.get(k8s_lease_name(name)))
        except LeaseNotFound:
            return 0


class StubLeaseApi:
    """In-memory apiserver stub with optimistic concurrency — what the
    unit tests (and any hermetic integration of ``KubeLeaseHost``) run
    against. Models exactly the transport surface: resourceVersion bumps
    on every write, ``update`` with a stale version raises
    :class:`ConflictError`, ``get`` of a missing object raises
    :class:`LeaseNotFound`."""

    def __init__(self):
        self._objects: dict[str, dict] = {}
        self._rv = 0
        self._lock = threading.Lock()
        # introspection for tests: every (verb, name) in arrival order
        self.writes: list[tuple[str, str]] = []

    def _bump(self, obj: dict) -> dict:
        self._rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
        return obj

    @staticmethod
    def _copy(obj: dict) -> dict:
        import copy

        return copy.deepcopy(obj)

    def get(self, name: str) -> dict:
        with self._lock:
            obj = self._objects.get(name)
            if obj is None:
                raise LeaseNotFound(name)
            return self._copy(obj)

    def create(self, name: str, obj: dict) -> dict:
        with self._lock:
            if name in self._objects:
                raise ConflictError(f"{name} already exists")
            stored = self._bump(self._copy(obj))
            self._objects[name] = stored
            self.writes.append(("create", name))
            return self._copy(stored)

    def update(self, name: str, obj: dict, resource_version: str) -> dict:
        with self._lock:
            cur = self._objects.get(name)
            if cur is None:
                raise LeaseNotFound(name)
            if cur["metadata"].get("resourceVersion") != resource_version:
                raise ConflictError(
                    f"{name}: resourceVersion {resource_version} is stale"
                )
            stored = self._bump(self._copy(obj))
            self._objects[name] = stored
            self.writes.append(("update", name))
            return self._copy(stored)

    def list(self) -> list[dict]:
        with self._lock:
            return [self._copy(o) for o in self._objects.values()]
