"""Sharded control plane: per-partition leases, fencing tokens, ownership.

The single :class:`~.leaderelection.LeaderElector` makes replication
all-or-nothing: one replica owns every nodepool and a leader loss idles
the whole fleet for up to a lease TTL. This module generalizes it into a
**sharded lease layer** (designs/sharded-control-plane.md):

- **Partition leases.** Replicas contend for one lease per cluster
  partition, keyed on the store's stable ``(nodepool, zone)`` index
  (``Cluster.partition_key`` — the same key the partitioned encoder
  chains and the sharded screen/solve already shard by), plus one
  ``GLOBAL`` lease owning the unpartitioned work: the interruption
  queue, objects whose partition cannot be determined, and the
  work-stealing GLOBAL pod queue. Pending pods themselves are ROUTED,
  not GLOBAL-owned (:func:`pod_partition` / :func:`split_pending`,
  designs/sharded-provisioning.md): partition-pinned pods solve on
  their partition's lease holder, unpinned pods through the fenced
  queue on the lease host.
- **Fencing tokens.** Every lease carries a monotonic fencing token that
  bumps on every holder change (``CloudBackend.try_acquire_lease_fenced``;
  the fake hosts it the way a real control-plane store would). The token
  is stamped into every cloud-side write a replica makes under that lease
  (launch via ``LaunchRequest.fence``, terminate via per-id fences), and
  the store REJECTS any write carrying a token older than the lease's
  current one — a deposed leader's in-flight writes bounce off the cloud
  instead of racing the successor (``StaleFencingTokenError``,
  ``karpenter_fenced_writes_rejected_total``).
- **Ownership scope.** The :class:`~..controllers.base.Manager` wraps
  every reconcile in an ambient :func:`scope` carrying the replica's
  current :class:`Ownership` snapshot. Controllers filter their work
  through :func:`owns_key` / :func:`owns_claim` / :func:`owns_node` /
  :func:`owns_global`; with no ambient scope (single-replica deployments,
  every existing test) the predicates answer True and nothing changes.
- **Rebalancing + handoff barrier.** Desired ownership is rendezvous
  (highest-random-weight) hashing of partition keys over the live member
  set — deterministic, minimal movement on membership change. A replica
  acquires a partition only once the previous lease has expired (the CAS
  enforces that) and then ADOPTS the partition's unsettled claims —
  launched-but-unregistered NodeClaims whose previous owner died mid
  lifecycle — exactly once, at the acquire edge, extending the
  pods-bound-once invariant across replicas.

Chaos proves the invariants instead of asserting them:
``chaos/scenarios/replica-loss.json`` kills / pauses / netsplits a
replica mid-spot-storm and the ``no-double-launch`` /
``no-orphaned-claims`` / ``leases-partition-the-fleet`` invariants close
the run (chaos/invariants.py).
"""

from __future__ import annotations

import contextlib
import hashlib
import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

from ..utils.clock import Clock

log = logging.getLogger("karpenter.tpu.sharding")

#: sentinel partition key for the unpartitioned scope (pending pods, the
#: interruption queue, objects with no resolvable partition)
GLOBAL_KEY: tuple = ("__global__", "")

LEASE_PREFIX = "karpenter-shard"
MEMBER_PREFIX = "karpenter-shard-member"

SHARD_TTL_S = 15.0
# same shape as leaderelection.RENEW_DEADLINE_FRACTION: a replica stops
# acting on a lease strictly before the lease host would let a contender
# steal it
RENEW_DEADLINE_FRACTION = 2.0 / 3.0


def lease_name(key: tuple) -> str:
    return LEASE_PREFIX + "/" + "/".join(str(k) for k in key)


def rendezvous_owner(key: tuple, members: list[str]) -> Optional[str]:
    """Highest-random-weight owner of ``key`` among ``members``:
    deterministic, and a membership change moves only the partitions the
    joining/leaving replica wins/loses (minimal reshuffle)."""
    if not members:
        return None
    token = "/".join(str(k) for k in key)
    return max(
        members,
        key=lambda m: (
            hashlib.sha256(f"{token}@{m}".encode()).hexdigest(), m
        ),
    )


# -- ambient ownership -------------------------------------------------------

@dataclass(frozen=True)
class Ownership:
    """One replica's point-in-time lease holdings: partition key ->
    fencing token. Immutable — a controller pass runs against the
    snapshot taken when the pass started, and a snapshot that goes stale
    mid-pass is exactly what the cloud-side fencing check exists for."""

    replica: str
    keys: dict = field(default_factory=dict)   # partition key -> token

    def holds(self, key: tuple) -> bool:
        return key in self.keys

    def fence(self, key: tuple) -> Optional[tuple]:
        """(lease name, token) for stamping a write sanctioned by
        ``key``'s lease; None when this replica does not hold it."""
        token = self.keys.get(key)
        if token is None:
            return None
        return (lease_name(key), token)


_AMBIENT = threading.local()


@contextlib.contextmanager
def scope(ownership: Optional[Ownership]):
    """Ambient ownership for the current thread (the Manager enters this
    around every reconcile when a ShardElector is wired)."""
    prev = getattr(_AMBIENT, "own", None)
    _AMBIENT.own = ownership
    try:
        yield ownership
    finally:
        _AMBIENT.own = prev


def current() -> Optional[Ownership]:
    return getattr(_AMBIENT, "own", None)


@contextlib.contextmanager
def sanction(key: Optional[tuple]):
    """Name the partition lease sanctioning the cloud writes inside this
    block (e.g. a consolidation replacement launch is sanctioned by the
    OLD node's partition lease, wherever the new node lands)."""
    prev = getattr(_AMBIENT, "sanction", None)
    _AMBIENT.sanction = key
    try:
        yield
    finally:
        _AMBIENT.sanction = prev


def current_sanction() -> Optional[tuple]:
    """The ambient :func:`sanction` key for the current thread (None when
    no explicit sanction is in force) — captured by callers that hand
    work to other threads (the provisioner's launch pool) so the fencing
    resolution is identical whichever thread runs the write."""
    return getattr(_AMBIENT, "sanction", None)


def owns_global() -> bool:
    own = current()
    if own is None:
        return True
    return own.holds(GLOBAL_KEY)


# -- pending-pod routing (sharded provisioning) ------------------------------

#: name of the work-stealing queue for truly global pending pods on the
#: lease host (designs/sharded-provisioning.md)
WORK_QUEUE = "karpenter-global-pods"


def _pinned_value(value_set) -> Optional[str]:
    """The single label value a requirement ValueSet pins its key to, or
    None (unconstrained / complement / multi-valued sets don't pin)."""
    if value_set is None or value_set.complement:
        return None
    if len(value_set.values) != 1:
        return None
    return next(iter(value_set.values))


def pod_partition(pod, nodepools=None) -> Optional[tuple]:
    """The FEASIBLE (nodepool, zone) partition a pending pod's required
    constraints pin it to, or None (a truly global pod).

    A pod is partition-pinned iff its nodeSelector + required node
    affinity constrain ``topology.kubernetes.io/zone`` to exactly one
    zone AND the nodepool is determined — either pinned by a
    ``karpenter.sh/nodepool`` selector or unambiguous because the cluster
    runs exactly one nodepool. The rule is a pure function of the pod
    spec (plus the stable nodepool list), so every replica routes every
    pod identically — the property the ownership split relies on."""
    from ..models import labels as lbl

    reqs = pod.requirements()
    zone = _pinned_value(reqs.get(lbl.TOPOLOGY_ZONE))
    if not zone:
        return None
    pool = _pinned_value(reqs.get(lbl.NODEPOOL))
    if not pool:
        pools = list(nodepools or ())
        if len(pools) != 1:
            return None
        pool = getattr(pools[0], "name", pools[0])
    return (str(pool), str(zone))


def routes_here(pod, nodepools=None, own: Optional[Ownership] = None) -> bool:
    """Does this replica own ``pod``'s provisioning/binding work? The ONE
    routing predicate both the provisioner's split and the host binder
    filter through — the no-double-bind guarantee rests on every replica
    routing every pod identically, so the rule must not be re-derived at
    call sites. Pinned pods route to their partition's holder; unpinned
    (or unleased-partition) pods to the GLOBAL holder; no ownership
    scope means single-replica — everything routes here."""
    own = own if own is not None else current()
    if own is None:
        return True
    key = pod_partition(pod, nodepools)
    if key is None or key not in _known_keys(own):
        return own.holds(GLOBAL_KEY)
    return own.holds(key)


def split_pending(pods, nodepools=None, own: Optional[Ownership] = None):
    """Route a pending-pod list through the ownership snapshot:
    ``(local, global_pods, foreign)`` where ``local`` maps each OWNED
    partition key to its pinned pods, ``global_pods`` are the unpinned
    (or unleased-partition) pods that flow through the work-stealing
    GLOBAL queue, and ``foreign`` are pods pinned to partitions another
    replica owns (skipped here; their owner solves them).

    With no ownership (single-replica), everything lands in
    ``global_pods`` — the unchanged legacy path."""
    own = own if own is not None else current()
    local: dict[tuple, list] = {}
    global_pods: list = []
    foreign: list = []
    if own is None:
        return {}, list(pods), []
    known = _known_keys(own)
    for pod in pods:
        key = pod_partition(pod, nodepools)
        if key is None or key not in known:
            # unpinned, or pinned to a partition no elector has contended
            # yet: GLOBAL scope (same fall-through as owns_key)
            global_pods.append(pod)
        elif own.holds(key):
            local.setdefault(key, []).append(pod)
        else:
            foreign.append(pod)
    return local, global_pods, foreign


def steal_fence(own: Optional[Ownership] = None) -> Optional[tuple]:
    """The (key, (lease name, token)) pair sanctioning this replica's
    claims against the GLOBAL work queue: the GLOBAL lease when held,
    else the replica's first held partition lease (lease-name order, so
    the choice is stable across passes). None when the replica holds
    nothing — a lease-less replica must not touch the queue."""
    own = own if own is not None else current()
    if own is None:
        return None
    if own.holds(GLOBAL_KEY):
        return (GLOBAL_KEY, own.fence(GLOBAL_KEY))
    for key in sorted(own.keys, key=lease_name):
        return (key, own.fence(key))
    return None


def owns_key(key: Optional[tuple]) -> bool:
    """Does this replica own partition ``key``? ``None`` and keys no
    elector has contended yet (a brand-new pool/zone's first node) fall
    to the GLOBAL owner, so no object is orphaned between a partition
    appearing and its lease being contended."""
    own = current()
    if own is None:
        return True
    if key is None:
        return own.holds(GLOBAL_KEY)
    return own.holds(key) or (
        key not in _known_keys(own) and own.holds(GLOBAL_KEY)
    )


def _partition_of_claim(cluster, claim) -> Optional[tuple]:
    """The partition a claim's work routes to: its backing node's router
    mapping when registered, else the (nodepool, zone-label) pair when the
    launch pinned a zone, else None (global)."""
    node_name = getattr(getattr(claim, "status", None), "node_name", "")
    if node_name:
        key = cluster.partition_of(node_name)
        if key is not None:
            return key
    from ..models import labels as lbl

    zone = claim.labels.get(lbl.TOPOLOGY_ZONE, "")
    if zone:
        return (claim.nodepool_name, zone)
    return None


def owns_claim(cluster, claim) -> bool:
    own = current()
    if own is None:
        return True
    key = _partition_of_claim(cluster, claim)
    if key is None:
        return own.holds(GLOBAL_KEY)
    return own.holds(key) or (
        # unleased partition (no replica has contended it yet) falls to
        # the global owner — checked against the elector's known-key set
        key not in _known_keys(own) and own.holds(GLOBAL_KEY)
    )


def owns_node(cluster, node) -> bool:
    own = current()
    if own is None:
        return True
    key = cluster.partition_of(node.name)
    if key is None:
        from ..state.cluster import Cluster

        key = Cluster.partition_key(node)
    return own.holds(key) or (
        key not in _known_keys(own) and own.holds(GLOBAL_KEY)
    )


def _known_keys(own: Ownership) -> frozenset:
    return getattr(own, "_known", frozenset())


def write_fence(cluster=None, claim=None, key: Optional[tuple] = None):
    """The (lease name, token) to stamp into a cloud write, resolved from
    the ambient ownership: an explicit ``key``, the ambient
    :func:`sanction` key, the claim's partition, or the GLOBAL lease —
    whichever this replica holds, in that order. ``None`` when no
    sharding is active (single-replica: writes are unfenced).

    A replica whose snapshot no longer matches the cloud (deposed while a
    pass was in flight) still stamps its OLD token here — that is the
    point: the cloud rejects it."""
    own = current()
    if own is None:
        return None
    candidates = []
    if key is not None:
        candidates.append(key)
    sk = getattr(_AMBIENT, "sanction", None)
    if sk is not None:
        candidates.append(sk)
    if claim is not None and cluster is not None:
        ck = _partition_of_claim(cluster, claim)
        if ck is not None:
            candidates.append(ck)
    candidates.append(GLOBAL_KEY)
    for k in candidates:
        f = own.fence(k)
        if f is not None:
            return f
    # held nothing relevant: stamp the first candidate with a token the
    # cloud has certainly superseded (explicitly stale — never silent)
    return (lease_name(candidates[0]), 0)


# -- the sharded elector -----------------------------------------------------

class ShardElector:
    """A controller that contends for per-partition leases and publishes
    this replica's :class:`Ownership` snapshot.

    Runs as ``Manager.elector``: reconcile = membership heartbeat +
    rendezvous target computation + acquire/renew/release, exactly one
    CAS per lease per tick. ``is_leader()`` answers "does this replica
    own at least one partition within its renew deadline" — the Manager
    idles every other controller when False (a zero-partition replica is
    a hot standby), and wraps each reconcile in ``sharding.scope(
    elector.ownership())`` when True."""

    name = "sharding"
    interval_s = 2.0

    def __init__(self, cloud, cluster, identity: str, clock: Optional[Clock] = None,
                 ttl_s: float = SHARD_TTL_S):
        import socket
        import uuid

        self.cloud = cloud
        self.cluster = cluster
        self.identity = identity or f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        self.clock = clock
        self.ttl_s = float(ttl_s)
        if not 0 < RENEW_DEADLINE_FRACTION < 1:  # pragma: no cover - constant
            raise ValueError("renew deadline must sit strictly inside the TTL")
        self._nonce = uuid.uuid4().hex
        self._lock = threading.Lock()
        self._held: dict[tuple, int] = {}      # key -> fencing token
        self._known: frozenset = frozenset()   # every key this pass saw
        # per-lease date of the last SUCCESSFUL renew (taken BEFORE the
        # CAS round-trip). An indeterminate renew failure — transport
        # error, lease-host brownout, netsplit — says nothing about the
        # lease's state, so the lease stays in the snapshot with its old
        # date and the renew-deadline check stands it down on time; only
        # a definitive answer (another holder) drops it immediately.
        self._renewed: dict[tuple, float] = {}
        # key -> token at the last adoption: a re-acquire of our own
        # unchanged tenancy (token never bumped, e.g. healed within the
        # TTL after a deadline stand-down) must not re-adopt
        self._adopted: dict[tuple, int] = {}
        # chaos seam: a netsplit replica's lease RPCs all fail (it keeps
        # reconciling on its snapshot until the renew deadline lapses)
        self.partitioned = False
        self._host_unreachable = False  # edge-triggered outage logging
        # adoption log: (partition key, claim names) per acquire edge —
        # the exactly-once evidence the ReplicaSet tests assert on
        self.adoptions: list[tuple[tuple, tuple]] = []
        self.rebalances: list[tuple[str, tuple]] = []  # (reason, key)
        # holders this elector last published lease_ownership for
        # (vanished ones are zeroed on the next export)
        self._ownership_exported: set = set()

    # -- clock -------------------------------------------------------------
    def _now(self) -> float:
        import time

        return self.clock.now() if self.clock is not None else time.monotonic()

    # -- lease RPCs (all veto-able by the netsplit chaos seam) -------------
    def _acquire(self, name: str, ttl: Optional[float] = None):
        if self.partitioned:
            raise ConnectionError("sharding: netsplit (chaos)")
        return self.cloud.try_acquire_lease_fenced(
            name, self.identity, ttl if ttl is not None else self.ttl_s,
            nonce=self._nonce,
        )

    def _release(self, name: str) -> None:
        if self.partitioned:
            raise ConnectionError("sharding: netsplit (chaos)")
        self.cloud.release_lease(name, self.identity)

    # -- the reconcile ------------------------------------------------------
    def reconcile(self) -> None:
        from ..metrics import SHARD_LEASES_HELD, SHARD_REBALANCES

        pre = self._now()  # pessimistic freshness: time BEFORE the CAS round
        try:
            # 1. membership heartbeat + live-member discovery
            self._acquire(f"{MEMBER_PREFIX}/{self.identity}")
            members = sorted(
                name[len(MEMBER_PREFIX) + 1:]
                for name, (holder, _exp, _nonce) in
                self.cloud.list_leases(MEMBER_PREFIX + "/").items()
            )
        except Exception as e:
            # membership unknown (API brownout / netsplit): keep renewing
            # what we hold if we can, but never re-target — rebalancing on
            # a partial member list would thrash ownership. This is
            # expected weather, not a crash: the renew deadline stands the
            # replica down if the outage outlasts it. Logged on the edge
            # only — a 30s outage must not spam one line per tick.
            if not self._host_unreachable:
                self._host_unreachable = True
                log.warning(
                    "%s lease-host unreachable (%s: %s); renewing held only",
                    self.identity, type(e).__name__, e,
                )
            self._renew_held_only()
            return
        if self._host_unreachable:
            self._host_unreachable = False
            log.info("%s lease host reachable again", self.identity)
        if self.identity not in members:  # pragma: no cover - defensive
            members.append(self.identity)
            members.sort()

        # 2. the partition universe: every key the store knows + GLOBAL
        keys = [GLOBAL_KEY] + list(self.cluster.partition_keys())
        desired = {
            k for k in keys if rendezvous_owner(k, members) == self.identity
        }

        acquired: dict[tuple, int] = {}
        with self._lock:
            held = dict(self._held)
        # 3. voluntary hand-off of partitions rebalanced away from us:
        # release BEFORE acquiring so a rebalance never transits through
        # overlap (two holders) — the successor CAS-acquires next tick
        for k in [k for k in held if k not in desired]:
            try:
                self._release(lease_name(k))
            except Exception:
                pass  # expiry hands it off anyway
            held.pop(k, None)
            with self._lock:
                self._renewed.pop(k, None)
            self.rebalances.append(("rebalance", k))
            SHARD_REBALANCES.inc(reason="rebalance")
        # 4. renew held + contend for desired
        for k in sorted(desired, key=lease_name):
            try:
                holder, token, nonce = self._acquire(lease_name(k))
            except Exception:
                # indeterminate (transport error): a held lease KEEPS its
                # old renew date and rides toward the renew deadline — the
                # lease host may still consider us the holder, and the
                # deadline stands us down strictly before a contender can
                # get in (renew-failed counts the miss)
                if k in held:
                    self.rebalances.append(("renew-failed", k))
                    SHARD_REBALANCES.inc(reason="renew-failed")
                continue
            if holder == self.identity and nonce == self._nonce:
                if k not in held:
                    acquired[k] = token
                    self.rebalances.append(("acquired", k))
                    SHARD_REBALANCES.inc(reason="acquired")
                held[k] = token
                with self._lock:
                    self._renewed[k] = pre
            elif k in held:
                # lost to a contender (e.g. we paused past the TTL) — a
                # definitive answer, unlike a failed RPC: drop immediately
                held.pop(k, None)
                with self._lock:
                    self._renewed.pop(k, None)
                self.rebalances.append(("lost", k))
                SHARD_REBALANCES.inc(reason="lost")
        with self._lock:
            self._held = held
            self._known = frozenset(keys)
            self._renewed = {k: at for k, at in self._renewed.items() if k in held}
        SHARD_LEASES_HELD.set(float(len(held)), replica=self.identity)
        self._export_imbalance()
        # 5. handoff barrier, adopt side: partitions we JUST acquired may
        # carry unsettled claims from a dead predecessor — adopt them at
        # the acquire edge, exactly once per TENANCY (token bump). A
        # re-acquire of our own unchanged tenancy (healed within the TTL)
        # keeps the same token and must not re-adopt.
        for k, token in sorted(acquired.items(), key=lambda kv: lease_name(kv[0])):
            if self._adopted.get(k) == token:
                continue
            self._adopted[k] = token
            self._adopt(k)

    def _renew_held_only(self) -> None:
        """Best-effort renew of current holdings when membership discovery
        failed; never grows the snapshot. An indeterminate per-lease
        failure keeps the lease on its old renew date (it stands down at
        the renew deadline, per the failure matrix — one browned-out tick
        must not idle every partition); a definitive foreign holder drops
        it immediately."""
        from ..metrics import SHARD_LEASES_HELD, SHARD_REBALANCES

        pre = self._now()
        with self._lock:
            held = dict(self._held)
        for k in list(held):
            try:
                holder, token, nonce = self._acquire(lease_name(k))
            except Exception:
                self.rebalances.append(("renew-failed", k))
                SHARD_REBALANCES.inc(reason="renew-failed")
                continue
            if holder == self.identity and nonce == self._nonce:
                held[k] = token
                with self._lock:
                    self._renewed[k] = pre
            else:
                held.pop(k, None)
                with self._lock:
                    self._renewed.pop(k, None)
                self.rebalances.append(("lost", k))
                SHARD_REBALANCES.inc(reason="lost")
        with self._lock:
            self._held = held
        SHARD_LEASES_HELD.set(float(len(held)), replica=self.identity)

    def _export_imbalance(self) -> None:
        """Publish the fleet-wide lease distribution the lease host sees:
        ``karpenter_lease_ownership{replica}`` per holder and
        ``karpenter_rendezvous_imbalance`` = max/mean held — the ROADMAP's
        16-keys/8-replicas rendezvous skew, measured instead of anecdotal.
        One extra prefix listing per elector tick (~2s); every replica
        computes the same answer from the same lease table."""
        from ..metrics import LEASE_OWNERSHIP, RENDEZVOUS_IMBALANCE

        try:
            leases = self.cloud.list_leases(LEASE_PREFIX + "/")
        except Exception:
            return  # brownout: keep the last published distribution
        by_holder: dict[str, int] = {}
        for _name, (holder, _exp, _nonce) in leases.items():
            by_holder[holder] = by_holder.get(holder, 0) + 1
        # holders that vanished since the last export (crashed replica,
        # leases expired) must drop to 0, not freeze at their last value
        # — the replica-loss dashboard reads exactly this edge
        for holder in self._ownership_exported - set(by_holder):
            LEASE_OWNERSHIP.set(0.0, replica=holder)
        self._ownership_exported = set(by_holder)
        for holder, n in sorted(by_holder.items()):
            LEASE_OWNERSHIP.set(float(n), replica=holder)
        if by_holder:
            mean = sum(by_holder.values()) / len(by_holder)
            RENDEZVOUS_IMBALANCE.set(
                round(max(by_holder.values()) / mean, 4) if mean else 0.0
            )

    def _adopt(self, key: tuple) -> None:
        """Adopt a freshly-acquired partition's unsettled claims: every
        launched-but-unregistered (and every draining) NodeClaim whose
        lifecycle the previous owner left in flight. The adoption itself
        is bookkeeping — the successor's registration/liveness/termination
        controllers pick the claims up because the ownership filter now
        includes this partition — but it happens exactly once, at the
        acquire edge, and leaves an audit trail."""
        # successor warmup: before the first owned pass compiles anything,
        # replay the fleet's warmup manifest (no-op and jax-import-free
        # unless KARPENTER_TPU_WARMUP_MANIFEST is set; never raises)
        from ..trace.warmup import warm_on_adoption

        warm_on_adoption()
        unsettled = []
        for claim in self.cluster.snapshot_claims():
            if key != GLOBAL_KEY:
                if _partition_of_claim(self.cluster, claim) != key:
                    continue
            else:
                ck = _partition_of_claim(self.cluster, claim)
                if ck is not None and ck in self._known:
                    continue
            if claim.deleted or (
                claim.is_launched() and not claim.is_registered()
            ):
                unsettled.append(claim.name)
        self.adoptions.append((key, tuple(sorted(unsettled))))
        # flight recorder: one adopt hop per claim, under the NEW
        # tenancy's fencing token (the elector reconciles outside the
        # ownership scope, so the replica is stamped explicitly)
        ledger = getattr(
            getattr(self.cluster, "observer", None), "ledger", None
        )
        if ledger is not None:
            token = self._held.get(key, 0)
            for name in sorted(unsettled):
                try:
                    ledger.record_once(
                        ledger.mint("NodeClaim", name), "adopt",
                        key=f"{lease_name(key)}@{token}",
                        subject_kind="NodeClaim", subject=name,
                        replica=self.identity,
                        fence=(lease_name(key), token),
                        detail={"partition": list(key)},
                    )
                except Exception:
                    pass
        if unsettled:
            log.info(
                "%s adopted partition %s with %d unsettled claims: %s",
                self.identity, key, len(unsettled), unsettled[:4],
            )

    # -- Manager protocol ---------------------------------------------------
    def _prune_stale_locked(self) -> None:
        """Drop every lease whose last successful renew is at or past the
        renew deadline — a lease we could not renew must leave the
        snapshot strictly before the lease host would let successors in
        (the same client-go renewDeadline < leaseDuration shape the
        single elector uses; the boundary tie goes to safety). Per lease,
        so one unreachable partition's lease never stands down the rest.
        Caller holds the lock."""
        deadline = self.ttl_s * RENEW_DEADLINE_FRACTION
        now = self._now()
        for k in [k for k in self._held
                  if now - self._renewed.get(k, -float("inf")) >= deadline]:
            self._held.pop(k, None)
            self._renewed.pop(k, None)
            log.warning(
                "%s dropping shard lease %s: no successful renew within %.0fs",
                self.identity, k, deadline,
            )

    def is_leader(self) -> bool:
        """True while this replica owns >= 1 lease renewed inside the
        renew deadline."""
        with self._lock:
            self._prune_stale_locked()
            return bool(self._held)

    def ownership(self) -> Ownership:
        """The snapshot the Manager hands to sharding.scope() — leases
        past their renew deadline are pruned out first."""
        with self._lock:
            self._prune_stale_locked()
            own = Ownership(replica=self.identity, keys=dict(self._held))
        object.__setattr__(own, "_known", self._known)
        return own

    def owned_keys(self) -> list[tuple]:
        with self._lock:
            self._prune_stale_locked()
            return sorted(self._held, key=lease_name)

    def release(self) -> None:
        """Voluntary hand-off of everything (clean shutdown)."""
        from ..metrics import SHARD_LEASES_HELD

        with self._lock:
            held = list(self._held)
            self._held = {}
            self._renewed = {}
        for k in held:
            try:
                self._release(lease_name(k))
            except Exception:
                pass
        try:
            self._release(f"{MEMBER_PREFIX}/{self.identity}")
        except Exception:
            pass
        SHARD_LEASES_HELD.set(0.0, replica=self.identity)
