"""Manifest ingestion: k8s wire-shape YAML -> validated model objects.

The inverse of ``crds.nodeclass_to_obj`` / ``crds.nodepool_to_obj`` — the
operator's CR-ingestion path. The reference gets this for free from
controller-runtime's scheme decoding (``cmd/controller/main.go:32-73``
registers the typed ``pkg/apis/v1beta1`` structs); here the decode is
explicit, and every document passes the SAME two gates an apiserver-routed
object passes:

 1. CRD structural schema + CEL XValidations (``crds.validate_object`` —
    what the apiserver enforces from ``pkg/apis/crds/*.yaml``), then
 2. the admission chain (``webhooks.admit`` = defaulting + validation,
    parity ``pkg/webhooks/webhooks.go:30-60``).

Workload documents (Pod / Deployment) decode into solver ``Pod`` models —
the analogue of the scheduler watching pending pods. Used by ``examples/``
loading, tests, and any host embedding the framework without a live
apiserver.
"""

from __future__ import annotations

from typing import Iterable, Union

from ..models.nodeclass import (
    BlockDevice,
    KubeletConfiguration,
    MetadataOptions,
    NodeClass,
    SelectorTerm,
)
from ..models.nodepool import Budget, Disruption, Limits, NodePool, Taint
from ..models.pod import (
    Pod,
    PodAffinityTerm,
    Toleration,
    TopologySpreadConstraint,
)
from ..models.requirements import Operator, Requirement
from ..models.resources import ResourceVector
from . import crds
from .webhooks import admit

API_VERSION = f"{crds.API_GROUP}/v1"


class ManifestError(ValueError):
    """A document failed schema validation, admission, or decoding."""


def load_documents(text: str) -> list[dict]:
    """YAML stream -> list of non-empty documents."""
    import yaml

    return [d for d in yaml.safe_load_all(text) if d]


# -- wire -> model decoders --------------------------------------------------

def _terms_from(raw) -> list[SelectorTerm]:
    out = []
    for t in raw or ():
        out.append(SelectorTerm(
            tags=tuple(sorted((t.get("tags") or {}).items())),
            id=t.get("id", ""),
            name=t.get("name", ""),
        ))
    return out


def _taints_from(raw) -> list[Taint]:
    return [
        Taint(key=t["key"], value=t.get("value", ""),
              effect=t.get("effect", "NoSchedule"))
        for t in raw or ()
    ]


def _requirements_from(raw) -> list[Requirement]:
    return [
        Requirement(
            key=r["key"],
            operator=Operator(r["operator"]),
            values=tuple(str(v) for v in r.get("values") or ()),
            min_values=r.get("minValues"),
        )
        for r in raw or ()
    ]


_KUBELET_KEYS = (
    ("maxPods", "max_pods"),
    ("podsPerCore", "pods_per_core"),
    ("evictionMaxPodGracePeriod", "eviction_max_pod_grace_period"),
    ("imageGCHighThresholdPercent", "image_gc_high_threshold_percent"),
    ("imageGCLowThresholdPercent", "image_gc_low_threshold_percent"),
    ("cpuCFSQuota", "cpu_cfs_quota"),
)
_KUBELET_MAPS = (
    ("systemReserved", "system_reserved"),
    ("kubeReserved", "kube_reserved"),
    ("evictionHard", "eviction_hard"),
    ("evictionSoft", "eviction_soft"),
    ("evictionSoftGracePeriod", "eviction_soft_grace_period"),
)


def _kubelet_from(raw) -> KubeletConfiguration:
    kw = {}
    for wire, attr in _KUBELET_KEYS:
        if wire in raw:
            kw[attr] = raw[wire]
    for wire, attr in _KUBELET_MAPS:
        if wire in raw:
            kw[attr] = tuple(sorted(raw[wire].items()))
    if "clusterDNS" in raw:
        kw["cluster_dns"] = tuple(raw["clusterDNS"])
    return KubeletConfiguration(**kw)


def nodepool_from_obj(obj: dict, name: str = "") -> NodePool:
    """{spec: ...} wire shape -> NodePool (inverse of nodepool_to_obj).

    Absent optional wire fields take model defaults; ``consolidateAfter`` /
    ``expireAfter`` absent means the model default (0 / Never respectively),
    matching what ``nodepool_to_obj`` omits."""
    spec = obj.get("spec") or {}
    kw: dict = {"name": name or _meta_name(obj)}
    if "nodeClassRef" in spec:
        kw["nodeclass_name"] = spec["nodeClassRef"].get("name", "default")
    for wire, attr in (("weight", "weight"), ("labels", "labels")):
        if wire in spec:
            kw[attr] = spec[wire]
    kw["requirements"] = _requirements_from(spec.get("requirements"))
    kw["taints"] = _taints_from(spec.get("taints"))
    kw["startup_taints"] = _taints_from(spec.get("startupTaints"))
    if spec.get("limits"):
        kw["limits"] = Limits(
            resources=ResourceVector.from_map(spec["limits"]), unlimited=False
        )
    d = spec.get("disruption")
    if d:
        dkw: dict = {}
        if "consolidationPolicy" in d:
            dkw["consolidation_policy"] = d["consolidationPolicy"]
        if "consolidateAfter" in d:
            dkw["consolidate_after_s"] = d["consolidateAfter"]
        if "expireAfter" in d:
            dkw["expire_after_s"] = d["expireAfter"]
        if "budgets" in d:
            dkw["budgets"] = [
                Budget(
                    nodes=str(b.get("nodes", "10%")),
                    reasons=tuple(b.get("reasons") or ()),
                    schedule=b.get("schedule"),
                    duration_s=b.get("duration"),
                )
                for b in d["budgets"]
            ]
        kw["disruption"] = Disruption(**dkw)
    if spec.get("kubelet"):
        kw["kubelet"] = _kubelet_from(spec["kubelet"])
    return NodePool(**kw)


def nodeclass_from_obj(obj: dict, name: str = "") -> NodeClass:
    """{spec: ...} wire shape -> NodeClass (inverse of nodeclass_to_obj)."""
    spec = obj.get("spec") or {}
    kw: dict = {"name": name or _meta_name(obj)}
    for wire, attr in (
        ("role", "role"),
        ("instanceProfile", "instance_profile"),
        ("imageFamily", "image_family"),
        ("userData", "user_data"),
        ("tags", "tags"),
        ("detailedMonitoring", "detailed_monitoring"),
        ("associatePublicIPAddress", "associate_public_ip"),
        ("context", "context"),
        ("instanceStorePolicy", "instance_store_policy"),
    ):
        if wire in spec and spec[wire] is not None:
            kw[attr] = spec[wire]
    for wire, attr in (
        ("imageSelectorTerms", "image_selector"),
        ("subnetSelectorTerms", "subnet_selector"),
        ("securityGroupSelectorTerms", "security_group_selector"),
        ("capacityReservationSelectorTerms", "capacity_reservation_selector"),
    ):
        if wire in spec:
            kw[attr] = _terms_from(spec[wire])
    if "blockDeviceMappings" in spec:
        kw["block_devices"] = [
            BlockDevice(
                device_name=bd.get("deviceName", "/dev/xvda"),
                volume_size_gib=bd.get("volumeSizeGiB", 20),
                volume_type=bd.get("volumeType", "gp3"),
                root_volume=bd.get("rootVolume", False),
                encrypted=bd.get("encrypted", True),
            )
            for bd in spec["blockDeviceMappings"]
        ]
    if "metadataOptions" in spec:
        mo = spec["metadataOptions"]
        kw["metadata_options"] = MetadataOptions(**{
            attr: mo[wire]
            for wire, attr in (
                ("httpEndpoint", "http_endpoint"),
                ("httpProtocolIPv6", "http_protocol_ipv6"),
                ("httpPutResponseHopLimit", "http_put_response_hop_limit"),
                ("httpTokens", "http_tokens"),
            )
            if wire in mo
        })
    return NodeClass(**kw)


# -- workload decoding -------------------------------------------------------

def _pod_from_podspec(name: str, podspec: dict, labels: dict,
                      replicas: int = 1, owner_key: str = "") -> list[Pod]:
    # container requests sum into the pod's effective request; summed in
    # axis units (ResourceVector addition), NOT by re-parsing quantities
    requests = ResourceVector()
    for c in podspec.get("containers") or ():
        requests = requests + ResourceVector.from_map(
            (c.get("resources") or {}).get("requests") or {}
        )
    tolerations = [
        Toleration(
            key=t.get("key", ""), operator=t.get("operator", "Equal"),
            value=t.get("value", ""), effect=t.get("effect", ""),
        )
        for t in podspec.get("tolerations") or ()
    ]
    spread = [
        TopologySpreadConstraint(
            topology_key=t["topologyKey"],
            max_skew=t.get("maxSkew", 1),
            when_unsatisfiable=t.get("whenUnsatisfiable", "DoNotSchedule"),
            label_selector=(t.get("labelSelector") or {}).get("matchLabels", {}),
        )
        for t in podspec.get("topologySpreadConstraints") or ()
    ]
    affinity = podspec.get("affinity") or {}

    def _pod_terms(section: str) -> list[PodAffinityTerm]:
        sec = affinity.get(section) or {}
        return [
            PodAffinityTerm(
                topology_key=t["topologyKey"],
                label_selector=(t.get("labelSelector") or {}).get("matchLabels", {}),
            )
            for t in sec.get("requiredDuringSchedulingIgnoredDuringExecution") or ()
        ]

    node_affinity: list[Requirement] = []
    preferred: list[Requirement] = []
    na = affinity.get("nodeAffinity") or {}
    req_terms = (na.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
                 ).get("nodeSelectorTerms") or ()
    for term in req_terms:
        node_affinity += _requirements_from(
            [{**e, "minValues": None} for e in term.get("matchExpressions") or ()]
        )
    for pref in na.get("preferredDuringSchedulingIgnoredDuringExecution") or ():
        preferred += _requirements_from(
            [{**e, "minValues": None}
             for e in (pref.get("preference") or {}).get("matchExpressions") or ()]
        )
    out = []
    for i in range(replicas):
        out.append(Pod(
            name=f"{name}-{i}" if replicas > 1 else name,
            labels=dict(labels),
            # a fresh vector per replica: Pod.__post_init__ mutates it
            requests=ResourceVector(requests.v.copy()),
            node_selector=dict(podspec.get("nodeSelector") or {}),
            node_affinity=list(node_affinity),
            preferred_node_affinity=list(preferred),
            tolerations=list(tolerations),
            topology_spread=list(spread),
            anti_affinity=_pod_terms("podAntiAffinity"),
            affinity=_pod_terms("podAffinity"),
            owner_key=owner_key,
        ))
    return out


def pods_from_workload(doc: dict) -> list[Pod]:
    """Pod or Deployment manifest -> solver Pod models (replicas expanded)."""
    kind = doc.get("kind")
    name = _meta_name(doc)
    if kind == "Pod":
        return _pod_from_podspec(
            name, doc.get("spec") or {},
            (doc.get("metadata") or {}).get("labels") or {},
        )
    if kind == "Deployment":
        spec = doc.get("spec") or {}
        template = spec.get("template") or {}
        return _pod_from_podspec(
            name,
            template.get("spec") or {},
            (template.get("metadata") or {}).get("labels") or {},
            replicas=spec.get("replicas", 1),
            owner_key=f"deployment/{name}",
        )
    raise ManifestError(f"unsupported workload kind {kind!r}")


# -- the validated load path -------------------------------------------------

def _meta_name(doc: dict) -> str:
    return (doc.get("metadata") or {}).get("name") or doc.get("name") or ""


# The CRD dicts are pure functions of static code; the admission hot path
# must not rebuild the whole nested schema per apiserver write. Callers of
# these cached copies treat them as read-only.
_CRD_CACHE: dict[str, dict] = {}


def cached_crd(kind: str) -> dict:
    crd = _CRD_CACHE.get(kind)
    if crd is None:
        crd = _CRD_CACHE[kind] = (
            crds.nodeclass_crd() if kind == "NodeClass" else crds.nodepool_crd()
        )
    return crd


def admit_wire_object(kind: str, raw: dict) -> tuple[object, list[str]]:
    """THE wire-admission gate, shared by manifest loading and the webhook
    envelope path: CRD structural schema + CEL -> decode -> defaulting +
    validation. Returns (admitted_object, []) or (None, violations)."""
    if kind not in ("NodeClass", "NodePool"):
        return None, [f"unsupported kind {kind!r}"]
    violations = crds.validate_object(cached_crd(kind), {"spec": raw.get("spec") or {}})
    if violations:
        return None, violations
    try:
        obj = (nodeclass_from_obj if kind == "NodeClass" else nodepool_from_obj)(raw)
        return admit(obj), []
    except Exception as e:
        msgs = list(getattr(e, "violations", ())) or [f"malformed object: {e}"]
        return None, msgs


def load_object(doc: dict) -> Union[NodeClass, NodePool, list[Pod]]:
    """One document through the full gate: CRD schema -> decode -> admission.

    Raises ManifestError listing every violation (schema violations and
    admission violations use the same channel, like an apiserver reply)."""
    kind = doc.get("kind")
    if kind in ("Pod", "Deployment"):
        return pods_from_workload(doc)
    if kind not in ("NodeClass", "NodePool"):
        raise ManifestError(f"unsupported kind {kind!r}")
    api = doc.get("apiVersion")
    if api != API_VERSION:
        raise ManifestError(f"{kind} {_meta_name(doc)!r}: apiVersion {api!r} "
                            f"(want {API_VERSION})")
    obj, violations = admit_wire_object(kind, doc)
    if violations:
        raise ManifestError(
            f"{kind} {_meta_name(doc)!r}: " + "; ".join(violations)
        )
    return obj


def load_manifest(text: str) -> list:
    """A whole YAML stream through load_object, in document order."""
    return [load_object(d) for d in load_documents(text)]


def iter_example_files(examples_dir) -> Iterable:
    import pathlib

    root = pathlib.Path(examples_dir)
    return sorted(p for p in root.rglob("*.yaml") if p.is_file())


__all__ = [
    "API_VERSION",
    "ManifestError",
    "load_documents",
    "load_manifest",
    "load_object",
    "nodeclass_from_obj",
    "nodepool_from_obj",
    "pods_from_workload",
    "iter_example_files",
]
