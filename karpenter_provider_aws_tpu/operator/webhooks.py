"""Admission: defaulting + validation for NodeClass and NodePool.

Parity: ``pkg/webhooks/webhooks.go`` (knative defaulting/validation
admission) and the CEL rules embedded in the EC2NodeClass CRD markers
(``pkg/apis/v1beta1/ec2nodeclass_validation.go``). Without an apiserver the
admission chain runs at apply time: ``admit(obj)`` defaults then validates,
raising ``AdmissionError`` with every violation.
"""

from __future__ import annotations

from ..models import labels as lbl
from ..models.nodeclass import NodeClass
from ..models.nodepool import NodePool
from ..models.requirements import Operator, Requirement


class AdmissionError(ValueError):
    def __init__(self, violations: list[str]):
        super().__init__("; ".join(violations))
        self.violations = violations


# -- NodeClass ---------------------------------------------------------------

def default_nodeclass(nc: NodeClass) -> NodeClass:
    from ..models.nodeclass import MetadataOptions
    from ..providers.imagefamily import get_family

    if not nc.image_family:
        nc.image_family = "standard"
    from ..models.nodeclass import BlockDevice

    family = get_family(nc.image_family)
    # per-family defaults (parity: AMIFamily.DefaultBlockDeviceMappings /
    # DefaultMetadataOptions, resolver.go:80-112) — the model's generic
    # one-gp3-volume default counts as "unset" here
    if not nc.block_devices or nc.block_devices == [BlockDevice()]:
        nc.block_devices = family.default_block_device_mappings()
    if nc.metadata_options == MetadataOptions():
        nc.metadata_options = family.default_metadata_options()
    return nc


def validate_nodeclass(nc: NodeClass) -> None:
    v: list[str] = []
    if nc.role and nc.instance_profile:
        v.append("role and instanceProfile are mutually exclusive")  # CEL rule parity
    if not nc.role and not nc.instance_profile:
        v.append("one of role or instanceProfile is required")
    from ..providers.imagefamily import FAMILIES

    if nc.image_family not in FAMILIES:
        v.append(f"unknown imageFamily {nc.image_family!r}")
    if nc.image_family == "custom" and not nc.image_selector:
        v.append("imageFamily custom requires imageSelector terms")
    if nc.image_family == "custom" and not nc.user_data:
        v.append("imageFamily custom requires userData")
    # enum parity: ec2nodeclass.go InstanceStorePolicy kubebuilder enum
    if nc.instance_store_policy not in (None, "RAID0"):
        v.append(
            f"instanceStorePolicy must be RAID0 or unset, got {nc.instance_store_policy!r}"
        )
    # CEL rule parity (ec2nodeclass.go:31-51 selector-term XValidations):
    # at least one of id/name/tags; 'id' mutually exclusive with the rest;
    # term tags carry no empty keys/values; at most 30 terms per selector.
    for label, terms in (
        ("subnetSelectorTerms", nc.subnet_selector),
        ("securityGroupSelectorTerms", nc.security_group_selector),
        ("imageSelectorTerms", nc.image_selector),
    ):
        if len(terms) > 30:
            v.append(f"{label}: at most 30 terms")
        for term in terms:
            if not term.id and not term.tags and not term.name:
                v.append(f"{label}: terms must set id, name, or tags")
            if term.id and (term.tags or term.name):
                v.append(f"{label}: 'id' is mutually exclusive with other fields")
            for k, val in term.tags:
                if not k or not val:
                    v.append(f"{label}: empty tag keys or values aren't supported")
    if len(nc.block_devices) > 50:
        v.append("at most 50 block device mappings")
    if sum(1 for bd in nc.block_devices if bd.root_volume) > 1:
        v.append("must have only one blockDeviceMappings with rootVolume")
    for bd in nc.block_devices:
        if bd.volume_size_gib <= 0:
            v.append("block device volume size must be positive")
    mo = nc.metadata_options
    if mo.http_tokens not in ("required", "optional"):
        v.append("metadataOptions.httpTokens must be required|optional")
    if not 1 <= mo.http_put_response_hop_limit <= 64:
        v.append("metadataOptions hop limit must be in [1, 64]")
    # restricted tags (CEL parity: ec2nodeclass.go:80-85 — empty keys, the
    # cluster-ownership prefix, and the framework's own namespaces)
    for k in nc.tags:
        if not k:
            v.append("empty tag keys aren't supported")
        elif k.startswith("kubernetes.io/cluster"):
            v.append("tag matches restricted prefix kubernetes.io/cluster/")
        elif k.startswith("karpenter.tpu/"):
            v.append("tags may not use the karpenter.tpu/ namespace")
    if v:
        raise AdmissionError(v)


# -- NodePool ----------------------------------------------------------------

def default_nodepool(pool: NodePool) -> NodePool:
    if not pool.requirements:
        pool.requirements = [
            Requirement(lbl.CAPACITY_TYPE, Operator.IN, tuple(lbl.CAPACITY_TYPES)),
        ]
    return pool


def validate_nodepool(pool: NodePool) -> None:
    v: list[str] = []
    for r in pool.requirements:
        # karpenter.sh/nodepool rides along with the restricted set: the
        # controller stamps it itself, a template requirement on it is
        # always a mistake (and the shipped CRD rule rejects it — the
        # webhook must agree in BOTH directions)
        if r.key in lbl.RESTRICTED_LABELS or r.key == lbl.NODEPOOL:
            v.append(f"requirement on restricted label {r.key}")
        if r.min_values is not None and r.min_values < 1:
            v.append("minValues must be >= 1")
    for key in pool.labels:
        if key in lbl.RESTRICTED_LABELS or key == lbl.NODEPOOL:
            v.append(f"template label {key} is restricted")
    # evictionSoft <-> evictionSoftGracePeriod must pair BOTH directions
    # (parity: the reference CRD's kubelet XValidations — a soft threshold
    # without a grace period makes the kubelet refuse to start)
    if pool.kubelet is not None:
        k8 = pool.kubelet
        soft = {k for k, _ in k8.eviction_soft}
        grace = {k for k, _ in k8.eviction_soft_grace_period}
        for k in sorted(soft - grace):
            v.append(f"evictionSoft {k} has no matching evictionSoftGracePeriod")
        for k in sorted(grace - soft):
            v.append(f"evictionSoftGracePeriod {k} has no matching evictionSoft")
        # range parity with the shipped CRD schema (both directions: what
        # the webhook admits, the apiserver must accept, and vice versa)
        if k8.max_pods is not None and k8.max_pods < 0:
            v.append("kubelet.maxPods must be >= 0")
        if k8.pods_per_core is not None and k8.pods_per_core < 0:
            v.append("kubelet.podsPerCore must be >= 0")
        for name, pct in (
            ("imageGCHighThresholdPercent", k8.image_gc_high_threshold_percent),
            ("imageGCLowThresholdPercent", k8.image_gc_low_threshold_percent),
        ):
            if pct is not None and not 0 <= pct <= 100:
                v.append(f"kubelet.{name} must be in [0, 100]")
        if (
            k8.image_gc_high_threshold_percent is not None
            and k8.image_gc_low_threshold_percent is not None
            and k8.image_gc_high_threshold_percent
            <= k8.image_gc_low_threshold_percent
        ):
            v.append(
                "kubelet.imageGCHighThresholdPercent must be greater than "
                "imageGCLowThresholdPercent"
            )
    d = pool.disruption
    if d.consolidation_policy not in ("WhenEmpty", "WhenUnderutilized"):
        v.append(f"unknown consolidationPolicy {d.consolidation_policy!r}")
    if d.consolidate_after_s is not None and d.consolidate_after_s < 0:
        v.append("consolidateAfter must be >= 0")
    if d.expire_after_s is not None and d.expire_after_s <= 0:
        v.append("expireAfter must be positive")
    from ..models.nodepool import DISRUPTION_REASONS, Budget

    for b in d.budgets:
        nodes = b.nodes if isinstance(b, Budget) else b
        try:
            val = float(nodes[:-1]) if nodes.endswith("%") else int(nodes)
            if val < 0:
                v.append(f"budget {nodes!r} must be >= 0")
        except (ValueError, AttributeError):
            v.append(f"malformed budget {nodes!r}")
        if isinstance(b, Budget):
            for r in b.reasons:
                if r not in DISRUPTION_REASONS:
                    v.append(f"budget reason {r!r} not in {DISRUPTION_REASONS}")
            if b.schedule is not None:
                from ..utils.cron import CronSchedule

                try:
                    CronSchedule(b.schedule)
                except ValueError as e:
                    v.append(f"budget schedule: {e}")
                if not b.duration_s or b.duration_s <= 0:
                    v.append("budget schedule requires a positive duration")
    if not pool.nodeclass_name:
        v.append("nodeClassRef is required")
    if v:
        raise AdmissionError(v)


def admit(obj):
    """Default + validate (the webhook chain at apply time)."""
    if isinstance(obj, NodeClass):
        default_nodeclass(obj)
        validate_nodeclass(obj)
    elif isinstance(obj, NodePool):
        default_nodepool(obj)
        validate_nodepool(obj)
    return obj
