"""Operator runtime: options, wiring, metrics, validation.

Reference parity: ``cmd/controller/main.go`` + ``pkg/operator`` — compose
the providers, cloud provider, and all controllers from configuration, and
start the manager.
"""

from .options import Options  # noqa: F401
from .operator import Operator, new_operator  # noqa: F401
from ..metrics import Registry, Counter, Gauge, Histogram, REGISTRY  # noqa: F401
from .webhooks import admit, AdmissionError  # noqa: F401
