"""Leader election: single-writer gating for multi-replica deployments.

Parity: the reference inherits leader election from the controller-runtime
manager (``cmd/controller/main.go:34`` — a coordination.k8s.io Lease with
CAS acquire/renew) and ships 2 replicas behind it
(``charts/karpenter/templates/deployment.yaml``). Here the lease lives in
the cloud backend (``CloudBackend.try_acquire_lease`` — the control-plane
store this framework talks to; the fake hosts it in-memory, a real adapter
maps it to its coordination primitive), and the elector runs as a normal
controller: every tick it CAS-renews, and the ``Manager`` idles every other
controller while this replica does not hold the lease.

Timings follow client-go's defaults shape: lease TTL 15 s, renew every 2 s
— a dead leader is succeeded within one TTL, and a paused leader (GC,
network blip) shorter than the TTL never loses the lease mid-flight.
"""

from __future__ import annotations

import logging
import socket
import time
import uuid
from typing import Optional

from ..utils.clock import Clock

log = logging.getLogger("karpenter.tpu.leaderelection")

LEASE_NAME = "karpenter-tpu-controller-leader"
LEASE_TTL_S = 15.0
RENEW_INTERVAL_S = 2.0
# Local renew deadline as a fraction of the TTL (client-go: renewDeadline
# 10s STRICTLY below leaseDuration 15s). The margin is the point: a leader
# must stop writing strictly BEFORE the lease host would let a contender
# steal, or clock skew / boundary ties make both replicas leaders at once.
RENEW_DEADLINE_FRACTION = 2.0 / 3.0


class LeaderElector:
    """A controller that maintains (or contends for) the leader lease."""

    name = "leaderelection"

    def __init__(
        self,
        cloud,
        identity: str = "",
        lease_name: str = LEASE_NAME,
        ttl_s: float = LEASE_TTL_S,
        clock: Optional[Clock] = None,
    ):
        self.cloud = cloud
        self.identity = identity or f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        self.lease_name = lease_name
        self.ttl_s = ttl_s
        self.interval_s = RENEW_INTERVAL_S
        self.clock = clock
        self._leader = False
        self._renewed_at: Optional[float] = None
        # Holder-instance nonce: two replicas misconfigured with the SAME
        # identity string must not both believe they lead — a fenced lease
        # host distinguishes the instances by nonce, so the second is a
        # contender, not the holder renewing. (Fall back to the legacy
        # identity-only CAS on hosts without the fenced API.)
        self._nonce = uuid.uuid4().hex

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else time.monotonic()

    def reconcile(self) -> None:
        # Capture the clock BEFORE the CAS: the lease host stamps expiry
        # at some instant DURING the call, so dating the renewal after the
        # call returns would overstate freshness by the call's latency —
        # exactly the boundary where a slow renew lets the local deadline
        # and the host's expiry disagree (client-go dates renewals from
        # the request, not the response).
        pre = self._now()
        fenced = getattr(self.cloud, "try_acquire_lease_fenced", None)
        if fenced is not None:
            holder, _token, nonce = fenced(
                self.lease_name, self.identity, self.ttl_s, nonce=self._nonce
            )
            is_me = holder == self.identity and nonce == self._nonce
        else:
            holder = self.cloud.try_acquire_lease(
                self.lease_name, self.identity, self.ttl_s
            )
            is_me = holder == self.identity
        was = self._leader
        self._leader = is_me
        from ..metrics import LEADER

        LEADER.set(1.0 if self._leader else 0.0, identity=self.identity)
        if self._leader:
            self._renewed_at = pre
        if self._leader and not was:
            log.info("%s acquired leadership (%s)", self.identity, self.lease_name)
        elif was and not self._leader:
            # lost the lease (e.g. a pause longer than the TTL let another
            # replica steal it): stop writing IMMEDIATELY — the Manager
            # gates every other controller on is_leader()
            log.warning(
                "%s LOST leadership to %s (%s)",
                self.identity, holder, self.lease_name,
            )

    def is_leader(self) -> bool:
        """Leadership requires a renewal inside the renew deadline (2/3 of
        the TTL). Without this local deadline, a leader whose CAS renewals
        FAIL (cloud/API errors) would keep writing on stale state while a
        contender steals the expired lease — split-brain; and the deadline
        sits strictly BELOW the TTL so the old leader stops writing before
        the lease host would ever allow a steal (client-go's
        renewDeadline < leaseDuration shape)."""
        if not self._leader or self._renewed_at is None:
            return False
        # >=, not >: AT the deadline is already too late to keep writing
        # (the exact-boundary tie goes to safety, never to the old leader)
        if self._now() - self._renewed_at >= self.ttl_s * RENEW_DEADLINE_FRACTION:
            self._leader = False
            log.warning(
                "%s dropping leadership: no successful renew within %.0fs",
                self.identity, self.ttl_s * RENEW_DEADLINE_FRACTION,
            )
        return self._leader

    def release(self) -> None:
        """Voluntary hand-off (clean shutdown): drop the lease so the
        successor does not wait out the TTL."""
        if self._leader:
            self.cloud.release_lease(self.lease_name, self.identity)
            self._leader = False
            from ..metrics import LEADER

            LEADER.set(0.0, identity=self.identity)
