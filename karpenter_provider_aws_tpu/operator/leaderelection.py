"""Leader election: single-writer gating for multi-replica deployments.

Parity: the reference inherits leader election from the controller-runtime
manager (``cmd/controller/main.go:34`` — a coordination.k8s.io Lease with
CAS acquire/renew) and ships 2 replicas behind it
(``charts/karpenter/templates/deployment.yaml``). Here the lease lives in
the cloud backend (``CloudBackend.try_acquire_lease`` — the control-plane
store this framework talks to; the fake hosts it in-memory, a real adapter
maps it to its coordination primitive), and the elector runs as a normal
controller: every tick it CAS-renews, and the ``Manager`` idles every other
controller while this replica does not hold the lease.

Timings follow client-go's defaults shape: lease TTL 15 s, renew every 2 s
— a dead leader is succeeded within one TTL, and a paused leader (GC,
network blip) shorter than the TTL never loses the lease mid-flight.
"""

from __future__ import annotations

import logging
import socket
import time
import uuid
from typing import Optional

from ..utils.clock import Clock

log = logging.getLogger("karpenter.tpu.leaderelection")

LEASE_NAME = "karpenter-tpu-controller-leader"
LEASE_TTL_S = 15.0
RENEW_INTERVAL_S = 2.0
# Local renew deadline as a fraction of the TTL (client-go: renewDeadline
# 10s STRICTLY below leaseDuration 15s). The margin is the point: a leader
# must stop writing strictly BEFORE the lease host would let a contender
# steal, or clock skew / boundary ties make both replicas leaders at once.
RENEW_DEADLINE_FRACTION = 2.0 / 3.0


class LeaderElector:
    """A controller that maintains (or contends for) the leader lease."""

    name = "leaderelection"

    def __init__(
        self,
        cloud,
        identity: str = "",
        lease_name: str = LEASE_NAME,
        ttl_s: float = LEASE_TTL_S,
        clock: Optional[Clock] = None,
    ):
        self.cloud = cloud
        self.identity = identity or f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        self.lease_name = lease_name
        self.ttl_s = ttl_s
        self.interval_s = RENEW_INTERVAL_S
        self.clock = clock
        self._leader = False
        self._renewed_at: Optional[float] = None

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else time.monotonic()

    def reconcile(self) -> None:
        holder = self.cloud.try_acquire_lease(
            self.lease_name, self.identity, self.ttl_s
        )
        was = self._leader
        self._leader = holder == self.identity
        from ..metrics import LEADER

        LEADER.set(1.0 if self._leader else 0.0, identity=self.identity)
        if self._leader:
            self._renewed_at = self._now()
        if self._leader and not was:
            log.info("%s acquired leadership (%s)", self.identity, self.lease_name)
        elif was and not self._leader:
            # lost the lease (e.g. a pause longer than the TTL let another
            # replica steal it): stop writing IMMEDIATELY — the Manager
            # gates every other controller on is_leader()
            log.warning(
                "%s LOST leadership to %s (%s)",
                self.identity, holder, self.lease_name,
            )

    def is_leader(self) -> bool:
        """Leadership requires a renewal inside the renew deadline (2/3 of
        the TTL). Without this local deadline, a leader whose CAS renewals
        FAIL (cloud/API errors) would keep writing on stale state while a
        contender steals the expired lease — split-brain; and the deadline
        sits strictly BELOW the TTL so the old leader stops writing before
        the lease host would ever allow a steal (client-go's
        renewDeadline < leaseDuration shape)."""
        if not self._leader or self._renewed_at is None:
            return False
        if self._now() - self._renewed_at > self.ttl_s * RENEW_DEADLINE_FRACTION:
            self._leader = False
            log.warning(
                "%s dropping leadership: no successful renew within %.0fs",
                self.identity, self.ttl_s * RENEW_DEADLINE_FRACTION,
            )
        return self._leader

    def release(self) -> None:
        """Voluntary hand-off (clean shutdown): drop the lease so the
        successor does not wait out the TTL."""
        if self._leader:
            self.cloud.release_lease(self.lease_name, self.identity)
            self._leader = False
            from ..metrics import LEADER

            LEADER.set(0.0, identity=self.identity)
