"""Capacity-reservation discovery provider.

Same altitude as SubnetProvider/SecurityGroupProvider (parity:
``pkg/providers/`` adapters — each selector-resolving cloud lookup lives in
a cached provider, not in a controller): owns the describe call, a TTL
cache, and selector matching, so the status controller stays a pure
spec->status reconciler.
"""

from __future__ import annotations

from typing import Optional

from ..utils.cache import CacheTTL, TTLCache
from ..utils.clock import Clock


class ReservationProvider:
    def __init__(self, cloud, clock: Optional[Clock] = None):
        from ..utils.clock import RealClock

        self.cloud = cloud
        self.clock = clock or RealClock()
        self._cache = TTLCache(default_ttl=CacheTTL.DEFAULT, clock=clock)

    def reset(self) -> None:
        self._cache.flush()

    def list_all(self):
        """Every capacity reservation visible to the account (one describe
        serves all nodeclasses within the TTL window)."""
        hit = self._cache.get("all")
        if hit is not None:
            return hit
        out = list(self.cloud.describe_capacity_reservations())
        self._cache.set("all", out)
        return out

    def list(self, nodeclass):
        """Reservations matching the nodeclass selector terms."""
        if not nodeclass.capacity_reservation_selector:
            return []
        return [
            r
            for r in self.list_all()
            if any(term.matches(r) for term in nodeclass.capacity_reservation_selector)
        ]
