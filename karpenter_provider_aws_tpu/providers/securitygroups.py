"""Security-group provider: tag/id/name selector discovery, TTL-cached.

Parity: ``pkg/providers/securitygroup/securitygroup.go``.
"""

from __future__ import annotations

from typing import Optional

from ..models.nodeclass import NodeClass
from ..utils.cache import CacheTTL, TTLCache
from ..utils.clock import Clock


class SecurityGroupProvider:
    def __init__(self, cloud, clock: Optional[Clock] = None):
        self.cloud = cloud
        self._cache = TTLCache(default_ttl=CacheTTL.DEFAULT, clock=clock)

    def list(self, nodeclass: NodeClass):
        key = ("sgs", nodeclass.name, tuple(nodeclass.security_group_selector))
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        groups = [
            g
            for g in self.cloud.describe_security_groups()
            if any(term.matches(g) for term in nodeclass.security_group_selector)
            or not nodeclass.security_group_selector
        ]
        self._cache.set(key, groups)
        return groups

    def reset(self) -> None:
        self._cache.flush()
