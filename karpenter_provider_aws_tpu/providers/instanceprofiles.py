"""Instance-profile provider: idempotent create/delete from spec.role.

Parity: ``pkg/providers/instanceprofile/instanceprofile.go:42-105``.
"""

from __future__ import annotations

from typing import Optional

from ..models.nodeclass import NodeClass
from ..utils import errors
from ..utils.cache import CacheTTL, TTLCache
from ..utils.clock import Clock


class InstanceProfileProvider:
    def __init__(self, cloud, cluster_name: str = "cluster-1", clock: Optional[Clock] = None):
        self.cloud = cloud
        self.cluster_name = cluster_name
        self._cache = TTLCache(default_ttl=CacheTTL.INSTANCE_PROFILE, clock=clock)

    def profile_name(self, nodeclass: NodeClass) -> str:
        return f"{self.cluster_name}-{nodeclass.name}"

    def create(self, nodeclass: NodeClass) -> str:
        """Returns the profile name; explicit spec.instanceProfile wins over
        role-derived creation."""
        if nodeclass.instance_profile:
            return nodeclass.instance_profile
        name = self.profile_name(nodeclass)
        if self._cache.get(name):
            return name
        self.cloud.create_instance_profile(
            name, nodeclass.role, {"cluster": self.cluster_name}
        )
        self._cache.set(name, True)
        return name

    def delete(self, nodeclass: NodeClass) -> None:
        if nodeclass.instance_profile:
            return  # unmanaged
        name = self.profile_name(nodeclass)
        try:
            self.cloud.delete_instance_profile(name)
        except Exception as e:
            if not errors.is_not_found(e):
                raise
        self._cache.delete(name)

    def reset(self) -> None:
        self._cache.flush()
