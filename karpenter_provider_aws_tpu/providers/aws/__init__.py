"""Production AWS adapter layer (round-4 verdict missing #1).

Stdlib-only signed wire clients behind the framework's Protocol seams:

 - ``Session``          — credential chain, STS assume-role, SigV4,
                          retryer, user-agent (operator.go:92-106)
 - ``AwsCloudBackend``  — implements ``cloudprovider.backend.CloudBackend``
 - ``SqsQueueProvider`` — implements ``providers.queue.QueueProvider``
                          (sqs.go:53-101 long-poll semantics)
 - ``PricingClient``    — live pricing refresh (pricing.go:158-296)
 - ``Ec2Client`` / ``IamClient`` / ``EksClient`` — the raw signed calls

Contract-tested hermetically via ``ReplayTransport`` golden wire fixtures
(tests/test_aws_adapter.py + tests/golden/aws/) — zero network.
"""

from .backend import AwsCloudBackend
from .ec2 import Ec2Client
from .eks import EksClient
from .iam import IamClient
from .pricing_client import PricingClient
from .session import Session
from .sigv4 import Credentials
from .sqs import SqsQueueProvider
from .transport import (
    AwsApiError,
    RecordingTransport,
    ReplayTransport,
    UrllibTransport,
)

__all__ = [
    "AwsApiError",
    "AwsCloudBackend",
    "Credentials",
    "Ec2Client",
    "EksClient",
    "IamClient",
    "PricingClient",
    "RecordingTransport",
    "ReplayTransport",
    "Session",
    "SqsQueueProvider",
    "UrllibTransport",
]
