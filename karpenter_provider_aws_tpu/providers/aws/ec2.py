"""EC2 query-protocol client: the calls the framework's L4 makes.

Request construction mirrors the reference's SDK inputs call-for-call:
CreateFleet with per-(LT, zone, type) overrides
(``/root/reference/pkg/providers/instance/instance.go:202-258,320-360``),
DescribeInstanceTypes/Offerings pagination
(``pkg/providers/instancetype/instancetype.go:181-250``), subnet/SG/image
discovery, launch-template lifecycle
(``pkg/providers/launchtemplate/launchtemplate.go:202-312``). The wire
format is the EC2 query protocol: flattened ``A.N.B``-style form params in,
XML out.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Iterator, Optional

from .session import Session

API_VERSION = "2016-11-15"


def flatten(params: dict, out: Optional[dict] = None, prefix: str = "") -> dict:
    """dict/list structure -> EC2 query params (1-based list indices)."""
    out = {} if out is None else out
    for k, v in params.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flatten(v, out, f"{key}.")
        elif isinstance(v, (list, tuple)):
            for i, item in enumerate(v, 1):
                if isinstance(item, dict):
                    flatten(item, out, f"{key}.{i}.")
                else:
                    out[f"{key}.{i}"] = str(item)
        elif isinstance(v, bool):
            out[key] = "true" if v else "false"
        elif v is not None:
            out[key] = str(v)
    return out


def _strip(tag: str) -> str:
    return tag.split("}", 1)[-1]


def xml_to_data(el: ET.Element):
    """EC2 XML -> plain data: repeated ``<item>`` children become lists,
    leaves become strings."""
    children = list(el)
    if not children:
        return el.text or ""
    if all(_strip(c.tag) == "item" for c in children):
        return [xml_to_data(c) for c in children]
    out: dict = {}
    for c in children:
        name = _strip(c.tag)
        val = xml_to_data(c)
        if name in out:  # repeated non-item child: promote to list
            cur = out[name]
            out[name] = cur + [val] if isinstance(cur, list) else [cur, val]
        else:
            out[name] = val
    return out


class Ec2Client:
    def __init__(self, session: Session, endpoint: str = ""):
        self.session = session
        self.endpoint = endpoint

    def _call(self, action: str, params: Optional[dict] = None) -> dict:
        q = {"Action": action, "Version": API_VERSION}
        q.update(flatten(params or {}))
        root = self.session.call_query("ec2", q, endpoint=self.endpoint)
        data = xml_to_data(root)
        return data if isinstance(data, dict) else {"items": data}

    # -- preflight (operator.go:205-212 CheckEC2Connectivity) --------------

    def check_connectivity(self) -> None:
        """DryRun DescribeInstanceTypes; DryRunOperation IS success."""
        from .transport import AwsApiError

        try:
            self._call("DescribeInstanceTypes", {"DryRun": True, "MaxResults": 5})
        except AwsApiError as e:
            if e.code != "DryRunOperation":
                raise

    # -- capacity ----------------------------------------------------------

    def create_fleet(self, *, launch_template_configs: list[dict],
                     target_capacity: int, capacity_type: str,
                     on_demand_options: Optional[dict] = None,
                     spot_options: Optional[dict] = None,
                     tags: Optional[dict[str, str]] = None,
                     context: str = "") -> dict:
        """CreateFleet type=instant (instance.go:202-258): one call per
        batcher flush; overrides carry (InstanceType, SubnetId, AZ,
        Priority); tag specifications for instance + volume."""
        params: dict = {
            "Type": "instant",
            "LaunchTemplateConfigs": launch_template_configs,
            "TargetCapacitySpecification": {
                "TotalTargetCapacity": target_capacity,
                "DefaultTargetCapacityType": capacity_type,
            },
        }
        if capacity_type == "spot":
            params["SpotOptions"] = spot_options or {
                "AllocationStrategy": "price-capacity-optimized",
            }
        else:
            params["OnDemandOptions"] = on_demand_options or {
                "AllocationStrategy": "lowest-price",
            }
        if context:
            params["Context"] = context
        if tags:
            tag_list = [{"Key": k, "Value": v} for k, v in sorted(tags.items())]
            params["TagSpecification"] = [
                {"ResourceType": "instance", "Tag": tag_list},
                {"ResourceType": "volume", "Tag": tag_list},
            ]
        return self._call("CreateFleet", params)

    def describe_instances(self, ids: list[str]) -> list[dict]:
        out: list[dict] = []
        token = None
        while True:
            params: dict = {"InstanceId": list(ids)}
            if token:
                params["NextToken"] = token
            data = self._call("DescribeInstances", params)
            for res in _as_list(data.get("reservationSet")):
                out.extend(_as_list(res.get("instancesSet")))
            token = data.get("nextToken")
            if not token:
                return out

    def list_instances_by_tags(self, tag_filters: dict[str, str]) -> list[dict]:
        filters = [
            {"Name": f"tag:{k}", "Value": [v]} for k, v in sorted(tag_filters.items())
        ]
        filters.append({"Name": "instance-state-name",
                        "Value": ["pending", "running", "shutting-down", "stopping", "stopped"]})
        out: list[dict] = []
        token = None
        while True:
            params: dict = {"Filter": filters}
            if token:
                params["NextToken"] = token
            data = self._call("DescribeInstances", params)
            for res in _as_list(data.get("reservationSet")):
                out.extend(_as_list(res.get("instancesSet")))
            token = data.get("nextToken")
            if not token:
                return out

    def terminate_instances(self, ids: list[str]) -> list[dict]:
        data = self._call("TerminateInstances", {"InstanceId": list(ids)})
        return _as_list(data.get("instancesSet"))

    def create_tags(self, resource_ids: list[str], tags: dict[str, str]) -> None:
        self._call("CreateTags", {
            "ResourceId": list(resource_ids),
            "Tag": [{"Key": k, "Value": v} for k, v in sorted(tags.items())],
        })

    # -- discovery ---------------------------------------------------------

    def describe_subnets(self, filters: Optional[list[dict]] = None) -> list[dict]:
        data = self._call("DescribeSubnets", {"Filter": filters} if filters else {})
        return _as_list(data.get("subnetSet"))

    def describe_security_groups(self, filters: Optional[list[dict]] = None) -> list[dict]:
        data = self._call(
            "DescribeSecurityGroups", {"Filter": filters} if filters else {}
        )
        return _as_list(data.get("securityGroupInfo"))

    def describe_images(self, filters: Optional[list[dict]] = None,
                        image_ids: Optional[list[str]] = None,
                        owners: Optional[list[str]] = None) -> list[dict]:
        """DescribeImages, paginated (ami.go:176-199 parity: selector
        terms become server-side filters/ids/owners, and big shared-AMI
        accounts page — an unpaginated call silently truncated at the
        service's first-page cap)."""
        params: dict = {}
        if filters:
            params["Filter"] = filters
        if image_ids:
            params["ImageId"] = image_ids
        if owners:
            params["Owner"] = owners
        out: list[dict] = []
        token = None
        while True:
            if token:
                params["NextToken"] = token
            data = self._call("DescribeImages", params)
            out.extend(_as_list(data.get("imagesSet")))
            token = data.get("nextToken")
            if not token:
                return out

    def describe_availability_zones(self) -> list[dict]:
        data = self._call("DescribeAvailabilityZones")
        return _as_list(data.get("availabilityZoneInfo"))

    def describe_capacity_reservations(self, filters: Optional[list[dict]] = None) -> list[dict]:
        params: dict = {"Filter": filters} if filters else {}
        out: list[dict] = []
        token = None
        while True:
            if token:
                params["NextToken"] = token
            data = self._call("DescribeCapacityReservations", params)
            out.extend(_as_list(data.get("capacityReservationSet")))
            token = data.get("nextToken")
            if not token:
                return out

    # -- instance types (instancetype.go:181-250 pagination) ---------------

    def describe_instance_types(self) -> Iterator[dict]:
        token = None
        while True:
            params: dict = {"MaxResults": 100}
            if token:
                params["NextToken"] = token
            data = self._call("DescribeInstanceTypes", params)
            yield from _as_list(data.get("instanceTypeSet"))
            token = data.get("nextToken")
            if not token:
                return

    def describe_instance_type_offerings(self, location_type: str = "availability-zone") -> Iterator[dict]:
        token = None
        while True:
            params: dict = {"LocationType": location_type, "MaxResults": 1000}
            if token:
                params["NextToken"] = token
            data = self._call("DescribeInstanceTypeOfferings", params)
            yield from _as_list(data.get("instanceTypeOfferingSet"))
            token = data.get("nextToken")
            if not token:
                return

    # -- spot pricing (pricing.go:278-296) ---------------------------------

    def describe_spot_price_history(self, instance_types: Optional[list[str]] = None,
                                    product_description: str = "Linux/UNIX") -> Iterator[dict]:
        token = None
        while True:
            params: dict = {"ProductDescription": [product_description]}
            if instance_types:
                params["InstanceType"] = instance_types
            if token:
                params["NextToken"] = token
            data = self._call("DescribeSpotPriceHistory", params)
            yield from _as_list(data.get("spotPriceHistorySet"))
            token = data.get("nextToken")
            if not token:
                return

    # -- launch templates (launchtemplate.go:202-312) ----------------------

    def create_launch_template(self, name: str, data: dict,
                               tags: Optional[dict[str, str]] = None) -> dict:
        params: dict = {"LaunchTemplateName": name, "LaunchTemplateData": data}
        if tags:
            params["TagSpecification"] = [{
                "ResourceType": "launch-template",
                "Tag": [{"Key": k, "Value": v} for k, v in sorted(tags.items())],
            }]
        return self._call("CreateLaunchTemplate", params)

    def describe_launch_templates(self, name_prefix: str = "") -> list[dict]:
        params: dict = {}
        if name_prefix:
            params["Filter"] = [
                {"Name": "launch-template-name", "Value": [name_prefix + "*"]}
            ]
        out: list[dict] = []
        token = None
        while True:
            if token:
                params["NextToken"] = token
            data = self._call("DescribeLaunchTemplates", params)
            out.extend(_as_list(data.get("launchTemplates")))
            token = data.get("nextToken")
            if not token:
                return out

    def delete_launch_template(self, name: str) -> None:
        self._call("DeleteLaunchTemplate", {"LaunchTemplateName": name})


def _as_list(v) -> list:
    if v is None or v == "":
        return []
    return v if isinstance(v, list) else [v]
