"""SQS adapter implementing the framework's ``QueueProvider`` seam.

Parity: ``/root/reference/pkg/providers/sqs/sqs.go:53-101`` — long-poll
receive with MaxNumberOfMessages=10, VisibilityTimeout=20s,
WaitTimeSeconds=20 (the SQS long-poll maximum), plus send and per-receipt
delete. The interruption controller consumes this through the
``QueueProvider`` Protocol (``providers/queue.py``) and never sees the
wire."""

from __future__ import annotations

import json
from typing import Optional

from ..queue import MAX_RECEIVE, WAIT_TIME_S, QueueMessage
from .session import Session

API_VERSION = "2012-11-05"


class SqsQueueProvider:
    """QueueProvider over the SQS query protocol."""

    # receive/delete are real network long-polls: the interruption
    # controller keeps its worker fan-out (see providers/queue.py)
    blocking_io = True

    def __init__(self, session: Session, queue_url: str):
        self.session = session
        self.queue_url = queue_url

    @classmethod
    def from_queue_name(cls, session: Session, name: str) -> "SqsQueueProvider":
        """GetQueueUrl at construction (controllers.go:67-71 resolves the
        --interruption-queue name the same way)."""
        root = session.call_query("sqs", {
            "Action": "GetQueueUrl", "Version": API_VERSION, "QueueName": name,
        })
        url = root.findtext(".//{*}QueueUrl") or ""
        if not url:
            raise ValueError(f"no queue url for {name!r}")
        return cls(session, url)

    def name(self) -> str:
        return self.queue_url.rsplit("/", 1)[-1]

    def _call(self, action: str, extra: dict) -> "object":
        params = {"Action": action, "Version": API_VERSION,
                  "QueueUrl": self.queue_url}
        params.update(extra)
        # SQS query calls go to the queue's own host, not the service
        # endpoint (the URL embeds account + name)
        from urllib.parse import urlsplit

        endpoint = "{0.scheme}://{0.netloc}".format(urlsplit(self.queue_url))
        return self.session.call_query("sqs", params, endpoint=endpoint)

    # -- QueueProvider -----------------------------------------------------

    def send(self, body) -> None:
        if not isinstance(body, str):
            body = json.dumps(body)
        self._call("SendMessage", {"MessageBody": body})

    def receive(self, max_messages: Optional[int] = None) -> list[QueueMessage]:
        """One long poll (sqs.go:53-73): at most 10 messages, 20s wait,
        20s visibility, system attributes requested."""
        root = self._call("ReceiveMessage", {
            "MaxNumberOfMessages": str(min(max_messages or MAX_RECEIVE, MAX_RECEIVE)),
            "VisibilityTimeout": "20",
            "WaitTimeSeconds": str(WAIT_TIME_S),
            "AttributeName.1": "SentTimestamp",
            "MessageAttributeName.1": "All",
        })
        out = []
        for msg in root.iter():
            if msg.tag.split("}")[-1] != "Message":
                continue
            out.append(QueueMessage(
                body=msg.findtext("{*}Body") or msg.findtext("Body") or "",
                receipt=(msg.findtext("{*}ReceiptHandle")
                         or msg.findtext("ReceiptHandle") or ""),
            ))
        return out

    def delete(self, receipt: str) -> None:
        self._call("DeleteMessage", {"ReceiptHandle": receipt})
