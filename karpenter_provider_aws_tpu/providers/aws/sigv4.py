"""AWS Signature Version 4 request signing, stdlib-only.

The production wire layer's core: every request the adapters make is
signed exactly the way the reference's SDK session signs
(`/root/reference/pkg/operator/operator.go:92-106` builds an aws-sdk-go
session whose handlers do precisely this). Implemented against the
published SigV4 specification; `tests/test_aws_adapter.py` pins the
canonical-request and signature outputs against AWS's documented test
vector so a signing regression cannot ship.
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Credentials:
    access_key_id: str
    secret_access_key: str
    session_token: str = ""
    # unix seconds when these expire (STS); 0 = static
    expiration: float = 0.0


@dataclass
class SignableRequest:
    method: str
    url: str                       # full https URL incl. query string
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""


def _hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _uri_encode(s: str, *, is_path: bool) -> str:
    # SigV4 canonical encoding: unreserved chars stay; '/' preserved in paths
    safe = "-_.~" + ("/" if is_path else "")
    return urllib.parse.quote(s, safe=safe)


def canonical_request(req: SignableRequest, signed_headers: list[str],
                      payload_hash: str) -> str:
    parsed = urllib.parse.urlsplit(req.url)
    path = parsed.path or "/"
    # canonical query: key-sorted, value-sorted within key, strict encoding
    pairs = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    enc = sorted(
        (_uri_encode(k, is_path=False), _uri_encode(v, is_path=False))
        for k, v in pairs
    )
    cq = "&".join(f"{k}={v}" for k, v in enc)
    lower = {k.lower(): " ".join(v.split()) for k, v in req.headers.items()}
    ch = "".join(f"{h}:{lower[h].strip()}\n" for h in signed_headers)
    return "\n".join([
        req.method.upper(),
        _uri_encode(urllib.parse.unquote(path), is_path=True),
        cq,
        ch,
        ";".join(signed_headers),
        payload_hash,
    ])


def sign(req: SignableRequest, creds: Credentials, service: str, region: str,
         amz_date: str) -> SignableRequest:
    """Sign in place and return ``req`` with Authorization et al. set.

    ``amz_date`` is the ISO-basic timestamp (YYYYMMDDTHHMMSSZ) — injected,
    never read from a clock here, so signing is deterministic and the
    contract fixtures replay byte-exactly.
    """
    datestamp = amz_date[:8]
    host = urllib.parse.urlsplit(req.url).netloc
    req.headers.setdefault("host", host)
    req.headers["x-amz-date"] = amz_date
    if creds.session_token:
        req.headers["x-amz-security-token"] = creds.session_token
    # payload hash goes into the canonical request only (header form is an
    # S3-ism; query-protocol services sign without it, like aws-sdk-go v1)
    payload_hash = _hash(req.body)

    signed_headers = sorted(k.lower() for k in req.headers)
    creq = canonical_request(req, signed_headers, payload_hash)
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    sts = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope, _hash(creq.encode()),
    ])
    k = _hmac(b"AWS4" + creds.secret_access_key.encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
    req.headers["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={creds.access_key_id}/{scope}, "
        f"SignedHeaders={';'.join(signed_headers)}, Signature={signature}"
    )
    return req
