"""EKS cluster-discovery client.

Parity: ``/root/reference/pkg/operator/operator.go:214-245`` — cluster
endpoint + CA bundle + service CIDR discovery via DescribeCluster, feeding
bootstrap userdata and the kube-dns IP inference."""

from __future__ import annotations

from .session import Session


class EksClient:
    def __init__(self, session: Session):
        self.session = session

    def describe_cluster(self, name: str) -> dict:
        data = self.session.call_rest_json(
            "eks", "GET", f"/clusters/{name}"
        )
        return data.get("cluster", {})
