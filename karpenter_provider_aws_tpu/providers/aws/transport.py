"""The wire seam: one callable carries every AWS request.

Adapters build ``AwsRequest``s; a ``Transport`` turns one into an
``AwsResponse``. Production uses ``UrllibTransport`` (stdlib HTTPS);
contract tests use ``ReplayTransport`` over golden fixtures, asserting
REQUEST-SHAPE parity (action, params, headers, target) before answering —
the record/replay discipline that makes the whole adapter layer testable
with zero network (round-4 verdict missing #1).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class AwsRequest:
    method: str
    url: str
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    # metadata for signing + fixtures
    service: str = ""
    region: str = ""


@dataclass
class AwsResponse:
    status: int
    body: bytes
    headers: dict[str, str] = field(default_factory=dict)


Transport = Callable[[AwsRequest], AwsResponse]


class AwsApiError(Exception):
    """A non-2xx AWS reply, with the wire error code extracted (the
    adapter-layer twin of utils.errors' taxonomy inputs).

    ``retry_after`` carries a throttle reply's Retry-After header in
    seconds when the server sent one — the retryer prefers it (clamped)
    over its own full-jitter guess."""

    def __init__(self, status: int, code: str, message: str,
                 retry_after: Optional[float] = None):
        super().__init__(f"{code} ({status}): {message}")
        self.status = status
        self.code = code
        self.message = message
        self.retry_after = retry_after


class UrllibTransport:
    """stdlib HTTPS transport; no connection pooling (the batcher already
    coalesces the hot path into few large calls)."""

    def __init__(self, timeout_s: float = 30.0):
        self.timeout_s = timeout_s

    def __call__(self, req: AwsRequest) -> AwsResponse:
        r = urllib.request.Request(
            req.url, data=req.body or None, headers=req.headers,
            method=req.method,
        )
        try:
            with urllib.request.urlopen(r, timeout=self.timeout_s) as resp:
                return AwsResponse(
                    status=resp.status, body=resp.read(),
                    headers=dict(resp.headers),
                )
        except urllib.error.HTTPError as e:  # non-2xx still has a body
            return AwsResponse(
                status=e.code, body=e.read(), headers=dict(e.headers or {}),
            )
        except (urllib.error.URLError, OSError) as e:
            # connection resets / DNS blips must enter the retry loop like
            # the SDK DefaultRetryer's connection-error class — raw
            # URLError would bypass Session._retrying entirely
            raise AwsApiError(599, "ConnectionError", str(e)) from e


def _fixture_shape(req: AwsRequest) -> dict:
    """The request facts a fixture pins. Signature/date headers are
    excluded (they vary by clock/credentials); everything behavioral —
    method, host path, query/form params, protocol target headers, JSON
    body — is included."""
    parsed = urllib.parse.urlsplit(req.url)
    shape: dict = {
        "method": req.method.upper(),
        "host": parsed.netloc,
        "path": parsed.path or "/",
        "service": req.service,
    }
    if parsed.query:
        shape["query"] = [
            list(p) for p in sorted(urllib.parse.parse_qsl(parsed.query))
        ]
    ctype = next(
        (v for k, v in req.headers.items() if k.lower() == "content-type"), ""
    )
    target = next(
        (v for k, v in req.headers.items() if k.lower() == "x-amz-target"), ""
    )
    if target:
        shape["target"] = target
    if req.body:
        if "x-www-form-urlencoded" in ctype:
            # lists, not tuples: fixtures are JSON and shapes must compare
            shape["params"] = [
                list(p) for p in sorted(
                    urllib.parse.parse_qsl(req.body.decode(), keep_blank_values=True)
                )
            ]
        elif "json" in ctype:
            shape["json"] = json.loads(req.body.decode())
        else:
            shape["body"] = req.body.decode("utf-8", "replace")
    return shape


class ReplayTransport:
    """Golden-fixture transport: each call must match the next recorded
    request SHAPE exactly, then gets the recorded response. A mismatch is
    a contract break and raises with the first differing key.

    Fixture format (JSON): [{"request": <shape>, "response":
    {"status": N, "body": "...", "headers": {...}}}, ...]
    """

    def __init__(self, exchanges: list[dict], strict_order: bool = True):
        self.exchanges = list(exchanges)
        self.strict_order = strict_order
        self.calls: list[dict] = []

    @classmethod
    def from_file(cls, path) -> "ReplayTransport":
        with open(path) as f:
            return cls(json.load(f))

    def __call__(self, req: AwsRequest) -> AwsResponse:
        shape = _fixture_shape(req)
        self.calls.append(shape)
        pool = self.exchanges if not self.strict_order else self.exchanges[:1]
        for i, ex in enumerate(pool):
            if ex["request"] == shape:
                self.exchanges.remove(ex)
                resp = ex["response"]
                return AwsResponse(
                    status=resp.get("status", 200),
                    body=resp.get("body", "").encode(),
                    headers=resp.get("headers", {}),
                )
        expected = pool[0]["request"] if pool else None
        diff = _first_diff(expected, shape) if expected else "no exchanges left"
        raise AssertionError(
            f"request does not match the recorded contract: {diff}\n"
            f"got:      {json.dumps(shape, indent=1, default=str)[:2000]}\n"
            f"expected: {json.dumps(expected, indent=1, default=str)[:2000]}"
        )

    def assert_drained(self) -> None:
        assert not self.exchanges, (
            f"{len(self.exchanges)} recorded exchanges never happened: "
            + ", ".join(
                str(e['request'].get('params', e['request'].get('target', e['request']['path'])))[:80]
                for e in self.exchanges[:4]
            )
        )


def _first_diff(expected: Optional[dict], got: dict) -> str:
    if expected is None:
        return "no recorded request"
    for k in sorted(set(expected) | set(got)):
        if expected.get(k) != got.get(k):
            return (f"field {k!r}: expected {str(expected.get(k))[:300]!r}, "
                    f"got {str(got.get(k))[:300]!r}")
    return "shapes equal?"


class RecordingTransport:
    """Wraps a live transport and captures (shape, response) exchanges —
    how fixtures are (re)generated against a real endpoint or a local fake
    server."""

    def __init__(self, inner: Transport):
        self.inner = inner
        self.exchanges: list[dict] = []

    def __call__(self, req: AwsRequest) -> AwsResponse:
        resp = self.inner(req)
        self.exchanges.append({
            "request": _fixture_shape(req),
            "response": {
                "status": resp.status,
                "body": resp.body.decode("utf-8", "replace"),
            },
        })
        return resp

    def dump(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.exchanges, f, indent=1)
            f.write("\n")
