"""Live pricing refresh client (Pricing API + spot history).

Parity: ``/root/reference/pkg/providers/pricing/pricing.go:158-296`` —
on-demand prices via the Pricing service's ``GetProducts`` (json protocol,
X-Amz-Target) with the metal / non-metal TWO-FILTER fan-out and
pagination; spot prices via EC2 ``DescribeSpotPriceHistory`` per zone.
Feeds ``catalog.pricing.PricingProvider.apply_overrides`` — the catalog
remains the single price authority, this client only refreshes it.
"""

from __future__ import annotations

import json
from typing import Optional

from .ec2 import Ec2Client
from .session import Session

TARGET = "AWSPriceListService.GetProducts"


def _od_filters(region: str, metal: bool) -> list[dict]:
    """pricing.go:160-210: Shared/Compute Instance for standard types,
    Dedicated/Compute Instance (bare metal) for metal."""
    return [
        {"Field": "regionCode", "Type": "TERM_MATCH", "Value": region},
        {"Field": "serviceCode", "Type": "TERM_MATCH", "Value": "AmazonEC2"},
        {"Field": "preInstalledSw", "Type": "TERM_MATCH", "Value": "NA"},
        {"Field": "operatingSystem", "Type": "TERM_MATCH", "Value": "Linux"},
        {"Field": "capacitystatus", "Type": "TERM_MATCH", "Value": "Used"},
        {"Field": "marketoption", "Type": "TERM_MATCH", "Value": "OnDemand"},
        {
            "Field": "tenancy", "Type": "TERM_MATCH",
            "Value": "Dedicated" if metal else "Shared",
        },
        {
            "Field": "productFamily", "Type": "TERM_MATCH",
            "Value": "Compute Instance (bare metal)" if metal else "Compute Instance",
        },
    ]


def parse_price_item(price_json: str) -> Optional[tuple[str, float]]:
    """One GetProducts PriceList entry -> (instance_type, $/hr)."""
    try:
        item = json.loads(price_json)
        itype = item["product"]["attributes"]["instanceType"]
        terms = item["terms"]["OnDemand"]
        for term in terms.values():
            for dim in term["priceDimensions"].values():
                usd = float(dim["pricePerUnit"]["USD"])
                if usd > 0:
                    return itype, usd
    except (KeyError, ValueError, TypeError):
        return None
    return None


class PricingClient:
    def __init__(self, session: Session, ec2: Optional[Ec2Client] = None):
        self.session = session
        self.ec2 = ec2 or Ec2Client(session)

    def fetch_on_demand(self, region: str) -> dict[str, float]:
        """Both GetProducts fan-outs (standard + metal), paginated."""
        prices: dict[str, float] = {}
        for metal in (False, True):
            token = None
            while True:
                payload: dict = {
                    "ServiceCode": "AmazonEC2",
                    "Filters": _od_filters(region, metal),
                    "MaxResults": 100,
                }
                if token:
                    payload["NextToken"] = token
                data = self.session.call_json("pricing", TARGET, payload)
                for pj in data.get("PriceList", []):
                    parsed = parse_price_item(pj)
                    if parsed:
                        prices[parsed[0]] = parsed[1]
                token = data.get("NextToken")
                if not token:
                    break
        return prices

    def fetch_spot(self, instance_types: Optional[list[str]] = None
                   ) -> dict[tuple[str, str], float]:
        """(instance_type, zone) -> latest $/hr from spot history
        (pricing.go:278-296; newest timestamp wins per pool)."""
        latest: dict[tuple[str, str], tuple[str, float]] = {}
        for row in self.ec2.describe_spot_price_history(instance_types):
            key = (row.get("instanceType", ""), row.get("availabilityZone", ""))
            ts = row.get("timestamp", "")
            try:
                price = float(row.get("spotPrice", ""))
            except ValueError:
                continue
            if key not in latest or ts > latest[key][0]:
                latest[key] = (ts, price)
        return {k: v[1] for k, v in latest.items()}
