"""AWS session: credential chain, STS assume-role, retryer, user-agent.

Parity target: ``/root/reference/pkg/operator/operator.go:92-106`` — the
reference builds ONE aws-sdk session carrying (1) an STS assume-role
credential provider when ``--assume-role-arn`` is set, (2) the SDK default
retryer, (3) a user-agent handler stamping the karpenter version, (4)
region discovery from IMDS when unset. This module is that session for the
stdlib client: every adapter call funnels through ``Session.call`` which
signs (SigV4), stamps the user agent, retries on the SDK's retryable
classes with exponential backoff + jitter, and refreshes assume-role
credentials before expiry.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Callable, Optional

from ... import __version__ as _pkg_version
from ...trace import span as trace_span
from .sigv4 import Credentials, SignableRequest, sign
from .transport import (
    AwsApiError,
    AwsRequest,
    AwsResponse,
    Transport,
    UrllibTransport,
)

USER_AGENT = f"karpenter-tpu/{_pkg_version} (sigv4-stdlib)"

# aws-sdk-go DefaultRetryer parity: 3 retries max, retryable on throttle /
# 5xx / clock-skew codes, full-jitter exponential backoff.
MAX_RETRIES = 3
THROTTLE_CODES = frozenset({
    "Throttling", "ThrottlingException", "ThrottledException",
    "RequestLimitExceeded", "TooManyRequestsException",
    "ProvisionedThroughputExceededException", "RequestThrottled",
    "RequestThrottledException", "EC2ThrottledException",
})
RETRYABLE_CODES = THROTTLE_CODES | frozenset({
    "InternalError", "InternalFailure", "ServiceUnavailable",
    "RequestExpired",  # clock skew: retry after re-signing with fresh date
})
# backoff cap (full-jitter upper bound AND the Retry-After clamp)
RETRY_DELAY_CAP_S = 5.0
# hard wall cap per LOGICAL call: retries + Retry-After sleeps together
# must never exceed this (a hostile header or a long throttle storm must
# not stall a reconcile for minutes). Distinct from the per-attempt clamp
# above; surfaced as retry_reason="budget" when it stops the ladder.
REQUEST_DEADLINE_DEFAULT_S = 60.0


def _request_deadline_s() -> float:
    try:
        return float(os.environ.get(
            "KARPENTER_TPU_REQUEST_DEADLINE_S", "",
        ) or REQUEST_DEADLINE_DEFAULT_S)
    except ValueError:
        return REQUEST_DEADLINE_DEFAULT_S


def _retry_reason(e: AwsApiError) -> str:
    """Which class triggered backoff — throttle vs server vs connection
    (the span tag + per-reason counter chaos runs assert on)."""
    if e.code in THROTTLE_CODES or e.status == 429:
        return "throttle"
    if e.code == "ConnectionError" or e.status == 599:
        return "connection"
    return "server"


def _now_amz() -> str:
    return time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())


class CredentialError(Exception):
    pass


def env_credentials() -> Optional[Credentials]:
    """The chain's first link (env vars), like the SDK's EnvProvider."""
    ak = os.environ.get("AWS_ACCESS_KEY_ID", "")
    sk = os.environ.get("AWS_SECRET_ACCESS_KEY", "")
    if not ak or not sk:
        return None
    return Credentials(ak, sk, os.environ.get("AWS_SESSION_TOKEN", ""))


def shared_file_credentials(path: str = "", profile: str = "") -> Optional[Credentials]:
    """~/.aws/credentials INI (SharedCredentialsProvider parity)."""
    import configparser

    path = path or os.environ.get(
        "AWS_SHARED_CREDENTIALS_FILE",
        os.path.expanduser("~/.aws/credentials"),
    )
    profile = profile or os.environ.get("AWS_PROFILE", "default")
    if not os.path.exists(path):
        return None
    cp = configparser.ConfigParser()
    cp.read(path)
    if profile not in cp:
        return None
    sec = cp[profile]
    if "aws_access_key_id" not in sec:
        return None
    return Credentials(
        sec["aws_access_key_id"],
        sec.get("aws_secret_access_key", ""),
        sec.get("aws_session_token", ""),
    )


_ENDPOINT_OVERRIDE_ENV = "AWS_ENDPOINT_URL"


def default_endpoint(service: str, region: str) -> str:
    """Regional endpoint, overridable for tests/local stacks via
    AWS_ENDPOINT_URL (all services) or AWS_ENDPOINT_URL_<SERVICE>."""
    specific = os.environ.get(f"{_ENDPOINT_OVERRIDE_ENV}_{service.upper()}")
    if specific:
        return specific
    generic = os.environ.get(_ENDPOINT_OVERRIDE_ENV)
    if generic:
        return generic
    if service == "iam":
        return "https://iam.amazonaws.com"
    # pricing has endpoints only in a few regions (pricing.go:91-101)
    if service == "pricing":
        if region.startswith("ap-"):
            return "https://api.pricing.ap-south-1.amazonaws.com"
        if region.startswith("cn-"):
            return "https://api.pricing.cn-northwest-1.amazonaws.com.cn"
        if region.startswith("eu-"):
            return "https://api.pricing.eu-central-1.amazonaws.com"
        return "https://api.pricing.us-east-1.amazonaws.com"
    return f"https://{service}.{region}.amazonaws.com"


def _parse_error(service: str, resp: AwsResponse) -> AwsApiError:
    body = resp.body.decode("utf-8", "replace")
    code, message = "UnknownError", body[:300]
    try:
        if body.lstrip().startswith("{"):
            d = json.loads(body)
            code = (d.get("__type") or d.get("code") or code).split("#")[-1]
            message = d.get("message") or d.get("Message") or message
        else:
            root = ET.fromstring(body)
            # both query-error shapes: <ErrorResponse><Error><Code> and
            # <Response><Errors><Error><Code>
            el = root.find(".//{*}Error")
            if el is None:
                el = root.find(".//Error")
            if el is not None:
                code = (el.findtext("{*}Code") or el.findtext("Code") or code)
                message = (el.findtext("{*}Message") or el.findtext("Message")
                           or message)
    except Exception:
        pass
    retry_after = None
    ra = next(
        (v for k, v in resp.headers.items() if k.lower() == "retry-after"), ""
    )
    if ra:
        try:
            retry_after = float(ra)
        except ValueError:
            pass  # HTTP-date form: rare on AWS; fall back to jitter
    return AwsApiError(resp.status, code, message, retry_after=retry_after)


class Session:
    """One signed, retried, user-agent-stamped wire path for all adapters.

    ``assume_role_arn`` mirrors --assume-role-arn: when set, base
    credentials only ever sign STS AssumeRole calls; everything else signs
    with the (auto-refreshed) assumed credentials
    (operator.go:96-100 stscreds.NewCredentials).
    """

    def __init__(
        self,
        region: str = "",
        credentials: Optional[Credentials] = None,
        transport: Optional[Transport] = None,
        assume_role_arn: str = "",
        assume_role_duration_s: int = 900,
        session_name: str = "karpenter-tpu",
        sleep: Callable[[float], None] = time.sleep,
        now_amz: Callable[[], str] = _now_amz,
        rand: Callable[[], float] = None,
        breakers=None,
    ):
        self.region = region or os.environ.get(
            "AWS_REGION", os.environ.get("AWS_DEFAULT_REGION", "")
        )
        self._base_creds = credentials or env_credentials() or shared_file_credentials()
        self.transport = transport or UrllibTransport()
        self.assume_role_arn = assume_role_arn
        self.assume_role_duration_s = assume_role_duration_s
        self.session_name = session_name
        self._assumed: Optional[Credentials] = None
        # serializes the assume-role refresh: the interruption worker
        # fan-out calls credentials() concurrently, and N threads seeing
        # the same expiry must produce ONE STS AssumeRole, not N
        self._creds_lock = threading.Lock()
        self._sleep = sleep
        self._now_amz = now_amz
        import random

        self._rand = rand or random.random
        # per-service circuit breakers (aws.ec2, aws.sqs, ...): a service
        # whose logical calls fail repeatedly — ladders exhausted — is
        # refused instantly until its recovery window passes, instead of
        # paying the full retry ladder on every reconcile. Private
        # registry by default (each Session owns its failure memory); the
        # operator and the chaos harness pass the process registry so
        # breaker state shows on /debug/health and under the FakeClock.
        if breakers is None:
            from ...resilience.breaker import BreakerRegistry

            breakers = BreakerRegistry()
        self._breakers = breakers

    # -- credentials -------------------------------------------------------

    @staticmethod
    def _expiring(creds: Optional[Credentials]) -> bool:
        return creds is None or (
            creds.expiration and creds.expiration - time.time() < 60
        )

    def credentials(self) -> Credentials:
        if not self.assume_role_arn:
            if self._base_creds is None:
                raise CredentialError(
                    "no AWS credentials: set AWS_ACCESS_KEY_ID/"
                    "AWS_SECRET_ACCESS_KEY or a shared credentials file"
                )
            return self._base_creds
        # double-checked under the lock: concurrent expiry (the
        # interruption worker fan-out) must trigger exactly one STS
        # AssumeRole — parallel refreshes hammer STS and can interleave a
        # stale grab of a half-written credential
        if self._expiring(self._assumed):
            with self._creds_lock:
                if self._expiring(self._assumed):
                    self._assumed = self._assume_role()
        return self._assumed

    def _assume_role(self) -> Credentials:
        if self._base_creds is None:
            raise CredentialError("assume-role requires base credentials")
        params = {
            "Action": "AssumeRole",
            "Version": "2011-06-15",
            "RoleArn": self.assume_role_arn,
            "RoleSessionName": self.session_name,
            "DurationSeconds": str(self.assume_role_duration_s),
        }
        resp = self._do(
            "sts", f"https://sts.{self.region}.amazonaws.com",
            params=params, creds=self._base_creds,
        )
        root = ET.fromstring(resp.body)
        ns = {"sts": "https://sts.amazonaws.com/doc/2011-06-15/"}
        cred = root.find(".//sts:Credentials", ns)
        if cred is None:  # namespace-agnostic fallback
            cred = root.find(".//{*}Credentials")
        if cred is None:
            raise CredentialError("AssumeRole reply had no Credentials")

        def _txt(tag: str) -> str:
            return (cred.findtext(f"sts:{tag}", namespaces=ns)
                    or cred.findtext(f"{{*}}{tag}") or "")

        exp = _txt("Expiration")
        exp_unix = 0.0
        if exp:
            import calendar

            exp_unix = calendar.timegm(
                time.strptime(exp.split(".")[0].rstrip("Z"), "%Y-%m-%dT%H:%M:%S")
            )
        return Credentials(
            _txt("AccessKeyId"), _txt("SecretAccessKey"), _txt("SessionToken"),
            expiration=exp_unix,
        )

    # -- the wire ----------------------------------------------------------

    def call_query(self, service: str, params: dict[str, str],
                   endpoint: str = "") -> ET.Element:
        """AWS query-protocol call (EC2/IAM/STS/SQS): form-encoded action
        params, XML reply parsed to the root element."""
        resp = self._retrying(
            service, endpoint or default_endpoint(service, self.region),
            params=params,
        )
        return ET.fromstring(resp.body)

    def call_json(self, service: str, target: str, payload: dict,
                  endpoint: str = "") -> dict:
        """AWS json-protocol call (Pricing): X-Amz-Target + JSON body."""
        resp = self._retrying(
            service, endpoint or default_endpoint(service, self.region),
            json_target=target, payload=payload,
        )
        return json.loads(resp.body) if resp.body else {}

    def call_rest_json(self, service: str, method: str, path: str,
                       endpoint: str = "") -> dict:
        """REST-JSON call (EKS DescribeCluster)."""
        resp = self._retrying(
            service, endpoint or default_endpoint(service, self.region),
            method=method, path=path,
        )
        return json.loads(resp.body) if resp.body else {}

    @staticmethod
    def _span_action(kw: dict) -> str:
        """Human label for the request span: the query Action, the json
        X-Amz-Target, or the REST path."""
        params = kw.get("params")
        if params and params.get("Action"):
            return params["Action"]
        if kw.get("json_target"):
            return kw["json_target"]
        return kw.get("path") or "/"

    def _retrying(self, service: str, endpoint: str, **kw) -> AwsResponse:
        """DefaultRetryer parity: MAX_RETRIES with full-jitter exponential
        backoff on retryable codes and 5xx. The whole call (retries and
        backoff sleeps included) is one flight-recorder span carrying the
        retry count — so a reconcile stall traces straight to the throttled
        AWS action, and /metrics gets per-service latency + retry totals.

        Two resilience bounds on top of the SDK ladder:
        - a hard deadline per logical call (KARPENTER_TPU_REQUEST_DEADLINE_S,
          default 60 s) on the SUM of backoff sleeps — a hostile Retry-After
          stream cannot stall the caller indefinitely — plus the ambient
          per-reconcile budget when one is in scope; both stop the ladder
          with retry_reason="budget";
        - a per-service circuit breaker: after consecutive exhausted
          ladders the service is refused instantly (AwsApiError 503
          CircuitOpen) until its recovery window passes. Definitive 4xx
          answers (EntityAlreadyExists, NotFound, ...) are the service
          WORKING and count as breaker successes — idempotent callers
          use them as normal control flow.
        """
        breaker = self._breakers.get(f"aws.{service}")
        if not breaker.allow():
            raise AwsApiError(
                503, "CircuitOpen",
                f"circuit breaker aws.{service} is open "
                f"({breaker.last_error or 'recent failures'})",
            )
        # prime the credential chain BEFORE the span: an assume-role
        # refresh is a full STS round trip and must not be attributed to
        # the wrapped service's latency histogram (nor report its
        # CredentialError as this service's span error)
        try:
            self.credentials()
        except Exception:
            # a credential failure is not the wrapped service's fault —
            # hand back the (possibly half-open) probe without a verdict
            breaker.release()
            raise
        with trace_span(f"aws.{service}", action=self._span_action(kw)) as sp:
            try:
                return self._ladder(
                    service, endpoint, kw, sp, breaker,
                    deadline=_request_deadline_s(),
                )
            except AwsApiError:
                raise  # the ladder already gave the breaker its verdict
            except BaseException:
                # anything else (CredentialError mid-ladder, transport
                # bugs) is not the wrapped service's fault: hand back a
                # possibly-held half-open probe so the breaker can't
                # wedge with _probe_inflight stuck True
                breaker.release()
                raise

    def _ladder(self, service, endpoint, kw, sp, breaker, deadline):
        from ...metrics import AWS_REQUEST_RETRY_REASONS
        from ...resilience import budget as _budget

        slept = 0.0
        attempt = 0
        while True:
            try:
                resp = self._do(
                    service, endpoint, creds=self.credentials(), **kw
                )
                sp.set(retries=attempt, status=resp.status)
                breaker.record_success()
                return resp
            except AwsApiError as e:
                retryable = e.code in RETRYABLE_CODES or e.status >= 500
                if not retryable:
                    # a definitive 4xx means the service ANSWERED —
                    # idempotent callers treat codes like
                    # EntityAlreadyExists / NotFound as normal control
                    # flow, so this must never count against the
                    # breaker (it closes a half-open probe instead)
                    sp.set(retries=attempt, error_code=e.code)
                    breaker.record_success()
                    raise
                if attempt >= MAX_RETRIES:
                    sp.set(retries=attempt, error_code=e.code)
                    breaker.record_failure(e)
                    raise
                reason = _retry_reason(e)
                if e.retry_after is not None and e.retry_after > 0:
                    # the server said when to come back; honor it
                    # (clamped to the backoff cap — a hostile header
                    # must not stall a reconcile for minutes)
                    delay = min(RETRY_DELAY_CAP_S, e.retry_after)
                else:
                    # full-jitter: U(0, min(cap, base * 2^attempt));
                    # SDK base 30ms scale for throttles
                    delay = self._rand() * min(
                        RETRY_DELAY_CAP_S, 0.03 * (2 ** attempt) * 10
                    )
                # deadline check BEFORE sleeping: the remaining wall
                # is the per-call cap minus sleeps already taken,
                # further shrunk by the ambient reconcile budget
                remaining = deadline - slept
                ambient = _budget.remaining()
                if ambient is not None:
                    remaining = min(remaining, ambient)
                if delay >= remaining:
                    sp.set(retries=attempt, retry_reason="budget",
                           error_code=e.code)
                    AWS_REQUEST_RETRY_REASONS.inc(
                        service=service, reason="budget"
                    )
                    breaker.record_failure(e)
                    raise
                sp.set(retry_reason=reason)
                AWS_REQUEST_RETRY_REASONS.inc(
                    service=service, reason=reason
                )
                self._sleep(delay)
                slept += delay
                _budget.charge(delay)
                attempt += 1

    @staticmethod
    def _signing_region(service: str, endpoint: str, default: str) -> str:
        """The region a request must be SIGNED for is the ENDPOINT's, not
        the session's: IAM is global (us-east-1 scope only) and Pricing
        lives in a few fixed regions — signing those with the session
        region fails auth everywhere else (advisor round-5)."""
        if service == "iam":
            return "us-east-1"
        host = urllib.parse.urlsplit(endpoint).netloc
        # api.pricing.<region>.amazonaws.com / <svc>.<region>.amazonaws.com
        parts = host.split(".")
        for i, p in enumerate(parts):
            if p == "amazonaws" and i >= 1:
                cand = parts[i - 1]
                if "-" in cand and not cand.startswith("pricing"):
                    return cand
        return default or "us-east-1"

    def _do(self, service: str, endpoint: str, params: Optional[dict] = None,
            json_target: str = "", payload: Optional[dict] = None,
            method: str = "POST", path: str = "",
            creds: Optional[Credentials] = None) -> AwsResponse:
        url = endpoint.rstrip("/") + (path or "/")
        headers = {"user-agent": USER_AGENT}
        body = b""
        if params is not None:
            body = urllib.parse.urlencode(sorted(params.items())).encode()
            headers["content-type"] = "application/x-www-form-urlencoded; charset=utf-8"
        elif json_target:
            body = json.dumps(payload or {}).encode()
            headers["content-type"] = "application/x-amz-json-1.1"
            headers["x-amz-target"] = json_target
        sreq = SignableRequest(method=method, url=url, headers=headers, body=body)
        sign(sreq, creds, service,
             self._signing_region(service, endpoint, self.region),
             self._now_amz())
        resp = self.transport(AwsRequest(
            method=method, url=url, headers=sreq.headers, body=body,
            service=service, region=self.region,
        ))
        if resp.status >= 300:
            raise _parse_error(service, resp)
        return resp
