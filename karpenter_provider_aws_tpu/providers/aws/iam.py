"""IAM instance-profile client.

Parity: ``/root/reference/pkg/providers/instanceprofile/instanceprofile.go:60-105``
— idempotent create (EntityAlreadyExists tolerated), role attach, and the
remove-role-then-delete teardown ordering."""

from __future__ import annotations

from .session import Session
from .transport import AwsApiError

API_VERSION = "2010-05-08"


class IamClient:
    def __init__(self, session: Session):
        self.session = session

    def _call(self, action: str, params: dict) -> None:
        q = {"Action": action, "Version": API_VERSION}
        q.update(params)
        self.session.call_query("iam", q)

    def create_instance_profile(self, name: str, role: str,
                                tags: dict[str, str]) -> None:
        params: dict = {"InstanceProfileName": name}
        for i, (k, v) in enumerate(sorted(tags.items()), 1):
            params[f"Tags.member.{i}.Key"] = k
            params[f"Tags.member.{i}.Value"] = v
        try:
            self._call("CreateInstanceProfile", params)
        except AwsApiError as e:
            if e.code != "EntityAlreadyExists":
                raise
        try:
            self._call("AddRoleToInstanceProfile", {
                "InstanceProfileName": name, "RoleName": role,
            })
        except AwsApiError as e:
            if e.code != "LimitExceeded":  # role already attached
                raise

    def delete_instance_profile(self, name: str, role: str = "") -> None:
        if role:
            try:
                self._call("RemoveRoleFromInstanceProfile", {
                    "InstanceProfileName": name, "RoleName": role,
                })
            except AwsApiError as e:
                if e.code != "NoSuchEntity":
                    raise
        try:
            self._call("DeleteInstanceProfile", {"InstanceProfileName": name})
        except AwsApiError as e:
            if e.code != "NoSuchEntity":  # idempotent delete
                raise
