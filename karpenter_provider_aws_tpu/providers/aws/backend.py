"""``AwsCloudBackend``: the production implementation of the
``CloudBackend`` Protocol over the signed stdlib clients.

This is the layer round-4's verdict called the biggest structural absence:
every Protocol seam previously had only the in-memory fake behind it. The
adapter translates the framework's model objects (``fake.cloud``'s
dataclasses double as the neutral model types) to/from AWS wire shapes,
call-for-call with the reference's L4:

 - create_fleet      -> EC2 CreateFleet type=instant, same-config requests
                        merged into one call with TotalTargetCapacity=N and
                        results scattered back positionally
                        (createfleet.go:52-110); per-pool ICE errors map to
                        ``InsufficientCapacityError`` so the unavailable-
                        offerings cache works unchanged (instance.go:362-368)
 - describe/terminate/tag instances, subnets, SGs, images, AZs,
   capacity reservations, launch templates, instance profile — each the
   same-named reference provider's wire call
 - describe_cluster  -> EKS DescribeCluster (operator.go:214-245)
 - leases            -> delegated: AWS has no native lease host; the
   deployment's lease lives in kube (the reference rides the
   controller-runtime Lease the same way). Single-process default is a
   local lease so a standalone operator still runs.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ...cloudprovider.backend import LaunchRequest
from ...fake.cloud import (
    CapacityReservation,
    Image,
    Instance,
    SecurityGroup,
    Subnet,
)
from ...utils.errors import InsufficientCapacityError, NotFoundError
from .ec2 import Ec2Client, _as_list
from .eks import EksClient
from .iam import IamClient
from .session import Session
from .transport import AwsApiError

# EC2 unfulfillable-capacity codes (errors.go:44-52)
ICE_CODES = frozenset({
    "InsufficientInstanceCapacity", "InsufficientHostCapacity",
    "InsufficientReservedInstanceCapacity", "InsufficientFreeAddressesInSubnet",
    "InsufficientCapacityOnOutpost", "MaxSpotInstanceCountExceeded",
    "SpotMaxPriceTooLow", "UnfulfillableCapacity", "Unsupported",
})


def _tags(wire) -> dict[str, str]:
    return {
        t.get("key", t.get("Key", "")): t.get("value", t.get("Value", ""))
        for t in _as_list(wire)
    }


class _LocalLease:
    """Single-process lease host (standalone operator); multi-replica
    deployments pass a kube-backed delegate instead. Hosts the fenced
    per-name variant too (operator/sharding.py) with the same semantics
    as the fake/control-plane store: tokens bump per holder change."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (holder, expiry, nonce); "" name = the legacy singleton
        self._leases: dict[str, tuple[str, float, str]] = {}
        self._tokens: dict[str, int] = {}

    def try_acquire(self, name: str, holder: str, ttl_s: float) -> str:
        return self.try_acquire_fenced(name, holder, ttl_s)[0]

    def try_acquire_fenced(self, name: str, holder: str, ttl_s: float,
                           nonce: str = "") -> tuple[str, int, str]:
        with self._lock:
            now = time.monotonic()
            lease = self._leases.get(name)
            if lease is None or now >= lease[1] or (
                lease[0] == holder and lease[2] == nonce
            ):
                if lease is None or lease[0] != holder or lease[2] != nonce \
                        or now >= lease[1]:
                    self._tokens[name] = self._tokens.get(name, 0) + 1
                self._leases[name] = (holder, now + ttl_s, nonce)
                return holder, self._tokens[name], nonce
            return lease[0], self._tokens.get(name, 0), lease[2]

    def release(self, name: str, holder: str) -> None:
        with self._lock:
            lease = self._leases.get(name)
            if lease is not None and lease[0] == holder:
                del self._leases[name]

    def list(self, prefix: str = "") -> dict[str, tuple[str, float, str]]:
        with self._lock:
            now = time.monotonic()
            return {
                name: lease for name, lease in self._leases.items()
                if name.startswith(prefix) and now < lease[1]
            }


class AwsCloudBackend:
    def __init__(self, session: Session, cluster_name: str,
                 lease_host=None):
        self.session = session
        self.cluster_name = cluster_name
        self.ec2 = Ec2Client(session)
        self.iam = IamClient(session)
        self.eks = EksClient(session)
        self._lease = lease_host or _LocalLease()
        # instance-profile -> role memory for the teardown ordering
        self._profile_roles: dict[str, str] = {}

    # -- capacity ----------------------------------------------------------

    def create_fleet(self, requests: list[LaunchRequest]) -> list:
        """Batch-merge identical-config requests (createfleet.go:52-110):
        one CreateFleet with TotalTargetCapacity=N per distinct config,
        instances + errors scattered back positionally."""
        results: list = [None] * len(requests)
        by_cfg: dict[tuple, list[int]] = {}
        for i, req in enumerate(requests):
            key = (
                req.launch_template_name, tuple(req.instance_type_options),
                tuple(req.offering_options), req.image_id,
                tuple(sorted(req.subnet_by_zone.items())),
                tuple(sorted(req.tags.items())), req.context,
            )
            by_cfg.setdefault(key, []).append(i)
        for key, idxs in by_cfg.items():
            req = requests[idxs[0]]
            out = self._fleet_once(req, len(idxs))
            for slot, res in zip(idxs, out):
                results[slot] = res
        return results

    def _fleet_once(self, req: LaunchRequest, capacity: int) -> list:
        captype = req.offering_options[0][1] if req.offering_options else "on-demand"
        overrides = []
        for prio, itype in enumerate(req.instance_type_options):
            for zone, ct in req.offering_options:
                if ct != captype:
                    continue
                ov: dict = {"InstanceType": itype, "Priority": prio}
                subnet = req.subnet_by_zone.get(zone)
                if subnet:
                    ov["SubnetId"] = subnet
                else:
                    ov["AvailabilityZone"] = zone
                overrides.append(ov)
        cfg: dict = {"Overrides": overrides}
        if req.launch_template_name:
            cfg["LaunchTemplateSpecification"] = {
                "LaunchTemplateName": req.launch_template_name,
                "Version": "$Latest",
            }
        wire_captype = "spot" if captype == "spot" else "on-demand"
        try:
            data = self.ec2.create_fleet(
                launch_template_configs=[cfg],
                target_capacity=capacity,
                capacity_type=wire_captype,
                tags=req.tags,
                context=req.context,
            )
        except AwsApiError as e:
            if "LaunchTemplateName" in e.code:
                return [NotFoundError(e.message, code=e.code)] * capacity
            raise
        launched: list = []
        for fleet_inst in _as_list(data.get("fleetInstanceSet")):
            itype = fleet_inst.get("instanceType", "")
            zone = (fleet_inst.get("launchTemplateAndOverrides", {})
                    .get("overrides", {}).get("availabilityZone", ""))
            for iid in _as_list(fleet_inst.get("instanceIds")):
                launched.append(Instance(
                    id=iid if isinstance(iid, str) else iid.get("instanceId", ""),
                    instance_type=itype,
                    zone=zone,
                    capacity_type=captype,
                    image_id=req.image_id,
                    subnet_id=req.subnet_by_zone.get(zone, ""),
                    security_group_ids=req.security_group_ids,
                    launch_time=time.time(),
                    tags=dict(req.tags),
                ))
        # per-pool errors: ICE codes -> InsufficientCapacityError for the
        # unfulfilled remainder (instance.go:362-368 feeds these to the
        # unavailable-offerings cache)
        errors = _as_list(data.get("errorSet"))
        while len(launched) < capacity and errors:
            err = errors[len(launched) % len(errors)]
            code = err.get("errorCode", "")
            ov = (err.get("launchTemplateAndOverrides", {}) or {}).get("overrides", {})
            if code in ICE_CODES:
                launched.append(InsufficientCapacityError(
                    instance_type=ov.get("instanceType", ""),
                    zone=ov.get("availabilityZone", ""),
                    capacity_type=captype,
                ))
            else:
                launched.append(NotFoundError(
                    err.get("errorMessage", code), code=code,
                ))
        while len(launched) < capacity:
            launched.append(InsufficientCapacityError(
                message="fleet returned fewer instances than requested"
            ))
        return launched[:capacity]

    def _wire_instance(self, w: dict) -> Instance:
        return Instance(
            id=w.get("instanceId", ""),
            instance_type=w.get("instanceType", ""),
            zone=w.get("placement", {}).get("availabilityZone", ""),
            capacity_type=(
                "spot" if w.get("instanceLifecycle") == "spot"
                else ("reserved" if w.get("capacityReservationId") else "on-demand")
            ),
            image_id=w.get("imageId", ""),
            subnet_id=w.get("subnetId", ""),
            state=w.get("instanceState", {}).get("name", "running"),
            private_ip=w.get("privateIpAddress", ""),
            launch_time=_parse_time(w.get("launchTime", "")),
            tags=_tags(w.get("tagSet")),
            capacity_reservation_id=w.get("capacityReservationId", ""),
        )

    def describe_instances(self, ids: list[str]) -> list[Instance]:
        if not ids:
            return []
        return [self._wire_instance(w) for w in self.ec2.describe_instances(ids)]

    def list_instances(self, tag_filters: Optional[dict[str, str]] = None) -> list[Instance]:
        filters = dict(tag_filters or {})
        filters.setdefault(f"kubernetes.io/cluster/{self.cluster_name}", "owned")
        return [
            self._wire_instance(w)
            for w in self.ec2.list_instances_by_tags(filters)
        ]

    def terminate_instances(self, ids: list[str]) -> list:
        if not ids:
            return []
        return self.ec2.terminate_instances(ids)

    def get_instance(self, instance_id: str) -> Instance:
        found = self.describe_instances([instance_id])
        if not found:
            raise NotFoundError(f"instance {instance_id} not found")
        return found[0]

    def tag_instance(self, instance_id: str, tags: dict[str, str]) -> None:
        self.ec2.create_tags([instance_id], tags)

    # -- coordination ------------------------------------------------------

    def try_acquire_lease(self, name: str, holder: str, ttl_s: float) -> str:
        return self._lease.try_acquire(name, holder, ttl_s)

    def try_acquire_lease_fenced(self, name: str, holder: str, ttl_s: float,
                                 nonce: str = "") -> tuple[str, int, str]:
        return self._lease.try_acquire_fenced(name, holder, ttl_s, nonce=nonce)

    def list_leases(self, prefix: str = "") -> dict:
        return self._lease.list(prefix)

    def release_lease(self, name: str, holder: str) -> None:
        self._lease.release(name, holder)

    # -- networking / discovery -------------------------------------------

    def describe_availability_zones(self) -> dict[str, str]:
        return {
            z.get("zoneName", ""): z.get("zoneType", "availability-zone")
            for z in self.ec2.describe_availability_zones()
        }

    def describe_cluster(self) -> dict:
        c = self.eks.describe_cluster(self.cluster_name)
        kubernetes = c.get("kubernetesNetworkConfig", {}) or {}
        return {
            "endpoint": c.get("endpoint", ""),
            "version": c.get("version", ""),
            "ca_bundle": (c.get("certificateAuthority") or {}).get("data", ""),
            "service_ipv4_cidr": kubernetes.get("serviceIpv4Cidr", ""),
            "service_ipv6_cidr": kubernetes.get("serviceIpv6Cidr", ""),
        }

    def describe_subnets(self) -> list[Subnet]:
        return [
            Subnet(
                id=w.get("subnetId", ""),
                zone=w.get("availabilityZone", ""),
                available_ips=int(w.get("availableIpAddressCount", 0) or 0),
                tags=_tags(w.get("tagSet")),
                public=(w.get("mapPublicIpOnLaunch") == "true"),
                ipv6_native=(w.get("ipv6Native") == "true"),
            )
            for w in self.ec2.describe_subnets()
        ]

    def describe_security_groups(self) -> list[SecurityGroup]:
        return [
            SecurityGroup(
                id=w.get("groupId", ""),
                name=w.get("groupName", ""),
                tags=_tags(w.get("tagSet")),
            )
            for w in self.ec2.describe_security_groups()
        ]

    def describe_capacity_reservations(self) -> list[CapacityReservation]:
        return [
            CapacityReservation(
                id=w.get("capacityReservationId", ""),
                instance_type=w.get("instanceType", ""),
                zone=w.get("availabilityZone", ""),
                count=int(w.get("totalInstanceCount", 0) or 0),
                used=(int(w.get("totalInstanceCount", 0) or 0)
                      - int(w.get("availableInstanceCount", 0) or 0)),
                tags=_tags(w.get("tagSet")),
            )
            for w in self.ec2.describe_capacity_reservations()
            if w.get("state") == "active"
        ]

    def describe_images(self, selector_terms=None) -> list[Image]:
        """Scoped image discovery (ami.go:176-199 parity): each selector
        term becomes ITS OWN DescribeImages call with the term pushed into
        the wire — ids as ImageId, name as a name filter, tags as tag
        filters, owner as the Owner param — instead of one unscoped
        describe of every AMI the account can see (tens of thousands of
        public images, paged). Results are unioned by image id; the host-
        side ``term.matches`` filter in ImageProvider stays the
        enforcement point. No terms = the old account-wide discovery (the
        family-alias path needs the full set)."""
        base = [{"Name": "state", "Value": ["available"]}]
        calls: list[tuple] = []  # (filters, image_ids, owners)
        for t in (selector_terms or ()):
            if getattr(t, "id", ""):
                # explicit id: resolve exactly it (no state filter — a
                # pinned AMI is the operator's call, like the reference)
                calls.append((None, [t.id], None))
                continue
            fl = list(base)
            if getattr(t, "name", ""):
                fl.append({"Name": "name", "Value": [t.name]})
            for k, v in getattr(t, "tags", ()):
                if v == "*":
                    fl.append({"Name": "tag-key", "Value": [k]})
                else:
                    fl.append({"Name": f"tag:{k}", "Value": [v]})
            owner = getattr(t, "owner", "")
            calls.append((fl, None, [owner] if owner else None))
        if not calls:
            calls.append((base, None, None))
        by_id: dict[str, Image] = {}
        for fl, ids, owners in calls:
            for w in self.ec2.describe_images(
                filters=fl, image_ids=ids, owners=owners
            ):
                img = Image(
                    id=w.get("imageId", ""),
                    name=w.get("name", ""),
                    arch="arm64" if w.get("architecture") == "arm64" else "amd64",
                    created_seq=int(_parse_time(w.get("creationDate", ""))),
                    deprecated=bool(w.get("deprecationTime", "")
                                    and w["deprecationTime"] < _iso_now()),
                    tags=_tags(w.get("tagSet")),
                )
                by_id[img.id] = img
        return list(by_id.values())

    # -- launch templates --------------------------------------------------

    def create_launch_template(self, name: str, image_id: str, user_data: str = "",
                               **kwargs) -> None:
        import base64

        data: dict = {"ImageId": image_id}
        if user_data:
            data["UserData"] = base64.b64encode(user_data.encode()).decode()
        sgs = kwargs.get("security_group_ids") or ()
        if sgs:
            data["SecurityGroupId"] = list(sgs)
        profile = kwargs.get("instance_profile", "")
        if profile:
            data["IamInstanceProfile"] = {"Name": profile}
        if kwargs.get("detailed_monitoring"):
            data["Monitoring"] = {"Enabled": True}
        mo = kwargs.get("metadata_options")
        if mo is not None:
            data["MetadataOptions"] = {
                "HttpEndpoint": getattr(mo, "http_endpoint", "enabled"),
                "HttpTokens": getattr(mo, "http_tokens", "required"),
                "HttpPutResponseHopLimit": getattr(
                    mo, "http_put_response_hop_limit", 2),
            }
        self.ec2.create_launch_template(
            name, data, tags=kwargs.get("tags") or {},
        )

    def describe_launch_templates(self) -> list:
        return [
            type("LT", (), {"name": w.get("launchTemplateName", "")})()
            for w in self.ec2.describe_launch_templates()
        ]

    def delete_launch_template(self, name: str) -> None:
        try:
            self.ec2.delete_launch_template(name)
        except AwsApiError as e:
            if "NotFound" not in e.code:
                raise

    # -- identity ----------------------------------------------------------

    def create_instance_profile(self, name: str, role: str, tags: dict[str, str]) -> None:
        self.iam.create_instance_profile(name, role, tags)
        self._profile_roles[name] = role

    def delete_instance_profile(self, name: str) -> None:
        self.iam.delete_instance_profile(name, self._profile_roles.pop(name, ""))


def _parse_time(iso: str) -> float:
    if not iso:
        return 0.0
    import calendar

    try:
        return float(calendar.timegm(
            time.strptime(iso.split(".")[0].rstrip("Z"), "%Y-%m-%dT%H:%M:%S")
        ))
    except ValueError:
        return 0.0


def _iso_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
