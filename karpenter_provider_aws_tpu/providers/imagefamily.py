"""Image-family strategies: per-family defaults + feature flags.

Parity: ``/root/reference/pkg/providers/amifamily/resolver.go:80-112`` — the
``AMIFamily`` interface gives every family (al2/al2023/bottlerocket/ubuntu/
windows/custom) its own DefaultAMIs queries, default block-device mappings,
default metadata options, ephemeral device name, bootstrap generator, and
``FeatureFlags``. This module is that strategy layer for this framework's
families; ``providers.bootstrap`` keeps the per-family userdata generators
and ``operator.webhooks`` consults the registry + flags for admission.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..models.nodeclass import BlockDevice, KubeletConfiguration, MetadataOptions
from .bootstrap import (
    CustomBootstrap,
    NodeadmBootstrap,
    PowershellBootstrap,
    ShellBootstrap,
    TomlBootstrap,
)


@dataclass(frozen=True)
class FeatureFlags:
    """What a family's node agent supports (resolver.go:94-112)."""

    uses_eni_limited_memory_overhead: bool = True
    pods_per_core_enabled: bool = True
    eviction_soft_enabled: bool = True
    supports_eni_limited_pod_density: bool = True


@dataclass(frozen=True)
class DefaultImageQuery:
    """One default-image lookup (the SSM-parameter-alias analogue,
    ami.go:127-165): an alias plus the hardware it serves."""

    alias: str
    arch: str = "amd64"
    gpu: bool = False


class ImageFamily:
    """Base strategy: shell bootstrap, gp3 root volume, IMDSv2 defaults,
    all features on (the reference's DefaultFamily + AL2 shape)."""

    name = "standard"
    bootstrap_cls = ShellBootstrap
    ephemeral_device = "/dev/xvda"

    def default_images(self, k8s_version: str = "") -> list[DefaultImageQuery]:
        return [
            DefaultImageQuery(alias=self.name, arch="amd64"),
            DefaultImageQuery(alias=self.name, arch="arm64"),
            DefaultImageQuery(alias=self.name, arch="amd64", gpu=True),
        ]

    def default_block_device_mappings(self) -> list[BlockDevice]:
        return [BlockDevice(device_name=self.ephemeral_device,
                            volume_size_gib=20, volume_type="gp3")]

    def default_metadata_options(self) -> MetadataOptions:
        return MetadataOptions()  # IMDSv2 required, hop limit 2

    def feature_flags(self) -> FeatureFlags:
        return FeatureFlags()

    def bootstrapper(self, cluster, kubelet: Optional[KubeletConfiguration] = None,
                     labels=None, taints=(), custom: str = "",
                     instance_store_policy: Optional[str] = None):
        # feature-flag enforcement (parity: bottlerocket.go rejecting
        # evictionSoft in UserData): a kubelet knob the family's agent
        # cannot honor fails loudly at resolve time, not silently on-node
        flags = self.feature_flags()
        if kubelet is not None:
            if (
                (kubelet.eviction_soft or kubelet.eviction_soft_grace_period)
                and not flags.eviction_soft_enabled
            ):
                raise ValueError(
                    f"family {self.name} does not support evictionSoft"
                )
            if kubelet.pods_per_core is not None and not flags.pods_per_core_enabled:
                raise ValueError(
                    f"family {self.name} does not support podsPerCore"
                )
        return self.bootstrap_cls(
            cluster, kubelet or KubeletConfiguration(), labels or {}, taints,
            custom, instance_store_policy=instance_store_policy,
        )


class MinimalFamily(ImageFamily):
    name = "minimal"


class GpuFamily(ImageFamily):
    name = "gpu"

    def default_images(self, k8s_version: str = "") -> list[DefaultImageQuery]:
        return [DefaultImageQuery(alias="gpu", arch="amd64", gpu=True)]


class NodeadmFamily(ImageFamily):
    """AL2023-style: YAML NodeConfig bootstrap; memory overhead is reported
    by the agent, not ENI-derived (al2023.go FeatureFlags)."""

    name = "nodeadm"
    bootstrap_cls = NodeadmBootstrap

    def feature_flags(self) -> FeatureFlags:
        return FeatureFlags(uses_eni_limited_memory_overhead=False)


class BottlerocketFamily(ImageFamily):
    """TOML settings bootstrap; separate data volume; the agent manages
    eviction/pods-per-core itself (bottlerocket.go FeatureFlags +
    DefaultBlockDeviceMappings: xvda root 4Gi + xvdb data)."""

    name = "bottlerocket"
    bootstrap_cls = TomlBootstrap
    ephemeral_device = "/dev/xvdb"

    def default_block_device_mappings(self) -> list[BlockDevice]:
        return [
            BlockDevice(device_name="/dev/xvda", volume_size_gib=4,
                        volume_type="gp3", root_volume=True),
            BlockDevice(device_name="/dev/xvdb", volume_size_gib=20,
                        volume_type="gp3"),
        ]

    def feature_flags(self) -> FeatureFlags:
        return FeatureFlags(
            pods_per_core_enabled=False,
            eviction_soft_enabled=False,
            supports_eni_limited_pod_density=True,
        )


class UbuntuFamily(ImageFamily):
    """Ubuntu-style: shell bootstrap, /dev/sda1 root (ubuntu.go)."""

    name = "ubuntu"
    ephemeral_device = "/dev/sda1"


class WindowsFamily(ImageFamily):
    """Windows-style: PowerShell bootstrap, big /dev/sda1 root, hop limit 1,
    no ENI-limited pod density (windows.go FeatureFlags +
    DefaultMetadataOptions)."""

    name = "windows"
    bootstrap_cls = PowershellBootstrap
    ephemeral_device = "/dev/sda1"

    def default_images(self, k8s_version: str = "") -> list[DefaultImageQuery]:
        return [DefaultImageQuery(alias="windows", arch="amd64")]

    def default_block_device_mappings(self) -> list[BlockDevice]:
        return [BlockDevice(device_name="/dev/sda1", volume_size_gib=50,
                            volume_type="gp3")]

    def default_metadata_options(self) -> MetadataOptions:
        return MetadataOptions(http_put_response_hop_limit=1)

    def feature_flags(self) -> FeatureFlags:
        return FeatureFlags(
            uses_eni_limited_memory_overhead=False,
            pods_per_core_enabled=True,
            eviction_soft_enabled=True,
            supports_eni_limited_pod_density=False,
        )


class CustomFamily(ImageFamily):
    """User owns everything: no default images, no default devices beyond a
    root volume, verbatim userdata (custom.go)."""

    name = "custom"
    bootstrap_cls = CustomBootstrap

    def default_images(self, k8s_version: str = "") -> list[DefaultImageQuery]:
        return []  # imageSelector terms are mandatory (validated)


FAMILIES: dict[str, ImageFamily] = {
    f.name: f
    for f in (
        ImageFamily(),
        MinimalFamily(),
        GpuFamily(),
        NodeadmFamily(),
        BottlerocketFamily(),
        UbuntuFamily(),
        WindowsFamily(),
        CustomFamily(),
    )
}


def get_family(name: str) -> ImageFamily:
    """Family alias -> strategy; unknown aliases resolve to the standard
    family (the reference's default-to-AL2 behavior)."""
    return FAMILIES.get(name, FAMILIES["standard"])
