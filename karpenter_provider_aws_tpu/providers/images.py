"""Image provider: the AMI-family system's analogue.

Parity: ``pkg/providers/amifamily`` — default images resolved by family
alias (SSM-parameter analogue, ami.go:127-165), explicit selector-term
discovery (ami.go:176-199), newest-first ordering (ami.go:67-76), and
image -> compatible-instance-type mapping by architecture/accelerator
(ami.go:79-90 + resolver.go:123-162 grouping).
"""

from __future__ import annotations

from typing import Optional

from ..catalog.instancetypes import InstanceType
from ..models.nodeclass import NodeClass
from ..utils.cache import CacheTTL, TTLCache
from ..utils.clock import Clock


class ImageProvider:
    def __init__(self, cloud, clock: Optional[Clock] = None):
        self.cloud = cloud
        self._cache = TTLCache(default_ttl=CacheTTL.DEFAULT, clock=clock)

    def list(self, nodeclass: NodeClass):
        """Resolved images for a nodeclass, newest first.

        Selector terms win over the family alias (parity: AMISelectorTerms
        override the default SSM alias lookup).
        """
        key = ("images", nodeclass.name, nodeclass.image_family, tuple(nodeclass.image_selector))
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        if nodeclass.image_selector:
            # selector terms ride into the backend so discovery is scoped
            # at the wire (AWS: per-term DescribeImages filters/ids/owners
            # + pagination) instead of describing the whole account; the
            # host-side matches() pass below stays the enforcement point
            all_images = self.cloud.describe_images(
                selector_terms=list(nodeclass.image_selector)
            )
            images = [
                i for i in all_images
                if any(term.matches(i) for term in nodeclass.image_selector)
            ]
        else:
            all_images = self.cloud.describe_images()
            # family strategy's default-image queries (the SSM-alias
            # analogue, resolver.go DefaultAMIs); custom yields none —
            # selector terms are mandatory there
            from .imagefamily import get_family

            aliases = {q.alias for q in get_family(nodeclass.image_family).default_images()}
            images = [i for i in all_images if i.family in aliases]
        images = sorted(images, key=lambda i: -i.created_seq)
        self._cache.set(key, images)
        return images

    def reset(self) -> None:
        self._cache.flush()


def resolve_image_for(images, instance_type: InstanceType):
    """Pick the newest image compatible with an instance type (arch +
    GPU requirement), or None. Mirrors MapToInstanceTypes: GPU types take a
    GPU image when the family provides one; everything else matches arch."""
    for img in images:
        if img.arch != instance_type.arch:
            continue
        needs_gpu = instance_type.gpu_count > 0
        if needs_gpu and not img.gpu and any(i.gpu for i in images):
            continue
        if img.gpu and not needs_gpu:
            continue
        return img
    return None
