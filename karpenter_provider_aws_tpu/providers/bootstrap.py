"""Bootstrap: per-image-family node userdata generation.

Parity: ``pkg/providers/amifamily/bootstrap/`` — the ``Bootstrapper``
strategy interface (bootstrap.go), kubelet args derived from a
KubeletConfiguration (bootstrap.go:36-64 kubeletExtraArgs), MIME-multipart
merge of custom userdata with the generated script (eksbootstrap.go),
TOML settings for the bottlerocket-style family (bottlerocket.go,
bottlerocketsettings.go), YAML node config for the nodeadm-style family
(nodeadm.go), and verbatim passthrough for ``custom`` (custom.go).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Mapping, Optional, Sequence

from ..models.nodeclass import KubeletConfiguration  # noqa: F401  (API-layer type)


@dataclass(frozen=True)
class ClusterInfo:
    """What a node needs to join the cluster (parity: the cluster
    name/endpoint/CA/DNS-IP resolved by operator.go:214-260)."""

    name: str
    endpoint: str = ""
    ca_bundle: str = ""
    dns_ip: str = ""
    version: str = ""
    ip_family: str = "ipv4"  # ipv4 | ipv6 (parity: ipv6 suite + KubeDNSIP discovery)
    # service CIDR, discovered from the cloud's cluster description
    # (parity: launchtemplate.go:429-450 ResolveClusterCIDR); consumed by
    # the nodeadm family's NodeConfig
    service_cidr: str = ""




def _node_labels_arg(labels: Mapping[str, str]) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def _taints_arg(taints: Sequence) -> str:
    return ",".join(
        f"{t.key}={t.value}:{t.effect}" if t.value else f"{t.key}:{t.effect}"
        for t in taints
    )


class ShellBootstrap:
    """eksbootstrap.sh-style shell script (families: standard/minimal/gpu).

    Custom userdata, when present, is merged ahead of the generated script in
    a MIME multipart document (parity: eksbootstrap.go mime merge — cloud
    init runs parts in order, user parts first)."""

    def __init__(self, cluster: ClusterInfo, kubelet: KubeletConfiguration,
                 labels: Mapping[str, str], taints: Sequence, custom: str = "",
                 instance_store_policy: Optional[str] = None):
        self.cluster = cluster
        self.kubelet = kubelet
        self.labels = labels
        self.taints = taints
        self.custom = custom
        # "RAID0" -> the bootstrap assembles instance-store disks into the
        # node filesystem (families that cannot honor it simply ignore it,
        # like the reference's bottlerocket/windows/custom bootstrappers)
        self.instance_store_policy = instance_store_policy

    def _dns_ip(self) -> str:
        """kubeletConfiguration ClusterDNS wins over the cluster-discovered
        kube-dns IP (parity: the ipv6 suite's kubeletConfig kube-dns case)."""
        if self.kubelet.cluster_dns:
            return self.kubelet.cluster_dns[0]
        return self.cluster.dns_ip

    def script(self) -> str:
        kubelet_args = list(self.kubelet.extra_args())
        if self.labels:
            kubelet_args.append(f"--node-labels={_node_labels_arg(self.labels)}")
        if self.taints:
            kubelet_args.append(f"--register-with-taints={_taints_arg(self.taints)}")
        lines = [
            "#!/bin/bash -xe",
            f"/etc/node/bootstrap.sh '{self.cluster.name}' \\",
            f"  --apiserver-endpoint '{self.cluster.endpoint}' \\",
            f"  --b64-cluster-ca '{self.cluster.ca_bundle}' \\",
        ]
        if self._dns_ip():
            lines.append(f"  --dns-cluster-ip '{self._dns_ip()}' \\")
        if self.cluster.ip_family == "ipv6":
            lines.append("  --ip-family 'ipv6' \\")
        if self.instance_store_policy == "RAID0":
            # parity: eksbootstrap.go:80-82 (--local-disks raid0)
            lines.append("  --local-disks raid0 \\")
        lines.append(f"  --kubelet-extra-args '{' '.join(kubelet_args)}'")
        generated = "\n".join(lines) + "\n"
        if not self.custom:
            return generated
        return mime_merge([self.custom, generated])


class NodeadmBootstrap(ShellBootstrap):
    """YAML NodeConfig (the AL2023/nodeadm-style family, nodeadm.go)."""

    def script(self) -> str:
        cfg = {
            "apiVersion": "node.karpenter.tpu/v1alpha1",
            "kind": "NodeConfig",
            "spec": {
                "cluster": {
                    "name": self.cluster.name,
                    "apiServerEndpoint": self.cluster.endpoint,
                    "certificateAuthority": self.cluster.ca_bundle,
                    "cidr": self.cluster.service_cidr,
                    "ipFamily": self.cluster.ip_family,
                },
                "kubelet": {
                    "flags": (
                        [f"--cluster-dns={self._dns_ip()}"]
                        if self._dns_ip() and not self.kubelet.cluster_dns
                        else []
                    )
                    + self.kubelet.extra_args()
                    + ([f"--node-labels={_node_labels_arg(self.labels)}"] if self.labels else [])
                    + ([f"--register-with-taints={_taints_arg(self.taints)}"] if self.taints else []),
                },
            },
        }
        if self.instance_store_policy == "RAID0":
            # parity: nodeadm.go:86-88 (LocalStorage.Strategy = RAID0)
            cfg["spec"]["instance"] = {"localStorage": {"strategy": "RAID0"}}
        generated = "# node.karpenter.tpu NodeConfig\n" + _yaml_dump(cfg)
        if not self.custom:
            return generated
        return mime_merge([self.custom, generated])


class TomlBootstrap(ShellBootstrap):
    """TOML settings document (the bottlerocket-style family).

    Custom userdata is parsed as TOML and deep-merged with the generated
    settings, generated keys winning (parity: bottlerocket.go merge
    semantics — karpenter-owned cluster settings are authoritative).
    Invalid custom TOML raises, surfacing at launch time."""

    def script(self) -> str:
        settings: dict = {"settings": {"kubernetes": {}}}
        k8s = settings["settings"]["kubernetes"]
        k8s["cluster-name"] = self.cluster.name
        k8s["api-server"] = self.cluster.endpoint
        if self.cluster.ca_bundle:
            k8s["cluster-certificate"] = self.cluster.ca_bundle
        if self._dns_ip():
            k8s["cluster-dns-ip"] = self._dns_ip()
        if self.kubelet.max_pods is not None:
            k8s["max-pods"] = self.kubelet.max_pods
        if self.labels:
            k8s["node-labels"] = dict(sorted(self.labels.items()))
        if self.taints:
            k8s["node-taints"] = {t.key: f"{t.value}:{t.effect}" for t in self.taints}
        if self.custom:
            import tomllib

            try:
                base = tomllib.loads(self.custom)
            except tomllib.TOMLDecodeError as e:
                raise ValueError(f"custom userdata is not valid TOML: {e}") from e
            settings = _deep_merge(base, settings)
        return _toml_dump(settings)


class PowershellBootstrap(ShellBootstrap):
    """PowerShell bootstrap (the windows-style family, windows.go): a
    <powershell> document invoking the bootstrap script with kubelet args;
    custom userdata is prepended inside the same block."""

    def script(self) -> str:
        args = []
        if self._dns_ip() and not self.kubelet.cluster_dns:
            args.append(f"--cluster-dns={self._dns_ip()}")
        args += self.kubelet.extra_args()
        if self.labels:
            args.append(f"--node-labels={_node_labels_arg(self.labels)}")
        if self.taints:
            args.append(f"--register-with-taints={_taints_arg(self.taints)}")
        lines = ["<powershell>"]
        if self.custom:
            lines.append(self.custom.rstrip("\n"))
        lines += [
            "[string]$BootstrapScript = 'C:\\Program Files\\Node\\Start-NodeBootstrap.ps1'",
            "& $BootstrapScript "
            + f"-ClusterName '{self.cluster.name}' "
            + f"-APIServerEndpoint '{self.cluster.endpoint}' "
            + (f"-Base64ClusterCA '{self.cluster.ca_bundle}' " if self.cluster.ca_bundle else "")
            + (
                "-KubeletExtraArgs '" + " ".join(args) + "' " if args else ""
            ).rstrip(),
            "</powershell>",
        ]
        return "\n".join(lines) + "\n"


class CustomBootstrap(ShellBootstrap):
    """Verbatim user data; the user owns the whole bootstrap (custom.go)."""

    def script(self) -> str:
        return self.custom


_MIME_BOUNDARY = "//KARPENTER-TPU-BOUNDARY//"


def mime_merge(parts: Sequence[str]) -> str:
    """Join userdata parts into one multipart/mixed document
    (parity: bootstrap/mime — parts execute in order)."""
    out = [
        "MIME-Version: 1.0",
        f'Content-Type: multipart/mixed; boundary="{_MIME_BOUNDARY}"',
        "",
    ]
    for part in parts:
        ctype = (
            "text/x-shellscript" if part.lstrip().startswith("#!") else "text/plain"
        )
        out += [
            f"--{_MIME_BOUNDARY}",
            f'Content-Type: {ctype}; charset="us-ascii"',
            "",
            part.rstrip("\n"),
        ]
    out.append(f"--{_MIME_BOUNDARY}--")
    return "\n".join(out) + "\n"


def bootstrapper_for(
    family: str,
    cluster: ClusterInfo,
    kubelet: Optional[KubeletConfiguration] = None,
    labels: Optional[Mapping[str, str]] = None,
    taints: Sequence = (),
    custom: str = "",
    instance_store_policy: Optional[str] = None,
) -> ShellBootstrap:
    """Family alias -> bootstrapper (parity: GetAMIFamily resolver.go:80-112).

    Thin delegate to the family strategy registry (providers.imagefamily) —
    ONE family->bootstrapper mapping exists, and every path gets the same
    feature-flag enforcement. Unknown families fall back to the standard
    (shell) family like the reference's default-to-AL2 behavior."""
    from .imagefamily import get_family  # here: imagefamily imports this module

    return get_family(family).bootstrapper(
        cluster, kubelet=kubelet, labels=labels, taints=taints, custom=custom,
        instance_store_policy=instance_store_policy,
    )


def _deep_merge(base: dict, override: dict) -> dict:
    """Recursive dict merge; override wins on scalar conflicts."""
    out = dict(base)
    for k, v in override.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _toml_key(k: str) -> str:
    return k if k.replace("-", "").replace("_", "").isalnum() else json.dumps(k)


def _toml_val(v) -> str:
    if isinstance(v, bool):
        return str(v).lower()
    if isinstance(v, str):
        return json.dumps(v)
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_toml_val(x) for x in v) + "]"
    return str(v)


def _toml_dump(obj: dict, path: tuple[str, ...] = ()) -> str:
    """Deterministic TOML emitter for the nested settings dict. A table
    header is emitted only for tables holding scalars (pure-container levels
    like [settings] are implied by their children's dotted headers)."""
    scalars = [(k, v) for k, v in obj.items() if not isinstance(v, dict)]
    tables = [(k, v) for k, v in obj.items() if isinstance(v, dict)]
    lines: list[str] = []
    if scalars and path:
        lines.append("[" + ".".join(_toml_key(p) for p in path) + "]")
    for k, v in scalars:
        lines.append(f"{_toml_key(k)} = {_toml_val(v)}")
    for k, v in tables:
        body = _toml_dump(v, path + (k,))
        if body:
            lines.append(body)
    text = "\n".join(lines)
    if text and not path:
        text += "\n"
    return text


def _yaml_dump(obj, indent: int = 0) -> str:
    """Tiny deterministic YAML emitter (avoids a yaml dependency)."""
    pad = "  " * indent
    if isinstance(obj, Mapping):
        lines = []
        for k, v in obj.items():
            if isinstance(v, (Mapping, list)) and v:
                lines.append(f"{pad}{k}:")
                lines.append(_yaml_dump(v, indent + 1))
            else:
                lines.append(f"{pad}{k}: {json.dumps(v) if isinstance(v, str) else v}")
        return "\n".join(lines)
    if isinstance(obj, list):
        return "\n".join(f"{pad}- {json.dumps(v) if isinstance(v, str) else v}" for v in obj)
    return f"{pad}{obj}"
