"""Subnet provider: discovery + zonal pick + in-flight IP accounting.

Parity: ``pkg/providers/subnet/subnet.go`` — selector-term discovery
(:75-117), per-zone choice of the subnet with the most available IPs
(:133-176), and in-flight IP pre-deduction with give-back for zones the
fleet didn't choose (:168-234).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..models.nodeclass import NodeClass
from ..utils.cache import CacheTTL, TTLCache
from ..utils.clock import Clock


class SubnetProvider:
    def __init__(self, cloud, clock: Optional[Clock] = None):
        from ..utils.clock import RealClock

        self.cloud = cloud
        self.clock = clock or RealClock()
        self._cache = TTLCache(default_ttl=CacheTTL.DEFAULT, clock=clock)
        # subnet id -> expiry timestamps of pre-deducted IPs; entries decay
        # after the inflight TTL (parity: 5m inflight-IP cache, cache.go)
        self._inflight: dict[str, list[float]] = {}
        self._lock = threading.Lock()

    def _prune(self, subnet_id: str) -> list[float]:
        now = self.clock.now()
        entries = [t for t in self._inflight.get(subnet_id, []) if t > now]
        if entries:
            self._inflight[subnet_id] = entries
        else:
            self._inflight.pop(subnet_id, None)
        return entries

    def reset(self) -> None:
        with self._lock:
            self._cache.flush()
            self._inflight.clear()

    def list(self, nodeclass: NodeClass):
        """Subnets matching the nodeclass selector terms."""
        key = ("subnets", nodeclass.name, tuple(nodeclass.subnet_selector))
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        subnets = [
            s
            for s in self.cloud.describe_subnets()
            if any(term.matches(s) for term in nodeclass.subnet_selector)
            or not nodeclass.subnet_selector
        ]
        self._cache.set(key, subnets)
        return subnets

    def zonal_subnets_for_launch(self, nodeclass: NodeClass, zones,
                                 subnets=None) -> dict[str, str]:
        """zone -> subnet id, choosing the most-available-IP subnet per zone
        and pre-deducting one IP (given back by ``release_unused``).
        ``subnets`` lets the caller pin one discovery snapshot across every
        decision of a single launch (see associate_public_ip_value)."""
        if subnets is None:
            subnets = self.list(nodeclass)
        with self._lock:
            chosen: dict[str, str] = {}
            for zone in zones:
                best = None
                best_ips = -1
                for s in subnets:
                    if s.zone != zone:
                        continue
                    effective = s.available_ips - len(self._prune(s.id))
                    if effective > best_ips:
                        best, best_ips = s, effective
                if best is not None and best_ips > 0:
                    chosen[zone] = best.id
                    self._inflight.setdefault(best.id, []).append(
                        self.clock.now() + CacheTTL.INFLIGHT_IPS
                    )
            return chosen

    def associate_public_ip_value(self, nodeclass: NodeClass,
                                  subnets=None) -> Optional[bool]:
        """Explicit ``False`` only when EVERY subnet the nodeclass resolves
        is known to not auto-assign public IPs; ``None`` (leave the cloud
        default) when any subnet is public or unknown (parity:
        subnet.go:119-130 AssociatePublicIPAddressValue). Pass the SAME
        ``subnets`` snapshot the launch selected from, or a cache expiry
        between the two reads could pin False onto a public-subnet launch."""
        if subnets is None:
            subnets = self.list(nodeclass)
        if subnets and all(getattr(s, "public", None) is False for s in subnets):
            return False
        return None

    def release_unused(self, chosen: dict[str, str], used_zone: str) -> None:
        """Give back pre-deducted IPs for the zones the launch didn't use."""
        with self._lock:
            for zone, subnet_id in chosen.items():
                if zone != used_zone:
                    entries = self._prune(subnet_id)
                    if entries:
                        entries.pop(0)

    def inflight(self, subnet_id: str) -> int:
        with self._lock:
            return len(self._prune(subnet_id))
