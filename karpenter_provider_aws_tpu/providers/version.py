"""Version provider: cluster control-plane version discovery + support gate.

Parity: ``pkg/providers/version/version.go:31-89`` — the server version is
fetched once and cached, and a supported range is enforced with a warning
outside it (the reference supports 1.23-1.29; this framework tracks its own
window).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..utils.cache import TTLCache
from ..utils.clock import Clock

log = logging.getLogger("karpenter.tpu.version")

MIN_SUPPORTED_MINOR = 23
MAX_SUPPORTED_MINOR = 33
_VERSION_TTL_S = 15 * 60  # parity: version poll period


class VersionProvider:
    def __init__(self, cluster, clock: Optional[Clock] = None):
        self.cluster = cluster
        self._cache = TTLCache(default_ttl=_VERSION_TTL_S, clock=clock)
        self._warned = False

    def get(self) -> str:
        """Cached "major.minor" of the cluster control plane."""
        hit = self._cache.get("version")
        if hit is not None:
            return hit
        version = getattr(self.cluster, "server_version", "") or "1.29"
        version = version.lstrip("v")
        self._cache.set("version", version)
        self._check_supported(version)
        return version

    def minor(self) -> int:
        try:
            return int(self.get().split(".")[1])
        except (IndexError, ValueError):
            return 0

    def supported(self) -> bool:
        return MIN_SUPPORTED_MINOR <= self.minor() <= MAX_SUPPORTED_MINOR

    def _check_supported(self, version: str) -> None:
        if not self.supported() and not self._warned:
            self._warned = True
            log.warning(
                "cluster version %s outside the supported window 1.%d-1.%d",
                version, MIN_SUPPORTED_MINOR, MAX_SUPPORTED_MINOR,
            )

    def reset(self) -> None:
        self._cache.flush()
        self._warned = False
