"""Cloud resource adapters (reference L4: ``pkg/providers/*``).

Each provider wraps the cloud backend with TTL caching and the selection
logic its reference counterpart implements: subnet zonal pick + in-flight IP
accounting, security-group discovery, image resolution (AMI-family
analogue), instance-profile lifecycle, launch-template ensure/dedupe with
per-family bootstrap userdata, and cluster-version discovery.
"""

from .subnets import SubnetProvider  # noqa: F401
from .securitygroups import SecurityGroupProvider  # noqa: F401
from .images import ImageProvider, resolve_image_for  # noqa: F401
from .instanceprofiles import InstanceProfileProvider  # noqa: F401
from .bootstrap import ClusterInfo, KubeletConfiguration, bootstrapper_for, mime_merge  # noqa: F401
from .launchtemplates import LaunchTemplateProvider, ResolvedTemplate  # noqa: F401
from .version import VersionProvider  # noqa: F401
