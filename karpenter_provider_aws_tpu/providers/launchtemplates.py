"""Launch-template provider: ensure-or-create deduped launch templates.

Parity: ``pkg/providers/launchtemplate/launchtemplate.go`` — template name
is a hash of the resolved parameters (:149-151), a TTL cache dedupes
ensure calls with hydration on startup (:100-109), templates carry block
devices, IMDS metadata options and generated userdata (:235-312), and the
nodeclass termination path deletes every managed template by tag
(termination/controller.go:87-105).
"""

from __future__ import annotations

import hashlib
import json
import logging
from dataclasses import asdict, dataclass, field
from typing import Optional, Sequence

from ..models.nodeclass import NodeClass
from ..utils.cache import CacheTTL, TTLCache
from ..utils.clock import Clock
from .bootstrap import ClusterInfo, KubeletConfiguration

log = logging.getLogger("karpenter.tpu.launchtemplates")

MANAGED_BY_TAG = "karpenter.tpu/managed-by"        # value: cluster name
NODECLASS_LT_TAG = "karpenter.tpu/nodeclass"


@dataclass(frozen=True)
class ResolvedTemplate:
    """The fully-resolved launch parameters for one image group (the
    amifamily.Resolver output analogue, resolver.go:123-162)."""

    image_id: str
    user_data: str
    instance_profile: str
    security_group_ids: tuple[str, ...] = ()
    block_devices: tuple = ()
    metadata_options: Optional[object] = None
    tags: tuple[tuple[str, str], ...] = ()
    # None = leave the subnet's default; True/False = pin it — either the
    # user's spec override (ec2nodeclass.go:45-47) or inferred False when
    # every resolved subnet is known private (subnet.go:119-130)
    associate_public_ip: Optional[bool] = None
    # CloudWatch detailed monitoring (parity: launchtemplate.go:255-257
    # Monitoring.Enabled from nodeclass.spec.detailedMonitoring)
    detailed_monitoring: bool = False

    def content_hash(self) -> str:
        blob = json.dumps(
            {
                "image": self.image_id,
                "user_data": self.user_data,
                "profile": self.instance_profile,
                "sgs": list(self.security_group_ids),
                "bdm": [asdict(b) for b in self.block_devices],
                "md": asdict(self.metadata_options) if self.metadata_options else None,
                "tags": list(self.tags),
                "public_ip": self.associate_public_ip,
                "monitoring": self.detailed_monitoring,
            },
            sort_keys=True,
        ).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def resolve_service_cidr(cloud, ip_family: str) -> str:
    """Cluster service CIDR from the backend's cluster description (parity:
    launchtemplate.go:429-450 ResolveClusterCIDR — ipv4 preferred, ipv6
    fallback, empty when the backend cannot say)."""
    describe = getattr(cloud, "describe_cluster", None)
    if describe is None:
        return ""
    try:
        info = describe() or {}
    except Exception as e:
        log.warning("cluster CIDR resolution failed (will retry): %s", e)
        return ""
    if ip_family == "ipv6":
        return info.get("service_ipv6_cidr") or info.get("service_ipv4_cidr") or ""
    return info.get("service_ipv4_cidr") or info.get("service_ipv6_cidr") or ""


class LaunchTemplateProvider:
    def __init__(self, cloud, cluster_info: ClusterInfo, clock: Optional[Clock] = None):
        from ..utils.clock import RealClock

        self.cloud = cloud
        self.cluster_info = cluster_info
        self._cache = TTLCache(default_ttl=CacheTTL.LAUNCH_TEMPLATE, clock=clock)
        self._hydrated = False
        self._clock = clock or RealClock()
        self._cidr_next_try = 0.0

    def _maybe_resolve_cidr(self) -> None:
        """Retry service-CIDR discovery until it succeeds (parity: the
        reference re-calls ResolveClusterCIDR from the launch path and
        no-ops once resolved, launchtemplate.go:429-432). Rate-limited so a
        down endpoint cannot add a describe call to every launch."""
        if self.cluster_info.service_cidr:
            return
        now = self._clock.now()
        if now < self._cidr_next_try:
            return
        self._cidr_next_try = now + 60.0
        cidr = resolve_service_cidr(self.cloud, self.cluster_info.ip_family)
        if cidr:
            # ClusterInfo is frozen; late CIDR discovery is the one sanctioned
            # mutation (the reference stores it in an atomic.Pointer for the
            # same reason, launchtemplate.go:81)
            object.__setattr__(self.cluster_info, "service_cidr", cidr)
            log.info("discovered cluster service CIDR %s", cidr)

    # -- the launch path ---------------------------------------------------
    def ensure_all(
        self,
        nodeclass: NodeClass,
        image_groups: Sequence[tuple],     # [(Image, [InstanceType, ...])]
        labels: Optional[dict] = None,
        taints: Sequence = (),
        kubelet: Optional[KubeletConfiguration] = None,
        associate_public_ip: Optional[bool] = None,
    ) -> dict[str, str]:
        """image_id -> launch template name, creating what is missing.

        One template per image group (parity: Resolver.Resolve grouping by
        (amiID, maxPods, efa); our grouping key is the image, since maxPods
        comes from the kubelet config and efa is N/A)."""
        self._hydrate_once()
        self._maybe_resolve_cidr()
        out: dict[str, str] = {}
        from .imagefamily import get_family

        family = get_family(nodeclass.image_family)
        for image, _types in image_groups:
            # The NODECLASS family picks the bootstrapper — not the image's
            # (parity: resolver.go:80-112, AMIFamily comes from the spec).
            boot = family.bootstrapper(
                self.cluster_info,
                kubelet=kubelet,
                labels=labels,
                taints=taints,
                custom=nodeclass.user_data,
                instance_store_policy=nodeclass.instance_store_policy,
            )
            resolved = ResolvedTemplate(
                image_id=image.id,
                user_data=boot.script(),
                instance_profile=nodeclass.status.instance_profile
                or nodeclass.instance_profile,
                security_group_ids=tuple(g.id for g in nodeclass.status.security_groups),
                block_devices=tuple(nodeclass.block_devices),
                metadata_options=nodeclass.metadata_options,
                tags=tuple(sorted(nodeclass.tags.items())),
                associate_public_ip=associate_public_ip,
                detailed_monitoring=nodeclass.detailed_monitoring,
            )
            out[image.id] = self._ensure_one(nodeclass, resolved)
        self._gc_stale(nodeclass, keep=set(out.values()))
        return out

    def _name(self, nodeclass: NodeClass, resolved: ResolvedTemplate) -> str:
        # The nodeclass name is part of the template name so two nodeclasses
        # with identical resolved parameters never share one template (either
        # one's termination teardown would destroy the other's).
        return f"karpenter.tpu/{self.cluster_info.name}/{nodeclass.name}/{resolved.content_hash()}"

    def _ensure_one(self, nodeclass: NodeClass, resolved: ResolvedTemplate) -> str:
        name = self._name(nodeclass, resolved)
        if self._cache.get(("lt", name)) is not None:
            return name
        existing = {t.name for t in self.cloud.describe_launch_templates()}
        if name not in existing:
            self.cloud.create_launch_template(
                name=name,
                image_id=resolved.image_id,
                user_data=resolved.user_data,
                instance_profile=resolved.instance_profile,
                security_group_ids=resolved.security_group_ids,
                block_devices=resolved.block_devices,
                metadata_options=resolved.metadata_options,
                associate_public_ip=resolved.associate_public_ip,
                detailed_monitoring=resolved.detailed_monitoring,
                tags={
                    # user tags first: the managed tags must win or hydration
                    # and termination teardown lose track of the template
                    **dict(resolved.tags),
                    MANAGED_BY_TAG: self.cluster_info.name,
                    NODECLASS_LT_TAG: nodeclass.name,
                },
            )
            log.info("created launch template %s", name)
        self._cache.set(("lt", name), True)
        return name

    def _gc_stale(self, nodeclass: NodeClass, keep: set[str]) -> None:
        """Delete superseded templates for this nodeclass (image/userdata/tag
        rotations mint a new hash name; the old one would otherwise live until
        nodeclass termination). A template still vouched for by the dedupe
        cache is kept — it may back an in-flight launch — so deletion happens
        one cache-TTL after the template stopped being resolved (parity: the
        reference deletes launch templates on cache eviction)."""
        for t in list(self.cloud.describe_launch_templates()):
            if (
                t.tags.get(MANAGED_BY_TAG) == self.cluster_info.name
                and t.tags.get(NODECLASS_LT_TAG) == nodeclass.name
                and t.name not in keep
                and self._cache.get(("lt", t.name)) is None
            ):
                self.cloud.delete_launch_template(t.name)
                log.info("garbage-collected stale launch template %s", t.name)

    # -- cache lifecycle ---------------------------------------------------
    def _hydrate_once(self) -> None:
        """Warm the dedupe cache from the cloud on first use (parity:
        hydration goroutine on leader election, launchtemplate.go:100-109)."""
        if self._hydrated:
            return
        self._hydrated = True
        for t in self.cloud.describe_launch_templates():
            if t.tags.get(MANAGED_BY_TAG) == self.cluster_info.name:
                self._cache.set(("lt", t.name), True)

    def invalidate(self, name: str) -> None:
        """Drop one template from the dedupe cache (parity: InvalidateCache
        after a launch failed with launch-template-not-found)."""
        self._cache.delete(("lt", name))

    def reset(self) -> None:
        self._cache.flush()
        self._hydrated = False

    # -- teardown ----------------------------------------------------------
    def delete_all(self, nodeclass: NodeClass) -> int:
        """Delete every managed template for a nodeclass (parity:
        nodeclass termination controller.go:87-105)."""
        n = 0
        for t in list(self.cloud.describe_launch_templates()):
            if (
                t.tags.get(MANAGED_BY_TAG) == self.cluster_info.name
                and t.tags.get(NODECLASS_LT_TAG) == nodeclass.name
            ):
                self.cloud.delete_launch_template(t.name)
                self._cache.delete(("lt", t.name))
                n += 1
        return n
