"""The interruption-queue provider seam.

Parity: ``/root/reference/pkg/providers/sqs/sqs.go:53-73`` — the reference
isolates queue I/O behind a provider interface (long-poll receive of at most
10 messages, explicit per-receipt delete, send for tests/tools) so the
interruption controller never touches the wire client. ``QueueProvider`` is
that declared seam here; ``fake.FakeQueue`` implements it in-memory, a real
adapter (SQS/PubSub/...) slots in at operator wiring without touching the
controller.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Protocol, runtime_checkable

# sqs.go:62 MaxNumberOfMessages — one poll returns at most this many.
MAX_RECEIVE = 10
# sqs.go:63 WaitTimeSeconds — the long-poll window a real adapter should use.
WAIT_TIME_S = 20


@dataclass
class QueueMessage:
    """One received message: raw body + the receipt handle that deletes it."""

    body: str
    receipt: str = ""

    def parsed(self) -> dict:
        import json

        return json.loads(self.body)


@runtime_checkable
class QueueProvider(Protocol):
    # Optional attribute (NOT a Protocol member — a data member would make
    # structural isinstance fail for adapters that omit it): providers may
    # set ``blocking_io = False`` to declare receive/delete never touch the
    # network. The interruption controller fans message handling over a
    # worker pool ONLY for blocking providers (the reference's
    # ParallelizeUntil(10) exists to overlap SQS and kube round-trips,
    # controller.go:104); for an in-memory provider the pool is pure
    # dispatch overhead on GIL-bound work and halves small-drain
    # throughput. Consumers read it via getattr(queue, "blocking_io", True).

    def send(self, body) -> None: ...

    def receive(self, max_messages: Optional[int] = None) -> list: ...

    def delete(self, receipt: str) -> None: ...
