"""The interruption-queue provider seam.

Parity: ``/root/reference/pkg/providers/sqs/sqs.go:53-73`` — the reference
isolates queue I/O behind a provider interface (long-poll receive of at most
10 messages, explicit per-receipt delete, send for tests/tools) so the
interruption controller never touches the wire client. ``QueueProvider`` is
that declared seam here; ``fake.FakeQueue`` implements it in-memory, a real
adapter (SQS/PubSub/...) slots in at operator wiring without touching the
controller.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Protocol, runtime_checkable

# sqs.go:62 MaxNumberOfMessages — one poll returns at most this many.
MAX_RECEIVE = 10
# sqs.go:63 WaitTimeSeconds — the long-poll window a real adapter should use.
WAIT_TIME_S = 20


@dataclass
class QueueMessage:
    """One received message: raw body + the receipt handle that deletes it."""

    body: str
    receipt: str = ""

    def parsed(self) -> dict:
        import json

        return json.loads(self.body)


@runtime_checkable
class QueueProvider(Protocol):
    def send(self, body) -> None: ...

    def receive(self, max_messages: Optional[int] = None) -> list: ...

    def delete(self, receipt: str) -> None: ...
