"""karpenter_provider_aws_tpu — a TPU-native node-provisioning framework.

A brand-new framework with the capabilities of Karpenter's AWS provider
(reference: gjreasoner/karpenter-provider-aws): a node-autoscaling control
plane that watches pending pods, bin-packs them onto the cheapest feasible
cloud capacity, launches/reaps instances, handles spot interruption and
insufficient-capacity feedback, and continuously consolidates the cluster.

The architecture is TPU-first, not a port:

- ``models/``     — the data model: label-requirement engine, Pod, NodePool,
                    NodeClass, NodeClaim (reference: ``pkg/apis/v1beta1``).
- ``catalog/``    — the instance-type "device catalog": capacities,
                    allocatable math, zonal spot/on-demand offerings, ICE
                    masking (reference: ``pkg/providers/instancetype``,
                    ``pkg/providers/pricing``).
- ``ops/``        — the TPU compute path: tensor encoding of the scheduling
                    problem and jitted solvers (FFD bin-packing scan,
                    consolidation simulator) built on jax.numpy/lax.
- ``scheduling/`` — the ``Solver`` plugin boundary + host-side oracle
                    (reference: the core scheduler's ``Solve()``,
                    ``designs/bin-packing.md``).
- ``parallel/``   — jax.sharding Mesh / shard_map distribution of the solve
                    across chips (pods axis data-parallel over ICI).
- ``cloudprovider/`` — the cloud plugin: NodeClaim -> instance lifecycle
                    (reference: ``pkg/cloudprovider``).
- ``controllers/``— reconcile loops: provisioning, disruption, interruption,
                    garbage collection, node-class status, tagging
                    (reference: ``pkg/controllers``).
- ``fake/``       — hermetic in-memory cloud + queue backends for tests
                    (reference: ``pkg/fake``).
- ``utils/``      — TTL caches, seqnum'd unavailable-offerings cache,
                    batcher, error taxonomy (reference: ``pkg/cache``,
                    ``pkg/batcher``, ``pkg/errors``).
"""

__version__ = "0.1.0"
