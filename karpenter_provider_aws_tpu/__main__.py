"""CLI entry point: ``python -m karpenter_provider_aws_tpu``.

Parity: ``cmd/controller/main.go`` — parse options, build the operator,
serve metrics/health, run reconcile loops until interrupted. With
``--role sidecar`` it instead runs the gRPC solver sidecar that owns the
TPU (the process split from the BASELINE north star).
"""

from __future__ import annotations

import logging
import signal
import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    if argv[:2] == ["--role", "sidecar"] or "--sidecar" in argv:
        from .runtime.sidecar import serve

        address = "127.0.0.1:50151"
        for i, a in enumerate(argv):
            if a == "--address" and i + 1 < len(argv):
                address = argv[i + 1]
        server = serve(address)
        print(f"solver sidecar on {address}", flush=True)
        server.wait()
        return 0

    from .operator import Options, new_operator

    options = Options.from_env_and_args(argv)
    op = new_operator(options)
    op.start()
    print(f"karpenter-tpu operator running (metrics port {op.metrics_port})", flush=True)
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            signal.pause()
    finally:
        op.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
