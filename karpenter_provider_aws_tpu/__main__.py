"""CLI entry point: ``python -m karpenter_provider_aws_tpu``.

Parity: ``cmd/controller/main.go`` — parse options, build the operator,
serve metrics/health, run reconcile loops until interrupted. With
``--role sidecar`` it instead runs the gRPC solver sidecar that owns the
TPU (the process split from the BASELINE north star).
"""

from __future__ import annotations

import logging
import signal
import sys
import threading


def _sidecar_requested(argv: list[str]) -> bool:
    if "--sidecar" in argv:
        return True
    for i, a in enumerate(argv):
        if a == "--role" and i + 1 < len(argv) and argv[i + 1] == "sidecar":
            return True
        if a == "--role=sidecar":
            return True
    return False


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    import os

    if os.environ.get("JAX_PLATFORMS"):
        # honor the operator's platform choice even when a site plugin
        # force-registers another platform via jax.config (which beats the
        # env var); must run before any backend initializes
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if _sidecar_requested(argv):
        from .metrics import REGISTRY
        from .runtime.sidecar import serve

        address = "127.0.0.1:50151"
        metrics_port = 8081  # distinct from the operator's 8080 default
        for i, a in enumerate(argv):
            if a == "--address" and i + 1 < len(argv):
                address = argv[i + 1]
            if a == "--metrics-port" and i + 1 < len(argv):
                metrics_port = int(argv[i + 1])
        server = serve(address)
        if metrics_port:
            # the per-method RPC histograms/error counters accumulate in
            # THIS process — without a scrape endpoint here they would be
            # write-only in the real split deployment
            port = REGISTRY.serve(metrics_port)
            print(f"sidecar metrics on 127.0.0.1:{port}/metrics", flush=True)
        print(f"solver sidecar on {address}", flush=True)
        server.wait()
        return 0

    from .operator import Options, new_operator

    options = Options.from_env_and_args(argv)
    op = new_operator(options)
    op.start()
    print(f"karpenter-tpu operator running (metrics port {op.metrics_port})", flush=True)
    # An Event closes the check-then-pause race a bare signal.pause() has:
    # a signal landing between the loop check and pause() would be lost.
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    try:
        stop.wait()
    finally:
        op.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
