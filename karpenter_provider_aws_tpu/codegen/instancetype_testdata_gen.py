"""Generates fake/zz_generated_describe_instance_types.py.

Reference parity: ``hack/code/instancetype_testdata_gen`` producing the
782-line ``pkg/fake/zz_generated.describe_instance_types.go`` fixture — a
frozen, representative slice of the catalog that hermetic suites pin against
so fixture drift is an explicit regeneration, not a silent model change.
"""

from __future__ import annotations

import pathlib

from ._emit import FAKE_DIR, write_module

# One representative per axis the reference fixture spans: generic x86/arm
# across sizes, burstable, storage, metal, GPU, neuron, EFA-heavy.
FIXTURE_NAMES = (
    "c5.large", "c5.xlarge", "c5.2xlarge", "c5.metal",
    "c6g.large", "c6g.xlarge", "c7g.16xlarge", "c7gn.8xlarge",
    "m5.large", "m5.4xlarge", "m6a.xlarge", "m7g.2xlarge",
    "r5.large", "r5.24xlarge", "r6gd.4xlarge", "x2idn.16xlarge",
    "t3.micro", "t3.medium", "t4g.small", "t4g.xlarge",
    "i3.2xlarge", "i4i.8xlarge", "d3.xlarge",
    "g4dn.xlarge", "g5.12xlarge", "g5g.xlarge", "p4d.24xlarge", "p5.48xlarge",
    "inf1.6xlarge", "inf2.24xlarge", "trn1.32xlarge",
    "hpc7g.16xlarge",
)

_FIELDS = (
    "name", "category", "family", "generation", "size", "arch", "os",
    "vcpus", "memory_mib", "network_bandwidth_mbps", "ebs_bandwidth_mbps",
    "max_enis", "ips_per_eni", "branch_enis", "local_nvme_gib",
    "gpu_manufacturer", "gpu_name", "gpu_count", "gpu_memory_mib",
    "accelerator_manufacturer", "accelerator_name", "accelerator_count",
    "efa_count", "bare_metal", "hypervisor",
)


def generate_instancetype_testdata() -> pathlib.Path:
    from ..catalog.instancetypes import generate_catalog

    by_name = {it.name: it for it in generate_catalog(apply_generated=False)}
    missing = [n for n in FIXTURE_NAMES if n not in by_name]
    if missing:
        raise SystemExit(f"fixture names not in catalog: {missing}")
    lines = [
        "# Frozen DescribeInstanceTypes-style fixtures for hermetic suites.\n",
        "DESCRIBE_INSTANCE_TYPES: list[dict] = [\n",
    ]
    for name in FIXTURE_NAMES:
        it = by_name[name]
        kv = ", ".join(f"{f!r}: {getattr(it, f)!r}" for f in _FIELDS)
        lines.append(f"    {{{kv}}},\n")
    lines.append("]\n\n")
    lines.append(
        "def fixture_instance_types():\n"
        '    """Materialize the fixtures as InstanceType objects (offerings\n'
        "    attached by the caller / test env as needed).\"\"\"\n"
        "    from ..catalog.instancetypes import InstanceType\n"
        "    return [InstanceType(**d) for d in DESCRIBE_INSTANCE_TYPES]\n"
    )
    return write_module(
        FAKE_DIR / "zz_generated_describe_instance_types.py", "".join(lines)
    )


if __name__ == "__main__":
    print(generate_instancetype_testdata())
