"""Generates catalog/zz_generated_vpclimits.py.

Reference parity: ``hack/code/vpc_limits_gen`` producing
``pkg/providers/instancetype/zz_generated.vpclimits.go`` — the per-type
ENI / IPs-per-ENI / branch-interface (pod-ENI) limits map consumed by the
capacity math (types.go:255-262, :326-340).
"""

from __future__ import annotations

import pathlib

from ._emit import CATALOG_DIR, write_module


def generate_vpc_limits() -> pathlib.Path:
    from ..catalog.instancetypes import generate_catalog

    types = generate_catalog(apply_generated=False)
    lines = [
        "# name: (max_enis, ips_per_eni, branch_enis)\n",
        "LIMITS: dict[str, tuple[int, int, int]] = {\n",
    ]
    for it in sorted(types, key=lambda t: t.name):
        lines.append(
            f"    {it.name!r}: ({it.max_enis}, {it.ips_per_eni}, {it.branch_enis}),\n"
        )
    lines.append("}\n")
    return write_module(CATALOG_DIR / "zz_generated_vpclimits.py", "".join(lines))


if __name__ == "__main__":
    print(generate_vpc_limits())
