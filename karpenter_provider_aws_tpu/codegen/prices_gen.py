"""Generates catalog/zz_generated_pricing.py.

Reference parity: ``hack/code/prices_gen`` producing the
``zz_generated.pricing_aws*.go`` static seed-price tables loaded at
pricing.go:43 — the warm-start prices used until a live refresh lands.
Spot seeds are per-zone, mirroring the zonal spot map (pricing.go:75-90).
"""

from __future__ import annotations

import pathlib

from ._emit import CATALOG_DIR, write_module


def generate_prices() -> pathlib.Path:
    """Real us-east-1 on-demand seed prices from the committed snapshot
    (the reference's 2024-04-25 table), plus zonal spot seeds derived as a
    deterministic 24-44% fraction of on-demand — the reference's own
    fallback rule when no live spot data exists (pricing.go:141-156), which
    also guarantees spot < on-demand for every seeded offering."""
    import json

    from ..catalog.instancetypes import DEFAULT_ZONES, generate_catalog
    from ..catalog.pricing import _jitter

    snapshot = json.loads((CATALOG_DIR / "aws_snapshot.json").read_text())["types"]
    types = generate_catalog(apply_generated=False)
    od_lines = ["INITIAL_ON_DEMAND_PRICES: dict[str, float] = {\n"]
    spot_lines = ["INITIAL_SPOT_PRICES: dict[str, dict[str, float]] = {\n"]
    for it in sorted(types, key=lambda t: t.name):
        od = snapshot[it.name]["od"]
        od_lines.append(f"    {it.name!r}: {od},\n")
        per_zone = ", ".join(
            f"{z!r}: {round(od * _jitter(f'{it.name}:{z}', 0.24, 0.44), 5)}"
            for z in DEFAULT_ZONES
        )
        spot_lines.append(f"    {it.name!r}: {{{per_zone}}},\n")
    od_lines.append("}\n\n")
    spot_lines.append("}\n")
    return write_module(
        CATALOG_DIR / "zz_generated_pricing.py", "".join(od_lines + spot_lines)
    )


if __name__ == "__main__":
    print(generate_prices())
