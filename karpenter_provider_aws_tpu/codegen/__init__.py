"""Codegen: data-pipeline layer regenerating the static catalog tables.

Reference parity: ``hack/codegen.sh:10-41`` drives four Go generators
(``hack/code/{prices_gen,vpc_limits_gen,bandwidth_gen,instancetype_testdata_gen}``)
that scrape public AWS data into committed ``zz_generated.*.go`` tables. Here
the upstream "source of truth" is the deterministic catalog/pricing model
(zero-egress environment), and each generator snapshots it into a committed
``zz_generated_*.py`` table which the providers consult first at runtime —
same data-not-API-calls philosophy, same refresh workflow
(``python -m karpenter_provider_aws_tpu.codegen``).
"""

from .aws_snapshot_gen import generate_aws_snapshot
from .bandwidth_gen import generate_bandwidth
from .instancetype_testdata_gen import generate_instancetype_testdata
from .prices_gen import generate_prices
from .vpc_limits_gen import generate_vpc_limits

# aws-snapshot is intentionally NOT in the default set: it needs the
# reference tree on disk (dev-time only); the committed snapshot is the
# source of truth everywhere else.
GENERATORS = {
    "vpc-limits": generate_vpc_limits,
    "bandwidth": generate_bandwidth,
    "prices": generate_prices,
    "instancetype-testdata": generate_instancetype_testdata,
}

__all__ = [
    "GENERATORS",
    "generate_bandwidth",
    "generate_instancetype_testdata",
    "generate_prices",
    "generate_vpc_limits",
]
