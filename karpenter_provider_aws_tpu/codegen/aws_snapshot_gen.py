"""Generates catalog/aws_snapshot.json — the frozen real-world catalog.

Reference parity: ``hack/codegen.sh:10-41`` scrapes public AWS data into
committed ``zz_generated.*.go`` tables. This generator plays the same role
with the same provenance chain, one hop removed: it parses those committed
reference tables (real us-east-1 prices generated 2024-04-25, real per-type
VPC ENI/branch limits, real bandwidth megabits) into one JSON snapshot that
is CHECKED IN. The catalog generator consumes the snapshot at import time;
this parser only runs at dev time when the reference tree is present (the
moral analogue of codegen.sh needing AWS credentials).

Parsed sources (data tables only — no code):
 - pkg/providers/pricing/zz_generated.pricing_aws.go   (on-demand $/hr)
 - pkg/providers/instancetype/zz_generated.vpclimits.go (ENI/IP/branch/hyp)
 - pkg/providers/instancetype/zz_generated.bandwidth.go (network Mbps)
"""

from __future__ import annotations

import json
import pathlib
import re

from ._emit import CATALOG_DIR

REFERENCE = pathlib.Path("/root/reference")
SNAPSHOT_PATH = CATALOG_DIR / "aws_snapshot.json"


def _parse_prices(src: str) -> dict[str, float]:
    pairs = re.findall(r'"([a-z0-9][a-z0-9.\-]+)":\s*([0-9.]+)', src)
    return {n: float(p) for n, p in pairs if "." in n}


def _parse_vpclimits(src: str) -> dict[str, dict]:
    out: dict[str, dict] = {}
    # entry blocks: "name": { Interface: N, IPv4PerInterface: N, ...
    # IsTrunkingCompatible: bool, BranchInterface: N, ... Hypervisor: "x" }
    for m in re.finditer(
        r'"([a-z0-9.\-]+)":\s*\{(.*?)\n\t\},', src, re.DOTALL
    ):
        name, body = m.group(1), m.group(2)

        def _int(field: str) -> int:
            mm = re.search(rf"{field}:\s*(\d+)", body)
            return int(mm.group(1)) if mm else 0

        hyp = re.search(r'Hypervisor:\s*"([a-z]*)"', body)
        out[name] = {
            "enis": _int("Interface"),
            "ips": _int("IPv4PerInterface"),
            "branch": _int("BranchInterface"),
            "trunk": "IsTrunkingCompatible:    true" in body
            or "IsTrunkingCompatible: true" in body,
            "hyp": hyp.group(1) if hyp else "",
        }
    return out


def _parse_bandwidth(src: str) -> dict[str, int]:
    body = src.split("InstanceTypeBandwidthMegabits", 1)[-1]
    return {
        n: int(v)
        for n, v in re.findall(r'"([a-z0-9.\-]+)":\s*(\d+)', body)
        if "." in n
    }


def generate_aws_snapshot() -> pathlib.Path:
    if not REFERENCE.exists():
        raise FileNotFoundError(
            "reference tree not present; the committed snapshot is the "
            "source of truth in this checkout"
        )
    prices = _parse_prices(
        (REFERENCE / "pkg/providers/pricing/zz_generated.pricing_aws.go").read_text()
    )
    limits = _parse_vpclimits(
        (REFERENCE / "pkg/providers/instancetype/zz_generated.vpclimits.go").read_text()
    )
    bandwidth = _parse_bandwidth(
        (REFERENCE / "pkg/providers/instancetype/zz_generated.bandwidth.go").read_text()
    )
    types = {}
    for name in sorted(prices):
        row: dict = {"od": prices[name]}
        lim = limits.get(name)
        if lim:
            row.update(lim)
        bw = bandwidth.get(name)
        if bw is not None:
            row["bw"] = bw
        types[name] = row
    snapshot = {
        "provenance": (
            "parsed from karpenter-provider-aws zz_generated data tables: "
            "us-east-1 on-demand prices (generated 2024-04-25), VPC "
            "ENI/branch limits (2024-04-30), bandwidth megabits"
        ),
        "types": types,
    }
    SNAPSHOT_PATH.write_text(json.dumps(snapshot, indent=1, sort_keys=True) + "\n")
    return SNAPSHOT_PATH


if __name__ == "__main__":
    print(generate_aws_snapshot())
