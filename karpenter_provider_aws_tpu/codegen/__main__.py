"""Codegen driver: regenerate all static data tables.

Usage: ``python -m karpenter_provider_aws_tpu.codegen [name ...]``
(no args = all). Parity: ``hack/codegen.sh:10-41``.
"""

from __future__ import annotations

import sys

from . import GENERATORS


def main(argv: list[str]) -> int:
    names = argv or list(GENERATORS)
    unknown = [n for n in names if n not in GENERATORS]
    if unknown:
        print(f"unknown generators {unknown}; available: {list(GENERATORS)}", file=sys.stderr)
        return 2
    for name in names:
        path = GENERATORS[name]()
        print(f"{name}: wrote {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
