"""Shared emission helpers for the codegen generators."""

from __future__ import annotations

import pathlib

CATALOG_DIR = pathlib.Path(__file__).resolve().parent.parent / "catalog"
FAKE_DIR = pathlib.Path(__file__).resolve().parent.parent / "fake"

HEADER = (
    '"""GENERATED FILE — DO NOT EDIT.\n'
    "\n"
    "Regenerate with: python -m karpenter_provider_aws_tpu.codegen\n"
    "(parity: the reference's zz_generated.*.go tables produced by\n"
    "hack/codegen.sh:10-41).\n"
    '"""\n\n'
)


def write_module(path: pathlib.Path, body: str) -> pathlib.Path:
    path.write_text(HEADER + body)
    return path
