"""Generates catalog/zz_generated_bandwidth.py.

Reference parity: ``hack/code/bandwidth_gen`` producing
``pkg/providers/instancetype/zz_generated.bandwidth.go`` — the
``InstanceTypeBandwidthMegabits`` map consumed at types.go:122-124.
"""

from __future__ import annotations

import pathlib

from ._emit import CATALOG_DIR, write_module


def generate_bandwidth() -> pathlib.Path:
    from ..catalog.instancetypes import generate_catalog

    types = generate_catalog(apply_generated=False)
    lines = ["INSTANCE_TYPE_BANDWIDTH_MBPS: dict[str, int] = {\n"]
    for it in sorted(types, key=lambda t: t.name):
        lines.append(f"    {it.name!r}: {it.network_bandwidth_mbps},\n")
    lines.append("}\n")
    return write_module(CATALOG_DIR / "zz_generated_bandwidth.py", "".join(lines))


if __name__ == "__main__":
    print(generate_bandwidth())
