"""Solver sidecar: gRPC server + client carrying npz tensor bundles.

Service contract in ``solver.proto``. Methods are registered with grpc's
generic handlers (no codegen dependency); payloads are npz archives of the
same tensors the in-process solver consumes, so the sidecar is a thin
process boundary around ``ops.ffd.ffd_solve`` / ``ops.consolidate``.
"""

from __future__ import annotations

import contextlib
import io
import logging
from concurrent import futures
from typing import Optional

import grpc
import numpy as np

log = logging.getLogger("karpenter.tpu.sidecar")

SERVICE = "karpenter.tpu.v1.Solver"


def pack(**arrays) -> bytes:
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def unpack(blob: bytes) -> dict[str, np.ndarray]:
    with np.load(io.BytesIO(blob)) as z:
        return {k: z[k] for k in z.files}


class SolverServer:
    """Owns the device; serves Solve / SimulateConsolidation / Health."""

    def __init__(self, address: str = "127.0.0.1:0", max_workers: int = 4):
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        handlers = {
            "Solve": grpc.unary_unary_rpc_method_handler(
                self._solve,
                request_deserializer=bytes,
                response_serializer=bytes,
            ),
            "SimulateConsolidation": grpc.unary_unary_rpc_method_handler(
                self._simulate,
                request_deserializer=bytes,
                response_serializer=bytes,
            ),
            "Health": grpc.unary_unary_rpc_method_handler(
                self._health,
                request_deserializer=bytes,
                response_serializer=bytes,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        self.port = self._server.add_insecure_port(address)

    # -- handlers ----------------------------------------------------------
    @staticmethod
    @contextlib.contextmanager
    def _timed(method: str):
        """RPC latency/error accounting (SURVEY.md section 5: 'optional
        gRPC tracing' — the sidecar is a process boundary and its latency
        must be observable server-side, not just at the client). Latency
        rides the registry's own Histogram.time(); errors carry the
        error-type label, same convention as the cloudprovider metrics
        decorator."""
        from ..metrics import SIDECAR_ERRORS, SIDECAR_RPC_SECONDS
        from ..trace import span as trace_span

        with SIDECAR_RPC_SECONDS.time(method=method):
            # the flight recorder sees the same region: a Chrome trace of
            # the sidecar shows RPC lanes alongside the solve phases the
            # handler runs (server-side attribution, SURVEY.md section 5)
            with trace_span(f"sidecar.{method}"):
                try:
                    yield
                except Exception as e:
                    SIDECAR_ERRORS.inc(method=method, error=type(e).__name__)
                    raise

    def _solve(self, request: bytes, context) -> bytes:
        with self._timed("Solve"):
            return self._solve_inner(request)

    def _solve_inner(self, request: bytes) -> bytes:
        import jax.numpy as jnp

        from ..ops.ffd import ffd_solve

        t = unpack(request)
        max_nodes = int(t.get("max_nodes", np.int32(1024)))
        res = ffd_solve(
            jnp.asarray(t["requests"]),
            jnp.asarray(t["counts"]),
            jnp.asarray(t["compat"]),
            jnp.asarray(t["capacity"]),
            jnp.asarray(t["price"]),
            jnp.asarray(t["group_window"]),
            jnp.asarray(t["type_window"]),
            max_per_node=jnp.asarray(t["max_per_node"]) if "max_per_node" in t else None,
            max_nodes=max_nodes,
        )
        return pack(
            node_type=np.asarray(res.node_type),
            node_price=np.asarray(res.node_price),
            used=np.asarray(res.used),
            node_window=np.asarray(res.node_window),
            n_open=np.asarray(res.n_open, dtype=np.int32),
            placed=np.asarray(res.placed),
            unplaced=np.asarray(res.unplaced),
        )

    def _simulate(self, request: bytes, context) -> bytes:
        with self._timed("SimulateConsolidation"):
            return self._simulate_inner(request)

    def _simulate_inner(self, request: bytes) -> bytes:
        import jax.numpy as jnp

        from ..ops.consolidate import repack_check

        t = unpack(request)
        ok = repack_check(
            jnp.asarray(t["free"]),
            jnp.asarray(t["requests"]),
            jnp.asarray(t["group_ids"]),
            jnp.asarray(t["group_counts"]),
            jnp.asarray(t["compat"]),
            jnp.asarray(t["candidates"]),
        )
        return pack(ok=np.asarray(ok))

    def _health(self, request: bytes, context) -> bytes:
        # instrumented too: jax.devices() is exactly what stalls when the
        # device runtime wedges, and Health is the probe that must show it
        with self._timed("Health"):
            import jax

            return pack(
                device_count=np.asarray(len(jax.devices()), dtype=np.int32)
            )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        self._server.start()
        log.info("solver sidecar listening on port %d", self.port)
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)

    def wait(self) -> None:
        self._server.wait_for_termination()


class SolverClient:
    """Tensor-bundle client; also usable as a TPUSolver drop-in through
    ``RemoteSolver`` below."""

    # A hung sidecar must not wedge the reconcile loop behind a deadline-less
    # RPC: first jit of a new shape bucket can take ~40s, so the default
    # leaves generous headroom over that, but is still finite.
    DEFAULT_TIMEOUT_S = 120.0

    def __init__(self, target: str, timeout_s: Optional[float] = None):
        self._channel = grpc.insecure_channel(target)
        self.timeout_s = timeout_s if timeout_s is not None else self.DEFAULT_TIMEOUT_S

    def _call(self, method: str, payload: bytes, timeout_s: Optional[float] = None) -> bytes:
        fn = self._channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=bytes,
            response_deserializer=bytes,
        )
        return fn(payload, timeout=timeout_s or self.timeout_s)

    def solve(self, **tensors) -> dict[str, np.ndarray]:
        return unpack(self._call("Solve", pack(**tensors)))

    def simulate_consolidation(self, **tensors) -> dict[str, np.ndarray]:
        return unpack(self._call("SimulateConsolidation", pack(**tensors)))

    def health(self) -> int:
        return int(unpack(self._call("Health", pack(), timeout_s=10.0))["device_count"])

    def close(self) -> None:
        self._channel.close()


class RemoteSolver:
    """Solver-plugin implementation backed by a sidecar: encode host-side,
    solve across the process boundary, decode host-side (the exact split the
    BASELINE north star describes for the Go control plane)."""

    def __init__(self, client: SolverClient, max_nodes: Optional[int] = None):
        self.client = client
        self.max_nodes = max_nodes

    def backend_label(self) -> str:
        return "sidecar"

    def solve_encoded(self, problem, existing=None):
        from ..ops.encode import bucket, pad_problem
        from ..scheduling.solver import _host_prefill
        from .solver_bridge import decode_remote

        binds = []
        if existing:
            # host-side prefill onto live nodes; only the fresh-capacity
            # remainder crosses the sidecar wire
            binds, problem = _host_prefill(problem, existing)
        G = len(problem.group_pods)
        if G == 0:
            return [], binds, {}
        num_pods = int(problem.counts[:G].sum())
        from ..scheduling.solver import _node_bucket

        N = self.max_nodes or _node_bucket(num_pods)
        padded = pad_problem(problem, bucket(G))
        out = self.client.solve(
            requests=padded.requests,
            counts=padded.counts,
            compat=padded.compat,
            capacity=padded.capacity,
            price=padded.price,
            group_window=padded.group_window,
            type_window=padded.type_window,
            max_per_node=padded.max_per_node,
            max_nodes=np.int32(N),
        )
        specs, unplaced = decode_remote(problem, out)
        return specs, binds, unplaced

    def solve(self, pods, nodepools, catalog, in_use=None, occupancy=None, type_allow=None,
              reserved_allow=None, existing=None, nodeclass_by_pool=None):
        from ..scheduling.solver import _solve_multi_nodepool

        # the nodeclass-adjusted capacity tensor is built host-side by
        # encode_problem, so the sidecar wire needs no new fields
        return _solve_multi_nodepool(self, pods, nodepools, catalog, in_use, occupancy,
                                     type_allow, reserved_allow, existing,
                                     nodeclass_by_pool=nodeclass_by_pool)


def serve(address: str = "127.0.0.1:50151") -> SolverServer:
    server = SolverServer(address)
    server.start()
    return server
