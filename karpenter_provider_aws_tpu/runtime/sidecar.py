"""Solver sidecar: gRPC server + client carrying npz tensor bundles.

Service contract in ``solver.proto``. Methods are registered with grpc's
generic handlers (no codegen dependency); payloads are npz archives of the
same tensors the in-process solver consumes, so the sidecar is a thin
process boundary around ``ops.ffd.ffd_solve`` / ``ops.consolidate``.
"""

from __future__ import annotations

import contextlib
import io
import logging
from collections import OrderedDict
from concurrent import futures
from typing import Optional

import grpc
import numpy as np

log = logging.getLogger("karpenter.tpu.sidecar")

SERVICE = "karpenter.tpu.v1.Solver"


def pack(**arrays) -> bytes:
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def unpack(blob: bytes) -> dict[str, np.ndarray]:
    with np.load(io.BytesIO(blob)) as z:
        return {k: z[k] for k in z.files}


class SolverServer:
    """Owns the device; serves Solve / SimulateConsolidation / Health.

    Device-residency across RPCs (ops/device_state.py's sibling for the
    process-boundary path): the server keeps a content-addressed cache of
    uploaded tensors, so a reconcile loop re-solving near-identical problems
    through the sidecar pays the host->device link only for arrays that
    actually changed — the npz wire still crosses the process boundary, but
    the device session stays warm. The cache is torn down whenever the
    ``sidecar.device`` circuit breaker records a device failure (a lost
    device session must not serve stale handles), and while that breaker is
    open the server fails fast — the client's ``solver.sidecar`` breaker +
    host-FFD fallback (RemoteSolver) then own the request.
    """

    def __init__(self, address: str = "127.0.0.1:0", max_workers: int = 4):
        import os
        import threading

        self._dev_cache: "OrderedDict[tuple, object]" = OrderedDict()
        self._dev_cache_bytes = 0
        self._dev_cache_budget = int(
            os.environ.get("KARPENTER_TPU_SIDECAR_DEVCACHE_MB", "256")
        ) * (1 << 20)
        self._dev_lock = threading.Lock()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        handlers = {
            "Solve": grpc.unary_unary_rpc_method_handler(
                self._solve,
                request_deserializer=bytes,
                response_serializer=bytes,
            ),
            "SimulateConsolidation": grpc.unary_unary_rpc_method_handler(
                self._simulate,
                request_deserializer=bytes,
                response_serializer=bytes,
            ),
            "Health": grpc.unary_unary_rpc_method_handler(
                self._health,
                request_deserializer=bytes,
                response_serializer=bytes,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        self.port = self._server.add_insecure_port(address)

    # -- handlers ----------------------------------------------------------
    @staticmethod
    @contextlib.contextmanager
    def _timed(method: str):
        """RPC latency/error accounting (SURVEY.md section 5: 'optional
        gRPC tracing' — the sidecar is a process boundary and its latency
        must be observable server-side, not just at the client). Latency
        rides the registry's own Histogram.time(); errors carry the
        error-type label, same convention as the cloudprovider metrics
        decorator."""
        from ..metrics import SIDECAR_ERRORS, SIDECAR_RPC_SECONDS
        from ..trace import span as trace_span

        with SIDECAR_RPC_SECONDS.time(method=method):
            # the flight recorder sees the same region: a Chrome trace of
            # the sidecar shows RPC lanes alongside the solve phases the
            # handler runs (server-side attribution, SURVEY.md section 5)
            with trace_span(f"sidecar.{method}"):
                try:
                    yield
                except Exception as e:
                    SIDECAR_ERRORS.inc(method=method, error=type(e).__name__)
                    raise

    # -- warm device session -------------------------------------------------
    def _dput(self, x: np.ndarray):
        """device_put through the server's content-addressed cache: repeat
        RPCs with unchanged tensors (catalog capacity/windows above all)
        reuse the resident device buffer instead of re-uploading."""
        import hashlib

        import jax

        x = np.ascontiguousarray(x)
        key = (x.shape, str(x.dtype), hashlib.blake2b(x, digest_size=16).digest())
        with self._dev_lock:
            hit = self._dev_cache.get(key)
            if hit is not None:
                self._dev_cache.move_to_end(key)
                return hit
        arr = jax.device_put(x)
        # link-byte attribution: a cache miss is real host->device payload;
        # the device-plane accountant folds it into the sidecar's family
        # (trace/jitwatch.py — no-op when jitwatch is off)
        from ..trace.jitwatch import note_dispatch

        note_dispatch("sidecar.devcache", x.nbytes)
        with self._dev_lock:
            # re-check under the lock: two workers can miss on the same key
            # concurrently (the shared catalog arrays), and overwriting the
            # winner would double-count _dev_cache_bytes — the overwritten
            # entry's bytes are added twice but evicted once, permanently
            # shrinking the effective budget
            hit = self._dev_cache.get(key)
            if hit is not None:
                self._dev_cache.move_to_end(key)
                return hit
            self._dev_cache[key] = arr
            self._dev_cache_bytes += x.nbytes
            while (
                self._dev_cache_bytes > self._dev_cache_budget
                and len(self._dev_cache) > 1
            ):
                _, old = self._dev_cache.popitem(last=False)
                self._dev_cache_bytes -= old.nbytes
        return arr

    def _teardown_device(self) -> None:
        """Drop every resident buffer (the device session is suspect)."""
        with self._dev_lock:
            self._dev_cache.clear()
            self._dev_cache_bytes = 0

    @contextlib.contextmanager
    def _device_session(self):
        """Breaker-gated device work: an open ``sidecar.device`` breaker
        fails fast (no device call attempted), a failure tears the resident
        cache down before re-raising — the client's host-FFD fallback then
        serves the solve from host buffers."""
        from ..resilience import breakers
        from ..resilience.breaker import BreakerOpen

        br = breakers.get("sidecar.device")
        if not br.allow():
            raise BreakerOpen("sidecar.device")
        try:
            yield
        except Exception as e:
            br.record_failure(e)
            self._teardown_device()
            raise
        br.record_success()

    def _solve(self, request: bytes, context) -> bytes:
        with self._timed("Solve"):
            return self._solve_inner(request)

    def _solve_inner(self, request: bytes) -> bytes:
        from ..ops.ffd import ffd_solve

        t = unpack(request)
        max_nodes = int(t.get("max_nodes", np.int32(1024)))
        with self._device_session():
            res = ffd_solve(
                self._dput(t["requests"]),
                self._dput(t["counts"]),
                self._dput(t["compat"]),
                self._dput(t["capacity"]),
                self._dput(t["price"]),
                self._dput(t["group_window"]),
                self._dput(t["type_window"]),
                max_per_node=self._dput(t["max_per_node"]) if "max_per_node" in t else None,
                max_nodes=max_nodes,
            )
        return pack(
            node_type=np.asarray(res.node_type),
            node_price=np.asarray(res.node_price),
            used=np.asarray(res.used),
            node_window=np.asarray(res.node_window),
            n_open=np.asarray(res.n_open, dtype=np.int32),
            placed=np.asarray(res.placed),
            unplaced=np.asarray(res.unplaced),
        )

    def _simulate(self, request: bytes, context) -> bytes:
        with self._timed("SimulateConsolidation"):
            return self._simulate_inner(request)

    def _simulate_inner(self, request: bytes) -> bytes:
        from ..ops.consolidate import repack_check

        t = unpack(request)
        with self._device_session():
            ok = repack_check(
                self._dput(t["free"]),
                self._dput(t["requests"]),
                self._dput(t["group_ids"]),
                self._dput(t["group_counts"]),
                self._dput(t["compat"]),
                self._dput(t["candidates"]),
            )
        return pack(ok=np.asarray(ok))

    def _health(self, request: bytes, context) -> bytes:
        # instrumented too: jax.devices() is exactly what stalls when the
        # device runtime wedges, and Health is the probe that must show it
        with self._timed("Health"):
            import jax

            return pack(
                device_count=np.asarray(len(jax.devices()), dtype=np.int32)
            )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        self._server.start()
        log.info("solver sidecar listening on port %d", self.port)
        # zero-cold-start: replay the fleet warmup manifest (and point jax
        # at the shared persistent compile cache) before the first Solve
        # RPC pays a compile. Env-gated no-op; never raises.
        from ..trace.warmup import startup_warm

        startup_warm()
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)

    def wait(self) -> None:
        self._server.wait_for_termination()


class SolverClient:
    """Tensor-bundle client; also usable as a TPUSolver drop-in through
    ``RemoteSolver`` below.

    Sidecar-restart survival (resilience layer): ``close()`` is
    idempotent; an ``UNAVAILABLE`` status re-dials the channel once and
    retries the call; after any reconnect the first solve is gated behind
    a ``Health`` probe so work never lands on a half-initialized device
    runtime. RPC deadlines shrink to the ambient per-reconcile budget
    when one is in scope (resilience/budget.py) instead of always paying
    the flat default below.
    """

    # A hung sidecar must not wedge the reconcile loop behind a deadline-less
    # RPC: first jit of a new shape bucket can take ~40s, so the default
    # leaves generous headroom over that, but is still finite.
    DEFAULT_TIMEOUT_S = 120.0
    # never hand gRPC a zero/negative deadline, even with a dry budget —
    # the error should be DEADLINE_EXCEEDED from the wire, not a local throw
    MIN_TIMEOUT_S = 0.05

    def __init__(self, target: str, timeout_s: Optional[float] = None):
        import threading

        self._target = target
        self._lock = threading.Lock()
        self._closed = False
        self._needs_probe = False
        self._channel = grpc.insecure_channel(target)
        self.timeout_s = timeout_s if timeout_s is not None else self.DEFAULT_TIMEOUT_S

    def _effective_timeout(self, timeout_s: Optional[float]) -> float:
        timeout = timeout_s or self.timeout_s
        from ..resilience import budget

        remaining = budget.remaining()
        if remaining is not None:
            timeout = min(timeout, remaining)
        return max(timeout, self.MIN_TIMEOUT_S)

    def _stub(self, method: str):
        with self._lock:
            if self._closed or self._channel is None:
                raise RuntimeError("SolverClient is closed")
            channel = self._channel
        return channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=bytes,
            response_deserializer=bytes,
        )

    def _call(self, method: str, payload: bytes, timeout_s: Optional[float] = None) -> bytes:
        timeout = self._effective_timeout(timeout_s)
        try:
            return self._stub(method)(payload, timeout=timeout)
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            if code != grpc.StatusCode.UNAVAILABLE or self._closed:
                raise
            # sidecar restarted (or the connection died) under us: one
            # re-dial, health-gate the new channel, then retry the call
            log.warning(
                "sidecar %s UNAVAILABLE on %s; re-dialing", self._target, method
            )
            self._redial()
            if method != "Health":
                self.health()
            # wait_for_ready: the fresh channel may still be connecting —
            # the retry must ride the connection attempt out (within the
            # deadline) instead of failing fast mid-handshake. The
            # deadline is RECOMPUTED: the first attempt + health probe
            # already spent ambient reconcile budget, and the retry must
            # fit what is left, not what was left at entry.
            return self._stub(method)(
                payload, timeout=self._effective_timeout(timeout_s),
                wait_for_ready=True,
            )

    def _redial(self) -> None:
        with self._lock:
            if self._closed:
                raise RuntimeError("SolverClient is closed")
            old, self._channel = self._channel, grpc.insecure_channel(self._target)
            self._needs_probe = True
        try:
            if old is not None:
                old.close()
        except Exception:
            pass

    def solve(self, **tensors) -> dict[str, np.ndarray]:
        if self._needs_probe:
            self.health()  # gate the first post-reconnect solve
        return unpack(self._call("Solve", pack(**tensors)))

    def simulate_consolidation(self, **tensors) -> dict[str, np.ndarray]:
        if self._needs_probe:
            self.health()
        return unpack(self._call("SimulateConsolidation", pack(**tensors)))

    def health(self) -> int:
        # a health probe never deserves more deadline than a solve, and
        # 10s is plenty for a live runtime to answer
        count = int(unpack(self._call(
            "Health", pack(), timeout_s=min(10.0, self.timeout_s),
        ))["device_count"])
        self._needs_probe = False
        return count

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            channel, self._channel = self._channel, None
        try:
            if channel is not None:
                channel.close()
        except Exception:
            pass


class RemoteSolver:
    """Solver-plugin implementation backed by a sidecar: encode host-side,
    solve across the process boundary, decode host-side (the exact split the
    BASELINE north star describes for the Go control plane).

    Guarded by the ``solver.sidecar`` circuit breaker: a dead/restarting
    sidecar fails a few solves (each served from the host FFD instead of
    erroring the reconcile), trips the breaker so subsequent solves skip
    the RPC latency entirely, and is re-admitted by a half-open probe
    after the recovery window.
    """

    def __init__(self, client: SolverClient, max_nodes: Optional[int] = None):
        self.client = client
        self.max_nodes = max_nodes
        # per-solve stage timings + fallback notes (same contract as
        # TPUSolver.timings; _solve_multi_nodepool resets per solve and
        # solve_record lifts *_fallback keys into provenance)
        self.timings: dict = {}

    def backend_label(self) -> str:
        if self.timings.get("degraded"):
            return "host-ffd(degraded)"
        return "sidecar"

    def solve_encoded(self, problem, existing=None):
        from ..resilience import breakers, faultgate
        from ..scheduling.solver import host_solve_encoded

        breaker = breakers.get("solver.sidecar")
        if not breaker.allow():
            self.timings["breaker_fallback"] = "breaker:solver.sidecar"
            self.timings["degraded"] = "host-ffd"
            self.timings["residency"] = "fallback"
            return host_solve_encoded(problem, existing)
        try:
            faultgate.check("sidecar")
            out = self._solve_remote(problem, existing)
        except Exception as e:
            breaker.record_failure(e)
            log.warning(
                "sidecar solve failed; serving this solve from the host "
                "FFD path: %s: %s", type(e).__name__, e,
            )
            self.timings["sidecar_fallback"] = f"{type(e).__name__}: {e}"[:200]
            self.timings["degraded"] = "host-ffd"
            self.timings["residency"] = "fallback"
            return host_solve_encoded(problem, existing)
        breaker.record_success()
        return out

    def _solve_remote(self, problem, existing=None):
        from ..ops.encode import bucket, pad_problem
        from ..scheduling.solver import _host_prefill
        from .solver_bridge import decode_remote

        binds = []
        if existing:
            # host-side prefill onto live nodes; only the fresh-capacity
            # remainder crosses the sidecar wire
            binds, problem = _host_prefill(problem, existing)
        G = len(problem.group_pods)
        if G == 0:
            return [], binds, {}
        num_pods = int(problem.counts[:G].sum())
        from ..scheduling.solver import _node_bucket

        N = self.max_nodes or _node_bucket(num_pods)
        padded = pad_problem(problem, bucket(G))
        out = self.client.solve(
            requests=padded.requests,
            counts=padded.counts,
            compat=padded.compat,
            capacity=padded.capacity,
            price=padded.price,
            group_window=padded.group_window,
            type_window=padded.type_window,
            max_per_node=padded.max_per_node,
            max_nodes=np.int32(N),
        )
        specs, unplaced = decode_remote(problem, out)
        return specs, binds, unplaced

    def solve(self, pods, nodepools, catalog, in_use=None, occupancy=None, type_allow=None,
              reserved_allow=None, existing=None, nodeclass_by_pool=None):
        from ..scheduling.solver import _solve_multi_nodepool

        # the nodeclass-adjusted capacity tensor is built host-side by
        # encode_problem, so the sidecar wire needs no new fields
        return _solve_multi_nodepool(self, pods, nodepools, catalog, in_use, occupancy,
                                     type_allow, reserved_allow, existing,
                                     nodeclass_by_pool=nodeclass_by_pool)


def serve(address: str = "127.0.0.1:50151") -> SolverServer:
    server = SolverServer(address)
    server.start()
    return server
