"""Decode helpers shared by the remote solver path."""

from __future__ import annotations

import numpy as np

from ..scheduling.solver import _decode_nodes


def decode_remote(problem, out: dict[str, np.ndarray]):
    G = len(problem.group_pods)
    n_open = int(out["n_open"])
    specs, _ = _decode_nodes(
        problem,
        out["node_type"],
        out["node_price"],
        out["used"],
        n_open,
        out["placed"],
        problem.nodepool.name if problem.nodepool else "",
        out["node_window"].astype(bool),
    )
    unplaced = {g: int(c) for g, c in enumerate(out["unplaced"][:G]) if c > 0}
    return specs, unplaced
