"""The gRPC sidecar: the Go<->device process boundary.

Reference-domain analogue (SURVEY.md sections 2.3, 5): where the reference's
controllers call AWS over REST and coalesce via the batcher, this framework's
control plane ships the packed problem tensors to the device-owning sidecar
over gRPC; ICI/XLA collectives handle multi-chip inside, DCN/gRPC handles
host boundaries outside.
"""

from .sidecar import SolverServer, SolverClient, serve  # noqa: F401
