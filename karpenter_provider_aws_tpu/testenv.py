"""Hermetic test environment wiring every component against fakes.

Parity: ``pkg/test/environment.go:52-197`` — one call builds the fake cloud,
catalog, cluster store, cloud provider, and all controllers with an
injectable fake clock and millisecond batch windows; ``reset()`` wipes state
between specs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .catalog.provider import CatalogProvider, OverheadOptions
from .cloudprovider.cloudprovider import CloudProvider
from .events import EventRecorder
from .controllers import (
    DisruptionController,
    GarbageCollectionController,
    InterruptionController,
    LivenessController,
    Manager,
    NodeClassHashController,
    NodeClassStatusController,
    NodeClassTerminationController,
    ProvisioningController,
    RegistrationController,
    SchedulingController,
    TaggingController,
    TerminationController,
)
from .fake import CapacityReservation, FakeCloud, FakeQueue
from .models.nodeclass import NodeClass
from .models.nodepool import NodePool
from .scheduling.solver import HostSolver, Solver, TPUSolver
from .state.cluster import Cluster
from .utils.batcher import BatcherOptions
from .utils.clock import FakeClock


@dataclass
class Environment:
    clock: FakeClock
    cloud: FakeCloud
    queue: FakeQueue
    catalog: CatalogProvider
    cluster: Cluster
    cloudprovider: CloudProvider
    solver: Solver
    provisioning: ProvisioningController
    scheduling: SchedulingController
    registration: RegistrationController
    termination: TerminationController
    disruption: DisruptionController
    interruption: InterruptionController
    garbagecollection: GarbageCollectionController
    liveness: LivenessController
    tagging: TaggingController
    nodeclass_hash: NodeClassHashController
    nodeclass_status: NodeClassStatusController
    nodeclass_termination: NodeClassTerminationController
    manager: Manager
    # env-local event sink on the env's FakeClock (controllers publish here;
    # two environments in one process never share or wipe each other's)
    events: "EventRecorder" = None
    # env-local observability bundle (obs/): audit ring, SLO engine,
    # lifecycle SLI observer — installed on this env's cluster
    obs: "object" = None

    def close(self) -> None:
        """Join the cloud provider's batcher worker pools. Environments are
        commonly module-scoped and live to process exit; call this from
        teardown when constructing many short-lived environments."""
        self.cloudprovider.close()

    def reset(self) -> None:
        self.cloud.reset()
        self.events.reset()
        self.obs.reset()
        self.queue.reset()
        self.cluster.__init__(clock=self.clock)
        self.catalog.unavailable.flush()
        self.catalog.reservations.flush()
        self.cloudprovider.reset_caches()
        self.provisioning.nominations.clear()
        self.provisioning.last_unschedulable.clear()
        self.disruption.disrupted.clear()
        self.disruption._consol_seen.clear()
        self.disruption._reject_logged.clear()
        self.interruption.handled.clear()
        self.garbagecollection.reaped.clear()
        self.liveness.reaped.clear()

    def step(self, n: int = 1) -> None:
        """n deterministic reconcile passes over every controller."""
        for _ in range(n):
            self.manager.reconcile_all_once()

    def apply_defaults(self, nodepool: Optional[NodePool] = None) -> tuple[NodePool, NodeClass]:
        """Apply a ready default NodeClass + NodePool pair."""
        nodeclass = NodeClass(name="default", role="node-role")
        pool = nodepool or NodePool(name="default")
        self.cluster.apply(nodeclass)
        self.cluster.apply(pool)
        self.nodeclass_status.reconcile()
        self.nodeclass_hash.reconcile()
        return pool, nodeclass


def seed_instance(cloud: FakeCloud, *, instance_id: str, instance_type: str,
                  zone: str, capacity_type: str, image_id: str,
                  tags: Optional[dict] = None, launch_time: float = 0.0):
    """Place a pre-existing running instance directly into the fake cloud
    (the fleet simulator's pre-built-fleet seam). Lives here because
    testenv is the ONE sanctioned production-side importer of ``fake/``
    (tests/test_backend_contract.py) — harnesses that need synthetic
    cloud state route through it instead of importing fake themselves."""
    from .fake.cloud import Instance

    inst = Instance(
        id=instance_id, instance_type=instance_type, zone=zone,
        capacity_type=capacity_type, image_id=image_id,
        launch_time=launch_time, tags=dict(tags or {}),
        # sentinel fence: a harness-seeded fleet predates the lease layer
        # by construction; the no-double-launch invariant exempts it
        launch_fence=("__seeded__", 0),
    )
    with cloud._lock:
        cloud.instances[inst.id] = inst
    return inst


@dataclass
class Replica:
    """One control-plane replica of a :class:`ReplicaSetEnv`: its own
    controllers + Manager + ShardElector over the SHARED world."""

    identity: str
    manager: Manager
    elector: object
    cloudprovider: CloudProvider
    provisioning: ProvisioningController
    alive: bool = True
    paused: bool = False
    # ownership snapshot captured at pause time — the "in-flight work" a
    # resumed (GC-paused / live-migrated) process acts on before its
    # elector refreshes; the fencing layer exists to reject exactly this
    stale_ownership: object = None


class _ManagerView:
    """Duck-types the single Environment's ``manager`` for harnesses that
    read ``env.manager.errors`` (chaos invariants) across every replica."""

    def __init__(self, rs: "ReplicaSetEnv"):
        self._rs = rs

    @property
    def errors(self):
        out = []
        for r in self._rs.replicas:
            out.extend(r.manager.errors)
        return out


@dataclass
class ReplicaSetEnv:
    """N active-active control-plane replicas over ONE shared world (the
    N-replicas-one-apiserver shape): one FakeClock, FakeCloud, queue,
    catalog, cluster store, event recorder, and obs bundle; per replica
    its own controllers, Manager, and ShardElector contending for the
    partition leases (operator/sharding.py). Duck-types ``Environment``
    closely enough that the chaos harness and fleet simulator drive it
    unchanged.

    ``step()`` runs each live replica's deterministic pass in index order
    and then audits the lease layer: any EFFECTIVE-ownership overlap
    between two replicas is appended to ``lease_overlaps`` (the
    leases-partition-the-fleet invariant must find it empty), and the
    current unowned-partition count lands in ``coverage_history`` so a
    driver can measure recovery time after a replica loss."""

    clock: FakeClock
    cloud: FakeCloud
    queue: FakeQueue
    catalog: CatalogProvider
    cluster: Cluster
    replicas: "list[Replica]"
    events: "EventRecorder"
    obs: object
    nodeclass_status: NodeClassStatusController
    nodeclass_hash: NodeClassHashController

    def __post_init__(self):
        self.manager = _ManagerView(self)
        self.lease_overlaps: list = []
        self.coverage_history: list = []
        # ownership Gantt source (obs/fleet.py FleetRecorder): one edge
        # per effective-holder change — (t, key, previous holder, new
        # holder, fencing token); "" marks an ownership gap
        self.ownership_timeline: list = []
        self._last_owners: dict = {}

    # -- Environment duck type ---------------------------------------------
    @property
    def cloudprovider(self) -> CloudProvider:
        return self.replicas[0].cloudprovider

    @property
    def provisioning(self) -> ProvisioningController:
        return self.replicas[0].provisioning

    def close(self) -> None:
        for r in self.replicas:
            r.cloudprovider.close()

    def apply_defaults(self, nodepool: Optional[NodePool] = None):
        nodeclass = NodeClass(name="default", role="node-role")
        pool = nodepool or NodePool(name="default")
        self.cluster.apply(nodeclass)
        self.cluster.apply(pool)
        self.nodeclass_status.reconcile()
        self.nodeclass_hash.reconcile()
        return pool, nodeclass

    def step(self, n: int = 1) -> None:
        for _ in range(n):
            for r in self.replicas:
                if r.alive and not r.paused:
                    r.manager.reconcile_all_once()
            self._audit_leases()

    # -- lease-layer audit ----------------------------------------------------
    def ownership_map(self) -> dict:
        """partition key -> [identities with EFFECTIVE ownership] (live
        replicas only; effective = inside the renew deadline)."""
        out: dict = {}
        for r in self.replicas:
            if not (r.alive and not r.paused):
                continue
            for key in r.elector.ownership().keys:
                out.setdefault(key, []).append(r.identity)
        return out

    def partition_gap(self) -> list:
        """Partition keys (incl. GLOBAL) with no effective owner."""
        from .operator.sharding import GLOBAL_KEY

        owned = set(self.ownership_map())
        keys = [GLOBAL_KEY] + list(self.cluster.partition_keys())
        return [k for k in keys if k not in owned]

    def work_claims(self) -> dict:
        """Live GLOBAL-queue work claims (pod uid -> (owner, expires_at))
        — the work-stealing provisioning surface the tests assert on."""
        from .operator.sharding import WORK_QUEUE

        return self.cloud.list_work_claims(WORK_QUEUE)

    def _audit_leases(self) -> None:
        now = round(self.clock.now(), 3)
        owners = self.ownership_map()
        for key, who in owners.items():
            if len(who) > 1:
                self.lease_overlaps.append((now, key, tuple(sorted(who))))
        self.coverage_history.append((now, len(self.partition_gap())))
        # edge-triggered ownership transitions (who held which partition
        # when): the merged timeline + Gantt read these, and a loss edge
        # (holder -> "") is the replica-loss recovery's visible start
        tokens: dict = {}
        current: dict = {}
        for r in self.replicas:
            if not (r.alive and not r.paused):
                continue
            own = r.elector.ownership()
            for key, token in own.keys.items():
                current[key] = r.identity
                tokens[key] = token
        from .operator.sharding import GLOBAL_KEY

        for key in [GLOBAL_KEY] + list(self.cluster.partition_keys()):
            prev = self._last_owners.get(key, "")
            cur = current.get(key, "")
            if cur != prev:
                self.ownership_timeline.append(
                    (now, key, prev, cur, tokens.get(key, 0))
                )
                self._last_owners[key] = cur

    # -- replica failure controls (the chaos seams) ---------------------------
    def _replica(self, i: int) -> Replica:
        return self.replicas[i]

    def crash(self, i: int) -> None:
        """Kill replica ``i`` outright: it stops reconciling and renewing;
        its leases (and membership) expire after the TTL."""
        self._replica(i).alive = False

    def restart(self, i: int) -> None:
        """Rejoin replica ``i`` as a FRESH process with the same identity:
        empty lease snapshot, new elector nonce (a restarted pod is a new
        holder instance — the nonce keeps a stale twin fenced out)."""
        import uuid

        r = self._replica(i)
        r.alive = True
        r.paused = False
        el = r.elector
        with el._lock:
            el._held = {}
            el._renewed = {}
        el._nonce = uuid.uuid4().hex
        el.partitioned = False

    def pause(self, i: int) -> None:
        """Stop-the-world pause (GC, VM migration): the replica freezes
        mid-flight with its ownership snapshot intact."""
        r = self._replica(i)
        r.paused = True
        r.stale_ownership = r.elector.ownership()

    def resume(self, i: int, stale_pass: bool = True) -> None:
        """Resume a paused replica. With ``stale_pass`` (the default) its
        controllers run ONE pass under the ownership snapshot captured at
        pause time, BEFORE the elector refreshes — exactly the in-flight
        writes a real deposed leader would have racing the successor.
        Past the TTL those writes carry superseded fencing tokens and the
        cloud rejects them (karpenter_fenced_writes_rejected_total)."""
        from .operator import sharding

        r = self._replica(i)
        r.paused = False
        own = r.stale_ownership
        r.stale_ownership = None
        if stale_pass and own is not None and own.keys:
            with sharding.scope(own):
                for c in r.manager.controllers:
                    if c is r.manager.elector:
                        continue
                    try:
                        c.reconcile()
                    except Exception as e:  # isolation, like the Manager
                        r.manager.errors.append((c.name, e))

    def netsplit(self, i: int) -> None:
        """Partition replica ``i`` from the lease host only: it keeps
        reconciling on its snapshot until the renew deadline lapses."""
        self._replica(i).elector.partitioned = True

    def heal(self, i: int) -> None:
        self._replica(i).elector.partitioned = False


def new_replicaset(n: int = 2, use_tpu_solver: bool = False,
                   zones=None, ttl_s: float = 15.0) -> ReplicaSetEnv:
    """N-replica hermetic control plane over one shared world (see
    :class:`ReplicaSetEnv`). Replica identities are ``replica-0..n-1`` —
    stable, so rendezvous hashing (and with it every chaos/sim run) is
    deterministic per seed."""
    from .operator.sharding import ShardElector
    from .resilience import breakers, faultgate

    clock = FakeClock()
    breakers.configure(clock=clock)
    faultgate.clear()
    cloud = FakeCloud(clock=clock, **({"zones": zones} if zones else {}))
    queue = FakeQueue()
    catalog = CatalogProvider(clock=clock, **({"zones": zones} if zones else {}))
    cluster = Cluster(clock=clock)
    recorder = EventRecorder(clock=clock)
    from . import obs as obs_mod

    obs_bundle = obs_mod.install(cluster=cluster, recorder=recorder, clock=clock)
    replicas: list[Replica] = []
    first_status = first_hash = None
    for i in range(n):
        identity = f"replica-{i}"
        cloudprovider = CloudProvider(
            cloud, catalog, cluster, clock=clock,
            batcher_options=BatcherOptions(idle_timeout_s=0.001, max_timeout_s=0.05),
        )
        solver = TPUSolver() if use_tpu_solver else HostSolver()
        provisioning = ProvisioningController(
            cluster, solver, cloudprovider, recorder=recorder, obs=obs_bundle,
        )
        scheduling = SchedulingController(cluster, provisioning, clock=clock)
        registration = RegistrationController(cluster, provisioning, clock=clock)
        termination = TerminationController(cluster, cloudprovider, clock=clock)
        disruption = DisruptionController(
            cluster, cloudprovider, clock=clock, provisioning=provisioning,
            recorder=recorder, validation_period_s=0.0, obs=obs_bundle,
        )
        interruption = InterruptionController(
            cluster, cloudprovider, queue, recorder=recorder, obs=obs_bundle,
        )
        gc = GarbageCollectionController(cluster, cloudprovider, clock=clock)
        liveness = LivenessController(cluster, clock=clock, recorder=recorder,
                                      obs=obs_bundle)
        tagging = TaggingController(cluster, cloudprovider)
        nc_hash = NodeClassHashController(cluster)
        nc_status = NodeClassStatusController(cluster, cloudprovider)
        nc_term = NodeClassTerminationController(cluster, cloudprovider)
        if i == 0:
            first_status, first_hash = nc_status, nc_hash
        elector = ShardElector(cloud, cluster, identity=identity, clock=clock,
                               ttl_s=ttl_s)
        # the provisioner's netsplit seam: a replica cut off from the
        # lease host must stop claiming GLOBAL-queue work too
        provisioning.elector = elector
        manager = Manager(
            [
                nc_status, nc_hash, interruption, termination, registration,
                scheduling, provisioning, tagging, disruption, gc, liveness,
                nc_term,
            ],
            elector=elector, clock=clock, recorder=recorder,
        )
        replicas.append(Replica(
            identity=identity, manager=manager, elector=elector,
            cloudprovider=cloudprovider, provisioning=provisioning,
        ))
    return ReplicaSetEnv(
        clock=clock, cloud=cloud, queue=queue, catalog=catalog,
        cluster=cluster, replicas=replicas, events=recorder, obs=obs_bundle,
        nodeclass_status=first_status, nodeclass_hash=first_hash,
    )


def new_environment(solver: Optional[Solver] = None, use_tpu_solver: bool = True,
                    zones=None, cluster_info=None) -> Environment:
    clock = FakeClock()
    # the resilience layer follows the freshest environment: breakers are
    # re-keyed onto THIS clock (stale wall-time state must never leak into
    # a virtual-clock run) and any leftover chaos dispatch hooks cleared
    from .resilience import breakers, faultgate

    breakers.configure(clock=clock)
    faultgate.clear()
    cloud = FakeCloud(clock=clock, **({"zones": zones} if zones else {}))
    queue = FakeQueue()
    catalog = CatalogProvider(clock=clock, **({"zones": zones} if zones else {}))
    cluster = Cluster(clock=clock)
    cloudprovider = CloudProvider(
        cloud,
        catalog,
        cluster,
        clock=clock,
        batcher_options=BatcherOptions(idle_timeout_s=0.001, max_timeout_s=0.05),
        cluster_info=cluster_info,
    )
    solver = solver or (TPUSolver() if use_tpu_solver else HostSolver())
    recorder = EventRecorder(clock=clock)
    # env-local observability bundle: lifecycle observer on THIS cluster,
    # SLO engine + audit ring on THIS clock/recorder (obs/)
    from . import obs as obs_mod

    obs_bundle = obs_mod.install(cluster=cluster, recorder=recorder, clock=clock)
    provisioning = ProvisioningController(cluster, solver, cloudprovider,
                                          recorder=recorder, obs=obs_bundle)
    scheduling = SchedulingController(cluster, provisioning, clock=clock)
    registration = RegistrationController(cluster, provisioning, clock=clock)
    termination = TerminationController(cluster, cloudprovider, clock=clock)
    # validation_period_s=0: specs drive single reconcile passes; the
    # window's own behavior is tested explicitly in test_disruption
    disruption = DisruptionController(cluster, cloudprovider, clock=clock,
                                      provisioning=provisioning, recorder=recorder,
                                      validation_period_s=0.0, obs=obs_bundle)
    interruption = InterruptionController(cluster, cloudprovider, queue,
                                          recorder=recorder, obs=obs_bundle)
    gc = GarbageCollectionController(cluster, cloudprovider, clock=clock)
    liveness = LivenessController(cluster, clock=clock, recorder=recorder,
                                  obs=obs_bundle)
    tagging = TaggingController(cluster, cloudprovider)
    nc_hash = NodeClassHashController(cluster)
    nc_status = NodeClassStatusController(cluster, cloudprovider)
    nc_term = NodeClassTerminationController(cluster, cloudprovider)
    manager = Manager(
        [
            nc_status,
            nc_hash,
            interruption,
            termination,
            registration,
            scheduling,
            provisioning,
            tagging,
            disruption,
            gc,
            liveness,
            nc_term,
        ],
        clock=clock,
        recorder=recorder,
    )
    return Environment(
        clock=clock,
        cloud=cloud,
        queue=queue,
        catalog=catalog,
        cluster=cluster,
        cloudprovider=cloudprovider,
        solver=solver,
        provisioning=provisioning,
        scheduling=scheduling,
        registration=registration,
        termination=termination,
        disruption=disruption,
        interruption=interruption,
        garbagecollection=gc,
        liveness=liveness,
        tagging=tagging,
        nodeclass_hash=nc_hash,
        nodeclass_status=nc_status,
        nodeclass_termination=nc_term,
        manager=manager,
        events=recorder,
        obs=obs_bundle,
    )
