"""Hermetic test environment wiring every component against fakes.

Parity: ``pkg/test/environment.go:52-197`` — one call builds the fake cloud,
catalog, cluster store, cloud provider, and all controllers with an
injectable fake clock and millisecond batch windows; ``reset()`` wipes state
between specs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .catalog.provider import CatalogProvider, OverheadOptions
from .cloudprovider.cloudprovider import CloudProvider
from .events import EventRecorder
from .controllers import (
    DisruptionController,
    GarbageCollectionController,
    InterruptionController,
    LivenessController,
    Manager,
    NodeClassHashController,
    NodeClassStatusController,
    NodeClassTerminationController,
    ProvisioningController,
    RegistrationController,
    SchedulingController,
    TaggingController,
    TerminationController,
)
from .fake import FakeCloud, FakeQueue
from .models.nodeclass import NodeClass
from .models.nodepool import NodePool
from .scheduling.solver import HostSolver, Solver, TPUSolver
from .state.cluster import Cluster
from .utils.batcher import BatcherOptions
from .utils.clock import FakeClock


@dataclass
class Environment:
    clock: FakeClock
    cloud: FakeCloud
    queue: FakeQueue
    catalog: CatalogProvider
    cluster: Cluster
    cloudprovider: CloudProvider
    solver: Solver
    provisioning: ProvisioningController
    scheduling: SchedulingController
    registration: RegistrationController
    termination: TerminationController
    disruption: DisruptionController
    interruption: InterruptionController
    garbagecollection: GarbageCollectionController
    liveness: LivenessController
    tagging: TaggingController
    nodeclass_hash: NodeClassHashController
    nodeclass_status: NodeClassStatusController
    nodeclass_termination: NodeClassTerminationController
    manager: Manager
    # env-local event sink on the env's FakeClock (controllers publish here;
    # two environments in one process never share or wipe each other's)
    events: "EventRecorder" = None
    # env-local observability bundle (obs/): audit ring, SLO engine,
    # lifecycle SLI observer — installed on this env's cluster
    obs: "object" = None

    def close(self) -> None:
        """Join the cloud provider's batcher worker pools. Environments are
        commonly module-scoped and live to process exit; call this from
        teardown when constructing many short-lived environments."""
        self.cloudprovider.close()

    def reset(self) -> None:
        self.cloud.reset()
        self.events.reset()
        self.obs.reset()
        self.queue.reset()
        self.cluster.__init__(clock=self.clock)
        self.catalog.unavailable.flush()
        self.catalog.reservations.flush()
        self.cloudprovider.reset_caches()
        self.provisioning.nominations.clear()
        self.provisioning.last_unschedulable.clear()
        self.disruption.disrupted.clear()
        self.disruption._consol_seen.clear()
        self.disruption._reject_logged.clear()
        self.interruption.handled.clear()
        self.garbagecollection.reaped.clear()
        self.liveness.reaped.clear()

    def step(self, n: int = 1) -> None:
        """n deterministic reconcile passes over every controller."""
        for _ in range(n):
            self.manager.reconcile_all_once()

    def apply_defaults(self, nodepool: Optional[NodePool] = None) -> tuple[NodePool, NodeClass]:
        """Apply a ready default NodeClass + NodePool pair."""
        nodeclass = NodeClass(name="default", role="node-role")
        pool = nodepool or NodePool(name="default")
        self.cluster.apply(nodeclass)
        self.cluster.apply(pool)
        self.nodeclass_status.reconcile()
        self.nodeclass_hash.reconcile()
        return pool, nodeclass


def seed_instance(cloud: FakeCloud, *, instance_id: str, instance_type: str,
                  zone: str, capacity_type: str, image_id: str,
                  tags: Optional[dict] = None, launch_time: float = 0.0):
    """Place a pre-existing running instance directly into the fake cloud
    (the fleet simulator's pre-built-fleet seam). Lives here because
    testenv is the ONE sanctioned production-side importer of ``fake/``
    (tests/test_backend_contract.py) — harnesses that need synthetic
    cloud state route through it instead of importing fake themselves."""
    from .fake.cloud import Instance

    inst = Instance(
        id=instance_id, instance_type=instance_type, zone=zone,
        capacity_type=capacity_type, image_id=image_id,
        launch_time=launch_time, tags=dict(tags or {}),
    )
    with cloud._lock:
        cloud.instances[inst.id] = inst
    return inst


def new_environment(solver: Optional[Solver] = None, use_tpu_solver: bool = True,
                    zones=None, cluster_info=None) -> Environment:
    clock = FakeClock()
    # the resilience layer follows the freshest environment: breakers are
    # re-keyed onto THIS clock (stale wall-time state must never leak into
    # a virtual-clock run) and any leftover chaos dispatch hooks cleared
    from .resilience import breakers, faultgate

    breakers.configure(clock=clock)
    faultgate.clear()
    cloud = FakeCloud(clock=clock, **({"zones": zones} if zones else {}))
    queue = FakeQueue()
    catalog = CatalogProvider(clock=clock, **({"zones": zones} if zones else {}))
    cluster = Cluster(clock=clock)
    cloudprovider = CloudProvider(
        cloud,
        catalog,
        cluster,
        clock=clock,
        batcher_options=BatcherOptions(idle_timeout_s=0.001, max_timeout_s=0.05),
        cluster_info=cluster_info,
    )
    solver = solver or (TPUSolver() if use_tpu_solver else HostSolver())
    recorder = EventRecorder(clock=clock)
    # env-local observability bundle: lifecycle observer on THIS cluster,
    # SLO engine + audit ring on THIS clock/recorder (obs/)
    from . import obs as obs_mod

    obs_bundle = obs_mod.install(cluster=cluster, recorder=recorder, clock=clock)
    provisioning = ProvisioningController(cluster, solver, cloudprovider,
                                          recorder=recorder, obs=obs_bundle)
    scheduling = SchedulingController(cluster, provisioning, clock=clock)
    registration = RegistrationController(cluster, provisioning, clock=clock)
    termination = TerminationController(cluster, cloudprovider, clock=clock)
    # validation_period_s=0: specs drive single reconcile passes; the
    # window's own behavior is tested explicitly in test_disruption
    disruption = DisruptionController(cluster, cloudprovider, clock=clock,
                                      provisioning=provisioning, recorder=recorder,
                                      validation_period_s=0.0, obs=obs_bundle)
    interruption = InterruptionController(cluster, cloudprovider, queue,
                                          recorder=recorder, obs=obs_bundle)
    gc = GarbageCollectionController(cluster, cloudprovider, clock=clock)
    liveness = LivenessController(cluster, clock=clock, recorder=recorder,
                                  obs=obs_bundle)
    tagging = TaggingController(cluster, cloudprovider)
    nc_hash = NodeClassHashController(cluster)
    nc_status = NodeClassStatusController(cluster, cloudprovider)
    nc_term = NodeClassTerminationController(cluster, cloudprovider)
    manager = Manager(
        [
            nc_status,
            nc_hash,
            interruption,
            termination,
            registration,
            scheduling,
            provisioning,
            tagging,
            disruption,
            gc,
            liveness,
            nc_term,
        ],
        clock=clock,
        recorder=recorder,
    )
    return Environment(
        clock=clock,
        cloud=cloud,
        queue=queue,
        catalog=catalog,
        cluster=cluster,
        cloudprovider=cloudprovider,
        solver=solver,
        provisioning=provisioning,
        scheduling=scheduling,
        registration=registration,
        termination=termination,
        disruption=disruption,
        interruption=interruption,
        garbagecollection=gc,
        liveness=liveness,
        tagging=tagging,
        nodeclass_hash=nc_hash,
        nodeclass_status=nc_status,
        nodeclass_termination=nc_term,
        manager=manager,
        events=recorder,
        obs=obs_bundle,
    )
