"""Multi-chip distribution of the solve via jax.sharding + shard_map.

The reference's concurrency inventory (SURVEY.md section 2.3) maps here:
reconcile-loop worker pools -> data-parallel group shards over the device
mesh; the request batcher -> the single packed problem tensor; the
kube/AWS API boundaries -> host<->device transfers. Collectives ride ICI
(psum for global cost/counts), never DCN, per the sharding design in
SURVEY.md section 5 ("distributed communication backend").
"""

from .mesh import (  # noqa: F401
    make_mesh,
    merge_sharded_plan,
    screen_sharded,
    sharded_screen_fn,
    sharded_solve_fn,
    solve_sharded,
)
