"""Device-mesh solve: shard the pod-group axis, solve shards in SPMD.

Design (TPU-first): FFD is sequential over groups *within* a bin-sharing
domain, but demand at cluster scale arrives in independent slices (the
reference batches pods per provisioning loop anyway, and never shares a bin
across batches). So the mesh axis ``pods`` shards pod groups; every device
runs the identical jitted FFD scan on its shard (pure SPMD, zero per-step
communication), and a final ``psum`` aggregates cost/node counts over ICI.

``merge_sharded_plan`` then consolidates the per-shard tail nodes on the
host: the flattened cross-shard plan goes through the same packed-cost
descent the single-device solve uses (_refine_plan) — under-filled nodes
from one shard drain into another shard's slack, bounding the sharded
solve's cost overhead vs the single-device plan.

This mirrors how the reference scales: more concurrent reconciles, no shared
state inside a solve — except here "a worker" is a TPU core on the mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.ffd import ffd_solve
from ..trace.jitwatch import tracked_jit

POD_AXIS = "pods"


def shard_map_impl():
    """The runtime's shard_map entry, laddered: ``jax.shard_map`` (new
    API) when the runtime ships it, else ``jax.experimental.shard_map``
    (same semantics; the replication check is spelled ``check_rep``
    there), else ``None`` — callers fall back to ``jax.vmap`` lanes.
    Returned as a uniform ``(f, mesh, in_specs, out_specs) -> wrapped``
    so every mesh path shares ONE compatibility seam."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return lambda f, mesh, in_specs, out_specs: fn(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    try:
        from jax.experimental.shard_map import shard_map as _esm
    except Exception:
        return None
    return lambda f, mesh, in_specs, out_specs: _esm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


@functools.lru_cache(maxsize=8)
def _cached_mesh(devices: tuple, n: int) -> Mesh:
    return Mesh(np.array(devices[:n]), (POD_AXIS,))


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    # cached per device tuple: callers (and jit caches keyed on the mesh)
    # must see ONE mesh object per configuration, not a fresh one per
    # reconcile; a backend reinit (tests) changes the device tuple and
    # naturally gets a fresh entry
    devices = tuple(jax.devices())
    n = n_devices or len(devices)
    return _cached_mesh(devices, n)


@functools.lru_cache(maxsize=16)
def sharded_solve_fn(mesh: Mesh, max_nodes: int):
    """Build the jitted SPMD solve: inputs sharded on the group axis, node
    state replicated per shard, cost psum'd over ICI."""
    smap = shard_map_impl()
    if smap is None:
        raise RuntimeError("no shard_map in this jax runtime")

    @functools.partial(
        smap,
        mesh=mesh,
        in_specs=(P(POD_AXIS), P(POD_AXIS), P(POD_AXIS), P(), P(POD_AXIS),
                  P(POD_AXIS), P(), P(POD_AXIS)),
        out_specs=(P(POD_AXIS), P(POD_AXIS, None), P(POD_AXIS), P(POD_AXIS), P(),
                   P(POD_AXIS), P(POD_AXIS, None, None), P(POD_AXIS, None)),
    )
    def _solve_shard(requests, counts, compat, capacity, price,
                     group_window, type_window, max_per_node):
        res = ffd_solve(requests, counts, compat, capacity, price,
                        group_window, type_window, max_per_node=max_per_node,
                        max_nodes=max_nodes)
        live = jnp.arange(max_nodes) < res.n_open
        local_cost = jnp.where(live, res.node_price, 0.0).sum()
        total_cost = jax.lax.psum(local_cost, POD_AXIS)
        # leading axis 1 per shard -> global shape [n_shards, ...]
        return (
            res.node_type[None, :],
            res.used[None, :, :],
            res.n_open[None],
            res.unplaced[None, :],
            total_cost,
            res.node_price[None, :],
            res.node_window[None, :, :, :],
            res.placed[None, :, :],
        )

    fn = tracked_jit(_solve_shard, family="mesh.solve_shard")
    # warmup manifest builder params (trace/warmup.py): the mesh itself is
    # re-derived from the fresh process's devices via make_mesh()
    fn.warmup_params = {"max_nodes": int(max_nodes)}
    return fn


def pad_problem_for_mesh(problem, mesh: Mesh):
    """Pad the group axis to a mesh-divisible bucket (the layout contract
    shared by the solve path and the partition-evidence bench)."""
    from ..ops.encode import bucket, pad_problem

    n_dev = mesh.devices.size
    G = problem.requests.shape[0]
    GB = max(bucket(G), n_dev)
    if GB % n_dev:
        GB += n_dev - (GB % n_dev)
    return pad_problem(problem, GB)


def place_solve_args(padded, mesh: Mesh):
    """Device-put a padded problem with ``sharded_solve_fn``'s layout:
    group-axis tensors sharded, catalog tensors replicated. ONE home for
    the arg order/spec contract — the evidence bench lowers exactly what
    this places."""
    shard = NamedSharding(mesh, P(POD_AXIS))
    rep = NamedSharding(mesh, P())
    return (
        jax.device_put(jnp.asarray(padded.requests), shard),
        jax.device_put(jnp.asarray(padded.counts), shard),
        jax.device_put(jnp.asarray(padded.compat), shard),
        jax.device_put(jnp.asarray(padded.capacity), rep),
        jax.device_put(jnp.asarray(padded.price), shard),
        jax.device_put(jnp.asarray(padded.group_window), shard),
        jax.device_put(jnp.asarray(padded.type_window), rep),
        jax.device_put(jnp.asarray(padded.max_per_node), shard),
    )


def solve_sharded(problem, mesh: Mesh, max_nodes: int = 1024, full: bool = False):
    """Host entry: pad the group axis to the mesh size, place shards, solve.

    Returns (node_type [D, N], used [D, N, R], n_open [D], unplaced [G],
    total_cost) with per-device node namespaces; with ``full=True`` also
    (node_price [D, N], node_window [D, N, Z, C], placed [D, Gs, N]) for
    the cross-shard merge.
    """
    G = problem.requests.shape[0]
    padded = pad_problem_for_mesh(problem, mesh)
    fn = sharded_solve_fn(mesh, max_nodes)
    args = place_solve_args(padded, mesh)
    (node_type, used, n_open, unplaced, total_cost,
     node_price, node_window, placed) = jax.device_get(fn(*args))
    out = (
        np.asarray(node_type),
        np.asarray(used),
        np.asarray(n_open).reshape(-1),
        np.asarray(unplaced).reshape(-1)[:G],
        float(np.asarray(total_cost).reshape(-1)[0]),
    )
    if full:
        return out + (np.asarray(node_price), np.asarray(node_window), np.asarray(placed))
    return out


@functools.lru_cache(maxsize=16)
def sharded_screen_fn(mesh: Mesh):
    """Build the jitted SPMD consolidation screen: the candidate axis is
    sharded over the mesh, cluster tensors replicated — each device answers
    "remove node i, do its pods fit elsewhere?" for its slice of candidates.
    Pure SPMD (the screen reads shared state, writes disjoint lanes), so
    there is zero cross-device communication; D devices screen a 5k-node
    cluster D-ways in parallel (SURVEY.md sections 2.3 / 7.7). lru_cache:
    jax.jit caches by function identity — rebuilding the shard_map closure
    per reconcile would recompile the screen every disruption pass."""
    from ..ops.consolidate import repack_check

    smap = shard_map_impl()
    if smap is None:
        raise RuntimeError("no shard_map in this jax runtime")

    @functools.partial(
        smap,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(POD_AXIS)),
        out_specs=P(POD_AXIS),
    )
    def _screen(free, requests, gids, gcounts, cap, candidates):
        return repack_check(free, requests, gids, gcounts, cap, candidates)

    fn = tracked_jit(_screen, family="mesh.screen")
    fn.warmup_params = {}
    return fn


def place_screen_args(ct, mesh: Mesh):
    """Device-put cluster tensors with ``sharded_screen_fn``'s layout:
    cluster state replicated, the candidate axis (padded to a mesh
    multiple; padded lanes re-screen node 0 and are discarded) sharded.
    Shared by the screen path and the partition-evidence bench."""
    from ..ops.consolidate import live_slot_width, screen_cap_wire

    S = live_slot_width(ct.group_counts)
    N = len(ct.node_names)
    D = mesh.devices.size
    NB = N if N % D == 0 else N + (D - N % D)
    cand = np.zeros(NB, dtype=np.int32)
    cand[:N] = np.arange(N, dtype=np.int32)
    shard = NamedSharding(mesh, P(POD_AXIS))
    rep = NamedSharding(mesh, P())
    return (
        jax.device_put(jnp.asarray(ct.free), rep),
        jax.device_put(jnp.asarray(ct.requests), rep),
        # slot axis sliced to the live width (see consolidate.live_slot_width
        # — semantics-exact; GMAX padding was 4-32x wasted slot work)
        jax.device_put(jnp.asarray(ct.group_ids[:, :S]), rep),
        jax.device_put(jnp.asarray(ct.group_counts[:, :S]), rep),
        jax.device_put(jnp.asarray(screen_cap_wire(ct)), rep),
        jax.device_put(jnp.asarray(cand), shard),
    )


def screen_lanes_per_device(n_nodes: int, n_resources: int) -> int:
    """Per-device lane budget for one screen dispatch: each lane's scan
    carries a [N, R] free matrix, so unchunked lanes materialize a
    [lanes, N, R] f32 intermediate per step — at 5k nodes and 625
    lanes/device that is ~110MB PER DEVICE and was the
    `multichip_8dev_5000node_screen` 20s cliff. KARPENTER_TPU_MESH_LANE_BYTES
    (default 32MiB, read per call like every sibling knob) caps that
    intermediate; lanes beyond it run as extra dispatches of the same
    compiled program (stable shapes — one compile per cluster size)."""
    import os

    budget = int(os.environ.get("KARPENTER_TPU_MESH_LANE_BYTES", 32 << 20))
    per_lane = max(n_nodes * n_resources * 4, 1)
    return max(16, budget // per_lane)


#: Measured per-mode screen cost on the CPU virtual mesh, keyed by
#: node-count bucket: {bucket: {"native": best_ms, "mesh": best_ms}}. The
#: PR 3 threshold picked native-vs-mesh by node count alone, and the cliff
#: moved with it: at 500 nodes (under the 1024 floor) the 8-way-sharded
#: virtual mesh measured 819ms where the native kernel answers in ~3ms —
#: an inversion against the 5k row's 28ms. Cost, not scale, decides now.
_SCREEN_MODE_COST: dict[int, dict[str, float]] = {}
_LAST_SCREEN_MODE = {"mode": ""}


def last_screen_mode() -> str:
    """The mode the most recent ``screen_sharded`` call actually ran
    ("native-fallback" | "mesh-chunked") — bench rows stamp it."""
    return _LAST_SCREEN_MODE["mode"]


def _screen_bucket(n: int) -> int:
    b = 64
    while b < n:
        b *= 2
    return b


def _pick_screen_mode(n: int, explore_bound: int) -> str:
    """Choose native vs mesh-chunked from MEASURED per-mode cost.

    The un-measured mode is explored once per node bucket, but only while
    its worst case is bounded: native is always cheap to try; the chunked
    mesh path is only explored under ``explore_bound`` nodes (above it the
    known O(N^2)-ish virtual-mesh cliff — 20s at 5k nodes — must never be
    paid in serving just to learn it is slow). KARPENTER_TPU_MESH_SCREEN_MODE
    pins a mode outright (tests / operators)."""
    import os

    pinned = os.environ.get("KARPENTER_TPU_MESH_SCREEN_MODE")
    if pinned in ("native", "mesh"):
        return pinned
    costs = _SCREEN_MODE_COST.setdefault(_screen_bucket(n), {})
    if "native" not in costs:
        return "native"
    if "mesh" not in costs and n < explore_bound:
        return "mesh"
    return min(costs, key=costs.get)


def screen_sharded(ct, mesh: Mesh, lanes_per_device: Optional[int] = None) -> np.ndarray:
    """Mesh-parallel ``consolidatable``: can_delete[N] with the candidate
    axis split across the mesh devices. Exact same semantics as the
    single-device screen (consolidate.consolidatable) — the blocked mask and
    the hostname-headroom cap ride along unchanged.

    The candidate axis is CHUNKED to ``lanes_per_device`` lanes per dispatch
    (auto-sized via KARPENTER_TPU_MESH_LANE_BYTES) so per-device memory stays flat
    as the cluster grows. On a CPU (virtual) mesh, where D-way sharding of
    one host's cores is pure overhead, the C++ screen substitutes whenever
    it is available, the cluster carries no hostname caps (the native
    kernel screens compat only), and MEASURED per-mode cost says it wins —
    both modes are timed per node bucket (the expensive mesh explore is
    bounded to small clusters, KARPENTER_TPU_MESH_SCREEN_NATIVE_N) and the
    cheaper one is pinned, so neither the 5k-node 20s virtual-mesh cliff nor
    the 500-node 819ms inversion can recur from a scale threshold alone."""
    import logging
    import os
    import time as _time

    N = len(ct.node_names)
    is_cpu_mesh = all(d.platform == "cpu" for d in mesh.devices.flat)
    explore_bound = int(os.environ.get("KARPENTER_TPU_MESH_SCREEN_NATIVE_N", 1024))
    mode_costs = _SCREEN_MODE_COST.setdefault(_screen_bucket(N), {})
    native_eligible = is_cpu_mesh and not ct.has_topology()
    mode = (
        _pick_screen_mode(N, explore_bound) if native_eligible else "mesh"
    )
    t_mode = _time.perf_counter()
    if mode == "native":
        try:
            out = _native_screen(ct, N)
            ms = (_time.perf_counter() - t_mode) * 1e3
            mode_costs["native"] = min(mode_costs.get("native", ms), ms)
            _LAST_SCREEN_MODE["mode"] = "native-fallback"
            return out
        except Exception as e:
            # no native build: the chunked mesh path still answers, but say
            # so — silently re-entering the O(N^2) CPU path at 5k nodes is
            # the 20s cliff this fallback exists to avoid. An unusable
            # kernel must also lose every future cost comparison.
            mode_costs["native"] = float("inf")
            logging.getLogger("karpenter.tpu.mesh").warning(
                "native screen fallback unavailable on the cpu mesh; "
                "using the chunked mesh screen: %s: %s",
                type(e).__name__, e,
            )
    t_mode = _time.perf_counter()
    try:
        out = _mesh_screen(ct, mesh, lanes_per_device, N)
    except Exception as e:
        # a broken mesh path (e.g. no jax.shard_map in this runtime) loses
        # every future comparison; serve via the native kernel when the
        # cluster allows it instead of failing the sweep
        mode_costs["mesh"] = float("inf")
        if not native_eligible or mode_costs.get("native") == float("inf"):
            raise
        logging.getLogger("karpenter.tpu.mesh").warning(
            "chunked mesh screen unavailable; using the native kernel: "
            "%s: %s", type(e).__name__, e,
        )
        out = _native_screen(ct, N)
        _LAST_SCREEN_MODE["mode"] = "native-fallback"
        return out
    if is_cpu_mesh:
        ms = (_time.perf_counter() - t_mode) * 1e3
        mode_costs["mesh"] = min(mode_costs.get("mesh", ms), ms)
    _LAST_SCREEN_MODE["mode"] = "mesh-chunked"
    return out


def _native_screen(ct, N: int) -> np.ndarray:
    from ..ops.consolidate import live_slot_width, native_screen_prefilter
    from ..scheduling.native import repack_check_native

    S = live_slot_width(ct.group_counts)
    gids_s = ct.group_ids[:, :S]
    gcounts_s = ct.group_counts[:, :S]
    # same triage as the single-device native path (ops/consolidate.py):
    # vectorized necessary-condition prune + exact single-group accept;
    # the O(C x N) kernel only sees multi-group candidates
    out, cand = native_screen_prefilter(ct, gids_s, gcounts_s)
    if len(cand):
        out[cand] = np.asarray(repack_check_native(
            ct.free, ct.requests, gids_s[cand], gcounts_s[cand],
            ct.compat, cand,
        ), dtype=bool)
    out &= ~ct.blocked
    return out


def _mesh_screen(ct, mesh: Mesh, lanes_per_device: Optional[int], N: int) -> np.ndarray:
    from ..ops.consolidate import live_slot_width, screen_cap_wire

    D = mesh.devices.size
    lanes = lanes_per_device or screen_lanes_per_device(N, ct.free.shape[1])
    chunk = lanes * D
    if chunk >= N:
        fn = sharded_screen_fn(mesh)
        ok = jax.device_get(fn(*place_screen_args(ct, mesh)))
        out = np.asarray(ok)[:N].copy()
        out &= ~ct.blocked
        return out
    S = live_slot_width(ct.group_counts)
    shard = NamedSharding(mesh, P(POD_AXIS))
    rep = NamedSharding(mesh, P())
    free = jax.device_put(jnp.asarray(ct.free), rep)
    requests = jax.device_put(jnp.asarray(ct.requests), rep)
    gids = jax.device_put(jnp.asarray(ct.group_ids[:, :S]), rep)
    gcounts = jax.device_put(jnp.asarray(ct.group_counts[:, :S]), rep)
    cap = jax.device_put(jnp.asarray(screen_cap_wire(ct)), rep)
    fn = sharded_screen_fn(mesh)
    out = np.zeros(N, dtype=bool)
    for start in range(0, N, chunk):
        idx = np.arange(start, min(start + chunk, N), dtype=np.int32)
        cand = np.zeros(chunk, dtype=np.int32)  # fixed shape: one compile
        cand[: len(idx)] = idx
        cand_dev = jax.device_put(jnp.asarray(cand), shard)
        ok = np.asarray(jax.device_get(
            fn(free, requests, gids, gcounts, cap, cand_dev)
        ))
        out[idx] = ok[: len(idx)]
    out &= ~ct.blocked
    return out


# -- partition lanes: K independent FFD problems in ONE device program ------

def lanes_mode() -> str:
    """How partition lanes run here: ``shard_map`` (lane axis sharded over
    the device mesh) on multi-device runtimes that expose one — the new
    ``jax.shard_map`` API or the experimental module (see
    :func:`shard_map_impl`) — else ``vmap`` (single-program vmapped lanes,
    the native fallback)."""
    try:
        if shard_map_impl() is not None and len(jax.devices()) > 1:
            return "shard_map"
    except Exception:
        pass
    return "vmap"


def _lane_body(max_nodes: int):
    from ..ops.ffd import _ffd_solve_impl

    def one(requests, counts, compat, capacity, price, gw, tw, mpn, state,
            n_pre):
        return _ffd_solve_impl(
            requests, counts, compat, capacity, price, gw, tw,
            max_per_node=mpn, max_nodes=max_nodes, init_state=state,
            n_pre=n_pre,
        )

    return one


@functools.lru_cache(maxsize=8)
def _lanes_vmap_fn(max_nodes: int):
    fn = tracked_jit(jax.vmap(_lane_body(max_nodes)), family="mesh.lanes")
    fn.warmup_params = {"max_nodes": int(max_nodes)}
    return fn


@functools.lru_cache(maxsize=8)
def _lanes_shard_fn(mesh: Mesh, max_nodes: int):
    """Lane axis sharded over the device mesh: each device runs its K/D
    lanes through the identical vmapped scan (pure SPMD, no cross-device
    communication — independent partitions share nothing inside a solve)."""
    smap = shard_map_impl()
    if smap is None:
        raise RuntimeError("no shard_map in this jax runtime")
    fn = smap(
        jax.vmap(_lane_body(max_nodes)),
        mesh,
        P(POD_AXIS),
        P(POD_AXIS),
    )
    wrapped = tracked_jit(fn, family="mesh.lanes_shard")
    wrapped.warmup_params = {"max_nodes": int(max_nodes)}
    return wrapped


def stack_lane_problems(padded_list):
    """Stack K group-padded ``EncodedProblem``s onto a leading lane axis
    with common type/zone buckets. Padded types are structurally unusable
    (compat False, price inf, dead offering windows) and padded zones carry
    no offerings, so every lane solves exactly its own problem; committed
    type indices stay valid in each lane's ORIGINAL axis (padding appends).
    Returns (args dict of stacked numpy arrays, (TB, ZB))."""
    TB = max(p.capacity.shape[0] for p in padded_list)
    ZB = max(p.group_window.shape[1] for p in padded_list)

    def padTZ(a, t_axis=None, z_axis=None, fill=0):
        widths = [(0, 0)] * a.ndim
        if t_axis is not None:
            widths[t_axis] = (0, TB - a.shape[t_axis])
        if z_axis is not None:
            widths[z_axis] = (0, ZB - a.shape[z_axis])
        if not any(w != (0, 0) for w in widths):
            return a
        return np.pad(a, widths, constant_values=fill)

    args = {
        "requests": np.stack([p.requests for p in padded_list]),
        "counts": np.stack([p.counts for p in padded_list]),
        "compat": np.stack([padTZ(p.compat, t_axis=1) for p in padded_list]),
        "capacity": np.stack([padTZ(p.capacity, t_axis=0) for p in padded_list]),
        "price": np.stack(
            [padTZ(p.price, t_axis=1, fill=np.inf) for p in padded_list]
        ),
        "group_window": np.stack(
            [padTZ(p.group_window, z_axis=1) for p in padded_list]
        ),
        "type_window": np.stack(
            [padTZ(p.type_window, t_axis=0, z_axis=1) for p in padded_list]
        ),
        "max_per_node": np.stack([p.max_per_node for p in padded_list]),
    }
    return args, (TB, ZB)


def solve_partition_lanes(args, init_state, n_pres, max_nodes: int,
                          dput=None, mode: Optional[str] = None):
    """Run K stacked lanes through one jitted program; returns the batched
    (leading lane axis) ``FFDResult`` of device arrays — the caller slices
    per lane and fetches once. ``init_state`` is a batched ``ops.ffd._State``
    (pre-opened existing rows per lane), ``n_pres`` the per-lane pre-row
    counts. ``mode`` pins shard_map/vmap (default: :func:`lanes_mode`);
    shard_map pads the lane axis to a device multiple with inert lanes."""
    import jax.numpy as jnp

    from ..ops.ffd import _State

    dput = dput or (lambda x: jnp.asarray(x))
    mode = mode or lanes_mode()
    K = args["requests"].shape[0]
    Kp = K
    if mode == "shard_map":
        D = len(jax.devices())
        if K % D:
            Kp = K + (D - K % D)

    def lane_pad(a):
        if Kp == K:
            return a
        widths = [(0, Kp - K)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths)

    arrs = tuple(
        dput(lane_pad(np.ascontiguousarray(args[k])))
        for k in ("requests", "counts", "compat", "capacity", "price",
                  "group_window", "type_window", "max_per_node")
    )
    state = _State(*(dput(lane_pad(np.asarray(f))) for f in init_state))
    n_pre = dput(lane_pad(np.asarray(n_pres, dtype=np.int32)))
    if mode == "shard_map":
        fn = _lanes_shard_fn(make_mesh(), max_nodes)
    else:
        fn = _lanes_vmap_fn(max_nodes)
    res = fn(*arrs, state, n_pre)
    if Kp != K:
        res = jax.tree_util.tree_map(lambda a: a[:K], res)
    # the device-resident stacked inputs ride along so callers can slice
    # per-lane views (post-scan ranking) without re-uploading anything
    dev_args = dict(zip(
        ("requests", "counts", "compat", "capacity", "price",
         "group_window", "type_window", "max_per_node"), arrs,
    ))
    return res, dev_args


def merge_partition_plans(problems, lane_plans, max_tries: int = 512,
                          util_threshold: float = 0.97):
    """Cross-partition plan merge: flatten per-partition lane plans into
    one global node namespace and run the packed-cost descent over the
    CONCATENATED group axis — exactly the multi-pool merge
    (:func:`merge_sharded_plan`): an under-filled tail node from one
    partition drains into another partition's slack whenever group
    compatibility, windows, and hostname caps allow.

    ``problems`` must share type/zone axes (partitions of one pool do);
    ``lane_plans`` are per-lane dicts with node_type/node_price/used/
    node_window/placed/n_open in host numpy. Returns the merged plan dict
    with cost_lanes / cost_merged.
    """
    import dataclasses

    from ..scheduling.solver import _refine_plan

    first = problems[0]
    for p in problems[1:]:
        if p.zones != first.zones:
            raise ValueError("merge_partition_plans needs a shared zone axis")
    # Union type axis by NAME: per-problem type-axis compaction keeps only
    # types with live offerings inside that problem's window, so two zone
    # partitions of one pool legitimately carry different type axes.
    union: list = list(first.type_names)
    uidx = {n: i for i, n in enumerate(union)}
    for p in problems[1:]:
        for n in p.type_names:
            if n not in uidx:
                uidx[n] = len(union)
                union.append(n)
    T = len(union)
    R = first.capacity.shape[1]
    Z, C = first.group_window.shape[1], first.group_window.shape[2]
    capacity = np.zeros((T, R), dtype=first.capacity.dtype)
    type_window = np.zeros((T, Z, C), dtype=bool)
    type_exotic = np.zeros(T, dtype=bool)
    tmaps = []
    for p in problems:
        tmap = np.array([uidx[n] for n in p.type_names], dtype=np.int64)
        tmaps.append(tmap)
        capacity[tmap] = p.capacity
        type_window[tmap] |= p.type_window
        if p.type_exotic is not None:
            type_exotic[tmap] |= p.type_exotic
    Gs = [len(p.group_pods) for p in problems]
    G = sum(Gs)

    def remapT(p, tmap, a, fill):
        out = np.full((a.shape[0], T), fill, dtype=a.dtype)
        out[:, tmap] = a
        return out

    combined = dataclasses.replace(
        first,
        requests=np.concatenate([p.requests[: len(p.group_pods)] for p in problems]),
        counts=np.concatenate([p.counts[: len(p.group_pods)] for p in problems]),
        compat=np.concatenate([
            remapT(p, tm, p.compat[: len(p.group_pods)], False)
            for p, tm in zip(problems, tmaps)
        ]),
        price=np.concatenate([
            remapT(p, tm, p.price[: len(p.group_pods)], np.inf)
            for p, tm in zip(problems, tmaps)
        ]),
        capacity=capacity,
        type_window=type_window,
        type_exotic=type_exotic,
        type_names=tuple(union),
        group_window=np.concatenate(
            [p.group_window[: len(p.group_pods)] for p in problems]
        ),
        max_per_node=np.concatenate(
            [p.max_per_node[: len(p.group_pods)] for p in problems]
        ),
        group_pods=[pl for p in problems for pl in p.group_pods],
        atomic=(
            np.concatenate([
                (p.atomic[: len(p.group_pods)] if p.atomic is not None
                 else np.zeros(len(p.group_pods), dtype=bool))
                for p in problems
            ])
            if any(p.atomic is not None for p in problems) else None
        ),
    )
    n_opens = [int(pl["n_open"]) for pl in lane_plans]
    offsets = np.concatenate([[0], np.cumsum(n_opens)]).astype(int)
    M = int(offsets[-1])
    m_type = np.zeros(M, dtype=np.int64)
    m_price = np.zeros(M, dtype=np.float32)
    m_used = np.zeros((M, R), dtype=np.float32)
    m_window = np.zeros((M, Z, C), dtype=bool)
    m_placed = np.zeros((G, M), dtype=np.int64)
    g_off = 0
    for k, (p, pl) in enumerate(zip(problems, lane_plans)):
        lo, hi = offsets[k], offsets[k + 1]
        n = hi - lo
        m_type[lo:hi] = tmaps[k][np.asarray(pl["node_type"][:n], dtype=np.int64)]
        m_price[lo:hi] = pl["node_price"][:n]
        m_used[lo:hi] = pl["used"][:n]
        m_window[lo:hi] = pl["node_window"][:n, :Z]
        m_placed[g_off:g_off + Gs[k], lo:hi] = pl["placed"][: Gs[k], :n]
        g_off += Gs[k]
    cost_lanes = float(m_price.sum())
    dropped, _ = _refine_plan(
        combined, m_type, m_price, m_used, m_window, m_placed, M,
        max_tries=max_tries, util_threshold=util_threshold,
    )
    cost_merged = float(np.where(~dropped, m_price, 0.0).sum())
    return {
        "node_type": m_type,
        "node_price": m_price,
        "used": m_used,
        "node_window": m_window,
        "placed": m_placed,
        "n_open": M,
        "dropped": dropped,
        "cost_lanes": cost_lanes,
        "cost_merged": cost_merged,
    }


def merge_sharded_plan(problem, mesh: Mesh, max_nodes: int = 1024):
    """Sharded solve + the promised cross-shard tail-node merge.

    Flattens the per-device plans into one global node list and runs the
    single-device packed-cost descent (scheduling.solver._refine_plan) over
    it: an under-filled tail node from shard A drains into shard B's slack
    whenever group compatibility, windows, and hostname caps allow — so the
    merged cost is <= the raw sharded cost, closing most of the gap to the
    single-device plan.

    Returns a dict with the merged plan (node_type, node_price, used,
    node_window, placed [G, M], n_open) plus unplaced, cost_sharded, and
    cost_merged.
    """
    from ..scheduling.solver import _refine_plan

    D = mesh.devices.size
    (node_type, used, n_open, unplaced, cost_sharded,
     node_price, node_window, placed) = solve_sharded(
        problem, mesh, max_nodes=max_nodes, full=True
    )
    G = problem.requests.shape[0]
    Gs = placed.shape[1]          # groups per shard (padded // D)
    # compact: concatenate each shard's live rows into one global namespace
    offsets = np.concatenate([[0], np.cumsum(n_open)]).astype(int)
    M = int(offsets[-1])
    R = used.shape[2]
    Z, C = node_window.shape[2], node_window.shape[3]
    m_type = np.zeros(M, dtype=node_type.dtype)
    m_price = np.zeros(M, dtype=np.float32)
    m_used = np.zeros((M, R), dtype=np.float32)
    m_window = np.zeros((M, Z, C), dtype=bool)
    m_placed = np.zeros((max(G, Gs * D), M), dtype=placed.dtype)
    for d in range(D):
        lo, hi = offsets[d], offsets[d + 1]
        k = hi - lo
        m_type[lo:hi] = node_type[d, :k]
        m_price[lo:hi] = node_price[d, :k]
        m_used[lo:hi] = used[d, :k]
        m_window[lo:hi] = node_window[d, :k]
        # shard d owns global group rows [d*Gs, (d+1)*Gs)
        m_placed[d * Gs:(d + 1) * Gs, lo:hi] = placed[d, :, :k]
    # A merge pass is once-per-reconcile, not per-solve: spend a bigger
    # descent budget than the in-solve refine, and admit nearly-full nodes
    # as candidates (0.97) — shard tails often pack to ~0.9+ and still
    # drain into another shard's slack.
    dropped, _ = _refine_plan(
        problem, m_type, m_price, m_used, m_window, m_placed, M,
        max_tries=512, util_threshold=0.97,
    )
    cost_merged = float(np.where(~dropped, m_price, 0.0).sum())
    return {
        "node_type": m_type,
        "node_price": m_price,
        "used": m_used,
        "node_window": m_window,
        "placed": m_placed[:G],
        "n_open": M,
        "dropped": dropped,
        "unplaced": unplaced,
        "cost_sharded": float(cost_sharded),
        "cost_merged": cost_merged,
    }
