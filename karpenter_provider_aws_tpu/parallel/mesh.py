"""Device-mesh solve: shard the pod-group axis, solve shards in SPMD.

Design (TPU-first): FFD is sequential over groups *within* a bin-sharing
domain, but demand at cluster scale arrives in independent slices (the
reference batches pods per provisioning loop anyway, and never shares a bin
across batches). So the mesh axis ``pods`` shards pod groups; every device
runs the identical jitted FFD scan on its shard (pure SPMD, zero per-step
communication), and a final ``psum`` aggregates cost/node counts over ICI.
The host merge pass can then consolidate partially-filled tail nodes, which
is exactly the consolidation simulator's job (ops/consolidate.py).

This mirrors how the reference scales: more concurrent reconciles, no shared
state inside a solve — except here "a worker" is a TPU core on the mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.ffd import ffd_solve

POD_AXIS = "pods"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(np.array(devices[:n]), (POD_AXIS,))


def sharded_solve_fn(mesh: Mesh, max_nodes: int):
    """Build the jitted SPMD solve: inputs sharded on the group axis, node
    state replicated per shard, cost psum'd over ICI."""

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(POD_AXIS), P(POD_AXIS), P(POD_AXIS), P(), P(POD_AXIS),
                  P(POD_AXIS), P(), P(POD_AXIS)),
        out_specs=(P(POD_AXIS), P(POD_AXIS, None), P(POD_AXIS), P(POD_AXIS), P()),
        check_vma=False,
    )
    def _solve_shard(requests, counts, compat, capacity, price,
                     group_window, type_window, max_per_node):
        res = ffd_solve(requests, counts, compat, capacity, price,
                        group_window, type_window, max_per_node=max_per_node,
                        max_nodes=max_nodes)
        live = jnp.arange(max_nodes) < res.n_open
        local_cost = jnp.where(live, res.node_price, 0.0).sum()
        total_cost = jax.lax.psum(local_cost, POD_AXIS)
        # leading axis 1 per shard -> global shape [n_shards, ...]
        return (
            res.node_type[None, :],
            res.used[None, :, :],
            res.n_open[None],
            res.unplaced[None, :],
            total_cost,
        )

    return jax.jit(_solve_shard)


def solve_sharded(problem, mesh: Mesh, max_nodes: int = 1024):
    """Host entry: pad the group axis to the mesh size, place shards, solve.

    Returns (node_type [D, N], used [D, N, R], n_open [D], unplaced [G],
    total_cost) with per-device node namespaces.
    """
    from ..ops.encode import bucket, pad_problem

    n_dev = mesh.devices.size
    G = problem.requests.shape[0]
    GB = max(bucket(G), n_dev)
    if GB % n_dev:
        GB += n_dev - (GB % n_dev)
    padded = pad_problem(problem, GB)

    fn = sharded_solve_fn(mesh, max_nodes)
    shard = NamedSharding(mesh, P(POD_AXIS))
    rep = NamedSharding(mesh, P())
    args = (
        jax.device_put(jnp.asarray(padded.requests), shard),
        jax.device_put(jnp.asarray(padded.counts), shard),
        jax.device_put(jnp.asarray(padded.compat), shard),
        jax.device_put(jnp.asarray(padded.capacity), rep),
        jax.device_put(jnp.asarray(padded.price), shard),
        jax.device_put(jnp.asarray(padded.group_window), shard),
        jax.device_put(jnp.asarray(padded.type_window), rep),
        jax.device_put(jnp.asarray(padded.max_per_node), shard),
    )
    node_type, used, n_open, unplaced, total_cost = fn(*args)
    return (
        np.asarray(node_type),
        np.asarray(used),
        np.asarray(n_open).reshape(-1),
        np.asarray(unplaced).reshape(-1)[:G],
        float(total_cost),
    )
