"""Circuit breakers: bounded memory for failing dependencies.

The reference control plane survives brownouts because the SDK bounds
every retry ladder and controller-runtime requeues failing reconciles;
what neither gives you is MEMORY — a broken dependency (a Pallas kernel
hitting a Mosaic gap, a wedged sidecar, a throttling AWS service) is
re-attempted at full failure latency on every pass. A ``CircuitBreaker``
closes that hole with the classic three-state machine:

- ``closed``    — traffic flows; consecutive failures are counted and
                  reset on any success.
- ``open``      — after ``failure_threshold`` consecutive failures the
                  breaker trips: callers are refused instantly (no
                  failure latency paid) until ``recovery_s`` has elapsed
                  on the injected clock.
- ``half-open`` — after the recovery window ONE probe call is admitted;
                  its outcome decides: success -> closed, failure ->
                  open again (with a fresh recovery window). Concurrent
                  callers during the probe are refused — the single-probe
                  token is handed out under the lock.

Determinism contract: time comes from the injectable clock (FakeClock-
compatible), state changes happen only on ``allow`` / ``record_*`` calls
— never on a background thread — so chaos runs stepping virtual time get
byte-identical transition sequences per seed.

Every breaker exports its state to ``karpenter_circuit_state{name}``
(0 = closed, 1 = half-open, 2 = open) and each transition to
``karpenter_circuit_transitions_total{name,to}``. Keyed instances live
in a ``BreakerRegistry``; the process-wide default (``resilience.
breakers``) is re-pointed at each hermetic environment's clock by
``testenv.new_environment`` so breaker state can never leak stale wall
time into a virtual-clock run.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional

from ..utils.clock import Clock, RealClock

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

# gauge encoding: ordered by "how broken" so dashboards can max() over it
STATE_VALUE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}

DEFAULT_FAILURE_THRESHOLD = 3
DEFAULT_RECOVERY_S = 30.0


class BreakerOpen(RuntimeError):
    """Raised (or signalled) when a call is refused by an open breaker."""

    def __init__(self, name: str):
        super().__init__(f"circuit breaker {name!r} is open")
        self.breaker_name = name


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class CircuitBreaker:
    """closed -> open -> half-open state machine with an injectable clock.

    Integration contract: call ``allow()`` immediately before attempting
    the dependency (it consumes the half-open probe token), then exactly
    one of ``record_success()`` / ``record_failure()`` with the outcome.
    ``available()`` is the non-consuming peek for routing decisions
    ("would a call be admitted?") — it never changes state.
    """

    def __init__(
        self,
        name: str,
        clock: Optional[Clock] = None,
        failure_threshold: Optional[int] = None,
        recovery_s: Optional[float] = None,
    ):
        self.name = name
        self._clock = clock or RealClock()
        self.failure_threshold = failure_threshold or _env_int(
            "KARPENTER_TPU_BREAKER_THRESHOLD", DEFAULT_FAILURE_THRESHOLD
        )
        self.recovery_s = (
            recovery_s
            if recovery_s is not None
            else _env_float("KARPENTER_TPU_BREAKER_RECOVERY_S", DEFAULT_RECOVERY_S)
        )
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self.last_error = ""
        # bounded (t, to_state) history — what tests and /debug/health read
        self.history: list[tuple[float, str]] = []
        self._publish(CLOSED, transition=False)

    # -- state machine -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def is_open(self) -> bool:
        return self.state == OPEN

    def available(self) -> bool:
        """Would a call be admitted right now? Never mutates state (an
        open breaker past its recovery window answers True — the actual
        ``allow()`` performs the open -> half-open transition)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return self._clock.now() - self._opened_at >= self.recovery_s
            return not self._probe_inflight

    def allow(self) -> bool:
        """Admission check; consumes the single half-open probe token."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock.now() - self._opened_at < self.recovery_s:
                    return False
                self._transition(HALF_OPEN)
                self._probe_inflight = True
                return True
            # HALF_OPEN: exactly one concurrent probe
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def release(self) -> None:
        """Hand back an admitted probe without a verdict (the attempt
        never reached the dependency — e.g. a credential failure before
        the wire). State is unchanged; a half-open probe slot reopens."""
        with self._lock:
            self._probe_inflight = False

    def record_failure(self, error: Optional[BaseException] = None) -> None:
        with self._lock:
            if error is not None:
                self.last_error = f"{type(error).__name__}: {error}"[:200]
            self._probe_inflight = False
            if self._state == HALF_OPEN:
                # failed probe: re-arm a fresh recovery window
                self._opened_at = self._clock.now()
                self._transition(OPEN)
            elif self._state == CLOSED:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    self._opened_at = self._clock.now()
                    self._transition(OPEN)
            else:
                # failures reported while already open (e.g. a racing
                # caller that was admitted just before the trip) refresh
                # the recovery window
                self._opened_at = self._clock.now()

    def guard(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` through the breaker: raises ``BreakerOpen`` when
        refused, records the outcome otherwise."""
        if not self.allow():
            raise BreakerOpen(self.name)
        try:
            out = fn(*args, **kwargs)
        except Exception as e:
            self.record_failure(e)
            raise
        self.record_success()
        return out

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "failure_threshold": self.failure_threshold,
                "recovery_s": self.recovery_s,
                "opened_at": self._opened_at if self._state != CLOSED else None,
                "last_error": self.last_error,
                "transitions": len(self.history),
            }

    # -- internals ---------------------------------------------------------

    def _transition(self, to: str) -> None:
        # lock held by caller
        self._state = to
        self.history.append((self._clock.now(), to))
        del self.history[:-64]
        self._publish(to, transition=True)

    def _publish(self, state: str, transition: bool) -> None:
        try:
            from ..metrics import CIRCUIT_STATE, CIRCUIT_TRANSITIONS

            CIRCUIT_STATE.set(STATE_VALUE[state], name=self.name)
            if transition:
                CIRCUIT_TRANSITIONS.inc(name=self.name, to=state)
        except Exception:
            pass  # telemetry must never take down the guarded path


class BreakerRegistry:
    """Keyed breaker instances sharing one clock (``solver.pallas``,
    ``solver.xla-scan``, ``solver.mesh``, ``solver.sidecar``,
    ``aws.<service>``, ...). ``configure(clock=...)`` drops all state and
    re-points the clock — a fresh hermetic environment owns the registry
    the same way it owns the /debug pages."""

    def __init__(self, clock: Optional[Clock] = None):
        self._clock = clock or RealClock()
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    def configure(self, clock: Optional[Clock] = None) -> None:
        with self._lock:
            if clock is not None:
                self._clock = clock
            self._breakers.clear()

    reset = configure

    def get(self, name: str, **kwargs) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(name)
            if br is None:
                br = CircuitBreaker(name, clock=self._clock, **kwargs)
                self._breakers[name] = br
            return br

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._breakers)

    def snapshot(self) -> dict:
        with self._lock:
            items = list(self._breakers.items())
        return {name: br.snapshot() for name, br in sorted(items)}


# the process-wide default registry (solver backends, controllers, the
# operator's AWS session); hermetic environments re-configure it onto
# their FakeClock at construction
breakers = BreakerRegistry()
