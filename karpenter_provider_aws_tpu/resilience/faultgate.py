"""The solver-dispatch fault seam.

Device backends fail through runtime machinery (Mosaic lowering, a
wedged TPU tunnel, a dead sidecar process) that hermetic tests cannot
reach. This gate is the injection point: the solver calls
``check(<backend>)`` immediately before running a device backend, and an
installed hook may raise — the chaos ``DeviceLost`` fault uses it to
simulate device loss deterministically (seeded, clock-driven), which the
breaker + degraded-mode path must then absorb.

Empty-gate cost is one truthiness test on a module list — nothing on the
warm no-fault path (the <0.1 ms breaker-check budget covers it with
orders of magnitude to spare).
"""

from __future__ import annotations

from typing import Callable

Hook = Callable[[str], None]


class DeviceLostError(RuntimeError):
    """A (simulated or real) device-runtime loss at the dispatch seam."""


_hooks: list[Hook] = []


def install(hook: Hook) -> Hook:
    _hooks.append(hook)
    return hook


def remove(hook: Hook) -> None:
    if hook in _hooks:
        _hooks.remove(hook)


def clear() -> None:
    del _hooks[:]


def active() -> bool:
    return bool(_hooks)


def check(backend: str) -> None:
    """Give every installed hook a chance to fail this dispatch. Called
    with the backend about to run ("pallas", "xla-scan", "sidecar",
    "mesh"); a hook raises to simulate the loss, returns to pass."""
    if not _hooks:
        return
    for hook in list(_hooks):
        hook(backend)
