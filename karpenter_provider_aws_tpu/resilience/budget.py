"""Per-reconcile deadline budgets, propagated ambiently.

A reconcile that is allowed to take forever starves every other control
loop behind it. The Manager opens one ``Budget`` per reconcile pass (N x
the controller's interval by default) and installs it in a thread-local
scope; the expensive seams consult it without plumbing a parameter
through every call site:

- ``SolverClient._call`` shrinks its RPC timeout to the remaining budget
  (instead of the flat 120 s default) — a solve dispatched with 4 s of
  reconcile budget left gets a 4 s deadline, not two minutes;
- ``Session._retrying`` stops its retry ladder (and Retry-After sleeps)
  when the budget is exhausted, surfacing ``retry_reason="budget"``.

Time accounting is ``max(clock elapsed, charged)``: under a RealClock the
clock dominates; under a FakeClock (or a Session with a no-op sleep) the
explicit ``charge()`` calls from skipped sleeps keep the arithmetic
honest without any wall-time dependence — deterministic under chaos.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

from ..utils.clock import Clock, RealClock

_tls = threading.local()


class Budget:
    """A monotonic deadline: ``total_s`` seconds from construction."""

    def __init__(self, total_s: float, clock: Optional[Clock] = None):
        self.total_s = float(total_s)
        self._clock = clock or RealClock()
        self._t0 = self._clock.now()
        self._charged = 0.0
        self._lock = threading.Lock()

    def charge(self, seconds: float) -> None:
        """Explicitly spend budget (for sleeps a fake clock swallows)."""
        with self._lock:
            self._charged += max(float(seconds), 0.0)

    def elapsed(self) -> float:
        with self._lock:
            charged = self._charged
        return max(self._clock.now() - self._t0, charged)

    def remaining(self) -> float:
        return max(0.0, self.total_s - self.elapsed())

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0


@contextmanager
def scope(budget: Budget):
    """Install ``budget`` as the ambient deadline for this thread."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(budget)
    try:
        yield budget
    finally:
        stack.pop()


def current() -> Optional[Budget]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def remaining() -> Optional[float]:
    """Seconds left in the ambient budget, or None when no scope is
    active (callers fall back to their own flat timeouts)."""
    b = current()
    return None if b is None else b.remaining()


def charge(seconds: float) -> None:
    """Charge the ambient budget, if any (no-op outside a scope)."""
    b = current()
    if b is not None:
        b.charge(seconds)
