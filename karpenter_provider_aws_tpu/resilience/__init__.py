"""Resilience layer: circuit breakers, deadline budgets, fault seam.

Three primitives threaded through the seams where the control plane
meets unreliable dependencies (``designs/circuit-breakers.md``):

- ``breaker``   — keyed closed/open/half-open ``CircuitBreaker`` per
  solver backend and AWS service; an open breaker is skipped instantly
  instead of re-paying the failure latency every pass, and stamps
  ``fallback="breaker:<name>"`` into solve provenance.
- ``budget``    — per-reconcile deadline budgets propagated ambiently
  into solver RPC timeouts and the AWS retry ladder.
- ``faultgate`` — the solver-dispatch fault seam the chaos ``DeviceLost``
  primitive raises through.

The capstone behavior: when every device backend's breaker is open,
provisioning degrades to the pure-host FFD path (pods keep binding) with
degraded provenance + an audit record — ``chaos/scenarios/
solver-brownout.json`` proves the full open -> half-open -> closed cycle
end to end.
"""

from . import budget, faultgate
from .breaker import (
    BreakerOpen,
    BreakerRegistry,
    CircuitBreaker,
    CLOSED,
    HALF_OPEN,
    OPEN,
    breakers,
)
from .faultgate import DeviceLostError

__all__ = [
    "Budget",
    "BreakerOpen",
    "BreakerRegistry",
    "CircuitBreaker",
    "CLOSED",
    "DeviceLostError",
    "HALF_OPEN",
    "OPEN",
    "breakers",
    "budget",
    "faultgate",
]

Budget = budget.Budget
