"""The cliff detector: sweep scale tiers, flag super-linear regressions.

``sweep`` runs the SAME trace at a ladder of fleet sizes and reduces each
run's fleet report to one tier row; ``detect_cliffs`` (a pure function —
unit-testable without running anything) compares consecutive tiers and
flags the FIRST tier where the system stops scaling linearly:

- **wall-superlinear** — driver wall per simulated hour grew faster than
  ``scale_ratio ** wall_exponent``: doubling the fleet may double the
  wall time, but a 2x fleet costing 3x wall is the next perf PR.
- **slo-burn-regression** — the worst SLO burn rate jumped past both an
  absolute floor and a multiple of the previous tier: the control plane
  is no longer keeping its promises at this size.
- **attribution-shift** — one span family's share of the wall profile
  jumped (relative AND absolute): whatever subsystem suddenly dominates
  at this tier is where the cliff lives. This is the span-level half of
  "find the cliff AND name it".

Method + thresholds are documented in ``designs/fleet-simulator.md``.
"""

from __future__ import annotations

from typing import Optional

#: defaults, chosen loose enough that measurement noise at small tiers
#: does not page and tight enough that a real N^2 blowup cannot hide
WALL_EXPONENT = 1.35          # allowed wall growth ~ scale ** exponent
WALL_FLOOR_S = 1.0            # ignore wall deltas below this (noise)
BURN_FLOOR = 1.0              # a burn below sustainable never flags
BURN_RATIO = 2.0              # ...and must at least double tier-to-tier
SHARE_JUMP_ABS = 0.10         # +10 percentage points of the profile
SHARE_JUMP_REL = 1.5          # and 1.5x its previous share


def tier_row(nodes: int, report) -> dict:
    """Reduce one fleet report to the tier metrics the detector compares."""
    wall = report.data.get("wall", {})
    att = wall.get("attribution", {})
    wall_s = wall.get("wall_s") or 0.0
    wall_ms = wall_s * 1e3
    shares: dict[str, float] = {}
    if wall_ms > 0:
        for name, cell in att.get("spans", {}).items():
            family = name.split(".", 1)[0] if "." in name else name
            # sim.controllers CONTAINS the controller.* spans; keep the
            # leaf families (controller/solve/consolidate/aws) and the
            # sim-only segments so shares don't double-count
            if family == "sim" and name != "sim.build":
                continue
            key = name if family in ("controller", "sim") else family
            shares[key] = round(
                shares.get(key, 0.0) + cell["total_ms"] / wall_ms, 4
            )
    return {
        "tier": int(nodes),
        "wall_s": round(wall_s, 3),
        "wall_per_sim_hour_s": wall.get("wall_per_sim_hour_s"),
        "slo_worst_burn": report.gate.get("slo_worst_burn", 0.0),
        "bind_p99_s": report.gate.get("pod_time_to_bind_p99_s"),
        "pending_end": report.gate.get("pending_end", 0),
        "shares": shares,
        "signature": report.signature(),
    }


def detect_cliffs(rows: list[dict],
                  wall_exponent: float = WALL_EXPONENT,
                  wall_floor_s: float = WALL_FLOOR_S,
                  burn_floor: float = BURN_FLOOR,
                  burn_ratio: float = BURN_RATIO,
                  share_jump_abs: float = SHARE_JUMP_ABS,
                  share_jump_rel: float = SHARE_JUMP_REL) -> dict:
    """Pure comparison over tier rows (sorted by ``tier`` ascending).

    Returns ``{"cliff_tier": first flagged tier or None,
    "findings": [...]}`` — each finding names the tier, the metric, and
    the evidence (previous vs current value and the allowed bound)."""
    rows = sorted(rows, key=lambda r: r["tier"])
    findings: list[dict] = []
    for prev, cur in zip(rows, rows[1:]):
        k = cur["tier"] / prev["tier"] if prev["tier"] else 1.0
        # wall growth vs scale growth
        w0 = prev.get("wall_per_sim_hour_s") or 0.0
        w1 = cur.get("wall_per_sim_hour_s") or 0.0
        bound = w0 * (k ** wall_exponent)
        if w0 > 0 and w1 - bound > wall_floor_s:
            findings.append({
                "tier": cur["tier"], "kind": "wall-superlinear",
                "detail": (
                    f"wall/sim-hour {w0:g}s -> {w1:g}s at {k:g}x scale "
                    f"(allowed <= {bound:.2f}s = prev * {k:g}^{wall_exponent})"
                ),
            })
        # SLO burn regression
        b0 = prev.get("slo_worst_burn") or 0.0
        b1 = cur.get("slo_worst_burn") or 0.0
        if b1 > burn_floor and b1 > max(b0 * burn_ratio, b0 + burn_floor):
            findings.append({
                "tier": cur["tier"], "kind": "slo-burn-regression",
                "detail": (
                    f"worst burn {b0:g} -> {b1:g} "
                    f"(floor {burn_floor:g}, ratio {burn_ratio:g}x)"
                ),
            })
        # attribution share shift
        for family in sorted(set(prev.get("shares", {}))
                             | set(cur.get("shares", {}))):
            s0 = prev.get("shares", {}).get(family, 0.0)
            s1 = cur.get("shares", {}).get(family, 0.0)
            if s1 - s0 > share_jump_abs and s1 > s0 * share_jump_rel:
                findings.append({
                    "tier": cur["tier"], "kind": "attribution-shift",
                    "detail": (
                        f"{family} share {s0:.1%} -> {s1:.1%} "
                        f"(+{share_jump_abs:.0%} abs and "
                        f"{share_jump_rel:g}x rel exceeded)"
                    ),
                })
    cliff: Optional[int] = min(
        (f["tier"] for f in findings), default=None
    )
    return {"cliff_tier": cliff, "findings": findings}


def sweep(trace, tiers, seed: int = 0, **kw) -> dict:
    """Run the trace at every tier and detect cliffs. Returns
    ``{"tiers": [tier rows], "cliff_tier": ..., "findings": [...]}``."""
    from .driver import run_trace

    rows = []
    for n in sorted(int(t) for t in tiers):
        report = run_trace(trace, seed=seed, nodes=n, **kw)
        rows.append(tier_row(n, report))
    out = detect_cliffs(rows)
    out["tiers"] = rows
    return out
