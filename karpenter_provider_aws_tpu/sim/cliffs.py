"""The cliff detector: sweep scale tiers, flag super-linear regressions.

``sweep`` runs the SAME trace at a ladder of fleet sizes and reduces each
run's fleet report to one tier row; ``detect_cliffs`` (a pure function —
unit-testable without running anything) compares consecutive tiers and
flags the FIRST tier where the system stops scaling linearly:

- **wall-superlinear** — driver wall per simulated hour grew faster than
  ``scale_ratio ** wall_exponent``: doubling the fleet may double the
  wall time, but a 2x fleet costing 3x wall is the next perf PR.
- **slo-burn-regression** — the worst SLO burn rate jumped past both an
  absolute floor and a multiple of the previous tier: the control plane
  is no longer keeping its promises at this size.
- **attribution-shift** — one span family's share of the wall profile
  jumped (relative AND absolute): whatever subsystem suddenly dominates
  at this tier is where the cliff lives. This is the span-level half of
  "find the cliff AND name it".

Method + thresholds are documented in ``designs/fleet-simulator.md``.

The pure detector and its thresholds now LIVE in ``obs/sentinel.py``
(the live steady-state sentinel shares them — one definition of
"super-linear" for the offline sweep and the on-fleet judge); this
module keeps the simulator-side halves (tier reduction + sweep) and
re-exports the names existing callers import from here.
"""

from __future__ import annotations

# re-exported for existing importers; canonical home is obs/sentinel.py
from ..obs.sentinel import (  # noqa: F401
    BURN_FLOOR,
    BURN_RATIO,
    SHARE_JUMP_ABS,
    SHARE_JUMP_REL,
    WALL_EXPONENT,
    WALL_FLOOR_S,
    detect_cliffs,
)


def tier_row(nodes: int, report) -> dict:
    """Reduce one fleet report to the tier metrics the detector compares."""
    wall = report.data.get("wall", {})
    att = wall.get("attribution", {})
    wall_s = wall.get("wall_s") or 0.0
    wall_ms = wall_s * 1e3
    shares: dict[str, float] = {}
    if wall_ms > 0:
        from ..obs.sentinel import span_family

        for name, cell in att.get("spans", {}).items():
            family = name.split(".", 1)[0] if "." in name else name
            # sim.controllers CONTAINS the controller.* spans; keep the
            # leaf families (controller/solve/consolidate/aws) and the
            # sim-only segments so shares don't double-count
            if family == "sim" and name != "sim.build":
                continue
            # jit.compile spans are nested inside their dispatching span
            # (same double-count rule the live sentinel applies); compile
            # judgment is the retrace sentinel's, not a tier share
            if family == "jit":
                continue
            key = name if family == "sim" else span_family(name)
            shares[key] = round(
                shares.get(key, 0.0) + cell["total_ms"] / wall_ms, 4
            )
    return {
        "tier": int(nodes),
        "wall_s": round(wall_s, 3),
        "wall_per_sim_hour_s": wall.get("wall_per_sim_hour_s"),
        "slo_worst_burn": report.gate.get("slo_worst_burn", 0.0),
        "bind_p99_s": report.gate.get("pod_time_to_bind_p99_s"),
        "pending_end": report.gate.get("pending_end", 0),
        "shares": shares,
        "signature": report.signature(),
    }


def sweep(trace, tiers, seed: int = 0, **kw) -> dict:
    """Run the trace at every tier and detect cliffs. Returns
    ``{"tiers": [tier rows], "cliff_tier": ..., "findings": [...]}``."""
    from .driver import run_trace

    rows = []
    for n in sorted(int(t) for t in tiers):
        report = run_trace(trace, seed=seed, nodes=n, **kw)
        rows.append(tier_row(n, report))
    out = detect_cliffs(rows)
    out["tiers"] = rows
    return out
