"""The fleet simulator: a seeded day of prod against the real controllers.

``FleetSimulator`` builds a hermetic environment (``testenv``), populates
it with an N-node fleet whose claims/nodes/bound pods all flow through the
sanctioned mutation surface AND whose instances exist in the fake cloud
(so GC, drift, tagging, and spot storms see a coherent world), then
replays a :mod:`sim.traces` event list against the FULL controller
manager on the FakeClock:

- **adaptive stepping** — a reconcile micro-burst (``burst_passes`` x
  ``burst_step_s``) right after every workload/fault event so pods bind
  at realistic virtual latencies, plus a steady ``heartbeat_s`` cadence
  between events; a quiet simulated hour costs a handful of passes, not
  3600 of them. This is what makes "a day of prod in a minute" hold.
- **chaos overlays** — fault timelines composed from ``chaos/plan.py``
  scenarios activate/deactivate at their windows through the same
  harness protocol the chaos subsystem uses (wire faults on a signed
  probe Session, cloud/queue faults on the fake cloud), and a settle
  phase + the chaos invariants close the run.
- **sub-tick SLIs** — the clock runs with sub-tick interpolation
  (``FakeClock.enable_subtick``), so fifty binds inside one pass land on
  distinct virtual timestamps and the time-to-bind histogram actually
  discriminates.
- **attribution** — every driver segment runs inside a ``sim.*`` span and
  a streaming :class:`trace.SpanAggregator` folds ALL spans (controller
  reconciles, solve phases, encode, AWS wire) into the report's
  wall-time profile; root-span totals over driver wall time state the
  profile's coverage (the acceptance bar is >= 95%).

Determinism: every random draw comes from a stream derived from the seed
(trace generation, fleet build, cloud-fault sampling, wire draws, retry
jitter), every timestamp from the FakeClock, and the report's
``signature()`` normalizes instance/claim/pod ids to per-run ordinals
(the chaos witness pattern) — two same-seed runs are byte-identical on
the report's deterministic core. Wall-clock attribution is reported
beside it but excluded from the signature by construction.
"""

from __future__ import annotations

import random
import time
from types import SimpleNamespace
from typing import Optional, Union

from ..chaos.harness import _is_wire_fault, _process_breakers
from ..chaos.invariants import check_all
from ..chaos.plan import TimedFault, compose_overlay
from ..chaos.transport import ChaosLog, ChaosTransport, StubAwsTransport
from ..models import Disruption, NodePool, Operator, Requirement
from ..models import labels as lbl
from ..models.nodeclaim import NodeClaim
from ..models.pod import make_pods
from ..providers.aws import Credentials, Ec2Client, Session
from ..providers.aws.session import CredentialError
from ..providers.aws.transport import AwsApiError
from ..testenv import new_environment
from ..trace import provenance
from ..trace.export import SpanAggregator
from ..trace.spans import TRACER, span
from ..utils.cache import CacheTTL
from .traces import Overlay, SimEvent, TraceSpec, canned_trace, generate

SETTLE_ADVANCE_S = 5.0

#: last finished run's summary — what /debug/sim serves
_LAST_RUN: dict = {}


def _debug_sim_page() -> dict:
    return _LAST_RUN or {"status": "no fleet-simulator run in this process"}


class FleetSimulator:
    """One seeded simulated day. Build, :meth:`run`, read the report."""

    def __init__(self, trace: Union[TraceSpec, str], seed: int = 0,
                 nodes: Optional[int] = None,
                 duration_s: Optional[float] = None,
                 overlays: Optional[list] = None,
                 use_tpu_solver: bool = False,
                 check_invariants: bool = True,
                 replicas: int = 1,
                 envelope_check: Optional[bool] = None):
        spec = canned_trace(trace) if isinstance(trace, str) else trace
        # private clone (data round-trip): overlay fault instances carry
        # per-run fire state, exactly like chaos scenarios
        self.trace = TraceSpec.from_dict(spec.to_dict())
        if nodes is not None:
            self.trace.nodes = int(nodes)
        if duration_s is not None:
            self.trace.duration_s = float(duration_s)
        if overlays:
            self.trace.overlays = list(self.trace.overlays) + [
                o if isinstance(o, Overlay) else Overlay.parse(o)
                for o in overlays
            ]
        self.seed = int(seed)
        self.check_invariants = check_invariants
        self.use_tpu_solver = use_tpu_solver
        # multi-replica mode: N in-process control-plane replicas over one
        # FakeClock/cluster/cloud, partition leases live (Replica* chaos
        # overlays drive the kill/pause/netsplit seams)
        self.replicas = int(replicas)
        # packing-envelope parity (designs/sharded-provisioning.md): a
        # multi-replica run first drives the SAME trace+seed on one
        # replica (Replica* faults ignored — they need a ReplicaSet) and
        # the invariant bounds this run's packing/cost against it
        self.envelope_check = (
            self.replicas > 1 if envelope_check is None else bool(envelope_check)
        )
        self.envelope: Optional[dict] = None
        self._envelope_ref: Optional[dict] = None
        # set on a reference sim so its composed overlays skip the
        # replica kill/pause/netsplit faults instead of raising
        self.ignore_replica_faults = False
        if self.replicas > 1:
            from ..testenv import new_replicaset

            self.env = new_replicaset(self.replicas,
                                      use_tpu_solver=use_tpu_solver)
        else:
            self.env = new_environment(use_tpu_solver=use_tpu_solver)
        # replica-loss recovery: armed by a Replica* overlay activation,
        # resolved at the first pass where every partition key has an
        # effective owner again — the gate thresholds the worst case
        self._loss_at: Optional[float] = None
        self.replica_recoveries: list[float] = []
        # sub-tick SLI stamps: cap stays under the smallest driver advance
        # (burst_step_s), so interpolation never crosses a tick
        self.env.clock.enable_subtick(
            resolution_s=0.001,
            cap_s=max(0.25, min(2.0, self.trace.burst_step_s * 0.5)),
        )
        # sentinels: findings are wall-time judgments (the retrace
        # sentinel's detail strings carry compile walls), so a slow CI
        # machine must never perturb the SIGNED event stream — both
        # sentinels keep judging (their findings land in the report's
        # unsigned wall plane) but publish no events here
        self.env.obs.sentinel.publish_events = False
        self.env.obs.retrace.publish_events = False
        # jitwatch warmup cursor: compiles BEFORE the trace's halfway
        # point are ladder discovery (first wave of each size bucket);
        # compiles after it are steady-state retraces — the
        # `retraces_after_warmup` gate key (wall.device plane)
        self._jit_warm_seq: Optional[int] = None
        # chaos seams (the harness protocol faults/invariants expect)
        self.log = ChaosLog()
        self.cloud_rng = random.Random(f"{self.seed}:cloud")
        self.wire = ChaosTransport(
            StubAwsTransport(), clock=self.env.clock,
            rng=random.Random(f"{self.seed}:wire"), log=self.log,
        )
        self.session = Session(
            region="us-east-1",
            credentials=Credentials("AKIDSIM", "sim-base-secret"),
            transport=self.wire,
            sleep=lambda s: None,
            now_amz=lambda: "20260804T000000Z",
            rand=random.Random(f"{self.seed}:jitter").random,
            breakers=_process_breakers(),
        )
        self._ec2 = Ec2Client(self.session)
        # audit/report state (same names the chaos invariants read)
        self.bind_events: list[tuple[str, str]] = []
        self.double_binds: list[str] = []
        # per-tenant bind samples (tenant, bound_at_s, pending_dur_s) —
        # the fairness plane's raw data (tenant_bind_p99_ratio gate key)
        self.tenant_binds: list[tuple[str, float, float]] = []
        self._id_ranks: dict[str, int] = {}
        self.active: list[TimedFault] = []
        self.probe_failures = 0
        self.probe_calls = 0
        self.settle_steps_used = 0
        self.errors_baseline = len(self.env.manager.errors)
        self.scenario = SimpleNamespace(
            name=self.trace.name,
            settle_reconciles=self.trace.settle_reconciles,
            # check_converged exempts the red-gate poison pods (they pend
            # forever by design on deliberately-starving traces)
            unschedulable_per_wave=self.trace.unschedulable_per_wave,
        )
        # market state (installed by _seed_market when the trace arms it)
        self._market_model = None
        self._market_pair = None
        # bookkeeping the report reads
        self._t = 0.0                      # virtual seconds into the trace
        self.passes = 0
        self.events_applied: dict[str, int] = {}
        self.samples: list[dict] = []
        self.quality_samples: list[float] = []   # cost_vs_oracle
        self.backend_counts: dict[str, int] = {}
        self.backend_wall_ms: dict[str, float] = {}
        self.residency_counts: dict[str, int] = {}
        self.fallback_counts: dict[str, int] = {}
        # zero-cold-start proof (designs/aot-warmup.md): the FIRST solve's
        # provenance `compiles` stamp — when the process warmed from a
        # manifest this must be 0 (the `first_solve_after_restart` gate)
        self.first_solve_compiles: Optional[int] = None
        self._first_solve_seen = False
        self._pods_by_prefix: dict[str, list[str]] = {}  # name -> pod uids
        # seen-record cursor over the process-global provenance registry:
        # id -> weakref of the record seen under that id. A bare id() set
        # is wrong — ids are addresses and get REUSED once an old run's
        # record is collected, so an id-keyed cursor silently dropped one
        # record per collision and broke the byte-identical contract; the
        # weakref disambiguates (a dead or different referent means the id
        # now names a NEW record). Pre-seeded so earlier runs/tests never
        # count into THIS run's backend/quality breakdowns.
        import weakref

        self._seen_records: dict[int, object] = {}
        for kind in ("solve", "consolidate.screen"):
            for rec in provenance._RECENT.get(kind, ()):
                self._seen_records[id(rec)] = weakref.ref(rec)
        self.invariants: list = []
        self.driver_wall_s = 0.0
        self._install_bind_audit()
        from ..metrics import REGISTRY

        REGISTRY.register_debug_page("/debug/sim", _debug_sim_page)

    # -- harness protocol (chaos faults + invariants) ------------------------

    def stable_id(self, instance_id: str) -> str:
        if instance_id not in self._id_ranks:
            self._id_ranks[instance_id] = len(self._id_ranks)
        return f"i#{self._id_ranks[instance_id]}"

    def record_cloud_fault(self, fault, detail: str = "") -> None:
        self.log.record(
            t=self.env.clock.now(), kind=fault.kind, service="cloud",
            action="inject", detail=detail or fault.describe(),
        )
        ChaosTransport._count(fault.kind)

    def active_fault_kinds(self) -> list[str]:
        return sorted({tf.fault.kind for tf in self.active})

    def _install_bind_audit(self) -> None:
        cluster = self.env.cluster
        orig_bind = cluster.bind_pod

        def audited_bind(pod_uid, node_name, now=0.0):
            pod = cluster.pods.get(pod_uid)
            if pod is not None and pod.node_name and pod.node_name != node_name:
                self.double_binds.append(
                    f"{pod.name}: {pod.node_name} -> {node_name}"
                )
            self.bind_events.append((pod_uid, node_name))
            if pod is not None:
                tenant = pod.labels.get(lbl.TENANT_LABEL, "")
                if tenant:
                    # read the pending stamp BEFORE orig_bind pops it
                    t0 = self.env.obs.sli._pod_pending.get(pod_uid)
                    t_now = self.env.clock.now()
                    self.tenant_binds.append((
                        tenant, round(t_now, 3),
                        round(max(0.0, t_now - t0), 4)
                        if t0 is not None else 0.0,
                    ))
            return orig_bind(pod_uid, node_name, now)

        cluster.bind_pod = audited_bind

    # -- fleet build ---------------------------------------------------------

    def _build_fleet(self) -> None:
        """N nodes with claims, fake-cloud instances, and bound ballast
        pods — all through the sanctioned mutation surface, so every
        downstream consumer (journals, encoders, GC, drift, storms) sees
        a coherent pre-existing fleet."""
        from ..cloudprovider.cloudprovider import MANAGED_TAG
        from ..state.cluster import Node
        from ..testenv import seed_instance

        spec = self.trace
        env = self.env
        # AOT warmup before the fleet exists: when the process carries a
        # warmup manifest (KARPENTER_TPU_WARMUP_MANIFEST) every solver
        # family is compiled here, so the run's first solve — and the
        # first_solve_after_restart gate it feeds — is warm
        from ..trace import warmup as _warmup

        _warmup.startup_warm()
        # per-node agent overhead: registered BEFORE any encode so every
        # capacity tensor of the run is net of the agents (cleared in
        # run()'s finally — the registry is process-global)
        from ..ops import overhead as _overhead

        agents = {}
        if spec.daemonset_cpu:
            agents["cpu"] = spec.daemonset_cpu
        if spec.daemonset_memory:
            agents["memory"] = spec.daemonset_memory
        _overhead.set_node_overhead(agents or None)
        # gang plane armed and the trace will exercise it: pre-trace the
        # gangs.feasible ladder buckets NOW, inside the warmup half, so a
        # late gang wave can never mint a first compile after the
        # retraces_after_warmup boundary
        if spec.gang_every_s > 0 or spec.hapair_every_s > 0:
            from ..models.pod import gangs_enabled
            from ..scheduling.groups import warm_gang_kernels

            if gangs_enabled():
                warm_gang_kernels()
        # why plane armed: pre-trace the elimination kernel's ladder
        # buckets inside the warmup half — the first unschedulable pod may
        # arrive long after the retraces_after_warmup boundary, and its
        # attribution must not mint a first compile there
        from ..obs.why import enabled as _why_enabled
        from ..obs.why import warm_why_kernels

        if _why_enabled():
            try:
                catalog_types = len(self.env.catalog.list())
                zones = len(self.env.catalog.zones)
            except Exception:
                catalog_types, zones = 32, 4
            warm_why_kernels(catalog_types=catalog_types, zones=zones)
        pool = NodePool(
            name="default",
            requirements=[
                Requirement(lbl.INSTANCE_CATEGORY, Operator.IN, ("c", "m")),
            ],
            disruption=Disruption(
                budgets=list(spec.consolidation_budgets),
                consolidate_after_s=spec.consolidate_after_s,
            ),
        )
        env.apply_defaults(pool)
        rng = random.Random(f"{self.seed}:fleet")
        catalog = env.catalog
        candidates = [
            t for t in catalog.list()
            if t.category in ("c", "m") and 4 <= t.vcpus <= 16
        ]
        # a fleet Karpenter launched is near price-optimal: draw from the
        # cheapest quartile by $/vCPU. Seeding random-priced types makes
        # day one a replace-with-cheaper festival — consolidation churning
        # through the whole fleet is a builder artifact, not prod load.
        def _per_cpu(t):
            try:
                p = catalog.pricing.on_demand_price(t)
            except Exception:
                p = None
            return (float(p) / t.vcpus) if p else float("inf")

        candidates.sort(key=lambda t: (_per_cpu(t), t.name))
        candidates = candidates[:max(8, len(candidates) // 4)]
        zones = list(catalog.zones)
        now = env.clock.now()
        for i in range(spec.nodes):
            it = candidates[rng.randrange(len(candidates))]
            zone = zones[rng.randrange(len(zones))]
            captype = "spot" if rng.random() < spec.spot_fraction else "on-demand"
            inst = seed_instance(
                env.cloud,
                instance_id=f"i-sim{i:06x}",
                instance_type=it.name,
                zone=zone,
                capacity_type=captype,
                image_id=("img-std-arm-2" if it.arch == "arm64" else "img-std-2"),
                launch_time=now,
                tags={MANAGED_TAG: "true", "Name": f"sim-node-{i}"},
            )
            claim = NodeClaim.fresh(
                nodepool_name="default",
                nodeclass_name="default",
                instance_type_options=[it.name],
                zone_options=[zone],
                capacity_type_options=[captype],
            )
            claim.status.provider_id = inst.provider_id
            claim.status.capacity = it.capacity()
            claim.status.allocatable = catalog.allocatable(it)
            claim.labels.update(it.labels())
            claim.labels[lbl.TOPOLOGY_ZONE] = zone
            claim.labels[lbl.CAPACITY_TYPE] = captype
            claim.labels[lbl.NODEPOOL] = "default"
            claim.annotations[lbl.ANNOTATION_INSTANCE_TAGGED] = "true"
            # the termination finalizer the launch path stamps: without it,
            # a consolidation delete drops the claim instantly with no
            # drain and the node's pods dangle (pods-bound-once fails)
            claim.finalizers.add("karpenter.tpu/termination")
            claim.status.set_condition("Launched", True)
            claim.status.set_condition("Registered", True)
            claim.status.set_condition("Initialized", True)
            env.cluster.apply(claim)
            node = Node(
                name=f"node-{claim.name}",
                provider_id=claim.status.provider_id,
                nodepool_name="default",
                nodeclaim_name=claim.name,
                labels=dict(claim.labels),
                capacity=claim.status.capacity,
                allocatable=claim.status.allocatable,
                ready=True,
            )
            node.labels[lbl.HOSTNAME] = node.name
            claim.status.node_name = node.name
            env.cluster.apply(node)
            # ballast (the fill) + small churn-target pods
            ballast_m = int(it.vcpus * 1000 * spec.fill_fraction)
            fill = [(f"{ballast_m}m", f"{max(1, int(it.memory_mib * 0.4))}Mi")]
            fill += [("250m", "512Mi")] * max(0, spec.pods_per_node - 1)
            for j, (cpu, mem) in enumerate(fill):
                p = make_pods(1, f"fleet{i}x{j}", {"cpu": cpu, "memory": mem})[0]
                env.cluster.apply(p)
                env.cluster.bind_pod(p.uid, node.name)
        self.nodes_start = len(env.cluster.nodes)
        self._seed_market()
        # the build's own binds are setup, not signal: wipe the judgment
        # plane (incl. the correlation ledger and the sentinel's span
        # cursor — build spans must not be the first tick's "regression")
        # so SLO/SLI/audit history starts at the trace's t=0
        env.obs.reset()

    def _seed_market(self) -> None:
        """Install the trace's market state (designs/market-engine.md):
        a seeded MarketModel on the sim clock (spot walks + reclaim
        discounts), and/or a standing ODCR on the fleet's cheapest
        candidate type — published through the REAL discovery path (fake
        cloud -> reservation provider -> nodeclass status -> catalog
        store), so ``pool_reserved_allowed`` arms the solver exactly as
        a live cluster would."""
        spec = self.trace
        env = self.env
        self._market_model = None
        self._market_pair = None
        wants_model = spec.market_tick_s > 0
        wants_res = spec.market_reservations > 0
        wants_block = spec.market_block_at_s >= 0 and spec.market_block_slots > 0
        if not (wants_model or wants_res or wants_block):
            return
        from ..market.scenarios import reserved_candidate

        self._market_pair = reserved_candidate(env.catalog)
        if wants_model:
            from ..catalog.pricing import MarketModel

            self._market_model = MarketModel(
                seed=self.seed, clock=env.clock,
                volatility=spec.market_volatility, tick_s=spec.market_tick_s,
            )
            env.catalog.pricing.market = self._market_model
            self._market_model.apply(env.catalog)
        if wants_res or wants_block:
            from ..models.nodeclass import SelectorTerm

            nc = env.cluster.nodeclasses.get("default")
            if nc is not None and not nc.capacity_reservation_selector:
                nc.capacity_reservation_selector = [
                    SelectorTerm(tags=(("sim-market", "true"),))
                ]
        if wants_res:
            from ..testenv import CapacityReservation

            itype, zone = self._market_pair
            env.cloud.capacity_reservations["sim-odcr-0"] = CapacityReservation(
                id="sim-odcr-0", instance_type=itype, zone=zone,
                count=int(spec.market_reservations),
                end_s=spec.market_reservation_end_s or None,
                name="sim-odcr-0", tags={"sim-market": "true"},
            )
        self._republish_reservations()

    def _republish_reservations(self) -> None:
        """Drop the discovery cache and reconcile the nodeclass status so
        a cloud-side reservation mutation lands in the catalog store (and
        the solver's reserved gating) THIS moment, not a cache-TTL later."""
        env = self.env
        env.cloudprovider.capacity_reservations.reset()
        env.nodeclass_status.reconcile()

    # -- stepping ------------------------------------------------------------

    def _advance(self, seconds: float) -> None:
        if seconds <= 0:
            return
        env = self.env
        if self.replicas > 1 and hasattr(env, "replicas"):
            # Lease renewal between driver moments: real replicas renew on
            # their own ~2s elector cadence regardless of workload, so a
            # quiet heartbeat must not leap past the TTL in one jump —
            # that would expire EVERY lease and member heartbeat at once
            # and let whichever replica reconciles first in the next pass
            # monopolize the whole key space (and the recovery stopwatch).
            # Chunk the advance at half the renew deadline and run the
            # live electors between chunks; everything stays on the
            # FakeClock, so determinism is unchanged.
            from ..operator.sharding import RENEW_DEADLINE_FRACTION

            ttl = min(r.elector.ttl_s for r in env.replicas)
            step = max(2.0, ttl * RENEW_DEADLINE_FRACTION * 0.5)
            remaining = seconds
            while remaining > step:
                env.clock.advance(step)
                self._t += step
                remaining -= step
                for r in env.replicas:
                    if r.alive and not r.paused:
                        try:
                            r.elector.reconcile()
                        except Exception:  # netsplit chaos: expected weather
                            pass
            env.clock.advance(remaining)
            self._t += remaining
        else:
            env.clock.advance(seconds)
            self._t += seconds

    def _pass(self) -> None:
        from ..metrics import SIM_PASSES

        with span("sim.controllers"):
            self.env.step(1)
        with span("sim.probe"):
            self._probe()
        with span("sim.collect"):
            self._scan_provenance()
        self.passes += 1
        SIM_PASSES.inc()
        if (
            self._jit_warm_seq is None
            and self._t >= self.trace.duration_s * 0.5
        ):
            from ..trace import jitwatch

            self._jit_warm_seq = jitwatch.ledger().seq()
        if self._loss_at is not None and hasattr(self.env, "partition_gap"):
            if not self.env.partition_gap():
                self.replica_recoveries.append(
                    round(self.env.clock.now() - self._loss_at, 3)
                )
                self._loss_at = None

    def _probe(self) -> None:
        self.probe_calls += 1
        try:
            self._ec2.describe_availability_zones()
        except (AwsApiError, CredentialError):
            self.probe_failures += 1

    def _quiesced(self) -> bool:
        """No pods pending and no launched-but-unregistered claims: the
        signal that a moment needs no further micro-passes. Without it,
        work started late in a pass (a disruption replacement launch)
        would sit until the next heartbeat — quantizing lifecycle SLIs
        at the heartbeat width and burning the time-to-ready SLO on a
        pure simulation artifact."""
        env = self.env
        if self._loss_at is not None:
            # replica-loss recovery in flight: keep micro-stepping so the
            # survivors' electors cross the lease TTL at burst resolution
            # — otherwise the recovery stopwatch quantizes at the
            # heartbeat width and the failover looks slower than it is
            return False
        if env.cluster.pending_pods():
            return False
        for c in env.cluster.nodeclaims.values():
            if not c.deleted and c.is_launched() and not c.is_registered():
                return False
        return True

    def _scan_provenance(self) -> None:
        """Fold solve/screen provenance records produced since the last
        scan into the backend/residency/fallback breakdowns and the
        cost-vs-oracle sample list. Runs every pass, so the bounded
        per-kind registries (64 records) can never rotate past us."""
        for kind in ("solve", "consolidate.screen"):
            with provenance._RECENT_LOCK:
                records = list(provenance._RECENT.get(kind, ()))
            import weakref

            for rec in records:
                ref = self._seen_records.get(id(rec))
                if ref is not None and ref() is rec:
                    continue
                self._seen_records[id(rec)] = weakref.ref(rec)
                if kind == "solve" and not self._first_solve_seen:
                    self._first_solve_seen = True
                    self.first_solve_compiles = rec.compiles
                self.backend_counts[rec.backend] = (
                    self.backend_counts.get(rec.backend, 0) + 1
                )
                self.backend_wall_ms[rec.backend] = round(
                    self.backend_wall_ms.get(rec.backend, 0.0) + rec.wall_ms, 3
                )
                if rec.residency:
                    self.residency_counts[rec.residency] = (
                        self.residency_counts.get(rec.residency, 0) + 1
                    )
                if rec.fallback:
                    self.fallback_counts[rec.fallback] = (
                        self.fallback_counts.get(rec.fallback, 0) + 1
                    )
                gap = rec.quality.get("cost_vs_oracle")
                if gap is not None:
                    self.quality_samples.append(round(float(gap), 4))

    # -- events --------------------------------------------------------------

    def _apply_event(self, ev: SimEvent) -> None:
        from ..metrics import SIM_EVENTS

        env = self.env
        self.events_applied[ev.kind] = self.events_applied.get(ev.kind, 0) + 1
        SIM_EVENTS.inc(kind=ev.kind)
        if ev.kind in ("wave", "flood", "gang"):
            kwargs = {}
            if ev.tenant:
                kwargs["labels"] = {lbl.TENANT_LABEL: ev.tenant}
            pods = make_pods(ev.pods, ev.name,
                             {"cpu": ev.cpu, "memory": ev.memory}, **kwargs)
            if ev.kind == "gang":
                from ..scheduling.groups import PodGroup

                PodGroup(
                    name=ev.name, min_count=ev.gang_min or ev.pods,
                    spread_skew=ev.spread_skew, anti_affine=ev.anti_affine,
                ).apply_to(pods)
            uids = []
            for p in pods:
                env.cluster.apply(p)
                uids.append(p.uid)
            self._pods_by_prefix[ev.name] = uids
        elif ev.kind == "expire":
            for uid in self._pods_by_prefix.pop(ev.name, []):
                pod = env.cluster.pods.get(uid)
                if pod is not None:
                    env.cluster.delete(pod)
        elif ev.kind == "churn":
            # deterministic victims: seeded draw over the SORTED names of
            # currently-bound pods (names are trace-derived and stable;
            # uids are process-global counters and are not)
            rng = random.Random(f"{self.seed}:{ev.name}")
            # gang members are excluded from churn victims: a workload
            # deleting ONE member of a live training job is not a thing
            # (jobs die whole via their expire event), and random single-
            # member deletion would fake a partial-gang invariant breach
            bound = sorted(
                (p.name, p.uid) for p in env.cluster.pods.values()
                if p.node_name and not p.gang_name()
            )
            victims = []
            for _ in range(min(ev.pods, len(bound))):
                victims.append(bound.pop(rng.randrange(len(bound))))
            for _name, uid in victims:
                pod = env.cluster.pods.get(uid)
                if pod is not None:
                    env.cluster.delete(pod)
            uids = []
            for p in make_pods(len(victims), ev.name,
                               {"cpu": "250m", "memory": "512Mi"}):
                env.cluster.apply(p)
                uids.append(p.uid)
            self._pods_by_prefix[ev.name] = uids
        elif ev.kind == "market":
            # one market tick: re-walk every spot price at the current
            # virtual time through the live update_spot channel (seqnums
            # bump, tensor caches invalidate — a real pricing backend)
            if self._market_model is not None:
                self._market_model.apply(env.catalog)
        elif ev.kind == "capacity_block":
            # a purchased capacity block opens NOW for ttl_s: install it
            # cloud-side at a committed discount and republish so the
            # reserved window column lights this moment
            from ..testenv import CapacityReservation

            itype, zone = self._market_pair or (None, None)
            if itype is not None:
                it = env.catalog.get(itype)
                committed = round(
                    0.35 * env.catalog.pricing.on_demand_price(it), 5
                )
                now = env.clock.now()
                env.cloud.capacity_reservations[f"sim-{ev.name}"] = (
                    CapacityReservation(
                        id=f"sim-{ev.name}", instance_type=itype, zone=zone,
                        count=int(ev.pods), start_s=now,
                        end_s=now + float(ev.ttl_s or 0.0) if ev.ttl_s else None,
                        committed_price=committed,
                        name=f"sim-{ev.name}", tags={"sim-market": "true"},
                    )
                )
                self._republish_reservations()
        else:  # pragma: no cover - generator never emits unknown kinds
            raise ValueError(f"unknown sim event kind {ev.kind!r}")
        self.log.record(
            t=env.clock.now(), kind="Workload", service="cluster",
            action=ev.kind, detail=f"{ev.name}:{ev.pods}",
        )

    def _activate(self, tf: TimedFault) -> None:
        from ..metrics import SIM_EVENTS

        if tf.fault.kind.startswith("Replica") and self.replicas == 1 \
                and self.ignore_replica_faults:
            # envelope reference run: the single-replica twin of a
            # multi-replica day keeps every workload/cloud/wire fault but
            # has no ReplicaSet for the replica seams to act on
            return
        self.active.append(tf)
        SIM_EVENTS.inc(kind="overlay-activate")
        if tf.fault.kind.startswith("Replica") and self._loss_at is None:
            # arm the recovery stopwatch at the loss edge
            self._loss_at = self.env.clock.now()
        self.log.record(
            t=self.env.clock.now(), kind=tf.fault.kind, service="timeline",
            action="activate", detail=tf.fault.describe(),
        )
        if _is_wire_fault(tf.fault):
            self.wire.add_fault(tf.fault)
        tf.fault.on_activate(self)

    def _deactivate(self, tf: TimedFault) -> None:
        from ..metrics import SIM_EVENTS

        if tf in self.active:
            self.active.remove(tf)
        SIM_EVENTS.inc(kind="overlay-deactivate")
        self.log.record(
            t=self.env.clock.now(), kind=tf.fault.kind, service="timeline",
            action="deactivate", detail=tf.fault.describe(),
        )
        if _is_wire_fault(tf.fault):
            self.wire.remove_fault(tf.fault)
        tf.fault.on_deactivate(self)

    # -- sampling ------------------------------------------------------------

    def _sample(self) -> None:
        with span("sim.sample"):
            env = self.env
            snap = env.obs.tick(now=self._t)
            slos = []
            for s in snap.get("slos", []):
                worst = max(
                    (r["burn_long"] for r in s.get("burn_rules", [])),
                    default=0.0,
                )
                slos.append({
                    "name": s["name"],
                    "budget_remaining": s["budget_remaining"],
                    "worst_burn": round(worst, 3),
                    "events_in_window": s["events_in_window"],
                    "bad_in_window": s["bad_in_window"],
                })
            packing = {}
            try:
                from ..obs.quality import cluster_packing
                from ..ops.consolidate import encode_cluster

                if env.cluster.nodes:
                    packing = dict(cluster_packing(
                        encode_cluster(env.cluster, env.catalog)
                    ))
            except Exception:
                packing = {}
            from ..metrics import SIM_VIRTUAL_SECONDS

            SIM_VIRTUAL_SECONDS.set(round(self._t, 3))
            self.samples.append({
                "t": round(self._t, 3),
                "slos": slos,
                "packing": {k: round(v, 4) for k, v in sorted(packing.items())},
                "pending_pods": len(env.cluster.pending_pods()),
                "nodes": len(env.cluster.nodes),
                "pods": len(env.cluster.pods),
            })

    # -- envelope reference (packing-envelope-parity) ------------------------

    def _run_envelope_reference(self) -> None:
        """Drive the single-replica twin of this trace+seed FIRST and
        remember its packing/cost envelope — the packing-envelope-parity
        invariant then bounds the multi-replica day against it (sharded
        provisioning must not buy a worse fleet than one replica would).
        Replica* overlay faults are ignored on the twin (no ReplicaSet to
        act on); every workload/cloud/wire fault replays identically. The
        nested environment re-keys the process-global resilience layer
        onto its own clock, so it is re-keyed back before this run."""
        from ..obs.quality import fleet_hourly_cost
        from ..resilience import breakers, faultgate

        ref = FleetSimulator(
            self.trace, seed=self.seed, replicas=1,
            use_tpu_solver=self.use_tpu_solver,
            check_invariants=False, envelope_check=False,
        )
        ref.ignore_replica_faults = True
        try:
            report = ref.run()
            cost = fleet_hourly_cost(ref.env.cluster, ref.env.catalog)
            self._envelope_ref = {
                "packing_cpu_mean": (
                    report.data["virtual"].get("packing", {}).get("cpu_mean")
                ),
                "fleet_cost_per_hr": cost,
                "bind_count": report.gate.get("bind_count"),
            }
        except Exception:
            # a broken reference run must not abort the multi-replica day:
            # with no reference attached, packing-envelope-parity reports
            # its explicit n/a skip instead of a never-compared PASS
            import logging

            logging.getLogger("karpenter.tpu.sim").exception(
                "envelope reference run failed; parity check will self-skip"
            )
            self._envelope_ref = None
        finally:
            breakers.configure(clock=self.env.clock)
            faultgate.clear()

    def _compute_envelope(self) -> dict:
        from ..obs.quality import fleet_hourly_cost

        ref = self._envelope_ref or {}
        packs = [
            s["packing"].get("cpu") for s in self.samples
            if s["packing"].get("cpu") is not None
        ]
        self_pack = round(sum(packs) / len(packs), 4) if packs else None
        self_cost = fleet_hourly_cost(self.env.cluster, self.env.catalog)
        ref_pack = ref.get("packing_cpu_mean")
        ref_cost = ref.get("fleet_cost_per_hr")
        return {
            "self_packing_cpu_mean": self_pack,
            "self_fleet_cost_per_hr": self_cost,
            "ref_packing_cpu_mean": ref_pack,
            "ref_fleet_cost_per_hr": ref_cost,
            "ref_bind_count": ref.get("bind_count"),
            "packing_ratio": (
                round(self_pack / ref_pack, 4)
                if self_pack is not None and ref_pack else None
            ),
            "cost_ratio": (
                round(self_cost / ref_cost, 4) if ref_cost else None
            ),
        }

    # -- the run -------------------------------------------------------------

    def flight_recorder(self):
        """The run's cross-replica flight recorder (obs/fleet.py) over
        the shared world — ``--flight-out`` serializes its snapshot for
        the ``obs fleet`` CLI."""
        from ..obs.fleet import FleetRecorder

        return FleetRecorder(self.env)

    def jit_summary(self) -> dict:
        """The run's device plane (wall-side: compile walls are real
        milliseconds): jitwatch ledger families, the warmup boundary, and
        the compiles that fired AFTER it — `retraces_after_warmup` is the
        zero-retrace steady-state gate's source. None entries mean
        jitwatch was off (KARPENTER_TPU_JITWATCH=0)."""
        from ..trace import jitwatch

        if not jitwatch.enabled():
            return {"enabled": False}
        led = jitwatch.ledger()
        snap = led.snapshot()
        after = (
            led.events_since(self._jit_warm_seq)
            if self._jit_warm_seq is not None else []
        )
        from ..trace import warmup as _warmup

        return {
            "enabled": True,
            "families": snap["families"],
            "monitoring": snap["monitoring"],
            "warmup_boundary_s": round(self.trace.duration_s * 0.5, 1),
            "warmup_cursor": self._jit_warm_seq,
            "retraces_after_warmup": len(after),
            "retrace_events_after_warmup": after,
            "sentinel": self.env.obs.retrace.summary(),
            # AOT manifest warmup (pre-fleet, designs/aot-warmup.md):
            # per-family replay accounting when the process warmed from a
            # manifest, plus the first solve's provenance compile stamp
            "aot_warmup": {
                "did_warm": _warmup.did_warm(),
                "accounting": _warmup.accounting(),
                "first_solve_compiles": self.first_solve_compiles,
            },
        }

    def run(self):
        """Drive the whole trace; returns the :class:`sim.report.FleetReport`."""
        from .report import FleetReport, build_report

        import contextlib
        import os

        spec = self.trace
        if self.envelope_check and self.replicas > 1:
            self._run_envelope_reference()
        agg = SpanAggregator()
        TRACER.on_finish(agg)
        # The simulator used to pin KARPENTER_TPU_REPACK=native on CPU
        # because the auto-selected vmap screen re-jitted (~270ms/sweep)
        # whenever churn changed the group axis. The host vmap path now
        # ladder-pads its group/slot/node axes to the same pow2 ladder the
        # device-resident buffers use (ops/consolidate.py `_screen`), so
        # jitted shapes are churn-stable and the pin is gone — the sim
        # measures whatever backend the repack heuristic really picks.
        screen_pin = contextlib.nullcontext()
        # byte-identical-per-seed contract: multi-spec launches must not
        # race worker threads over claim names / event order / capacity
        # pool draws (restored after the run)
        prev_serial = os.environ.get("KARPENTER_TPU_SERIAL_LAUNCH")
        os.environ["KARPENTER_TPU_SERIAL_LAUNCH"] = "1"
        provider = lambda: {  # noqa: E731
            "sim_trace": spec.name,
            "sim_seed": self.seed,
            "sim_active_faults": ",".join(self.active_fault_kinds()),
        }
        provenance.register_ambient_provider(provider)
        from ..metrics import AUDIT_RECORDS, NODES_CREATED, NODES_TERMINATED, \
            PROVISIONING_STEALS, UNSCHEDULABLE_PODS

        audit_kinds = ("placement", "disruption", "interruption", "eviction",
                       "lifecycle", "resilience")
        steal_outcomes = ("claimed", "stolen", "contended", "fenced")
        counters0 = {
            "audit": {k: AUDIT_RECORDS.value(kind=k) for k in audit_kinds},
            "launched": NODES_CREATED.total(),
            "terminated": NODES_TERMINATED.total(),
            "unschedulable": UNSCHEDULABLE_PODS.total(),
            "steals": {
                o: PROVISIONING_STEALS.value(outcome=o) for o in steal_outcomes
            },
        }
        wall0 = time.perf_counter()
        try:
            screen_pin.__enter__()
            with span("sim.build", nodes=spec.nodes):
                self._build_fleet()
            events = generate(spec, self.seed)
            overlay_faults: list[TimedFault] = []
            for o in spec.overlays:
                overlay_faults += compose_overlay(
                    o.scenario, at_s=o.at_s, stretch=o.stretch
                )
            # one merged agenda of moments: workload events, overlay
            # window edges, heartbeats, and sample points
            moments: dict[float, dict] = {}

            def at(t: float) -> dict:
                return moments.setdefault(
                    round(t, 3),
                    {"events": [], "on": [], "off": [], "sample": False},
                )

            for ev in events:
                at(ev.at_s)["events"].append(ev)
            for tf in overlay_faults:
                at(tf.at_s)["on"].append(tf)
                if tf.end_s is not None and tf.end_s < spec.duration_s:
                    at(tf.end_s)["off"].append(tf)
            t = spec.heartbeat_s
            while t < spec.duration_s:
                at(t)
                t += spec.heartbeat_s
            t = spec.sample_every_s
            while t < spec.duration_s:
                at(t)["sample"] = True
                t += spec.sample_every_s
            at(max(0.0, spec.duration_s - 1.0))["sample"] = True

            for when in sorted(moments):
                m = moments[when]
                self._advance(when - self._t)
                for tf in [tf for tf in self.active
                           if tf.end_s is not None and when >= tf.end_s]:
                    self._deactivate(tf)
                for tf in m["on"]:
                    self._activate(tf)
                if m["events"]:
                    with span("sim.workload", n=len(m["events"])):
                        for ev in m["events"]:
                            self._apply_event(ev)
                # one pass always; then micro-passes (bounded by
                # burst_passes) while work is visibly in flight — pods
                # pending or claims launched-but-unregistered. A quiet
                # heartbeat costs one pass; a busy moment converges at
                # burst_step_s virtual resolution instead of parking
                # in-flight lifecycle transitions until the next heartbeat.
                self._pass()
                extra = 0
                max_extra = spec.burst_passes
                if self.replicas > 1:
                    # sharded provisioning pipelines work ACROSS replicas
                    # (launch on the GLOBAL holder, register on the
                    # partition owner, bind the nomination back on the
                    # launcher) and each handoff lands one pass later in
                    # the serialized step order — give multi-replica runs
                    # the extra passes a real fleet's continuous reconcile
                    # cadence would provide for free
                    max_extra += 4
                while extra < max_extra and not self._quiesced():
                    step = spec.burst_step_s
                    if self._loss_at is not None:
                        # replica-loss recovery: cross the lease TTL at
                        # fine resolution (the stopwatch would otherwise
                        # quantize at burst_step_s) — bounded so a
                        # non-recovering lease layer cannot spin the day
                        step = min(step, 2.0)
                        max_extra = max(
                            max_extra, spec.burst_passes + 12
                        )
                    self._advance(step)
                    self._pass()
                    extra += 1
                if m["sample"]:
                    self._sample()
            self._advance(max(0.0, spec.duration_s - self._t))

            # fault-clear + settle (the chaos shape: re-converge within
            # the budget, then let the ICE TTL lapse before invariants)
            with span("sim.settle"):
                for tf in list(self.active):
                    self._deactivate(tf)
                # end of day: freeze NEW disruption (in-flight drains keep
                # finishing through the termination controller) so the
                # settle phase converges instead of measuring a run that
                # is still consolidating when the invariants fire
                for pool in self.env.cluster.nodepools.values():
                    pool.disruption.budgets = ["0%"]
                from ..chaos.cloud import uninstall_consistency_lag

                uninstall_consistency_lag(self.env.cloud)
                self.wire.clear_faults()
                converged_at = None
                for i in range(spec.settle_reconciles):
                    self._advance(SETTLE_ADVANCE_S)
                    self._pass()
                    if not self.env.cluster.pending_pods() \
                            and len(self.env.queue) == 0:
                        if converged_at is None:
                            converged_at = i + 1
                        # converged AND no drain in flight: stop burning
                        # full-fleet passes and jump the remaining settle
                        # window in virtual time (the chaos harness runs
                        # its whole budget; a 10k-node sim pass is ~0.5s
                        # and the budget exists for convergence, which is
                        # already proven)
                        draining = any(
                            c.deleted
                            for c in self.env.cluster.nodeclaims.values()
                        )
                        if not draining and i + 1 < spec.settle_reconciles:
                            self._advance(
                                SETTLE_ADVANCE_S
                                * (spec.settle_reconciles - i - 1)
                            )
                            self._pass()
                            break
                self.settle_steps_used = converged_at or spec.settle_reconciles
                self._advance(CacheTTL.UNAVAILABLE_OFFERINGS + 1.0)
                self._pass()
                self._sample()
                if self._envelope_ref is not None:
                    self.envelope = self._compute_envelope()
                if self.check_invariants:
                    self.invariants = check_all(self)
            self.driver_wall_s = time.perf_counter() - wall0
            # every family the day traced is in the ledger now: serialize
            # the warmup manifest (KARPENTER_TPU_WARMUP_SAVE; no-op when
            # unset) so the next process starts warm
            from ..trace import warmup as _warmup

            _warmup.maybe_save()
        finally:
            from ..ops import overhead as _overhead

            _overhead.set_node_overhead(None)
            if prev_serial is None:
                os.environ.pop("KARPENTER_TPU_SERIAL_LAUNCH", None)
            else:
                os.environ["KARPENTER_TPU_SERIAL_LAUNCH"] = prev_serial
            screen_pin.__exit__(None, None, None)
            TRACER.remove_on_finish(agg)
            provenance.unregister_ambient_provider(provider)
            self.env.close()
        counters1 = {
            "audit": {
                k: AUDIT_RECORDS.value(kind=k) for k in audit_kinds
            },
            "launched": NODES_CREATED.total(),
            "terminated": NODES_TERMINATED.total(),
            "unschedulable": UNSCHEDULABLE_PODS.total(),
            "steals": {
                o: PROVISIONING_STEALS.value(outcome=o) for o in steal_outcomes
            },
        }
        deltas = {
            "audit": {
                k: int(counters1["audit"][k] - counters0["audit"][k])
                for k in audit_kinds
            },
            "launched": int(counters1["launched"] - counters0["launched"]),
            "terminated": int(
                counters1["terminated"] - counters0["terminated"]
            ),
            "unschedulable": int(
                counters1["unschedulable"] - counters0["unschedulable"]
            ),
            "steals": {
                o: int(counters1["steals"][o] - counters0["steals"][o])
                for o in steal_outcomes
            },
        }
        report = build_report(self, agg.profile(), deltas)
        global _LAST_RUN
        _LAST_RUN = report.summary()
        return report


def run_trace(trace, seed: int = 0, **kw):
    """Build a fresh simulator and run one trace end to end."""
    return FleetSimulator(trace, seed=seed, **kw).run()


def run_deterministic(trace, seed: int = 0, runs: int = 2, **kw) -> list:
    """The acceptance gate: run the trace ``runs`` times with the same
    seed and raise unless every report's deterministic core is
    byte-identical (the chaos ``signature()`` witness pattern)."""
    reports = [run_trace(trace, seed=seed, **kw) for _ in range(runs)]
    first = reports[0].signature()
    for i, r in enumerate(reports[1:], start=2):
        if r.signature() != first:
            import difflib

            diff = "\n".join(list(difflib.unified_diff(
                reports[0].witness().splitlines(),
                r.witness().splitlines(), lineterm="", n=2,
            ))[:80])
            raise AssertionError(
                f"non-deterministic fleet report: run 1 and run {i} diverge "
                f"with seed {seed}\n{diff}"
            )
    return reports
