"""Workload-trace grammar + seeded generators: a day of prod as data.

A :class:`TraceSpec` declares the SHAPE of a simulated day — fleet size
and fill, diurnal deployment-wave rate, batch-job floods, pod-churn
cadence, chaos overlays — and :func:`generate` expands it into a sorted
list of :class:`SimEvent` s using nothing but streams derived from the
seed. Two calls with the same (spec, seed) produce the identical event
list; the driver (``sim/driver.py``) replays it against the full
controller manager on a FakeClock, so a whole simulated day is
byte-identical per seed.

Event kinds (the trace grammar, documented in ``designs/fleet-simulator.md``):

- ``wave``   — a diurnal deployment wave: N pods of a seeded shape, with a
  TTL after which the wave is deleted again (the scale-down half of the
  diurnal curve).
- ``flood``  — a batch-job burst: many large pods at once, sized past the
  per-node free capacity so the pass is a pure launch (which is also what
  arms the FFD-oracle cost sampler).
- ``churn``  — steady pod recycling: M bound pods die and M replacements
  arrive (victims drawn deterministically by sorted pod name).
- ``expire`` — the scheduled deletion of an earlier wave/flood's pods.

Overlays compose fault timelines from ``chaos/plan.py`` scenarios into
the day (``chaos.plan.compose_overlay``): a spot-storm at hour 6, an
API brownout at hour 14 — the same seeded fault primitives the chaos
harness runs, riding the simulator's clock.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Optional

#: shapes a wave draws from: (cpu, memory) request pairs, weighted to the
#: small end like a prod mix (the flood shape is configured separately)
WAVE_SHAPES = (
    ("250m", "512Mi"), ("250m", "1Gi"), ("500m", "1Gi"),
    ("500m", "2Gi"), ("1000m", "2Gi"), ("1000m", "4Gi"), ("2000m", "4Gi"),
)

#: fragmentation burst shapes: (tall, wide) pairs sized so a greedy FFD
#: interleaves singleton tail nodes — tall pods bind on cpu (~2 fit a
#: 16-vcpu node), wide pods bind on memory. Odd counts of each, arriving
#: together, are the config6/config8 failure mode the optimizer lane
#: exists to repack (designs/optimizer-lane.md); the `frag` trace makes
#: that workload a seeded, reproducible simulator input.
FRAG_SHAPES = (
    (("7000m", "6Gi"), ("1500m", "12Gi")),
    (("6000m", "4Gi"), ("2000m", "14Gi")),
    (("5000m", "8Gi"), ("1000m", "10Gi")),
)


@dataclass
class SimEvent:
    """One timed workload mutation."""

    at_s: float
    kind: str                     # wave | flood | churn | expire | gang | ...
    pods: int = 0
    cpu: str = "500m"
    memory: str = "1Gi"
    name: str = ""                # pod-name prefix (expire targets it)
    ttl_s: Optional[float] = None
    unschedulable: bool = False   # poison shape: no node can ever fit it
    # gang plane (kind="gang"): the wave is an all-or-nothing PodGroup
    gang_min: int = 0             # members required to place (0 = all)
    spread_skew: int = 0          # DoNotSchedule zone-spread skew cap
    anti_affine: bool = False     # HA pair: at most one member per zone
    tenant: str = ""              # tenant label stamped onto the pods

    def to_dict(self) -> dict:
        d = {"at_s": self.at_s, "kind": self.kind, "pods": self.pods,
             "cpu": self.cpu, "memory": self.memory, "name": self.name}
        if self.ttl_s is not None:
            d["ttl_s"] = self.ttl_s
        if self.unschedulable:
            d["unschedulable"] = True
        if self.gang_min:
            d["gang_min"] = self.gang_min
        if self.spread_skew:
            d["spread_skew"] = self.spread_skew
        if self.anti_affine:
            d["anti_affine"] = True
        if self.tenant:
            d["tenant"] = self.tenant
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "SimEvent":
        return cls(
            at_s=float(d["at_s"]), kind=str(d["kind"]),
            pods=int(d.get("pods", 0)), cpu=str(d.get("cpu", "500m")),
            memory=str(d.get("memory", "1Gi")), name=str(d.get("name", "")),
            ttl_s=(None if d.get("ttl_s") is None else float(d["ttl_s"])),
            unschedulable=bool(d.get("unschedulable", False)),
            gang_min=int(d.get("gang_min", 0)),
            spread_skew=int(d.get("spread_skew", 0)),
            anti_affine=bool(d.get("anti_affine", False)),
            tenant=str(d.get("tenant", "")),
        )


@dataclass
class Overlay:
    """A chaos scenario's fault timeline dropped into the day at ``at_s``."""

    scenario: str
    at_s: float = 0.0
    stretch: float = 1.0

    def to_dict(self) -> dict:
        d: dict = {"scenario": self.scenario, "at_s": self.at_s}
        if self.stretch != 1.0:
            d["stretch"] = self.stretch
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Overlay":
        return cls(scenario=str(d["scenario"]), at_s=float(d.get("at_s", 0.0)),
                   stretch=float(d.get("stretch", 1.0)))

    @classmethod
    def parse(cls, text: str) -> "Overlay":
        """CLI form ``scenario[@at_s[xstretch]]``, e.g. ``spot-storm@3600``."""
        at_s, stretch = 0.0, 1.0
        name = text
        if "@" in text:
            name, rest = text.split("@", 1)
            if "x" in rest:
                at, st = rest.split("x", 1)
                at_s, stretch = float(at), float(st)
            else:
                at_s = float(rest)
        return cls(scenario=name, at_s=at_s, stretch=stretch)


@dataclass
class TraceSpec:
    """The declarative shape of one simulated day (JSON round-trips)."""

    name: str
    # fleet
    nodes: int = 500
    pods_per_node: int = 4          # ballast + churn-target fill per node
    fill_fraction: float = 0.6      # target cpu utilization of the ballast
    spot_fraction: float = 0.6
    # time base
    duration_s: float = 7200.0
    heartbeat_s: float = 600.0      # steady reconcile cadence between events
    burst_passes: int = 3           # reconcile micro-burst after each event
    burst_step_s: float = 15.0      # virtual advance between burst passes
    sample_every_s: float = 900.0   # SLO/packing timeline cadence
    settle_reconciles: int = 40     # post-trace convergence budget
    # diurnal deployment waves
    waves_per_hour: float = 1.0
    wave_pods: int = 40
    wave_ttl_s: float = 7200.0
    diurnal_amplitude: float = 0.6  # peak-to-mean swing of the wave size
    peak_hour: float = 14.0
    # batch floods — the default shape exceeds any fleet node's free
    # capacity (fill_fraction leaves < 7 of <= 16 vcpus free), so a flood
    # pass is a pure launch: new capacity, and the pass the FFD-oracle
    # cost sampler (obs/quality.py) is allowed to judge
    floods: int = 1
    flood_pods: int = 64
    flood_cpu: str = "7000m"
    flood_memory: str = "12Gi"
    flood_ttl_s: float = 1800.0
    # pod churn
    churn_every_s: float = 1800.0
    churn_pods: int = 16
    # fragmentation bursts: paired tall/wide waves with seeded ODD counts
    # (FRAG_SHAPES) that a greedy FFD packs into interleaved singleton
    # tails — the optimizer lane's target workload. 0 = off.
    frag_every_s: float = 0.0
    frag_pods: int = 24
    frag_ttl_s: float = 3600.0
    # deliberate SLO regression (the red-gate injection): every wave also
    # lands this many pods NO node shape can serve — each solve pass they
    # pend is a solve-success SLO miss and an unschedulable-rate hit
    unschedulable_per_wave: int = 0
    # nodepool disruption posture
    consolidation_budgets: tuple = ("2%",)
    consolidate_after_s: Optional[float] = 600.0
    # market engine (designs/market-engine.md): tick_s > 0 arms a seeded
    # MarketModel on the sim clock — every tick re-walks all spot prices
    # through the live update_spot channel (kind="market" events below)
    market_tick_s: float = 0.0
    market_volatility: float = 0.35
    # reserved capacity seeded at t=0: an ODCR on the fleet's cheapest
    # candidate type (slots), optionally expiring mid-trace (end_s > 0 —
    # the reservation-expiry-day shape)
    market_reservations: int = 0
    market_reservation_end_s: float = 0.0   # 0 = open-ended
    # a capacity block ARRIVING mid-trace: opens at block_at_s for
    # block_duration_s with block_slots slots at a committed discount
    # (kind="capacity_block" event)
    market_block_at_s: float = -1.0         # < 0 = no block
    market_block_slots: int = 0
    market_block_duration_s: float = 14400.0
    # gang scheduling (designs/gang-scheduling.md): training gangs of
    # gang_size all-or-nothing members with a zone-spread skew cap arrive
    # every gang_every_s (0 = off); anti-affine HA pairs (one member per
    # zone) arrive every hapair_every_s
    gang_every_s: float = 0.0
    gang_size: int = 8
    gang_cpu: str = "4000m"
    gang_memory: str = "8Gi"
    gang_spread_skew: int = 2
    gang_ttl_s: float = 7200.0
    hapair_every_s: float = 0.0
    hapair_ttl_s: float = 7200.0
    # per-node agent (DaemonSet) overhead the encoders subtract from every
    # node's allocatable at encode time (ops/overhead.py); "" = none
    daemonset_cpu: str = ""
    daemonset_memory: str = ""
    # per-tenant arrival mix: > 0 stamps every wave/gang pod with a seeded
    # tenant label; the noisy-neighbor window lands a burst attributed to
    # tenant "noisy" so the fairness gate can compare quiet tenants' bind
    # p99 inside vs outside it (tenant_bind_p99_ratio)
    tenants: int = 0
    noisy_at_s: float = -1.0                # < 0 = no noisy window
    noisy_duration_s: float = 1800.0
    noisy_pods: int = 0
    # chaos overlays
    overlays: list = field(default_factory=list)

    def to_dict(self) -> dict:
        d = {
            k: getattr(self, k)
            for k in (
                "name", "nodes", "pods_per_node", "fill_fraction",
                "spot_fraction", "duration_s", "heartbeat_s", "burst_passes",
                "burst_step_s", "sample_every_s", "settle_reconciles",
                "waves_per_hour", "wave_pods", "wave_ttl_s",
                "diurnal_amplitude", "peak_hour", "floods", "flood_pods",
                "flood_cpu", "flood_memory", "flood_ttl_s", "churn_every_s",
                "churn_pods", "frag_every_s", "frag_pods", "frag_ttl_s",
                "unschedulable_per_wave", "consolidate_after_s",
                "market_tick_s", "market_volatility", "market_reservations",
                "market_reservation_end_s", "market_block_at_s",
                "market_block_slots", "market_block_duration_s",
                "gang_every_s", "gang_size", "gang_cpu", "gang_memory",
                "gang_spread_skew", "gang_ttl_s", "hapair_every_s",
                "hapair_ttl_s", "daemonset_cpu", "daemonset_memory",
                "tenants", "noisy_at_s", "noisy_duration_s", "noisy_pods",
            )
        }
        d["consolidation_budgets"] = list(self.consolidation_budgets)
        d["overlays"] = [o.to_dict() for o in self.overlays]
        return d

    def to_json(self, indent: int = 1) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @classmethod
    def from_dict(cls, d: dict) -> "TraceSpec":
        d = dict(d)
        overlays = [Overlay.from_dict(o) for o in d.pop("overlays", [])]
        budgets = tuple(d.pop("consolidation_budgets", ("2%",)))
        known = {f for f in cls.__dataclass_fields__}  # noqa: SIM118
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"trace: unknown fields {sorted(unknown)}")
        return cls(**d, overlays=overlays, consolidation_budgets=budgets)

    @classmethod
    def from_json(cls, text: str) -> "TraceSpec":
        return cls.from_dict(json.loads(text))


def canned_traces() -> dict[str, TraceSpec]:
    """The shipped traces. ``smoke`` is the tier-1 gate workload; the
    ``*-day`` traces are the sweep/acceptance tiers."""
    return {
        # 2 simulated hours at 500 nodes: the CI smoke under the fleet gate
        "smoke": TraceSpec(
            name="smoke", nodes=500, duration_s=2 * 3600.0,
            heartbeat_s=600.0, sample_every_s=900.0,
            waves_per_hour=2.0, wave_pods=24, wave_ttl_s=3600.0,
            floods=1, flood_pods=48, churn_every_s=1800.0, churn_pods=12,
            settle_reconciles=40,
        ),
        # the full diurnal day: hourly waves riding a sine, two floods,
        # steady churn — "a day of prod in a minute"
        "diurnal-day": TraceSpec(
            name="diurnal-day", nodes=1000, duration_s=86400.0,
            heartbeat_s=900.0, sample_every_s=1800.0,
            waves_per_hour=1.0, wave_pods=48, wave_ttl_s=4 * 3600.0,
            floods=2, flood_pods=96, churn_every_s=3600.0, churn_pods=24,
            settle_reconciles=60,
        ),
        # fragmentation: paired tall/wide odd-count bursts the greedy FFD
        # packs into interleaved singleton tails — the seeded reproducible
        # workload behind the optimizer lane's headline bench rows
        # (benchmarks/optimizer_bench.py builds its solve problems from
        # exactly these events)
        "frag": TraceSpec(
            name="frag", nodes=300, duration_s=2 * 3600.0,
            heartbeat_s=600.0, sample_every_s=900.0,
            waves_per_hour=1.0, wave_pods=16, wave_ttl_s=3600.0,
            floods=0, churn_every_s=0.0, churn_pods=0,
            frag_every_s=1200.0, frag_pods=28, frag_ttl_s=3000.0,
            settle_reconciles=40,
        ),
        # batch-heavy: big floods dominate, waves are background noise
        "flood-day": TraceSpec(
            name="flood-day", nodes=1000, duration_s=86400.0,
            heartbeat_s=900.0, sample_every_s=1800.0,
            waves_per_hour=0.5, wave_pods=24, wave_ttl_s=4 * 3600.0,
            floods=6, flood_pods=128, churn_every_s=7200.0, churn_pods=16,
            settle_reconciles=60,
        ),
        # a gang day at 500 nodes: topology-spread training gangs +
        # anti-affine HA pairs arrive on a tenant-mixed diurnal floor,
        # per-node agents tax every node's allocatable, and a noisy
        # tenant floods mid-morning (hour 1.5 — INSIDE the jitwatch
        # warmup half, so the fleet's peak tensor buckets are all minted
        # before the retrace gate arms) — the `make gang-smoke` workload
        # (fleet-gated vs sim/baselines/gang-500.json: zero partial
        # gangs, fairness ratio, zero steady-state retraces)
        "gang-day": TraceSpec(
            name="gang-day", nodes=500, duration_s=4 * 3600.0,
            heartbeat_s=600.0, sample_every_s=900.0,
            waves_per_hour=2.0, wave_pods=24, wave_ttl_s=3600.0,
            floods=1, flood_pods=48, churn_every_s=1800.0, churn_pods=12,
            settle_reconciles=40,
            gang_every_s=1500.0, gang_size=8, gang_spread_skew=2,
            gang_ttl_s=5400.0, hapair_every_s=2700.0, hapair_ttl_s=5400.0,
            daemonset_cpu="200m", daemonset_memory="256Mi",
            tenants=3, noisy_at_s=1.5 * 3600.0, noisy_duration_s=1800.0,
            noisy_pods=96,
        ),
        # the why-not engine's acceptance day (designs/why-engine.md): a
        # smoke-shaped 2h at 500 nodes that DELIBERATELY starves — every
        # wave lands two pods no shape can serve, training gangs ride the
        # floor, and a seeded market walks spot prices — so every
        # unschedulable record, withheld gang, and market-dark offering
        # must come back attributed (`make why-smoke` gates
        # why_coverage == 1.0 vs sim/baselines/why-500.json)
        "why-day": TraceSpec(
            name="why-day", nodes=500, duration_s=2 * 3600.0,
            heartbeat_s=600.0, sample_every_s=900.0,
            waves_per_hour=2.0, wave_pods=24, wave_ttl_s=3600.0,
            floods=1, flood_pods=48, churn_every_s=1800.0, churn_pods=12,
            settle_reconciles=40,
            unschedulable_per_wave=2,
            gang_every_s=1800.0, gang_size=8, gang_spread_skew=2,
            gang_ttl_s=5400.0,
            market_tick_s=900.0, market_volatility=0.35,
        ),
        # MARKET traces (moving prices / reserved windows) live in
        # market/scenarios.py next to the model they exercise
        **_market_traces(),
    }


def _market_traces() -> dict[str, TraceSpec]:
    # lazy import: market.scenarios builds TraceSpecs from THIS module
    from ..market.scenarios import market_traces

    return market_traces()


def canned_trace(name: str) -> TraceSpec:
    traces = canned_traces()
    if name not in traces:
        raise ValueError(f"unknown trace {name!r}; shipped: {sorted(traces)}")
    return traces[name]


def generate(spec: TraceSpec, seed: int) -> list[SimEvent]:
    """Expand a TraceSpec into the sorted, seeded event list.

    All randomness comes from ``Random(f"{seed}:trace")``; the diurnal
    curve scales each wave's size by
    ``1 + amplitude * sin(2*pi*(hour - peak + 6) / 24)`` so waves peak at
    ``peak_hour`` and trough 12 hours opposite. Expire events are
    scheduled at ``at_s + ttl_s`` (clamped inside the trace) for every
    wave/flood that declares a TTL."""
    import random

    rng = random.Random(f"{seed}:trace")
    events: list[SimEvent] = []

    def _expire(ev: SimEvent) -> None:
        if ev.ttl_s is None:
            return
        at = ev.at_s + ev.ttl_s
        if at < spec.duration_s:
            events.append(SimEvent(at_s=at, kind="expire", name=ev.name))

    # diurnal waves
    if spec.waves_per_hour > 0:
        period = 3600.0 / spec.waves_per_hour
        t = period * 0.5
        i = 0
        while t < spec.duration_s:
            hour = (t / 3600.0) % 24.0
            diurnal = 1.0 + spec.diurnal_amplitude * math.sin(
                2.0 * math.pi * (hour - spec.peak_hour + 6.0) / 24.0
            )
            pods = max(1, int(round(spec.wave_pods * diurnal)))
            cpu, mem = WAVE_SHAPES[rng.randrange(len(WAVE_SHAPES))]
            # tenant mix: guarded draw, so tenant-less traces consume the
            # exact same rng stream they always did
            tenant = (
                f"t{rng.randrange(spec.tenants)}" if spec.tenants > 0 else ""
            )
            ev = SimEvent(
                at_s=round(t, 3), kind="wave", pods=pods, cpu=cpu, memory=mem,
                name=f"wave{i}", ttl_s=spec.wave_ttl_s, tenant=tenant,
            )
            events.append(ev)
            _expire(ev)
            if spec.unschedulable_per_wave > 0:
                events.append(SimEvent(
                    at_s=round(t, 3), kind="wave",
                    pods=spec.unschedulable_per_wave,
                    cpu="512000m", memory="4096Gi",  # no catalog shape fits
                    name=f"poison{i}", unschedulable=True,
                ))
            t += period
            i += 1

    # batch floods, spread evenly through the middle of the trace
    for j in range(spec.floods):
        at = spec.duration_s * (j + 1) / (spec.floods + 1)
        ev = SimEvent(
            at_s=round(at, 3), kind="flood", pods=spec.flood_pods,
            cpu=spec.flood_cpu, memory=spec.flood_memory,
            name=f"flood{j}", ttl_s=spec.flood_ttl_s,
        )
        events.append(ev)
        _expire(ev)

    # fragmentation bursts: a tall wave and a wide wave land TOGETHER with
    # seeded odd counts, so the greedy's per-group tails interleave (new
    # capacity every burst: the shapes exceed fleet free slack, making the
    # pass a pure launch — the one the oracle sampler and optimizer lane
    # both judge)
    if spec.frag_every_s > 0 and spec.frag_pods > 0:
        t = spec.frag_every_s
        j = 0
        while t < spec.duration_s:
            tall, wide = FRAG_SHAPES[rng.randrange(len(FRAG_SHAPES))]
            n_tall = max(3, spec.frag_pods // 2) | 1   # odd by construction
            n_wide = max(3, spec.frag_pods - n_tall + rng.randrange(3)) | 1
            for suffix, (cpu, mem), n in (
                ("T", tall, n_tall), ("W", wide, n_wide),
            ):
                ev = SimEvent(
                    at_s=round(t, 3), kind="wave", pods=n, cpu=cpu,
                    memory=mem, name=f"frag{suffix}{j}",
                    ttl_s=spec.frag_ttl_s,
                )
                events.append(ev)
                _expire(ev)
            t += spec.frag_every_s
            j += 1

    # steady churn
    if spec.churn_every_s > 0 and spec.churn_pods > 0:
        t = spec.churn_every_s
        k = 0
        while t < spec.duration_s:
            events.append(SimEvent(
                at_s=round(t, 3), kind="churn", pods=spec.churn_pods,
                name=f"churn{k}",
            ))
            t += spec.churn_every_s
            k += 1

    # training gangs: all-or-nothing groups with a zone-spread skew cap,
    # tenant-attributed round-robin so the fairness plane sees gang load
    if spec.gang_every_s > 0 and spec.gang_size > 0:
        t = spec.gang_every_s
        g = 0
        while t < spec.duration_s:
            ev = SimEvent(
                at_s=round(t, 3), kind="gang", pods=spec.gang_size,
                cpu=spec.gang_cpu, memory=spec.gang_memory,
                name=f"gang{g}", ttl_s=spec.gang_ttl_s,
                gang_min=spec.gang_size, spread_skew=spec.gang_spread_skew,
                tenant=(f"t{g % spec.tenants}" if spec.tenants > 0 else ""),
            )
            events.append(ev)
            _expire(ev)
            t += spec.gang_every_s
            g += 1

    # anti-affine HA pairs: two replicas, at most one per zone
    if spec.hapair_every_s > 0:
        t = spec.hapair_every_s * 0.75
        h = 0
        while t < spec.duration_s:
            ev = SimEvent(
                at_s=round(t, 3), kind="gang", pods=2,
                cpu="500m", memory="1Gi", name=f"hapair{h}",
                ttl_s=spec.hapair_ttl_s, gang_min=2, anti_affine=True,
                tenant=(f"t{h % spec.tenants}" if spec.tenants > 0 else ""),
            )
            events.append(ev)
            _expire(ev)
            t += spec.hapair_every_s
            h += 1

    # the noisy neighbor: one tenant floods the control plane mid-trace;
    # the fairness gate compares quiet tenants' bind p99 inside vs
    # outside this window (no tenant's p99 may degrade > 2x)
    if spec.noisy_at_s >= 0 and spec.noisy_pods > 0:
        ev = SimEvent(
            at_s=round(spec.noisy_at_s, 3), kind="wave",
            pods=spec.noisy_pods, cpu="500m", memory="1Gi",
            name="noisy0", ttl_s=spec.noisy_duration_s, tenant="noisy",
        )
        events.append(ev)
        _expire(ev)

    # market ticks: each one re-walks every spot price through the live
    # update_spot channel (the driver holds the seeded MarketModel); the
    # tick times are trace data, the PRICES are the model's — both pure
    # functions of the seed, so the whole market day is byte-identical
    if spec.market_tick_s > 0:
        t = spec.market_tick_s
        m = 0
        while t < spec.duration_s:
            events.append(SimEvent(
                at_s=round(t, 3), kind="market", name=f"tick{m}",
            ))
            t += spec.market_tick_s
            m += 1

    # capacity-block arrival: a bounded reservation window opens mid-trace
    # (pods = slots, ttl_s = window length; the driver installs it in the
    # cloud and republishes the nodeclass status)
    if spec.market_block_at_s >= 0 and spec.market_block_slots > 0:
        events.append(SimEvent(
            at_s=round(spec.market_block_at_s, 3), kind="capacity_block",
            pods=spec.market_block_slots, name="block0",
            ttl_s=spec.market_block_duration_s,
        ))

    events.sort(key=lambda e: (e.at_s, e.kind, e.name))
    return events
