"""The fleet report: one JSON artifact per simulated day.

Two planes, deliberately separated:

- ``virtual`` — everything measured in virtual time or counts: SLO
  timelines and burn trajectories, SLI percentiles, packing-efficiency
  series, cost-vs-oracle distribution, audit decision counts, chaos
  injections, invariants. This is the DETERMINISTIC core:
  :meth:`FleetReport.signature` hashes exactly this plane (plus the
  trace + seed) after normalizing process-global identifiers (instance
  ids, claim-name suffixes, pod uids) to per-run ordinals — the same
  witness pattern ``chaos.ChaosLog.signature`` uses — so two same-seed
  runs are byte-identical here even though id counters kept counting.
- ``wall`` — wall-clock attribution: per-span totals (controller /
  solve-phase / backend breakdowns from the streaming SpanAggregator +
  provenance records) and the profile's coverage of driver wall time.
  Real and reportable, but excluded from the signature by construction.

``gate`` is the flat metric dict ``tools/fleet_gate.py`` thresholds
against a checked-in baseline; ``docs/simulation.md`` documents every
field.
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass

SCHEMA_VERSION = 1

#: process-global identifier shapes normalized out of the signature, in
#: one alternation so first-appearance ordinals interleave stably:
#: fake-cloud instance ids, NodeClaim.fresh name suffixes (and the node
#: names derived from them), pod uids.
_ID_RE = re.compile(r"i-[0-9a-f]{6,}|default-[0-9a-f]+|pod-[0-9]+")

#: how many audit/event records the artifact retains (the rings are
#: bounded anyway; this just caps artifact size for huge days)
RECORDS_CAP = 4096


def normalize_ids(text: str) -> str:
    """Replace every process-global id with a per-run ordinal keyed on
    first appearance (``i-…`` -> ``i#0``, ``default-…`` -> ``claim#1``,
    ``pod-…`` -> ``pod#2``)."""
    ranks: dict[str, str] = {}

    def sub(m: re.Match) -> str:
        tok = m.group(0)
        if tok not in ranks:
            prefix = ("i" if tok.startswith("i-")
                      else "claim" if tok.startswith("default-") else "pod")
            ranks[tok] = f"{prefix}#{len(ranks)}"
        return ranks[tok]

    return _ID_RE.sub(sub, text)


def _percentiles(samples: list[float]) -> dict:
    from ..obs import percentile

    return {
        "count": len(samples),
        "p50": percentile(samples, 0.50),
        "p95": percentile(samples, 0.95),
        "p99": percentile(samples, 0.99),
        "max": round(max(samples), 4) if samples else None,
    }


@dataclass
class FleetReport:
    data: dict

    # -- persistence ---------------------------------------------------------
    def to_json(self, indent: int = 1) -> str:
        return json.dumps(self.data, indent=indent, sort_keys=True)

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.to_json() + "\n")
        return path

    @classmethod
    def load(cls, path: str) -> "FleetReport":
        with open(path) as f:
            return cls(data=json.load(f))

    # -- determinism witness -------------------------------------------------
    def witness(self) -> str:
        """The canonical, id-normalized text of the deterministic core."""
        core = {
            "schema": self.data.get("schema"),
            "trace": self.data.get("trace"),
            "seed": self.data.get("seed"),
            "virtual": self.data.get("virtual"),
        }
        return normalize_ids(json.dumps(core, sort_keys=True))

    def signature(self) -> str:
        return hashlib.sha256(self.witness().encode()).hexdigest()

    # -- views ---------------------------------------------------------------
    @property
    def gate(self) -> dict:
        return self.data.get("gate", {})

    def summary(self) -> dict:
        """Compact one-screen view (/debug/sim, CLI output)."""
        v = self.data.get("virtual", {})
        w = self.data.get("wall", {})
        return {
            "trace": self.data.get("trace", {}).get("name"),
            "seed": self.data.get("seed"),
            "nodes": self.data.get("trace", {}).get("nodes"),
            "duration_s": self.data.get("trace", {}).get("duration_s"),
            "passes": v.get("driver", {}).get("passes"),
            "wall_s": w.get("wall_s"),
            "coverage": w.get("attribution", {}).get("coverage"),
            "gate": self.gate,
            "invariants_failed": [
                r["name"] for r in v.get("invariants", []) if not r["passed"]
            ],
            "signature": self.signature(),
        }

    def summary_text(self) -> str:
        v = self.data.get("virtual", {})
        w = self.data.get("wall", {})
        t = self.data.get("trace", {})
        lines = [
            f"fleet report: trace={t.get('name')} seed={self.data.get('seed')} "
            f"nodes={t.get('nodes')} sim_duration={t.get('duration_s'):g}s",
            f"  wall={w.get('wall_s', 0):.2f}s over "
            f"{v.get('driver', {}).get('passes')} controller passes "
            f"(coverage {100 * w.get('attribution', {}).get('coverage', 0):.1f}% "
            "of driver wall attributed to spans)",
            "  gate: " + ", ".join(
                f"{k}={vv}" for k, vv in sorted(self.gate.items())
            ),
        ]
        top = sorted(
            w.get("attribution", {}).get("spans", {}).items(),
            key=lambda kv: -kv[1]["total_ms"],
        )[:8]
        if top:
            lines.append("  top spans: " + ", ".join(
                f"{name}={cell['total_ms']:.0f}ms" for name, cell in top
            ))
        for r in v.get("invariants", []):
            lines.append(f"  [{'PASS' if r['passed'] else 'FAIL'}] "
                         f"{r['name']}: {r['detail']}")
        lines.append(f"  signature: {self.signature()}")
        return "\n".join(lines)


def build_report(sim, span_profile: dict, deltas: dict) -> FleetReport:
    """Assemble the artifact from a finished :class:`FleetSimulator`."""
    env = sim.env
    obs = env.obs

    binds = [round(d, 4) for d in obs.sli.bind_durations()]
    readies = [round(d, 4) for d in obs.sli.ready_durations()]

    slo_summary: dict[str, dict] = {}
    for sample in sim.samples:
        for s in sample["slos"]:
            cur = slo_summary.setdefault(s["name"], {
                "min_budget_remaining": 1.0, "worst_burn": 0.0,
                "bad_max_in_window": 0,
            })
            cur["min_budget_remaining"] = min(
                cur["min_budget_remaining"], s["budget_remaining"]
            )
            cur["worst_burn"] = round(
                max(cur["worst_burn"], s["worst_burn"]), 3
            )
            cur["bad_max_in_window"] = max(
                cur["bad_max_in_window"], s["bad_in_window"]
            )

    packing_cpu = [
        s["packing"]["cpu"] for s in sim.samples if "cpu" in s["packing"]
    ]
    worst_burn = max(
        (d["worst_burn"] for d in slo_summary.values()), default=0.0
    )
    min_budget = min(
        (d["min_budget_remaining"] for d in slo_summary.values()), default=1.0
    )
    quality = _percentiles(sorted(sim.quality_samples))

    audit_records = [
        r.as_dict() for r in obs.audit.tail(RECORDS_CAP)
    ]
    # seq is a PROCESS-global counter — rebase to per-run ordinals so the
    # deterministic core stays byte-identical across same-seed runs in one
    # process (the same reason instance/claim/pod ids are normalized)
    for i, rec in enumerate(audit_records, start=1):
        rec["seq"] = i
    events = [
        {
            "kind": e.kind, "name": e.name, "type": e.type,
            "reason": e.reason, "message": e.message,
            "at": round(e.at, 3), "count": e.count,
        }
        for e in env.events.query()[-RECORDS_CAP:]
    ]

    invariants = [
        {"name": r.name, "passed": r.passed, "detail": r.detail}
        for r in sim.invariants
    ]

    # cross-replica correlation (deterministic: hops ride the FakeClock
    # and the serialized pass order) — the fleet-obs-smoke gate's source
    try:
        correlation = sim.flight_recorder().coverage()
    except Exception:
        correlation = {}

    virtual = {
        "slo_timeline": sim.samples,
        "slo_summary": slo_summary,
        "sli": {
            "pod_time_to_bind_s": _percentiles(binds),
            "nodeclaim_time_to_ready_s": _percentiles(readies),
        },
        "packing": {
            "cpu_min": round(min(packing_cpu), 4) if packing_cpu else None,
            "cpu_mean": (
                round(sum(packing_cpu) / len(packing_cpu), 4)
                if packing_cpu else None
            ),
        },
        "quality": {
            "cost_vs_oracle": quality,
            "unschedulable_total": deltas["unschedulable"],
            "solve_backends": dict(sorted(sim.backend_counts.items())),
            # NOTE: residency counts deliberately live in the WALL plane
            # (wall.residency): the chained-vs-unchained screen chooser
            # picks from MEASURED per-bucket wall cost (ops/device_state
            # .pick_chained), so the labels are wall-clock-dependent and
            # must never enter the signed deterministic core — the PR 13
            # determinism divergence at smoke@120-nodes/2-replicas was
            # exactly this leak.
            "fallbacks": dict(sorted(sim.fallback_counts.items())),
        },
        "audit": {
            "counts_by_kind": deltas["audit"],
            "records": audit_records,
        },
        "events": events,
        "cluster": {
            "nodes_start": sim.nodes_start,
            "nodes_end": len(env.cluster.nodes),
            "pods_end": len(env.cluster.pods),
            "pending_end": len(env.cluster.pending_pods()),
            "launched": deltas["launched"],
            "terminated": deltas["terminated"],
            "binds_audited": len(sim.bind_events),
        },
        "chaos": {
            "injections": len(sim.log),
            "faults_by_kind": sim.log.by_kind(),
            "probe_failures": sim.probe_failures,
            "probe_calls": sim.probe_calls,
        },
        "driver": {
            "passes": sim.passes,
            "events_applied": dict(sorted(sim.events_applied.items())),
            "settle_steps_used": sim.settle_steps_used,
        },
        "correlation": correlation,
        "invariants": invariants,
    }

    # gang plane (designs/gang-scheduling.md): post-settle audit over live
    # pods — every declared gang must be fully bound or fully unbound.
    # Virtual-time data, inside the signature.
    gang_counts: dict = {}
    if sim.events_applied.get("gang"):
        from ..scheduling.groups import gang_partial_counts

        gang_counts = gang_partial_counts(env.cluster.pods.values())
        virtual["gangs"] = {
            "declared_live": len(gang_counts),
            "placed": sum(1 for b, m in gang_counts.values() if b >= m),
            "partial": sorted(
                g for g, (b, m) in gang_counts.items() if 0 < b < m
            ),
            "unplaced": sorted(
                g for g, (b, m) in gang_counts.items() if b == 0
            ),
        }

    # why plane (designs/why-engine.md): decoded constraint attribution
    # over the day's audit ring. Virtual-time data (the why stamps ride
    # the solve's own tensors and the FakeClock), inside the signature.
    # Keyed on the kill switch so KARPENTER_TPU_WHY=0 reports are
    # byte-identical to a build without the engine.
    from ..obs.why import enabled as _why_enabled

    if _why_enabled():
        unsched = [
            r for r in audit_records
            if r.get("kind") == "placement"
            and r.get("decision") == "unschedulable"
        ]
        stamped = [
            r for r in unsched if (r.get("detail") or {}).get("why")
        ]
        why_reasons: dict[str, int] = {}
        for r in stamped:
            top = (r["detail"]["why"].get("top") or "unknown")
            why_reasons[top] = why_reasons.get(top, 0) + 1
        reject_reasons: dict[str, int] = {}
        for r in audit_records:
            if (r.get("kind") == "disruption"
                    and str(r.get("decision", "")).startswith("reject:")):
                w = (r.get("detail") or {}).get("why") or {}
                if w.get("top"):
                    reject_reasons[w["top"]] = (
                        reject_reasons.get(w["top"], 0) + 1
                    )
        virtual["why"] = {
            # coverage over the ring's unschedulable records: every one
            # must carry a decoded attribution (1.0 when none — a clean
            # day attributes vacuously)
            "unschedulable_records": len(unsched),
            "attributed": len(stamped),
            "coverage": (
                round(len(stamped) / len(unsched), 4) if unsched else 1.0
            ),
            "reasons": dict(sorted(why_reasons.items())),
            "consolidation_rejects": dict(sorted(reject_reasons.items())),
        }

    # tenancy / fairness plane: quiet tenants' bind p99 inside the noisy-
    # neighbor window vs outside it (virtual-time durations: signed)
    noisy_at = getattr(sim.trace, "noisy_at_s", -1.0)
    tenancy: dict = {}
    if getattr(sim, "tenant_binds", None):
        per: dict[str, dict[str, list]] = {}
        w0 = noisy_at
        w1 = noisy_at + getattr(sim.trace, "noisy_duration_s", 0.0)
        for tenant, at_s, dur in sim.tenant_binds:
            cell = per.setdefault(tenant, {"in": [], "out": []})
            cell["in" if (w0 >= 0 and w0 <= at_s <= w1) else "out"].append(dur)
        for tenant, cell in sorted(per.items()):
            tenancy[tenant] = {
                "in_window": _percentiles(sorted(cell["in"])),
                "out_window": _percentiles(sorted(cell["out"])),
            }
        virtual["tenancy"] = tenancy
    if getattr(sim, "replicas", 1) > 1:
        # sharded-control-plane plane (all virtual-time: deterministic,
        # inside the signature): per-replica lease holdings, the audited
        # overlap list (must be empty), replica-loss recovery times, the
        # work-stealing queue's claim outcomes, and the packing-envelope
        # comparison against the single-replica reference run
        env_rs = sim.env
        with env_rs.cloud._lock:
            fenced_rejections = len(env_rs.cloud.fenced_rejections)
        leases_held = {
            r.identity: len(r.elector.owned_keys())
            for r in env_rs.replicas
        }
        held_alive = [
            n for r, n in (
                (r, leases_held[r.identity]) for r in env_rs.replicas
            ) if r.alive
        ]
        mean_held = (
            sum(held_alive) / len(held_alive) if held_alive else 0.0
        )
        queue_waits = sorted(obs.sli.queue_wait_durations())
        steal_waits = sorted(obs.sli.steal_wait_durations())
        virtual["sharding"] = {
            "replicas": sim.replicas,
            "alive": sum(1 for r in env_rs.replicas if r.alive),
            "leases_held": leases_held,
            # the ROADMAP's rendezvous skew, measured: max/mean leases
            # over live replicas at day end (1.0 = perfectly balanced)
            "rendezvous_imbalance": (
                round(max(held_alive) / mean_held, 4) if mean_held else None
            ),
            "lease_overlaps": len(env_rs.lease_overlaps),
            "partition_gap_end": len(env_rs.partition_gap()),
            "fenced_writes_rejected": fenced_rejections,
            "replica_loss_recoveries_s": list(sim.replica_recoveries),
            "steals": dict(deltas.get("steals", {})),
            # steal-latency SLI (obs/sli.py): enqueue->claim for every
            # GLOBAL pod; steal-wait = the stolen subset's tail
            "queue_wait_s": _percentiles(queue_waits),
            "steal_wait_s": _percentiles(steal_waits),
            "ownership_transitions": len(
                getattr(env_rs, "ownership_timeline", ())
            ),
            "envelope": dict(getattr(sim, "envelope", None) or {}),
        }

    wall_ms = sim.driver_wall_s * 1e3
    root_ms = sum(
        cell["total_ms"] for cell in span_profile.get("roots", {}).values()
    )
    coverage = round(root_ms / wall_ms, 4) if wall_ms > 0 else 0.0
    spans = span_profile.get("spans", {})

    def _family(prefix: str) -> dict:
        return {
            name[len(prefix):]: cell
            for name, cell in spans.items() if name.startswith(prefix)
        }

    # sentinel readings are wall-time judgments: reportable, NEVER signed
    sentinel = getattr(obs, "sentinel", None)
    sentinel_wall = {}
    if sentinel is not None:
        s = sentinel.summary()
        sentinel_wall = {
            "ticks": s["ticks"],
            "tick_wall_ewma_ms": s["tick_wall_ewma_ms"],
            "tick_wall_p99_ms": s["tick_wall_p99_ms"],
            "findings": s["findings"],
        }

    # the device plane (trace/jitwatch.py): compile/retrace ledger +
    # the zero-retrace steady-state witness. Wall-side by construction —
    # compile walls are real milliseconds, and residency labels depend on
    # the measured-cost screen chooser.
    try:
        device_plane = sim.jit_summary()
    except Exception:
        device_plane = {}

    wall = {
        "wall_s": round(sim.driver_wall_s, 3),
        "wall_per_sim_hour_s": (
            round(sim.driver_wall_s / (sim.trace.duration_s / 3600.0), 3)
            if sim.trace.duration_s else None
        ),
        "sentinel": sentinel_wall,
        "device": device_plane,
        "residency": dict(sorted(sim.residency_counts.items())),
        "attribution": {
            "coverage": coverage,
            "roots": span_profile.get("roots", {}),
            "spans": spans,
            "controllers": _family("controller."),
            "solve_phases": _family("solve."),
            "consolidate_phases": _family("consolidate."),
            "aws": _family("aws."),
            "backend_wall_ms": dict(sorted(sim.backend_wall_ms.items())),
        },
    }

    gate = {
        "slo_worst_burn": round(worst_burn, 3),
        "slo_budget_remaining_min": round(min_budget, 4),
        "pod_time_to_bind_p50_s": virtual["sli"]["pod_time_to_bind_s"]["p50"],
        "pod_time_to_bind_p99_s": virtual["sli"]["pod_time_to_bind_s"]["p99"],
        "nodeclaim_time_to_ready_p99_s": (
            virtual["sli"]["nodeclaim_time_to_ready_s"]["p99"]
        ),
        "bind_count": virtual["sli"]["pod_time_to_bind_s"]["count"],
        "packing_eff_min": virtual["packing"]["cpu_min"],
        "cost_vs_oracle_p95": quality["p95"],
        "unschedulable_total": deltas["unschedulable"],
        "pending_end": virtual["cluster"]["pending_end"],
        "invariants_failed": sum(1 for r in invariants if not r["passed"]),
        "attribution_coverage": coverage,
        "correlation_coverage": correlation.get("coverage"),
        "sentinel_findings": len(sentinel_wall.get("findings", ())),
        # the zero-retrace steady-state gate: compiles recorded after the
        # trace's warmup boundary (None when jitwatch was off — absence
        # fails the gate unless the baseline allows it)
        "retraces_after_warmup": device_plane.get("retraces_after_warmup"),
    }
    if sim.events_applied.get("gang"):
        # GANG traces gate atomicity by name: zero partially-placed gangs
        # at settle, and at least one fully placed (a zero-placement day
        # would pass atomicity vacuously)
        gate["gangs_partial"] = len(virtual["gangs"]["partial"])
        gate["gangs_placed"] = virtual["gangs"]["placed"]
    if "why" in virtual:
        # the why-not engine's own gate: full attribution coverage over
        # the ring's unschedulable records, plus the ranked top reason so
        # baselines can pin what a canned day is SUPPOSED to starve on
        gate["why_coverage"] = virtual["why"]["coverage"]
        ranked = sorted(
            virtual["why"]["reasons"].items(),
            key=lambda kv: (-kv[1], kv[0]),
        )
        gate["why_top_reason"] = ranked[0][0] if ranked else None
    if noisy_at >= 0 and tenancy:
        # the per-tenant fairness SLO: worst quiet-tenant ratio of bind
        # p99 inside the noisy window vs outside (the noisy tenant itself
        # is excluded — IT chose to flood)
        ratios = []
        for tenant, cell in tenancy.items():
            if tenant == "noisy":
                continue
            p_in = cell["in_window"]["p99"]
            p_out = cell["out_window"]["p99"]
            if p_in is not None and p_out:
                ratios.append(p_in / p_out)
        gate["tenant_bind_p99_ratio"] = (
            round(max(ratios), 4) if ratios else None
        )
    if getattr(sim.trace, "market_tick_s", 0.0) > 0:
        # MARKET traces gate cost-vs-oracle under moving prices by its own
        # name, so baselines can hold the market bar independently of the
        # static-price one (sim/baselines/market-500.json)
        gate["cost_vs_oracle_market_p95"] = quality["p95"]
    if getattr(sim, "replicas", 1) > 1:
        sharding = virtual["sharding"]
        gate["replica_loss_recovery_s"] = (
            max(sharding["replica_loss_recoveries_s"])
            if sharding["replica_loss_recoveries_s"] else None
        )
        gate["lease_overlaps"] = sharding["lease_overlaps"]
        gate["partition_gap_end"] = sharding["partition_gap_end"]
        gate["rendezvous_imbalance"] = sharding["rendezvous_imbalance"]
        gate["queue_wait_p99_s"] = sharding["queue_wait_s"]["p99"]
        gate["steal_wait_p99_s"] = sharding["steal_wait_s"]["p99"]
        envelope = sharding["envelope"]
        if envelope:
            gate["packing_envelope_ratio"] = envelope.get("packing_ratio")
            gate["cost_envelope_ratio"] = envelope.get("cost_ratio")
    aot = (device_plane or {}).get("aot_warmup") or {}
    if aot.get("did_warm"):
        # the zero-cold-start gate (designs/aot-warmup.md): the process
        # warmed from a manifest, so the run's FIRST solve must have
        # compiled nothing — only stamped when warmup actually ran, so
        # plain (cold) runs don't gate a key they can't satisfy
        gate["first_solve_after_restart"] = aot.get("first_solve_compiles")

    return FleetReport(data={
        "schema": SCHEMA_VERSION,
        "kind": "fleet-report",
        "trace": sim.trace.to_dict(),
        "seed": sim.seed,
        "virtual": virtual,
        "wall": wall,
        "gate": gate,
    })
