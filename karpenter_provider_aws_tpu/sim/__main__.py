"""CLI: ``python -m karpenter_provider_aws_tpu.sim <run|sweep|traces>``.

``run`` drives one simulated day and writes the fleet-report artifact
(optionally running twice to verify same-seed determinism); ``sweep``
runs the scale-tier ladder and prints the cliff detector's verdict;
``traces`` lists the shipped trace specs. Exit status: 0 on success,
1 when invariants failed / determinism broke / a cliff was found (so CI
can gate directly on the command).
"""

from __future__ import annotations

import argparse
import json
import sys

from .cliffs import sweep
from .driver import run_deterministic, run_trace
from .traces import canned_trace, canned_traces


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m karpenter_provider_aws_tpu.sim",
        description="deterministic fleet simulator: a day of prod in a minute",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_run = sub.add_parser("run", help="drive one simulated day")
    p_run.add_argument("--trace", default="smoke",
                       help="canned trace name or a TraceSpec JSON file path")
    p_run.add_argument("--seed", type=int, default=0)
    p_run.add_argument("--nodes", type=int, default=None,
                       help="override the trace's fleet size")
    p_run.add_argument("--hours", type=float, default=None,
                       help="override the trace's simulated duration")
    p_run.add_argument("--overlay", action="append", default=[],
                       help="chaos overlay as scenario[@at_s[xstretch]], "
                            "e.g. spot-storm@3600 (repeatable)")
    p_run.add_argument("--replicas", type=int, default=1,
                       help="control-plane replicas (>= 2 turns on the "
                            "sharded lease layer; Replica* overlays need it)")
    p_run.add_argument("--report", default="",
                       help="write the fleet-report JSON artifact here")
    p_run.add_argument("--flight-out", default="",
                       help="write the flight-recorder snapshot here "
                            "(the `obs fleet explain/timeline` input)")
    p_run.add_argument("--check-determinism", action="store_true",
                       help="run twice and require byte-identical reports")
    p_run.add_argument("--json", action="store_true",
                       help="print the summary as JSON instead of text")

    p_sweep = sub.add_parser("sweep", help="scale-tier sweep + cliff detector")
    p_sweep.add_argument("--trace", default="smoke")
    p_sweep.add_argument("--seed", type=int, default=0)
    p_sweep.add_argument("--tiers", default="500,1000,2000",
                         help="comma-separated fleet sizes")
    p_sweep.add_argument("--hours", type=float, default=None)
    p_sweep.add_argument("--report", default="",
                         help="write the sweep JSON here")

    sub.add_parser("traces", help="list the shipped traces")

    args = parser.parse_args(argv)

    if args.cmd == "traces":
        for name, spec in sorted(canned_traces().items()):
            print(f"{name}: {spec.nodes} nodes, {spec.duration_s / 3600:g}h, "
                  f"{spec.waves_per_hour:g} waves/h x {spec.wave_pods} pods, "
                  f"{spec.floods} floods, churn {spec.churn_pods} pods "
                  f"every {spec.churn_every_s:g}s")
        return 0

    def load_trace(name: str):
        if name.endswith(".json"):
            from .traces import TraceSpec

            with open(name) as f:
                return TraceSpec.from_json(f.read())
        return canned_trace(name)

    duration = args.hours * 3600.0 if args.hours is not None else None

    if args.cmd == "run":
        kw = dict(nodes=args.nodes, duration_s=duration,
                  overlays=list(args.overlay), replicas=args.replicas)
        if args.check_determinism:
            if args.flight_out:
                # the determinism harness discards its simulators, so
                # there is no ledger left to snapshot — be loud, not
                # silent, about the flag being unsupported here
                print("warning: --flight-out is ignored with "
                      "--check-determinism (rerun without it to write "
                      "the flight snapshot)", file=sys.stderr)
            try:
                reports = run_deterministic(
                    load_trace(args.trace), seed=args.seed, runs=2, **kw
                )
            except AssertionError as e:
                print(str(e), file=sys.stderr)
                return 1
            report = reports[0]
            print("determinism: 2 same-seed runs byte-identical",
                  file=sys.stderr)
        else:
            from .driver import FleetSimulator

            sim = FleetSimulator(load_trace(args.trace), seed=args.seed, **kw)
            report = sim.run()
            if args.flight_out:
                sim.flight_recorder().save(args.flight_out)
                print(f"wrote {args.flight_out}", file=sys.stderr)
        if args.report:
            report.save(args.report)
            print(f"wrote {args.report}", file=sys.stderr)
        print(json.dumps(report.summary(), indent=1, sort_keys=True)
              if args.json else report.summary_text())
        failed = [r for r in report.data["virtual"]["invariants"]
                  if not r["passed"]]
        return 1 if failed else 0

    # sweep
    tiers = [int(t) for t in args.tiers.split(",") if t]
    out = sweep(load_trace(args.trace), tiers, seed=args.seed,
                duration_s=duration)
    if args.report:
        with open(args.report, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        print(f"wrote {args.report}", file=sys.stderr)
    for row in out["tiers"]:
        print(f"tier {row['tier']}: wall={row['wall_s']}s "
              f"({row['wall_per_sim_hour_s']}s/sim-hour) "
              f"worst_burn={row['slo_worst_burn']} "
              f"bind_p99={row['bind_p99_s']}s")
    if out["cliff_tier"] is not None:
        print(f"CLIFF at tier {out['cliff_tier']}:")
        for f_ in out["findings"]:
            print(f"  [{f_['kind']}] tier {f_['tier']}: {f_['detail']}")
        return 1
    print("no cliff detected across tiers")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
