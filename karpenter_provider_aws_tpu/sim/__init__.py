"""sim/: the deterministic fleet simulator — a day of prod in a minute.

The chaos harness (``chaos/``) proves the control plane survives canned
fault timelines; this subsystem proves it keeps its PROMISES under
sustained realistic load, observed through the ``obs/`` judgment layer:

- :mod:`.traces` — the seeded workload-trace grammar (diurnal deployment
  waves, batch-job floods, pod churn, chaos overlays composed from
  ``chaos/plan.py`` scenarios) and its generators.
- :mod:`.driver` — :class:`FleetSimulator`: builds an N-node fleet,
  replays the trace against the FULL controller manager on a sub-tick
  FakeClock with adaptive stepping, and runs the chaos invariants after
  a settle phase. Byte-identical per seed.
- :mod:`.report` — the fleet-report artifact: SLO/burn timelines, SLI
  percentiles, packing + cost-vs-oracle series, audit decision counts,
  and a span-level wall-time attribution profile covering >= 95% of the
  driver's wall clock; ``signature()`` is the determinism witness.
- :mod:`.cliffs` — the scale-tier sweep + cliff detector that flags the
  first tier where SLO burn or a span family's attribution share
  regresses super-linearly — the instrument that finds the next scaling
  cliff (and names it) before a tier bump does.

CLI: ``python -m karpenter_provider_aws_tpu.sim run --trace smoke``;
CI gate: ``tools/fleet_gate.py`` against a checked-in baseline
(``make sim-smoke``). Docs: ``docs/simulation.md`` +
``designs/fleet-simulator.md``.
"""

from __future__ import annotations

from .cliffs import detect_cliffs, sweep, tier_row
from .driver import FleetSimulator, run_deterministic, run_trace
from .report import FleetReport, normalize_ids
from .traces import Overlay, SimEvent, TraceSpec, canned_trace, canned_traces, generate

__all__ = [
    "FleetReport", "FleetSimulator", "Overlay", "SimEvent", "TraceSpec",
    "canned_trace", "canned_traces", "detect_cliffs", "generate",
    "normalize_ids", "run_deterministic", "run_trace", "sweep", "tier_row",
]
